"""ggrs-verify pillar 1: the cross-language layout checker.

Three layers of pinning (ISSUE: the static-analysis plane):

* parser goldens — the C++/Python extractors read the exact constant
  shapes the native sources use (constexpr casts, enums with implicit
  increments, struct-format aliases);
* deliberate-skew fixtures — a 1-value mirror drift, a 1-byte header
  drift, and a jump-offset drift each FIRE (the tree is currently
  clean, so the fixtures are what prove the checker catches what it
  exists to catch);
* self-clean + runtime parity — the repo tree passes, and the static
  header table equals both the live ``np.dtype`` and the runtime
  ``ggrs_bank_hdr_stride()`` probe.
"""

from pathlib import Path

import numpy as np
import pytest

from ggrs_tpu.analysis import (
    LAYOUT_HEADER_FIELDS,
    check_layout,
    parse_cpp_constants,
    parse_py_constants,
    parse_py_struct_formats,
    static_bank_header,
)
from ggrs_tpu.analysis.layout import (
    LAYOUT_FD_FIELDS,
    LAYOUT_FD_STRIDE,
    LAYOUT_RECV_FIELDS,
    LAYOUT_RECV_STRIDE,
    LAYOUT_REQ_FIELDS,
    LAYOUT_REQ_STRIDE,
    LAYOUT_ROUTE_FIELDS,
    LAYOUT_ROUTE_STRIDE,
    LAYOUT_SEND_FIELDS,
    LAYOUT_SEND_STRIDE,
    LAYOUT_STAGE_FIELDS,
    LAYOUT_STAGE_STRIDE,
    MIRRORED_CONSTANTS,
    _check_field_table,
    _check_header,
    _check_mirrors,
)
from ggrs_tpu.net import _native

REPO = Path(__file__).resolve().parents[1]


# ----------------------------------------------------------------------
# parser goldens
# ----------------------------------------------------------------------


class TestCppParser:
    def test_constexpr_forms(self):
        src = """
        constexpr int kPlain = 42;
        constexpr int64_t kNeg = -70;
        constexpr size_t kShift = size_t{1} << 22;
        constexpr uint64_t kAllOnes = ~uint64_t{0};
        constexpr int64_t kNegShift = -(int64_t{1} << 62);
        constexpr uint8_t kHex = 0x80;
        static constexpr int kStatic = 7;
        """
        c = parse_cpp_constants(src)
        assert c["kPlain"] == 42
        assert c["kNeg"] == -70
        assert c["kShift"] == 1 << 22
        assert c["kAllOnes"] == (1 << 64) - 1
        assert c["kNegShift"] == -(1 << 62)
        assert c["kHex"] == 0x80
        assert c["kStatic"] == 7

    def test_enum_implicit_increment(self):
        src = """
        enum MsgTag : uint8_t {
          kTagA = 0,
          kTagB,      // implicit 1
          kTagC = 5,
          kTagD,      // implicit 6
        };
        enum class Verdict { kOk = 0, kErr = -3 };
        """
        c = parse_cpp_constants(src)
        assert (c["kTagA"], c["kTagB"], c["kTagC"], c["kTagD"]) == \
            (0, 1, 5, 6)
        assert c["kErr"] == -3

    def test_comments_do_not_confuse(self):
        src = """
        // constexpr int kCommented = 9;
        /* constexpr int kBlock = 10; */
        constexpr int kReal = 1;  // trailing = 2 garbage
        """
        c = parse_cpp_constants(src)
        assert c == {"kReal": 1}

    def test_non_integer_skipped(self):
        c = parse_cpp_constants(
            'constexpr char kName[] = "x";\n'
            "constexpr double kF = 1.5;\n"
            "constexpr int kOk = 3;\n"
        )
        assert c == {"kOk": 3}


class TestPySourceParser:
    def test_constants_and_folding(self):
        src = "A = 48\nB = 1 << 22\nC = -70\nD = A\n_E = 0x80\n"
        c = parse_py_constants(src)
        assert c == {"A": 48, "B": 1 << 22, "C": -70, "_E": 0x80}

    def test_struct_formats_direct_and_aliased(self):
        src = (
            "import struct\n"
            "from struct import unpack_from as uf\n"
            "pack = struct.pack\n"
            "H = struct.Struct('<2sBBII')\n"
            "def f(buf):\n"
            "    pack('<HI', 1, 2)\n"
            "    uf('<iqiqqBH', buf, 0)\n"
            "    struct.unpack('<qqq', buf)\n"
        )
        fmts = {(s.func, s.fmt) for s in parse_py_struct_formats(src)}
        assert ("Struct", "<2sBBII") in fmts
        assert ("pack", "<HI") in fmts
        assert ("unpack_from", "<iqiqqBH") in fmts
        assert ("unpack", "<qqq") in fmts


# ----------------------------------------------------------------------
# deliberate-skew fixtures: the checker must FIRE on drift
# ----------------------------------------------------------------------


def _mini_tree(tmp_path, native_py_text: str) -> Path:
    """A minimal fake repo holding just the files _check_header reads."""
    (tmp_path / "native").mkdir()
    (tmp_path / "ggrs_tpu/net").mkdir(parents=True)
    (tmp_path / "native/session_bank.cpp").write_text(
        "constexpr size_t kHdrStride = 48;\n"
    )
    (tmp_path / "ggrs_tpu/net/_native.py").write_text(native_py_text)
    return tmp_path


GOOD_FIELDS = (
    'BANK_HDR_FIELDS = (\n'
    '    ("flags", "<u4"), ("rec_len", "<u4"), ("err", "<i4"),\n'
    '    ("fa", "<i4"), ("landed", "<i8"), ("current", "<i8"),\n'
    '    ("confirmed", "<i8"), ("save_frame", "<i8"),\n'
    ')\n'
)


class TestDeliberateSkew:
    def test_clean_fixture_passes(self, tmp_path):
        root = _mini_tree(tmp_path, GOOD_FIELDS)
        assert _check_header(root) == []

    def test_one_byte_header_drift_fires(self, tmp_path):
        # err shrinks i4 -> i2: every later offset shifts, stride 46
        root = _mini_tree(
            tmp_path, GOOD_FIELDS.replace('("err", "<i4")',
                                          '("err", "<i2")')
        )
        findings = _check_header(root)
        assert findings, "1-byte field drift must fail lint"
        assert any("stride" in f.rule or "fields" in f.rule
                   for f in findings)

    def test_big_endian_field_fires(self, tmp_path):
        root = _mini_tree(
            tmp_path, GOOD_FIELDS.replace('("landed", "<i8")',
                                          '("landed", ">i8")')
        )
        assert any(
            f.rule == "layout/header-endian" for f in _check_header(root)
        )

    def test_native_stride_drift_fires(self, tmp_path):
        root = _mini_tree(tmp_path, GOOD_FIELDS)
        (root / "native/session_bank.cpp").write_text(
            "constexpr size_t kHdrStride = 56;\n"
        )
        assert any(
            f.rule == "layout/header-stride" for f in _check_header(root)
        )

    # ---- descriptor-plane structs (§21): same three layers of pinning --

    REQ_GOOD = (
        'BANK_REQ_FIELDS = (\n'
        '    ("pattern", "<u1"), ("rflags", "<u1"), ("n_adv", "<u2"),\n'
        '    ("adv_off", "<u4"), ("adv_stride", "<u4"),\n'
        '    ("ops_end", "<u4"), ("frame", "<i8"),\n'
        ')\n'
    )
    STAGE_GOOD = (
        'BANK_STAGE_FIELDS = (\n'
        '    ("slot", "<u4"), ("handle", "<i4"), ("frame", "<i8"),\n'
        '    ("off", "<u4"), ("len", "<u4"),\n'
        ')\n'
    )

    def _table_tree(self, tmp_path, text):
        (tmp_path / "ggrs_tpu/net").mkdir(parents=True)
        (tmp_path / "ggrs_tpu/net/_native.py").write_text(text)
        return tmp_path

    def test_clean_req_table_passes(self, tmp_path):
        root = self._table_tree(tmp_path, self.REQ_GOOD + self.STAGE_GOOD)
        assert _check_field_table(
            root, "BANK_REQ_FIELDS", LAYOUT_REQ_FIELDS, LAYOUT_REQ_STRIDE
        ) == []
        assert _check_field_table(
            root, "BANK_STAGE_FIELDS", LAYOUT_STAGE_FIELDS,
            LAYOUT_STAGE_STRIDE,
        ) == []

    def test_req_one_byte_drift_fires(self, tmp_path):
        # n_adv shrinks u2 -> u1: every later offset shifts, stride 23
        root = self._table_tree(
            tmp_path,
            self.REQ_GOOD.replace('("n_adv", "<u2")', '("n_adv", "<u1")'),
        )
        findings = _check_field_table(
            root, "BANK_REQ_FIELDS", LAYOUT_REQ_FIELDS, LAYOUT_REQ_STRIDE
        )
        assert findings, "1-byte descriptor field drift must fail lint"
        assert any("stride" in f.rule or "fields" in f.rule
                   for f in findings)

    def test_stage_big_endian_fires(self, tmp_path):
        root = self._table_tree(
            tmp_path,
            self.STAGE_GOOD.replace('("frame", "<i8")',
                                    '("frame", ">i8")'),
        )
        assert any(
            f.rule == "layout/table-endian"
            for f in _check_field_table(
                root, "BANK_STAGE_FIELDS", LAYOUT_STAGE_FIELDS,
                LAYOUT_STAGE_STRIDE,
            )
        )

    RECV_GOOD = (
        'NET_RECV_FIELDS = (\n'
        '    ("slot", "<i4"), ("fd_idx", "<i4"), ("ip", "<u4"),\n'
        '    ("port", "<u2"), ("seg", "<u2"), ("off", "<u4"),\n'
        '    ("len", "<u4"),\n'
        ')\n'
    )
    ROUTE_GOOD = (
        'NET_ROUTE_FIELDS = (\n'
        '    ("ip", "<u4"), ("port", "<u2"), ("pad", "<u2"),\n'
        '    ("slot", "<i4"),\n'
        ')\n'
    )

    def test_clean_gen2_tables_pass(self, tmp_path):
        root = self._table_tree(tmp_path, self.RECV_GOOD + self.ROUTE_GOOD)
        assert _check_field_table(
            root, "NET_RECV_FIELDS", LAYOUT_RECV_FIELDS, LAYOUT_RECV_STRIDE
        ) == []
        assert _check_field_table(
            root, "NET_ROUTE_FIELDS", LAYOUT_ROUTE_FIELDS,
            LAYOUT_ROUTE_STRIDE,
        ) == []

    def test_recv_record_one_byte_drift_fires(self, tmp_path):
        # port widens u2 -> u4: off/len shift, stride 26 — the §23a
        # record table is a wire struct and must fail lint like one
        root = self._table_tree(
            tmp_path,
            self.RECV_GOOD.replace('("port", "<u2")', '("port", "<u4")'),
        )
        findings = _check_field_table(
            root, "NET_RECV_FIELDS", LAYOUT_RECV_FIELDS, LAYOUT_RECV_STRIDE
        )
        assert findings, "recv-record field drift must fail lint"

    def test_route_row_field_order_drift_fires(self, tmp_path):
        # slot moves ahead of ip: same stride, different offsets — the
        # native binary search would read garbage keys
        root = self._table_tree(
            tmp_path,
            'NET_ROUTE_FIELDS = (\n'
            '    ("slot", "<i4"), ("ip", "<u4"), ("port", "<u2"),\n'
            '    ("pad", "<u2"),\n'
            ')\n',
        )
        findings = _check_field_table(
            root, "NET_ROUTE_FIELDS", LAYOUT_ROUTE_FIELDS,
            LAYOUT_ROUTE_STRIDE,
        )
        assert findings, "route-row field order drift must fail lint"

    def test_recv_stride_mirror_drift_fires(self, tmp_path):
        (tmp_path / "a.cpp").write_text(
            "constexpr size_t kRecvStride = 28;\n"
        )
        (tmp_path / "b.py").write_text("NET_RECV_STRIDE = 24\n")
        findings = _check_mirrors(
            tmp_path, [("a.cpp", "kRecvStride", "b.py", "NET_RECV_STRIDE")]
        )
        assert [f.rule for f in findings] == ["layout/mirror-mismatch"]

    def test_send_stride_mirror_drift_fires(self, tmp_path):
        # the C++ kSendStride is pinned through the mirror table — a
        # native-side stride bump without the Python twin fires
        (tmp_path / "a.cpp").write_text("constexpr size_t kSendStride = 24;\n")
        (tmp_path / "b.py").write_text("NET_SEND_STRIDE = 20\n")
        findings = _check_mirrors(
            tmp_path, [("a.cpp", "kSendStride", "b.py", "NET_SEND_STRIDE")]
        )
        assert [f.rule for f in findings] == ["layout/mirror-mismatch"]

    def test_mirror_value_drift_fires(self, tmp_path):
        (tmp_path / "a.cpp").write_text("constexpr int kX = -70;\n")
        (tmp_path / "b.py").write_text("X = -71\n")
        findings = _check_mirrors(
            tmp_path, [("a.cpp", "kX", "b.py", "X")]
        )
        assert [f.rule for f in findings] == ["layout/mirror-mismatch"]

    def test_mirror_missing_side_fires(self, tmp_path):
        (tmp_path / "a.cpp").write_text("constexpr int kX = -70;\n")
        (tmp_path / "b.py").write_text("OTHER = 1\n")
        findings = _check_mirrors(
            tmp_path, [("a.cpp", "kX", "b.py", "X")]
        )
        assert [f.rule for f in findings] == ["layout/mirror-missing"]


# ----------------------------------------------------------------------
# the tree itself + runtime parity
# ----------------------------------------------------------------------


class TestTreeIsClean:
    def test_repo_layout_clean(self):
        findings = check_layout(REPO)
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_mirror_table_covers_all_bank_errors(self):
        """Every kBankErr*/kHdr* the native source declares is in the
        mirror table — a NEW native constant without a declared mirror
        fails here, which is how the table stays complete."""
        native = parse_cpp_constants(REPO / "native/session_bank.cpp")
        mirrored = {
            c for f, c, _, _ in MIRRORED_CONSTANTS
            if f == "native/session_bank.cpp"
        }
        declared = {
            k for k in native
            if k.startswith("kBankErr") or k.startswith("kHdr")
            or k.startswith("kFlag") or k.startswith("kReq")
            or k.startswith("kStage")
        } - {"kHdrStride"}  # stride is pinned by the header check
        assert declared <= mirrored, (
            f"unmirrored native constants: {sorted(declared - mirrored)}"
        )

    def test_static_header_matches_live_dtype(self):
        header = static_bank_header()
        dtype = np.dtype(list(_native.BANK_HDR_FIELDS))
        assert header["stride"] == dtype.itemsize
        for name, fmt, offset in header["fields"]:
            assert dtype.fields[name][1] == offset
            assert np.dtype(fmt) == dtype.fields[name][0]
        assert tuple(dtype.names) == tuple(
            n for n, _, _ in LAYOUT_HEADER_FIELDS
        )

    def test_static_header_matches_runtime_probe(self):
        lib = _native.bank_lib()
        if lib is None or not hasattr(lib, "ggrs_bank_hdr_stride"):
            pytest.skip("no native bank library on this platform")
        assert int(lib.ggrs_bank_hdr_stride()) == \
            static_bank_header()["stride"]

    def test_descriptor_tables_match_live_dtypes_and_probes(self):
        """The §21 contract tables equal both the live np.dtypes and the
        runtime stride probes."""
        for fields, contract, stride in (
            (_native.BANK_REQ_FIELDS, LAYOUT_REQ_FIELDS,
             LAYOUT_REQ_STRIDE),
            (_native.BANK_STAGE_FIELDS, LAYOUT_STAGE_FIELDS,
             LAYOUT_STAGE_STRIDE),
            (_native.NET_SEND_FIELDS, LAYOUT_SEND_FIELDS,
             LAYOUT_SEND_STRIDE),
            (_native.NET_RECV_FIELDS, LAYOUT_RECV_FIELDS,
             LAYOUT_RECV_STRIDE),
            (_native.NET_ROUTE_FIELDS, LAYOUT_ROUTE_FIELDS,
             LAYOUT_ROUTE_STRIDE),
            (_native.NET_FD_FIELDS, LAYOUT_FD_FIELDS,
             LAYOUT_FD_STRIDE),
        ):
            dtype = np.dtype(list(fields))
            assert dtype.itemsize == stride
            for name, fmt, offset in contract:
                assert dtype.fields[name][1] == offset
                assert np.dtype(fmt) == dtype.fields[name][0]
        lib = _native.bank_lib()
        if lib is None or not hasattr(lib, "ggrs_bank_req_stride"):
            pytest.skip("no descriptor-plane library on this platform")
        assert int(lib.ggrs_bank_req_stride()) == LAYOUT_REQ_STRIDE
        assert int(lib.ggrs_bank_stage_stride()) == LAYOUT_STAGE_STRIDE

    def test_gen2_tables_match_runtime_probes(self):
        """The §23 drain/route/fd strides and stat-table widths equal the
        built library's probes (compiled on BOTH branches, so this pins
        the stub too)."""
        lib = _native.bank_lib()
        if lib is None or not hasattr(lib, "ggrs_net_recv_stride"):
            pytest.skip("no gen-2 library on this platform")
        assert int(lib.ggrs_net_recv_stride()) == LAYOUT_RECV_STRIDE
        assert int(lib.ggrs_net_route_stride()) == LAYOUT_ROUTE_STRIDE
        assert int(lib.ggrs_net_fd_stride()) == LAYOUT_FD_STRIDE
        assert int(lib.ggrs_net_send_stats_len()) == _native.NET_SEND_STATS
        assert int(lib.ggrs_net_recv_stats_len()) == \
            _native.NET_RECV_TABLE_STATS

    def test_cmd_flags_match_native_literals(self):
        native = parse_cpp_constants(REPO / "native/session_bank.cpp")
        assert _native.CMD_FLAG_INPUTS == native["kFlagInputs"]
        assert _native.CMD_FLAG_SKIP == native["kFlagSkip"]


class TestReviewRegressions:
    def test_enum_implicit_poisoned_after_unevaluable_entry(self):
        # B's true value is sizeof(int)+1, unknown statically: emitting
        # an implicit guess could mask (or fabricate) ABI drift
        c = parse_cpp_constants(
            "enum { kA = sizeof(int), kB, kC, kD = 9, kE };"
        )
        assert "kB" not in c and "kC" not in c
        assert c["kD"] == 9 and c["kE"] == 10

    def test_py_mirror_pair_drift_fires(self, tmp_path):
        from ggrs_tpu.analysis.layout import _check_py_mirrors

        (tmp_path / "a.py").write_text("P = 4\n")
        (tmp_path / "b.py").write_text("_P = 5\n")
        findings = _check_py_mirrors(
            tmp_path, [("a.py", "P", "b.py", "_P")]
        )
        assert [f.rule for f in findings] == ["layout/mirror-mismatch"]

    def test_pickle_protocol_pair_is_checked_on_tree(self):
        from ggrs_tpu.analysis.layout import PY_MIRRORED_CONSTANTS

        pairs = {(a, b) for a, _, b, _ in PY_MIRRORED_CONSTANTS}
        assert (
            "ggrs_tpu/fleet/rpc.py", "ggrs_tpu/parallel/host_bank.py"
        ) in pairs

    def test_unsigned_complement_uses_cast_width(self):
        c = parse_cpp_constants(
            "constexpr uint32_t kMask32 = ~uint32_t{0};\n"
            "constexpr uint64_t kMask64 = ~uint64_t{0};\n"
            "constexpr uint8_t kMask8 = ~uint8_t{0};\n"
        )
        assert c["kMask32"] == 0xFFFFFFFF
        assert c["kMask64"] == (1 << 64) - 1
        assert c["kMask8"] == 0xFF


# ----------------------------------------------------------------------
# §25 TCP handshake contract (PR 11 rule: wire structs land with their
# checker — deliberate-skew fixtures prove the checker catches drift)
# ----------------------------------------------------------------------

TP_GOOD = """\
import struct
HS_VERSION = 1
NONCE_BYTES = 16
MAC_BYTES = 32
CHALLENGE = struct.Struct("<2sBB16s")
AUTH_PREFIX = struct.Struct("<2sBBQQ16s")
AUTH = struct.Struct("<2sBBQQ16s32s")
VERDICT = struct.Struct("<2sBBQQ")
"""


class TestTcpHandshakeSkew:
    def _tree(self, tmp_path, text):
        (tmp_path / "ggrs_tpu/fleet").mkdir(parents=True)
        (tmp_path / "ggrs_tpu/fleet/transport.py").write_text(text)
        return tmp_path

    def _check(self, root):
        from ggrs_tpu.analysis.layout import _check_tcp_handshake
        return _check_tcp_handshake(root)

    def test_clean_fixture_passes(self, tmp_path):
        assert self._check(self._tree(tmp_path, TP_GOOD)) == []

    def test_auth_epoch_field_drift_fires(self, tmp_path):
        # shrinking the epoch from u64 to u32 must fire: a truncated
        # epoch is exactly the fence-defeating skew
        bad = TP_GOOD.replace('"<2sBBQQ16s"', '"<2sBBIQ16s"')
        findings = self._check(self._tree(tmp_path, bad))
        assert any(
            f.rule == "layout/tcp-handshake" and "auth prefix" in f.detail
            for f in findings
        )

    def test_resume_cursor_drift_fires(self, tmp_path):
        # dropping the resume cursor from the verdict fires
        bad = TP_GOOD.replace('"<2sBBQQ")', '"<2sBBQ")')
        findings = self._check(self._tree(tmp_path, bad))
        assert any(
            f.rule == "layout/tcp-handshake" and "verdict" in f.detail
            for f in findings
        )

    def test_mac_tail_drift_fires(self, tmp_path):
        # a 16-byte mac tail breaks auth = prefix + MAC_BYTES
        bad = TP_GOOD.replace('"<2sBBQQ16s32s"', '"<2sBBQQ16s16s"')
        findings = self._check(self._tree(tmp_path, bad))
        assert any("auth record" in f.detail or "mac" in f.detail
                   for f in findings)

    def test_mac_bytes_constant_drift_fires(self, tmp_path):
        bad = TP_GOOD.replace("MAC_BYTES = 32", "MAC_BYTES = 20")
        findings = self._check(self._tree(tmp_path, bad))
        assert any("MAC_BYTES" in f.detail for f in findings)

    def test_unversioned_handshake_fires(self, tmp_path):
        bad = TP_GOOD.replace("HS_VERSION = 1\n", "")
        findings = self._check(self._tree(tmp_path, bad))
        assert any("HS_VERSION" in f.detail for f in findings)

    def test_contract_matches_live_structs(self):
        from ggrs_tpu.analysis.layout import (
            TCP_AUTH_FMT,
            TCP_AUTH_PREFIX_FMT,
            TCP_CHALLENGE_FMT,
            TCP_VERDICT_FMT,
        )
        from ggrs_tpu.fleet import transport

        assert transport.CHALLENGE.format == TCP_CHALLENGE_FMT
        assert transport.AUTH_PREFIX.format == TCP_AUTH_PREFIX_FMT
        assert transport.AUTH.format == TCP_AUTH_FMT
        assert transport.VERDICT.format == TCP_VERDICT_FMT
        assert transport.AUTH.size == transport.AUTH_PREFIX.size + 32


# ----------------------------------------------------------------------
# §26 ingress wire contract (same PR 11 rule: wire structs land with
# their checker — deliberate-skew fixtures prove the checker catches
# drift in the forwarded-datagram header and route-update frame)
# ----------------------------------------------------------------------

ING_GOOD = """\
import struct
FWD_VERSION = 1
ROUTE_WIRE_VERSION = 2
ROUTE_OP_PUT = 1
ROUTE_OP_DEL = 2
FWD_HEADER = struct.Struct("<2sBBHH4s")
ROUTE_UPDATE = struct.Struct("<2sBBQQHH4s16s")
"""


class TestIngressWireSkew:
    def _tree(self, tmp_path, text):
        (tmp_path / "ggrs_tpu/fleet").mkdir(parents=True)
        (tmp_path / "ggrs_tpu/fleet/ingress.py").write_text(text)
        return tmp_path

    def _check(self, root):
        from ggrs_tpu.analysis.layout import _check_ingress_wire
        return _check_ingress_wire(root)

    def test_clean_fixture_passes(self, tmp_path):
        assert self._check(self._tree(tmp_path, ING_GOOD)) == []

    def test_fence_word_drift_fires(self, tmp_path):
        # shrinking the route epoch from u64 to u32 must fire: a
        # truncated epoch is exactly the fence-defeating skew that
        # would let a stale supervisor's route write wrap around
        bad = ING_GOOD.replace('"<2sBBQQHH4s16s"', '"<2sBBIQHH4s16s"')
        findings = self._check(self._tree(tmp_path, bad))
        assert any(
            f.rule == "layout/ingress-wire" and "route-update" in f.detail
            for f in findings
        )

    def test_fwd_header_drift_fires(self, tmp_path):
        # dropping the source-port word breaks peer-return routing
        bad = ING_GOOD.replace('"<2sBBHH4s"', '"<2sBBH4s"')
        findings = self._check(self._tree(tmp_path, bad))
        assert any(
            f.rule == "layout/ingress-wire"
            and "forwarded-datagram" in f.detail
            for f in findings
        )

    def test_unversioned_route_frame_fires(self, tmp_path):
        bad = ING_GOOD.replace("ROUTE_WIRE_VERSION = 2\n", "")
        findings = self._check(self._tree(tmp_path, bad))
        assert any("ROUTE_WIRE_VERSION" in f.detail for f in findings)

    def test_route_op_drift_fires(self, tmp_path):
        # the decode path refuses everything outside PUT=1/DEL=2; an
        # opcode renumber silently turns deletes into puts on old nodes
        bad = ING_GOOD.replace("ROUTE_OP_DEL = 2", "ROUTE_OP_DEL = 3")
        findings = self._check(self._tree(tmp_path, bad))
        assert any("route ops" in f.detail for f in findings)

    def test_contract_matches_live_structs(self):
        from ggrs_tpu.analysis.layout import (
            ING_FENCE_BYTES,
            ING_FWD_FMT,
            ING_ROUTE_FMT,
            TRACE_CTX_BYTES,
        )
        from ggrs_tpu.fleet import ingress

        assert ingress.FWD_HEADER.format == ING_FWD_FMT
        assert ingress.ROUTE_UPDATE.format == ING_ROUTE_FMT
        assert (ingress.ROUTE_UPDATE.size
                == ingress.FWD_HEADER.size + ING_FENCE_BYTES
                + TRACE_CTX_BYTES)


# ----------------------------------------------------------------------
# §28 trace-context contract: timeline.py owns the 16-byte context,
# transport.py mirrors it as a literal, the route frame tails it —
# deliberate-skew fixtures prove the checker catches each drifting alone
# ----------------------------------------------------------------------

TC_TL_GOOD = """\
import struct
TRACE_CTX_FMT = "<QII"
TRACE_CTX = struct.Struct("<QII")
TRACE_CTX_BYTES = 16
"""

TC_TP_GOOD = """\
import struct
TRACE_CTX_BYTES = 16
_TRACE = struct.Struct("<QII")
"""


class TestTraceContextSkew:
    def _tree(self, tmp_path, tl_text=TC_TL_GOOD, tp_text=TC_TP_GOOD):
        (tmp_path / "ggrs_tpu/obs").mkdir(parents=True)
        (tmp_path / "ggrs_tpu/fleet").mkdir(parents=True)
        (tmp_path / "ggrs_tpu/obs/timeline.py").write_text(tl_text)
        (tmp_path / "ggrs_tpu/fleet/transport.py").write_text(tp_text)
        return tmp_path

    def _check(self, root):
        from ggrs_tpu.analysis.layout import _check_trace_context
        return _check_trace_context(root)

    def test_clean_fixture_passes(self, tmp_path):
        assert self._check(self._tree(tmp_path)) == []

    def test_timeline_fmt_drift_fires(self, tmp_path):
        # shrinking the span word breaks every already-written 16-byte
        # tail on the wire — the owner drifting is the worst skew
        bad = TC_TL_GOOD.replace('"<QII"', '"<QIH"')
        findings = self._check(self._tree(tmp_path, tl_text=bad))
        assert any(
            f.rule == "layout/trace-context"
            and f.path == "ggrs_tpu/obs/timeline.py"
            for f in findings
        )

    def test_transport_mirror_drift_fires(self, tmp_path):
        # transport.py mirrors the struct as a literal (it cannot import
        # the obs plane into the runner hot path); a drifted mirror
        # corrupts every RPC-carried context
        bad = TP_GOOD.replace('"<QII"', '"<QQ"')
        findings = self._check(self._tree(tmp_path, tp_text=bad))
        assert any(
            f.rule == "layout/trace-context"
            and f.path == "ggrs_tpu/fleet/transport.py"
            for f in findings
        )

    def test_byte_count_drift_fires(self, tmp_path):
        bad = TC_TL_GOOD.replace("TRACE_CTX_BYTES = 16", "TRACE_CTX_BYTES = 12")
        findings = self._check(self._tree(tmp_path, tl_text=bad))
        assert any("TRACE_CTX_BYTES" in f.detail for f in findings)

    def test_contract_matches_live_structs(self):
        from ggrs_tpu.analysis import layout
        from ggrs_tpu.fleet import transport
        from ggrs_tpu.obs import timeline

        assert timeline.TRACE_CTX.format == layout.TRACE_CTX_FMT
        assert timeline.TRACE_CTX_BYTES == layout.TRACE_CTX_BYTES == 16
        assert timeline.TRACE_CTX.size == timeline.TRACE_CTX_BYTES
        assert transport.TRACE_CTX_BYTES == layout.TRACE_CTX_BYTES
        assert layout.ING_ROUTE_FMT.endswith(
            f"{layout.TRACE_CTX_BYTES}s")


VARREC_GOOD = """\
import struct
VARREC_HEADER_FMT = "<H"
VARREC_HEADER_BYTES = 2
VARREC_MAX_CAPACITY = 0xFFFF
def envelope_pack(payload, capacity):
    return struct.pack("<H", len(payload)) + payload
"""

RTSCMD_GOOD = """\
from ..core.varrec import VARREC_HEADER_BYTES
CMD_BYTES = 4
"""


class TestVarrecSkew:
    """§27 envelope contract: the [u16 len][payload][pad] framing is
    what makes variable-size inputs native-eligible, so a drifted
    header silently desyncs every varrec match — the fixtures prove the
    checker fires before that can land."""

    def _tree(self, tmp_path, varrec_text, rtscmd_text=RTSCMD_GOOD):
        (tmp_path / "ggrs_tpu/core").mkdir(parents=True)
        (tmp_path / "ggrs_tpu/games").mkdir(parents=True)
        (tmp_path / "ggrs_tpu/core/varrec.py").write_text(varrec_text)
        (tmp_path / "ggrs_tpu/games/rtscmd.py").write_text(rtscmd_text)
        return tmp_path

    def _check(self, root):
        from ggrs_tpu.analysis.layout import _check_varrec
        return _check_varrec(root)

    def test_clean_fixture_passes(self, tmp_path):
        assert self._check(self._tree(tmp_path, VARREC_GOOD)) == []

    def test_header_fmt_drift_fires(self, tmp_path):
        # widening the length prefix to u32 shifts every payload byte:
        # old and new nodes would decode different records from the
        # same envelope
        bad = VARREC_GOOD.replace('"<H"', '"<I"')
        findings = self._check(self._tree(tmp_path, bad))
        assert any(
            f.rule == "layout/varrec-header"
            and "length prefix" in f.detail
            for f in findings
        )

    def test_header_width_drift_fires(self, tmp_path):
        # the byte-literal width is what the device-side decode and the
        # native jump offsets consume; it must track the fmt
        bad = VARREC_GOOD.replace("VARREC_HEADER_BYTES = 2",
                                  "VARREC_HEADER_BYTES = 4")
        findings = self._check(self._tree(tmp_path, bad))
        assert any(
            f.rule == "layout/varrec-header"
            and "VARREC_HEADER_BYTES" in f.detail
            for f in findings
        )

    def test_capacity_bound_drift_fires(self, tmp_path):
        # a capacity past the u16 length prefix's reach could frame
        # payloads whose length does not round-trip
        bad = VARREC_GOOD.replace("0xFFFF", "0x1FFFF")
        findings = self._check(self._tree(tmp_path, bad))
        assert any(f.rule == "layout/varrec-capacity" for f in findings)

    def test_consumer_literal_offset_fires(self, tmp_path):
        # the in-kernel decode must read the header width through the
        # shared constant — a hand-inlined 2 drifts silently when the
        # envelope changes
        findings = self._check(self._tree(
            tmp_path, VARREC_GOOD,
            rtscmd_text="CMD_BYTES = 4\nHEADER = 2\n",
        ))
        assert any(f.rule == "layout/varrec-consumer" for f in findings)

    def test_contract_matches_live_module(self):
        from ggrs_tpu.analysis.layout import (
            VARREC_HEADER_BYTES,
            VARREC_HEADER_FMT,
            VARREC_MAX_CAPACITY,
        )
        from ggrs_tpu.core import varrec

        assert varrec.VARREC_HEADER_FMT == VARREC_HEADER_FMT
        assert varrec.VARREC_HEADER_BYTES == VARREC_HEADER_BYTES
        assert varrec.VARREC_MAX_CAPACITY == VARREC_MAX_CAPACITY
        assert varrec.envelope_size(60) == 60 + VARREC_HEADER_BYTES
