"""Tests for the observability subsystem (ggrs_tpu.obs + the pool's
one-crossing stat harvest; DESIGN.md §12).

Four layers of pins:

1. the registry/recorder/exporter primitives (no native code needed);
2. metrics stay correct across the supervision state machine
   (quarantine -> eviction -> dead), driven through the real chaos
   harness;
3. the scrape budget: a scrape per tick adds zero tick crossings and
   exactly one ``ggrs_bank_stats`` crossing;
4. metrics are observational only: survivors' wire bytes are
   bit-identical with metrics enabled vs disabled; and
   ``HostSessionPool.network_stats`` returns the exact per-session
   ``NetworkStats`` for native, quarantined, and evicted slots.
"""

from __future__ import annotations

import random

import pytest

from ggrs_tpu.chaos import drive_chaos
from ggrs_tpu.core import Local, Remote
from ggrs_tpu.core.config import Config
from ggrs_tpu.core.errors import BadPlayerHandle, StatsUnavailable
from ggrs_tpu.net import InMemoryNetwork, _native
from ggrs_tpu.obs import (
    FlightRecorder,
    Registry,
    json_snapshot,
    prometheus_text,
)
from ggrs_tpu.parallel.host_bank import (
    EVICT_MAX_ATTEMPTS,
    HostSessionPool,
    SLOT_DEAD,
    SLOT_EVICTED,
    SLOT_NATIVE,
    SLOT_QUARANTINED,
)
from ggrs_tpu.sessions import SessionBuilder

needs_native = pytest.mark.skipif(
    _native.bank_lib() is None, reason="native session bank unavailable"
)


# ---------------------------------------------------------------------------
# 1. registry / recorder / exporter primitives
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_counter_gauge_histogram(self):
        reg = Registry()
        c = reg.counter("c_total", "a counter")
        c.inc()
        c.inc(2)
        assert c.value == 3
        g = reg.gauge("g", "a gauge")
        g.set(5)
        g.dec()
        assert g.value == 4
        h = reg.histogram("h", "a histogram", buckets=(1, 4))
        for v in (0.5, 2, 3, 100):
            h.observe(v)
        assert h.count == 4 and h.sum == 105.5
        assert h.cumulative() == [(1, 1), (4, 3), (float("inf"), 4)]

    def test_labels(self):
        reg = Registry()
        fam = reg.counter("req_total", "requests", labels=("kind",))
        fam.labels(kind="save").inc(3)
        fam.labels(kind="load").inc()
        assert reg.value("req_total", kind="save") == 3
        assert reg.value("req_total", kind="load") == 1
        assert reg.value("req_total", kind="advance") is None
        with pytest.raises(ValueError):
            fam.labels(wrong="x")

    def test_idempotent_and_conflicting_registration(self):
        reg = Registry()
        a = reg.counter("x_total", "x")
        b = reg.counter("x_total", "x")
        assert a is b
        with pytest.raises(ValueError):
            reg.gauge("x_total", "now a gauge?")
        with pytest.raises(ValueError):
            reg.counter("x_total", "same kind, new labels", labels=("k",))

    def test_disabled_registry_is_null(self):
        reg = Registry(enabled=False)
        c = reg.counter("c_total")
        c.inc(100)
        g = reg.gauge("g", labels=("k",))
        g.labels(k="v").set(1)
        h = reg.histogram("h")
        h.observe(5)
        assert reg.families() == []
        assert prometheus_text(reg) == "\n"
        assert json_snapshot(reg) == {}


class TestExporters:
    def _reg(self):
        reg = Registry()
        reg.counter("ticks_total", "pool ticks").inc(7)
        fam = reg.gauge("state", "slots per state", labels=("state",))
        fam.labels(state="native").set(3)
        h = reg.histogram("depth", "rollback depth", buckets=(1, 2))
        h.observe(1)
        h.observe(5)
        return reg

    def test_prometheus_text(self):
        text = prometheus_text(self._reg())
        assert "# TYPE ticks_total counter" in text
        assert "ticks_total 7" in text
        assert 'state{state="native"} 3' in text
        assert 'depth_bucket{le="1"} 1' in text
        assert 'depth_bucket{le="+Inf"} 2' in text
        assert "depth_sum 6" in text
        assert "depth_count 2" in text

    def test_json_snapshot(self):
        snap = json_snapshot(self._reg())
        assert snap["ticks_total"]["samples"][0]["value"] == 7
        assert snap["state"]["samples"][0]["labels"] == {"state": "native"}
        hist = snap["depth"]["samples"][0]
        assert hist["count"] == 2 and hist["sum"] == 6

    def test_http_server_round_trip(self):
        import urllib.request

        from ggrs_tpu.obs import start_http_server

        try:
            server = start_http_server(self._reg(), port=0)
        except OSError:
            pytest.skip("cannot bind a loopback socket in this sandbox")
        try:
            url = f"http://127.0.0.1:{server.port}/metrics"
            body = urllib.request.urlopen(url, timeout=5).read().decode()
            assert "ticks_total 7" in body
            url_json = f"http://127.0.0.1:{server.port}/metrics.json"
            body = urllib.request.urlopen(url_json, timeout=5).read().decode()
            assert '"ticks_total"' in body
        finally:
            server.close()


class TestFlightRecorder:
    def test_ring_bounds_and_dump(self):
        rec = FlightRecorder(capacity=8)
        for i in range(20):
            rec.record(i, "state", f"event {i}")
        assert len(rec) == 8
        assert rec.recorded == 20
        events = rec.events()
        assert events[0][0] == 12 and events[-1][0] == 19
        dump = rec.dump(4)
        assert "event 19" in dump and "event 15" not in dump

    def test_wire_tuples_format_lazily(self):
        rec = FlightRecorder()
        rec.record(3, "wire", (1, 53, 0xAB12CD34))
        assert "ep=1 len=53B crc=ab12cd34" in rec.dump()


# ---------------------------------------------------------------------------
# 2. metrics across the supervision state machine
# ---------------------------------------------------------------------------


@needs_native
class TestSupervisionMetrics:
    def test_quarantine_then_eviction_counters(self):
        """A native fault: faults / transitions / evictions / latency all
        land, the slot-state gauge tracks occupancy, and the flight
        recorder holds the fault and both transitions."""
        reg = Registry()
        run = drive_chaos(
            120, n_matches=2, seed=3, metrics=reg,
            inject=lambda i, ctx: (
                ctx["pool"].inject_slot_error(ctx["target"])
                if i == 60 else None
            ),
        )
        pool, target = run["pool"], run["target"]
        assert run["states"][target] == SLOT_EVICTED
        code = str(_native.BANK_ERR_INJECTED)
        assert reg.value("ggrs_pool_slot_faults_total", code=code) == 1
        assert reg.value(
            "ggrs_pool_slot_transitions_total",
            src=SLOT_NATIVE, dst=SLOT_QUARANTINED,
        ) == 1
        assert reg.value(
            "ggrs_pool_slot_transitions_total",
            src=SLOT_QUARANTINED, dst=SLOT_EVICTED,
        ) == 1
        assert reg.value("ggrs_pool_evictions_total") == 1
        assert reg.value("ggrs_pool_eviction_failures_total") == 0
        # one eviction-latency observation (count; the immediate-evict
        # path lands in the first bucket)
        assert reg.value("ggrs_pool_eviction_latency_ticks") == 1
        # gauge occupancy: every slot accounted for, exactly one evicted
        assert reg.value("ggrs_pool_slot_state", state=SLOT_EVICTED) == 1
        assert reg.value("ggrs_pool_slot_state", state=SLOT_NATIVE) == (
            len(run["states"]) - 1
        )
        assert reg.value("ggrs_pool_slot_state", state=SLOT_QUARANTINED) == 0
        # crossing accounting: ticks + one harvest for the eviction, plus
        # drive_chaos's final scrape
        assert reg.value("ggrs_pool_crossings_total", kind="tick") == 120
        assert reg.value("ggrs_pool_crossings_total", kind="harvest") == 1
        assert reg.value("ggrs_pool_crossings_total", kind="stats") == 1
        # flight recorder: fault + both transitions are in the ring
        kinds = [k for _, k, _ in pool.flight_recorder(target).events()]
        assert "fault" in kinds and "state" in kinds and "evict" in kinds
        dump = pool.flight_dump(target, last=32)
        assert "native -> quarantined" in dump
        assert "quarantined -> evicted" in dump

    def test_eviction_failure_to_dead_counters(self):
        """Every eviction attempt fails (sabotaged harvest): the slot
        walks quarantined -> dead after EVICT_MAX_ATTEMPTS, with failures
        counted and the gauge ending on dead=1."""
        reg = Registry()

        def sabotage(i, ctx):
            if i == 20:
                pool = ctx["pool"]
                pool._evict = _raise  # every attempt now fails
                pool.inject_slot_error(ctx["target"])

        def _raise(index):
            raise RuntimeError("sabotaged eviction")

        run = drive_chaos(150, n_matches=2, seed=5, metrics=reg,
                          inject=sabotage)
        target = run["target"]
        assert run["states"][target] == SLOT_DEAD
        assert reg.value(
            "ggrs_pool_eviction_failures_total"
        ) == EVICT_MAX_ATTEMPTS
        assert reg.value("ggrs_pool_evictions_total") == 0
        assert reg.value(
            "ggrs_pool_slot_transitions_total",
            src=SLOT_QUARANTINED, dst=SLOT_DEAD,
        ) == 1
        assert reg.value("ggrs_pool_slot_state", state=SLOT_DEAD) == 1
        assert reg.value("ggrs_pool_slot_state", state=SLOT_QUARANTINED) == 0
        # dead slot that never evicted: nothing live to measure
        with pytest.raises(StatsUnavailable):
            run["pool"].network_stats(target, 0)


# ---------------------------------------------------------------------------
# 3. + 4. scrape budget, bit-identical wire, NetworkStats parity
# ---------------------------------------------------------------------------


@needs_native
class TestObservationalOnly:
    def test_wire_bit_identical_metrics_on_vs_off(self):
        """The whole obs layer — registry, per-slot flight recorders, wire
        digests, the final scrape — must not move a single wire byte:
        identical fault-injected runs with metrics on vs off."""
        inject = lambda i, ctx: (  # noqa: E731
            ctx["pool"].inject_slot_error(ctx["target"])
            if i == 60 else None
        )
        on = drive_chaos(160, n_matches=2, seed=9, metrics=Registry(),
                         inject=inject)
        off = drive_chaos(160, n_matches=2, seed=9,
                          metrics=Registry(enabled=False), inject=inject)
        assert on["states"] == off["states"]
        assert on["frames"] == off["frames"]
        for idx in range(len(on["states"])):
            assert on["wire"][idx] == off["wire"][idx], (
                f"slot {idx}: wire bytes diverged with metrics enabled"
            )
            assert on["reqs"][idx] == off["reqs"][idx]
            assert on["events"][idx] == off["events"][idx]
        # metrics-off pool really ran dark
        assert off["pool"].flight_recorder(0) is None
        assert off["registry"].families() == []

    def test_scrape_returns_native_counters(self):
        run = drive_chaos(100, n_matches=2, seed=2, metrics=Registry())
        for s in run["scrape"]:
            if s["state"] != SLOT_NATIVE:
                continue
            assert s["ticks"] == 100
            for es in s["endpoints"]:
                assert es["core"]["emits"] > 0
                assert es["packets_sent"] > 0
                assert es["bytes_sent"] > 0


@needs_native
class TestNetworkStatsParity:
    def _builders(self, net, clock):
        out = []
        names = ("X", "Y")
        for me in (0, 1):
            b = (
                SessionBuilder(Config.for_uint(16))
                .with_clock(lambda: clock[0])
                .with_rng(random.Random(3 + me))
                .add_player(Local(), me)
                .add_player(Remote(names[1 - me]), 1 - me)
            )
            out.append((b, net.socket(names[me])))
        return out

    @staticmethod
    def _fulfill(reqs):
        for r in reqs:
            if type(r).__name__ == "SaveGameState":
                r.cell.save(r.frame, None, None)

    def test_native_slot_matches_python_session(self):
        """The API-parity pin: the pooled ``network_stats`` equals the
        per-session one field-for-field under identical seeded traffic
        (ping, send queue, kbps, frame advantage both ways)."""
        clock = [0]
        faults = dict(seed=7, loss=0.05, duplicate=0.03, reorder=0.03,
                      latency_ticks=1)
        net_bank = InMemoryNetwork(**faults)
        net_py = InMemoryNetwork(**faults)
        pool = HostSessionPool(metrics=Registry())
        for b, s in self._builders(net_bank, clock):
            pool.add_session(b, s)
        pys = [
            b.start_p2p_session(s) for b, s in self._builders(net_py, clock)
        ]
        assert pool.native_active
        for i in range(200):
            clock[0] += 16
            for idx in range(2):
                pys[idx].add_local_input(idx, (i + idx) % 16)
                pool.add_local_input(idx, idx, (i + idx) % 16)
            for s in pys:
                self._fulfill(s.advance_frame())
            for reqs in pool.advance_all():
                self._fulfill(reqs)
            net_bank.tick()
            net_py.tick()
        for idx in range(2):
            assert (
                pool.network_stats(idx, 1 - idx)
                == pys[idx].network_stats(1 - idx)
            )
        with pytest.raises(BadPlayerHandle):
            pool.network_stats(0, 0)  # local handle
        with pytest.raises(BadPlayerHandle):
            pool.network_stats(0, 7)  # unknown handle

    def test_stats_unavailable_before_time_elapses(self):
        clock = [0]
        net = InMemoryNetwork()
        pool = HostSessionPool(metrics=Registry())
        for b, s in self._builders(net, clock):
            pool.add_session(b, s)
        assert pool.native_active
        with pytest.raises(StatsUnavailable):
            pool.network_stats(0, 1)

    def test_evicted_slot_serves_stats(self):
        """After an injected fault and eviction, ``network_stats`` keeps
        working, now backed by the live fallback session."""
        run = drive_chaos(
            200, n_matches=2, seed=4, metrics=Registry(),
            inject=lambda i, ctx: (
                ctx["pool"].inject_slot_error(ctx["target"])
                if i == 60 else None
            ),
        )
        pool, target = run["pool"], run["target"]
        assert run["states"][target] == SLOT_EVICTED
        stats = pool.network_stats(target, 1)
        assert stats.ping >= 0 and stats.send_queue_len >= 0
        # quarantined-or-native survivors answer from the bank harvest
        survivor = 0 if target != 0 else 1
        stats = pool.network_stats(survivor, 1 - (survivor % 2))
        assert stats.kbps_sent >= 0

    def test_fallback_pool_delegates(self, monkeypatch):
        monkeypatch.setattr(_native, "bank_lib", lambda: None)
        clock = [0]
        net = InMemoryNetwork()
        pool = HostSessionPool(metrics=Registry())
        for b, s in self._builders(net, clock):
            pool.add_session(b, s)
        assert not pool.native_active
        for i in range(80):
            clock[0] += 16
            for idx in range(2):
                pool.add_local_input(idx, idx, i % 16)
            for reqs in pool.advance_all():
                self._fulfill(reqs)
            net.tick()
        stats = pool.network_stats(0, 1)
        assert stats.ping >= 0
        # fallback scrape: no native crossing, but the same record shape
        scrape = pool.scrape()
        assert pool.stat_crossings == 0
        assert scrape[0]["endpoints"][0]["send_queue_len"] >= 0
        assert scrape[0]["ticks"] == 80
