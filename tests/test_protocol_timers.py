"""The endpoint protocol's timer machinery, driven by an injected clock.

Every timer in ``net/protocol.py``'s poll path (retry, quality/RTT,
keep-alive, the two-phase NetworkInterrupted→Disconnected failure detector,
NetworkResumed, and the shutdown linger) must observably fire — parity with
/root/reference/src/network/protocol.rs:329-376,349-366.
"""

import random

import pytest

from ggrs_tpu.core import DesyncDetection, StatsUnavailable
from ggrs_tpu.core.frame_info import PlayerInput
from ggrs_tpu.net.messages import (
    ConnectionStatus,
    InputAck,
    InputMessage,
    KeepAlive,
    Message,
    QualityReply,
    QualityReport,
)
from ggrs_tpu.net.protocol import (
    EvDisconnected,
    EvInput,
    EvNetworkInterrupted,
    EvNetworkResumed,
    PeerProtocol,
)

from stubs import stub_config


class FakeClock:
    def __init__(self) -> None:
        self.now = 0

    def __call__(self) -> int:
        return self.now


class CaptureSocket:
    """Records every sent message so tests can inspect and forward them."""

    def __init__(self) -> None:
        self.sent = []

    def send_to(self, msg: Message, addr) -> None:
        self.sent.append((addr, msg))

    def receive_all_messages(self):
        return []

    def drain(self):
        out = [m for _, m in self.sent]
        self.sent.clear()
        return out


def make_proto(clock, seed=5, **overrides):
    kwargs = dict(
        config=stub_config(),
        handles=[1],
        peer_addr="B",
        num_players=2,
        local_players=1,
        max_prediction=8,
        disconnect_timeout_ms=2000,
        disconnect_notify_start_ms=500,
        fps=60,
        desync_detection=DesyncDetection.off(),
        clock=clock,
        rng=random.Random(seed),
    )
    kwargs.update(overrides)
    return PeerProtocol(**kwargs)


def connect_status(n=2):
    return [ConnectionStatus() for _ in range(n)]


def bodies(msgs):
    return [type(m.body).__name__ for m in msgs]


class TestRetryTimer:
    def test_pending_output_resent_after_silence(self):
        clock = FakeClock()
        proto = make_proto(clock)
        sock = CaptureSocket()
        status = connect_status()

        proto.send_input({1: PlayerInput(0, 7)}, status)
        proto.send_all_messages(sock)
        first = [m for m in sock.drain() if isinstance(m.body, InputMessage)]
        assert len(first) == 1

        # under 200ms of input silence: no retry
        clock.now = 150
        proto.poll(status)
        proto.send_all_messages(sock)
        assert not any(isinstance(m.body, InputMessage) for m in sock.drain())

        # past 200ms: the unacked input goes out again, byte-identical window
        clock.now = 250
        proto.poll(status)
        proto.send_all_messages(sock)
        retried = [m for m in sock.drain() if isinstance(m.body, InputMessage)]
        assert len(retried) == 1
        assert retried[0].body.start_frame == first[0].body.start_frame == 0
        assert retried[0].body.bytes == first[0].body.bytes

    def test_ack_stops_retries(self):
        clock = FakeClock()
        proto = make_proto(clock)
        sock = CaptureSocket()
        status = connect_status()
        proto.send_input({1: PlayerInput(0, 7)}, status)
        proto.send_all_messages(sock)
        sock.drain()

        proto.handle_message(Message(magic=1, body=InputAck(ack_frame=0)))
        clock.now = 250
        proto.poll(status)
        proto.send_all_messages(sock)
        assert not any(isinstance(m.body, InputMessage) for m in sock.drain())


class TestQualityAndKeepAlive:
    def test_quality_roundtrip_measures_ping_into_stats(self):
        clock = FakeClock()
        a = make_proto(clock, seed=1)
        b = make_proto(clock, seed=2)
        sock_a, sock_b = CaptureSocket(), CaptureSocket()
        status = connect_status()

        clock.now = 201
        a.poll(status)
        a.send_all_messages(sock_a)
        reports = [m for m in sock_a.drain() if isinstance(m.body, QualityReport)]
        assert len(reports) == 1
        assert reports[0].body.ping == 201

        for m in reports:
            b.handle_message(m)
        b.send_all_messages(sock_b)
        replies = [m for m in sock_b.drain() if isinstance(m.body, QualityReply)]
        assert len(replies) == 1 and replies[0].body.pong == 201

        clock.now = 231  # 30ms later the reply arrives
        for m in replies:
            a.handle_message(m)

        clock.now = 1300  # stats need >= 1 elapsed second
        stats = a.network_stats()
        assert stats.ping == 30
        assert stats.kbps_sent >= 0

    def test_quality_report_carries_frame_advantage(self):
        clock = FakeClock()
        a = make_proto(clock, seed=1)
        b = make_proto(clock, seed=2)
        sock = CaptureSocket()
        a.local_frame_advantage = 4
        clock.now = 201
        a.poll(connect_status())
        a.send_all_messages(sock)
        report = next(m for m in sock.drain() if isinstance(m.body, QualityReport))
        assert report.body.frame_advantage == 4
        b.handle_message(report)
        assert b.remote_frame_advantage == 4

    def test_stats_unavailable_before_time_elapses(self):
        clock = FakeClock()
        proto = make_proto(clock)
        with pytest.raises(StatsUnavailable):
            proto.network_stats()

    def test_keepalive_fires_when_nothing_else_sent(self):
        clock = FakeClock()
        proto = make_proto(clock)
        sock = CaptureSocket()
        # the quality timer shares the 200ms cadence and normally refreshes
        # last-send first; push it into the future to expose the keep-alive
        # branch on its own
        proto._last_quality_report_time = 10_000
        clock.now = 250
        proto.poll(connect_status())
        proto.send_all_messages(sock)
        assert any(isinstance(m.body, KeepAlive) for m in sock.drain())

    def test_keepalive_suppressed_while_traffic_flows(self):
        clock = FakeClock()
        proto = make_proto(clock)
        sock = CaptureSocket()
        proto._last_quality_report_time = 10_000
        clock.now = 150  # under the 200ms threshold
        proto.poll(connect_status())
        proto.send_all_messages(sock)
        assert not any(isinstance(m.body, KeepAlive) for m in sock.drain())


class TestFailureDetector:
    def test_interrupted_then_disconnected_then_resumed(self):
        clock = FakeClock()
        proto = make_proto(clock)
        status = connect_status()

        # silence past disconnect_notify_start: one interrupt, no duplicates
        clock.now = 501
        events = proto.poll(status)
        assert [e for e in events if isinstance(e, EvNetworkInterrupted)] != []
        interrupted = next(
            e for e in events if isinstance(e, EvNetworkInterrupted)
        )
        assert interrupted.disconnect_timeout == 2000 - 500
        clock.now = 900
        assert not any(
            isinstance(e, EvNetworkInterrupted) for e in proto.poll(status)
        )

        # a packet arrives: NetworkResumed, detector re-arms
        proto.handle_message(Message(magic=1, body=KeepAlive()))
        events = proto.poll(status)
        assert any(isinstance(e, EvNetworkResumed) for e in events)

        # fresh silence: interrupt again, then the hard disconnect
        clock.now = 900 + 501
        assert any(
            isinstance(e, EvNetworkInterrupted) for e in proto.poll(status)
        )
        clock.now = 900 + 2001
        events = proto.poll(status)
        assert any(isinstance(e, EvDisconnected) for e in events)
        # disconnect fires exactly once
        clock.now = 900 + 3000
        assert not any(isinstance(e, EvDisconnected) for e in proto.poll(status))

    def test_shutdown_linger_then_silent(self):
        clock = FakeClock()
        proto = make_proto(clock)
        sock = CaptureSocket()

        clock.now = 100
        proto.disconnect()
        assert not proto.is_running()

        # during the linger the endpoint still flushes queued messages
        proto.send_checksum_report(5, 123)
        proto.send_all_messages(sock)
        assert len(sock.drain()) == 1

        # after the 5s linger: shutdown — queued messages are dropped and
        # inbound traffic is ignored
        clock.now = 100 + 5001
        proto.poll(connect_status())
        proto.send_checksum_report(6, 456)
        proto.send_all_messages(sock)
        assert sock.drain() == []
        proto.handle_message(Message(magic=1, body=KeepAlive()))
        assert proto.poll(connect_status()) == []


class TestSessionFailurePath:
    """The detector surfaced through a live P2P session: interrupted /
    disconnected events, rollback to the disconnect frame, and resume."""

    def _pair(self, clock):
        from ggrs_tpu.net import InMemoryNetwork
        from ggrs_tpu.sessions import SessionBuilder
        from ggrs_tpu.core import Local, Remote

        net = InMemoryNetwork()
        sessions = []
        for me, other, local_handle in (("A", "B", 0), ("B", "A", 1)):
            sessions.append(
                SessionBuilder(stub_config())
                .with_clock(clock)
                .with_rng(random.Random(11 + local_handle))
                .add_player(Local(), local_handle)
                .add_player(Remote(other), 1 - local_handle)
                .start_p2p_session(net.socket(me))
            )
        return net, sessions

    def test_peer_silence_interrupts_then_disconnects_with_rollback(self):
        from ggrs_tpu.core import (
            Disconnected,
            InputStatus,
            LoadGameState,
            NetworkInterrupted,
        )
        from stubs import GameStub

        clock = FakeClock()
        net, (sess_a, sess_b) = self._pair(clock)
        stub_a, stub_b = GameStub(), GameStub()

        for i in range(10):
            clock.now += 16
            sess_a.poll_remote_clients()
            sess_b.poll_remote_clients()
            sess_a.add_local_input(0, i)
            stub_a.handle_requests(sess_a.advance_frame())
            sess_b.add_local_input(1, i)
            stub_b.handle_requests(sess_b.advance_frame())
        sess_a.events()

        # B goes silent; A keeps ticking on predictions
        interrupted = disconnected = False
        saw_load_after_disconnect = False
        for i in range(10, 400):
            clock.now += 16
            sess_a.poll_remote_clients()
            events = sess_a.events()
            if any(isinstance(e, NetworkInterrupted) for e in events):
                assert not disconnected, "interrupt must precede disconnect"
                interrupted = True
            if any(isinstance(e, Disconnected) for e in events):
                assert interrupted
                disconnected = True
            sess_a.add_local_input(0, i)
            reqs = sess_a.advance_frame()
            if disconnected and any(
                isinstance(r, LoadGameState) for r in reqs
            ):
                saw_load_after_disconnect = True
            stub_a.handle_requests(reqs)
            if disconnected and saw_load_after_disconnect:
                break

        assert interrupted and disconnected
        # the disconnect erased predictions via a rollback...
        assert saw_load_after_disconnect
        assert sess_a.local_connect_status[1].disconnected

        # ...and the session keeps advancing with disconnect dummies
        frame_before = sess_a.current_frame
        for i in range(3):
            clock.now += 16
            sess_a.poll_remote_clients()
            sess_a.add_local_input(0, 0)
            reqs = sess_a.advance_frame()
            stub_a.handle_requests(reqs)
            for r in reqs:
                if hasattr(r, "inputs"):
                    assert r.inputs[1][1] == InputStatus.DISCONNECTED
        assert sess_a.current_frame > frame_before

    def test_resume_before_timeout_emits_network_resumed(self):
        from ggrs_tpu.core import Disconnected, NetworkInterrupted, NetworkResumed
        from stubs import GameStub

        clock = FakeClock()
        net, (sess_a, sess_b) = self._pair(clock)
        stub_a, stub_b = GameStub(), GameStub()

        for i in range(5):
            clock.now += 16
            sess_a.poll_remote_clients()
            sess_b.poll_remote_clients()
            sess_a.add_local_input(0, i)
            stub_a.handle_requests(sess_a.advance_frame())
            sess_b.add_local_input(1, i)
            stub_b.handle_requests(sess_b.advance_frame())
        sess_a.events()

        # drain B's in-flight packets first (receive time is poll time)
        clock.now += 16
        sess_a.poll_remote_clients()
        sess_a.events()

        # B pauses just past the notify threshold, then comes back
        clock.now += 600
        sess_a.poll_remote_clients()
        assert any(
            isinstance(e, NetworkInterrupted) for e in sess_a.events()
        )

        clock.now += 16
        sess_b.poll_remote_clients()
        sess_b.add_local_input(1, 5)
        stub_b.handle_requests(sess_b.advance_frame())  # sends packets to A
        sess_a.poll_remote_clients()
        events = sess_a.events()
        assert any(isinstance(e, NetworkResumed) for e in events)
        assert not any(isinstance(e, Disconnected) for e in events)
        assert not sess_a.local_connect_status[1].disconnected
