"""SyncTest session tests — including the request-sequence contract, the
single most valuable parity test (reference:
/root/reference/tests/test_synctest_session.rs)."""

import pytest

from ggrs_tpu.core import (
    AdvanceFrame,
    LoadGameState,
    MismatchedChecksum,
    SaveGameState,
)
from ggrs_tpu.sessions import SessionBuilder

from stubs import GameStub, RandomChecksumGameStub, stub_config


def test_create_session():
    SessionBuilder(stub_config()).start_synctest_session()


def test_advance_frame_no_rollbacks():
    stub = GameStub()
    sess = SessionBuilder(stub_config()).with_check_distance(0).start_synctest_session()

    for i in range(200):
        sess.add_local_input(0, i)
        sess.add_local_input(1, i)
        requests = sess.advance_frame()
        assert len(requests) == 1  # only advance
        stub.handle_requests(requests)
        assert stub.gs.frame == i + 1


def test_advance_frame_with_rollbacks():
    """The exact request pattern: [Save, Advance] during warm-up; at
    check_distance=2: [Load, Advance, Save, Advance, Save, Advance]
    (reference: test_synctest_session.rs:46-58)."""
    check_distance = 2
    stub = GameStub()
    sess = (
        SessionBuilder(stub_config())
        .with_check_distance(check_distance)
        .start_synctest_session()
    )

    for i in range(200):
        sess.add_local_input(0, i)
        sess.add_local_input(1, i)
        requests = sess.advance_frame()
        if i <= check_distance:
            assert len(requests) == 2
            assert isinstance(requests[0], SaveGameState)
            assert isinstance(requests[1], AdvanceFrame)
        else:
            assert len(requests) == 6
            assert isinstance(requests[0], LoadGameState)
            assert isinstance(requests[1], AdvanceFrame)
            assert isinstance(requests[2], SaveGameState)
            assert isinstance(requests[3], AdvanceFrame)
            assert isinstance(requests[4], SaveGameState)
            assert isinstance(requests[5], AdvanceFrame)

        stub.handle_requests(requests)
        assert stub.gs.frame == i + 1


def test_advance_frames_with_delayed_input():
    stub = GameStub()
    sess = (
        SessionBuilder(stub_config())
        .with_check_distance(7)
        .with_input_delay(2)
        .start_synctest_session()
    )

    for i in range(200):
        sess.add_local_input(0, i)
        sess.add_local_input(1, i)
        requests = sess.advance_frame()
        stub.handle_requests(requests)
        assert stub.gs.frame == i + 1


def test_advance_frames_with_random_checksums():
    stub = RandomChecksumGameStub()
    sess = SessionBuilder(stub_config()).with_input_delay(2).start_synctest_session()

    with pytest.raises(MismatchedChecksum):
        for i in range(200):
            sess.add_local_input(0, i)
            sess.add_local_input(1, i)
            requests = sess.advance_frame()
            stub.handle_requests(requests)


def test_check_distance_must_be_less_than_max_prediction():
    from ggrs_tpu.core import InvalidRequest

    with pytest.raises(InvalidRequest):
        SessionBuilder(stub_config()).with_check_distance(8).start_synctest_session()


def test_requests_per_tick_matches_2d_plus_2():
    """Steady-state request count is 2*check_distance + 2 (derived invariant,
    reference: sync_test_session.rs:85-150)."""
    for d in (1, 3, 5):
        stub = GameStub()
        sess = (
            SessionBuilder(stub_config())
            .with_check_distance(d)
            .with_max_prediction_window(8)
            .start_synctest_session()
        )
        for i in range(50):
            sess.add_local_input(0, i)
            sess.add_local_input(1, i)
            requests = sess.advance_frame()
            if i > d:
                assert len(requests) == 2 * d + 2
            stub.handle_requests(requests)
