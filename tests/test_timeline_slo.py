"""Match-lifecycle timeline & SLO plane tests (DESIGN.md §28).

The acceptance pins, mirrored by ``scripts/chaos.py --fault net`` and
``--fault lockstep`` artifacts:

* the stable event schema + 16-byte trace context round-trip, and
  ``fold_trace_aliases`` lands an ingress-observed (match-id-blind)
  ROUTE_FLIP inside the real match's causal chain;
* a merged timeline re-emits as a Perfetto trace that passes
  ``validate_chrome_trace`` — ONE export shows the cross-host life;
* burn rates are computed on the FLEET clock with the multi-window
  guard (both windows must burn hot before a page), and a critical
  verdict flips ``healthz()["ok"]`` — the 503 path;
* the plane is strictly piggyback — ZERO extra ctypes crossings per
  tick (the pool crossing budget is unchanged with the timeline sink
  installed and firing) and ZERO extra RPC round trips (the op set of
  the RPC latency histogram is exactly the serving path's);
* ``scripts/bench_report.py`` normalizes BENCH rounds and gates on p99
  regressions vs the best prior comparable round.
"""

from __future__ import annotations

import importlib.util
import json
import sys
import urllib.request
from pathlib import Path

import pytest

from ggrs_tpu.chaos import drive_chaos, drive_fleet_chaos, drive_proc_fleet
from ggrs_tpu.fleet import FleetTuning
from ggrs_tpu.net import _native
from ggrs_tpu.obs import (
    Registry,
    Tracer,
    json_snapshot,
    start_http_server,
    validate_chrome_trace,
)
from ggrs_tpu.obs.slo import (
    LEVEL_CRITICAL,
    LEVEL_OK,
    LEVEL_WARN,
    TIER_LOCKSTEP,
    TIER_ROLLBACK,
    BurnRateEngine,
    ShardSloMeter,
    SloPolicy,
)
from ggrs_tpu.obs.timeline import (
    EV_ADMIT,
    EV_DEMOTE_LOCKSTEP,
    EV_MIGRATE_BEGIN,
    EV_MIGRATE_COMMIT,
    EV_ROUTE_FLIP,
    TIMELINE_VERSION,
    TRACE_CTX_BYTES,
    ZERO_TRACE_CTX,
    MatchTimeline,
    TimelineStore,
    first_occurrence_order,
    fold_trace_aliases,
    format_timeline,
    match_trace_id,
    merge_timelines,
    pack_trace_ctx,
    timeline_event,
    timeline_ring_events,
    unpack_trace_ctx,
)

needs_native = pytest.mark.skipif(
    _native.bank_lib() is None, reason="native session bank unavailable"
)

REPO = Path(__file__).resolve().parents[1]


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, REPO / "scripts" / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ----------------------------------------------------------------------
# the trace context + event schema
# ----------------------------------------------------------------------


class TestTraceContext:
    def test_pack_unpack_round_trip(self):
        ctx = pack_trace_ctx("m3", 7, 42)
        assert len(ctx) == TRACE_CTX_BYTES == 16
        trace, epoch, span = unpack_trace_ctx(ctx)
        assert trace == match_trace_id("m3")
        assert (epoch, span) == (7, 42)

    def test_zero_ctx_is_no_context(self):
        assert unpack_trace_ctx(ZERO_TRACE_CTX) == (0, 0, 0)

    def test_trace_id_is_stable_and_distinct(self):
        # every process derives the SAME id with no coordination — the
        # property that joins a match's events across hosts
        assert match_trace_id("m0") == match_trace_id("m0")
        ids = {match_trace_id(f"m{i}") for i in range(256)}
        assert len(ids) == 256

    def test_event_schema_is_pinned(self):
        ev = timeline_event(EV_ADMIT, "m1", origin="h0", tick=3,
                            epoch=2, span=5, detail={"shard": "a0"},
                            ts_ns=1000)
        assert ev == {
            "v": TIMELINE_VERSION, "ev": EV_ADMIT, "mid": "m1",
            "ts_ns": 1000, "origin": "h0", "tick": 3,
            "trace": match_trace_id("m1"), "epoch": 2, "span": 5,
            "detail": {"shard": "a0"},
        }
        json.dumps(ev)  # JSON-safe by construction


# ----------------------------------------------------------------------
# bounded logs + the store
# ----------------------------------------------------------------------


class TestMatchTimeline:
    def test_time_sorted_with_arrival_tiebreak(self):
        tl = MatchTimeline("m0")
        tl.add(timeline_event("B", "m0", ts_ns=200))
        tl.add(timeline_event("A", "m0", ts_ns=100))
        tl.add(timeline_event("C", "m0", ts_ns=200))
        assert [e["ev"] for e in tl.events()] == ["A", "B", "C"]

    def test_capacity_evicts_oldest_by_time(self):
        # a late-ferried EARLY event must not push out the live tail
        tl = MatchTimeline("m0", capacity=4)
        for ts in (400, 300, 500, 600):
            tl.add(timeline_event("X", "m0", ts_ns=ts))
        tl.add(timeline_event("LATE_EARLY", "m0", ts_ns=100))
        assert tl.dropped == 1
        kept = [e["ts_ns"] for e in tl.events()]
        assert kept == [300, 400, 500, 600]  # the oldest (100) went


class TestTimelineStore:
    def test_record_and_read_back(self):
        store = TimelineStore(clock=lambda: 123)
        ev = store.record(EV_ADMIT, "m0", origin="fleet", tick=1)
        assert ev["ts_ns"] == 123
        assert store.timeline("m0") == [ev]
        assert store.match_ids() == ["m0"]
        assert store.counts() == {"m0": 1}

    def test_ingest_applies_clock_offset(self):
        # remote ts_ns shifts into the local clock domain (§18 offsets)
        store = TimelineStore()
        store.ingest([timeline_event("X", "m0", ts_ns=5000)],
                     offset_ns=2000)
        assert store.timeline("m0")[0]["ts_ns"] == 3000

    def test_malformed_remote_events_counted_not_raised(self):
        store = TimelineStore()
        n = store.ingest([
            {"no_mid": 1},
            {"mid": "m0", "ts_ns": "not-a-number"},
            timeline_event("OK", "m0", ts_ns=1),
        ])
        assert n == 1
        assert store.malformed == 2
        assert len(store.timeline("m0")) == 1

    def test_lru_match_eviction(self):
        store = TimelineStore(capacity_matches=2)
        store.record("A", "m0", ts_ns=1)
        store.record("A", "m1", ts_ns=2)
        store.record("A", "m0", ts_ns=3)  # touch m0: m1 becomes LRU
        store.record("A", "m2", ts_ns=4)
        assert sorted(store.match_ids()) == ["m0", "m2"]


# ----------------------------------------------------------------------
# merging, trace-alias folding, re-emission
# ----------------------------------------------------------------------


class TestMergeAndFold:
    def test_merge_stores_and_dicts_time_sorted(self):
        a = TimelineStore()
        a.record("B", "m0", ts_ns=200, origin="h0")
        b = {"m0": [timeline_event("A", "m0", ts_ns=100, origin="h1")]}
        merged = merge_timelines(a, b, None)
        assert [e["ev"] for e in merged["m0"]] == ["A", "B"]

    def test_fold_lands_ingress_flip_in_the_match_chain(self):
        # the ingress never learns match ids — it keys ROUTE_FLIP on the
        # wire trace context; the fold joins on match_trace_id
        trace = match_trace_id("m5")
        merged = {
            "m5": [timeline_event(EV_MIGRATE_BEGIN, "m5", ts_ns=100)],
            f"trace:{trace:016x}": [
                timeline_event(EV_ROUTE_FLIP, f"trace:{trace:016x}",
                               ts_ns=150, origin="ingress")],
        }
        folded = fold_trace_aliases(merged)
        assert list(folded) == ["m5"]
        assert [e["ev"] for e in folded["m5"]] == [
            EV_MIGRATE_BEGIN, EV_ROUTE_FLIP]

    def test_unresolvable_alias_stays_keyed_as_is(self):
        merged = {"trace:00000000deadbeef": [
            timeline_event(EV_ROUTE_FLIP, "trace:00000000deadbeef",
                           ts_ns=1)]}
        assert list(fold_trace_aliases(merged)) == [
            "trace:00000000deadbeef"]

    def test_first_occurrence_order(self):
        evs = [timeline_event(e, "m0", ts_ns=i * 10) for i, e in
               enumerate([EV_ADMIT, EV_MIGRATE_BEGIN, EV_ROUTE_FLIP,
                          EV_MIGRATE_COMMIT, EV_ROUTE_FLIP])]
        assert first_occurrence_order(
            evs, EV_ADMIT, EV_MIGRATE_BEGIN, EV_ROUTE_FLIP,
            EV_MIGRATE_COMMIT)
        assert not first_occurrence_order(
            evs, EV_MIGRATE_COMMIT, EV_ADMIT)      # out of order
        assert not first_occurrence_order(
            evs, EV_ADMIT, EV_DEMOTE_LOCKSTEP)     # absent event

    def test_ring_reemission_validates_as_chrome_trace(self):
        # the §28 acceptance: a merged timeline exports as ONE
        # schema-valid Perfetto trace
        evs = [timeline_event(e, "m0", ts_ns=1000 + i * 500,
                              origin="h0", detail={"k": i})
               for i, e in enumerate([EV_ADMIT, EV_MIGRATE_BEGIN,
                                      EV_ROUTE_FLIP, EV_MIGRATE_COMMIT])]
        tracer = Tracer(capacity=64)
        tracer.import_spans(timeline_ring_events(evs))
        trace = tracer.chrome_trace()
        assert validate_chrome_trace(trace) == []
        names = [e["name"] for e in trace["traceEvents"]
                 if e["name"].startswith("timeline.")]
        assert names == [f"timeline.{e}" for e in
                         (EV_ADMIT, EV_MIGRATE_BEGIN, EV_ROUTE_FLIP,
                          EV_MIGRATE_COMMIT)]

    def test_format_timeline_relative_offsets(self):
        evs = [timeline_event(EV_ADMIT, "m0", ts_ns=1_000_000,
                              origin="h0", tick=0),
               timeline_event(EV_ROUTE_FLIP, "m0", ts_ns=3_500_000)]
        lines = format_timeline(evs)
        assert len(lines) == 2
        assert "ADMIT" in lines[0] and "origin=h0" in lines[0]
        assert "+     2.500ms" in lines[1]
        assert format_timeline([]) == []


# ----------------------------------------------------------------------
# the SLO plane
# ----------------------------------------------------------------------


class TestShardSloMeter:
    def test_compliance_counters_by_tier(self):
        reg = Registry()
        meter = ShardSloMeter(reg)
        assert meter.observe_rollback(10.0)       # inside 16.7 ms
        assert not meter.observe_rollback(20.0)   # breach
        assert meter.observe_lockstep(2)
        assert not meter.observe_lockstep(9)      # beyond 4 frames
        assert reg.value("ggrs_slo_ticks_total", tier=TIER_ROLLBACK) == 2
        assert reg.value("ggrs_slo_breaches_total",
                         tier=TIER_ROLLBACK) == 1
        assert reg.value("ggrs_slo_ticks_total", tier=TIER_LOCKSTEP) == 2
        assert reg.value("ggrs_slo_breaches_total",
                         tier=TIER_LOCKSTEP) == 1


def _policy(**kw):
    kw.setdefault("target", 0.9)                 # budget = 0.1
    kw.setdefault("windows", (("w4", 4), ("w16", 16)))
    kw.setdefault("warn_burn", 2.0)
    kw.setdefault("critical_burn", 5.0)
    return SloPolicy(**kw)


class TestBurnRateEngine:
    def test_burn_is_error_rate_over_budget(self):
        reg = Registry()
        policy = _policy()
        meter = ShardSloMeter(reg, policy=policy)
        burn = BurnRateEngine(policy=policy)
        for tick in range(8):
            meter.observe_rollback(20.0)         # every tick breaches
            v = burn.update(tick, reg)
        # error rate 1.0 over budget 0.1 = burn 10 in both windows
        tiers = v["tiers"][TIER_ROLLBACK]
        assert tiers["burn"]["w4"] == pytest.approx(10.0)
        assert tiers["burn"]["w16"] == pytest.approx(10.0)
        assert tiers["level"] == LEVEL_CRITICAL
        assert v["level"] == LEVEL_CRITICAL and v["ok"] is False

    def test_multi_window_guard_no_page_on_a_blip(self):
        # a hot SHORT window with a cold LONG window must not page:
        # the verdict floor is min() across windows
        reg = Registry()
        policy = _policy(windows=(("w4", 4), ("w40", 40)))
        meter = ShardSloMeter(reg, policy=policy)
        burn = BurnRateEngine(policy=policy)
        for tick in range(40):
            meter.observe_rollback(20.0 if tick >= 37 else 1.0)
            v = burn.update(tick, reg)
        tiers = v["tiers"][TIER_ROLLBACK]
        assert tiers["burn"]["w4"] > policy.critical_burn
        assert tiers["burn"]["w40"] < policy.warn_burn
        assert tiers["level"] == LEVEL_OK and v["ok"] is True

    def test_escalation_counted_once_per_transition(self):
        reg = Registry()
        mreg = Registry()
        policy = _policy()
        meter = ShardSloMeter(reg, policy=policy)
        burn = BurnRateEngine(metrics=mreg, policy=policy)
        for tick in range(6):
            meter.observe_rollback(20.0)
            burn.update(tick, reg)
        assert mreg.value("ggrs_slo_escalations_total") == 1
        assert mreg.value("ggrs_slo_level") == 2
        assert mreg.value("ggrs_slo_burn_rate", tier=TIER_ROLLBACK,
                          window="w4") == pytest.approx(10.0)

    def test_warn_between_thresholds(self):
        reg = Registry()
        policy = _policy(warn_burn=2.0, critical_burn=50.0)
        meter = ShardSloMeter(reg, policy=policy)
        burn = BurnRateEngine(policy=policy)
        for tick in range(8):
            meter.observe_rollback(20.0 if tick % 2 else 1.0)
            v = burn.update(tick, reg)
        assert v["level"] == LEVEL_WARN and v["ok"] is True

    def test_policy_dict_round_trips_the_knobs(self):
        p = SloPolicy()
        d = p.as_dict()
        assert d["rollback_budget_ms"] == pytest.approx(16.7)
        assert d["lockstep_lag_frames"] == 4
        assert d["windows"] == {"5m": 18000, "1h": 216000}
        assert p.error_budget == pytest.approx(0.001)


# ----------------------------------------------------------------------
# the piggyback pins: zero extra crossings, zero extra RPC round trips
# ----------------------------------------------------------------------


@needs_native
class TestPiggybackBudgets:
    def test_timeline_sink_adds_zero_crossings(self):
        """The crossing budget with the timeline sink installed AND
        firing (a mid-run lockstep demotion) is exactly one tick
        crossing per advance_all — identical to a sink-less run."""
        TICKS = 32
        store = TimelineStore()

        def inject(i, ctx):
            if i == 0:
                ctx["pool"].timeline_sink = (
                    lambda etype, slot, detail:
                    store.record(etype, f"slot{slot}", origin="pool",
                                 detail=detail))
            if i == 16:
                ctx["pool"].demote_to_lockstep(ctx["target"])

        chaos = drive_chaos(TICKS, n_matches=2, seed=3, inject=inject)
        pool = chaos["pool"]
        control = drive_chaos(TICKS, n_matches=2, seed=3)
        # the demotion reached the store through the sink...
        demoted = [e for evs in store.to_dict().values() for e in evs
                   if e["ev"] == EV_DEMOTE_LOCKSTEP]
        assert len(demoted) == 1
        # ...and the tick crossing budget did not move
        assert pool.crossings == TICKS == control["pool"].crossings
        # the stats/harvest cadence is unchanged too (scrape-driven,
        # never timeline-driven)
        assert pool.stat_crossings <= control["pool"].stat_crossings + 1

    def test_fleet_run_rpc_ops_and_supervisor_timelines(self):
        """Proc fleet: the RPC op histogram carries ONLY the serving
        path's ops (timelines ride existing replies — §28's zero extra
        round trips), while the supervisor's store has every match's
        ADMIT."""
        tuning = FleetTuning(
            heartbeat_interval_s=0.05, heartbeat_deadline_s=1.0,
            rpc_timeout_s=5.0, spawn_timeout_s=120.0,
            drain_deadline_s=0.5, restart_max=0,
        )
        ctx = drive_proc_fleet(16, matches_per_shard=1, seed=13,
                               backend="proc", tuning=tuning,
                               desync_interval=0)
        sup = ctx["sup"]
        try:
            ops = {
                labels["op"]
                for fam in sup.metrics.families()
                if fam.name == "ggrs_fleet_proc_rpc_seconds"
                for labels, _child in fam.samples()
            }
            timelines = sup.fleet_obs.timelines.to_dict()
        finally:
            sup.close()
        assert ops <= {"hello", "tick", "admit", "adopt", "evict",
                       "drop", "identity", "healthz", "retire",
                       "shutdown"}
        for mid in ctx["match_ids"]:
            assert first_occurrence_order(timelines.get(mid, []),
                                          EV_ADMIT), mid


# ----------------------------------------------------------------------
# supervisor healthz + the /timeline endpoint
# ----------------------------------------------------------------------


@needs_native
class TestHealthAndEndpoint:
    def test_fleet_healthz_carries_the_slo_verdict(self):
        ctx = drive_fleet_chaos(16, matches_per_shard=1, seed=5)
        sup = ctx["sup"]
        try:
            hz = sup.healthz()
        finally:
            sup.close()
        slo = hz["slo"]
        assert slo["level"] in (LEVEL_OK, LEVEL_WARN, LEVEL_CRITICAL)
        assert set(slo["tiers"]) <= {TIER_ROLLBACK, TIER_LOCKSTEP}
        assert slo["policy"]["target"] == pytest.approx(0.999)

    def test_critical_burn_flips_healthz_to_503(self):
        # the SLO plane pages through the door the fleet already
        # watches: ok=False on the health dict -> MetricsServer 503
        reg = Registry()
        policy = _policy()
        meter = ShardSloMeter(reg, policy=policy)
        burn = BurnRateEngine(policy=policy)
        for tick in range(8):
            meter.observe_rollback(100.0)
            burn.update(tick, reg)
        health = {"ok": burn.verdict()["ok"], "slo": burn.verdict()}
        server = start_http_server(reg, port=0, health=lambda: health)
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/healthz")
            assert exc.value.code == 503
            body = json.loads(exc.value.read().decode())
            assert body["slo"]["level"] == LEVEL_CRITICAL
        finally:
            server.close()

    def test_timeline_endpoint_serves_merged_store(self):
        store = TimelineStore()
        store.record(EV_ADMIT, "m0", origin="fleet", tick=0, ts_ns=10)
        store.record(EV_ROUTE_FLIP, "m0", origin="ingress", ts_ns=20)
        server = start_http_server(Registry(), port=0,
                                   timelines=store.to_dict)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/timeline"
            ) as r:
                doc = json.loads(r.read().decode())
        finally:
            server.close()
        assert [e["ev"] for e in doc["m0"]] == [EV_ADMIT, EV_ROUTE_FLIP]


# ----------------------------------------------------------------------
# DesyncReport embeds its timeline
# ----------------------------------------------------------------------


class TestDesyncReportTimeline:
    def test_report_carries_the_life_up_to_the_desync(self):
        from ggrs_tpu.obs.forensics import DesyncReport

        tl = [timeline_event(EV_ADMIT, "m0", ts_ns=1, origin="h0")]
        rep = DesyncReport(
            "checksum", 12, 10, local_checksum=1, remote_checksum=2,
            timeline=tl,
        )
        d = rep.to_dict()
        assert d["timeline"] == tl
        json.dumps(d)


# ----------------------------------------------------------------------
# scripts: bench_report gate, match_timeline extraction, fleet_top render
# ----------------------------------------------------------------------


def _bench_round(tmp_path, n, metrics, rc=0):
    lines = [json.dumps({"metric": m, "value": v, "unit": "ms",
                         "vs_baseline": 1.0}) for m, v in metrics]
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps({
        "n": n, "cmd": ["x"], "rc": rc, "tail": "\n".join(lines),
    }))


class TestBenchReport:
    def setup_method(self):
        self.mod = _load_script("bench_report")

    def test_trajectory_and_gate_ok(self, tmp_path):
        _bench_round(tmp_path, 1, [("tick_ms_p99", 10.0),
                                   ("throughput", 100.0)])
        _bench_round(tmp_path, 2, [("tick_ms_p99", 10.5)])
        rounds = self.mod.load_rounds(str(tmp_path))
        traj = self.mod.trajectory(rounds)
        assert [r["value"] for r in traj["tick_ms_p99"]] == [10.0, 10.5]
        assert traj["throughput"][0]["p99"] is False
        assert self.mod.gate(traj) == []          # +5% < 15% tolerance
        text = self.mod.render(rounds, traj, [], 0.15)
        assert "GATE: ok" in text and "r01" in text

    def test_gate_fires_beyond_threshold_vs_best_prior(self, tmp_path):
        # best PRIOR round (r1), not the immediately previous one (r2)
        _bench_round(tmp_path, 1, [("tick_ms_p99", 10.0)])
        _bench_round(tmp_path, 2, [("tick_ms_p99", 14.0)])
        _bench_round(tmp_path, 3, [("tick_ms_p99", 12.0)])
        traj = self.mod.trajectory(self.mod.load_rounds(str(tmp_path)))
        regs = self.mod.gate(traj, threshold=0.15)
        assert len(regs) == 1
        assert regs[0]["best_prior_round"] == 1
        assert regs[0]["ratio"] == pytest.approx(1.2)

    def test_non_p99_metrics_never_gate(self, tmp_path):
        _bench_round(tmp_path, 1, [("throughput", 100.0)])
        _bench_round(tmp_path, 2, [("throughput", 10.0)])
        traj = self.mod.trajectory(self.mod.load_rounds(str(tmp_path)))
        assert self.mod.gate(traj) == []

    def test_timeout_round_is_dataless_not_a_regression(self, tmp_path):
        _bench_round(tmp_path, 1, [("tick_ms_p99", 10.0)])
        _bench_round(tmp_path, 2, [], rc=124)
        rounds = self.mod.load_rounds(str(tmp_path))
        assert self.mod.gate(self.mod.trajectory(rounds)) == []
        assert "timeout" in self.mod.render(
            rounds, self.mod.trajectory(rounds), [], 0.15)

    def test_repo_bench_files_all_parse(self):
        # the real rounds: every file loads, r05 (rc=124) is data-less
        rounds = self.mod.load_rounds(str(REPO))
        assert len(rounds) >= 11
        by_n = {r["round"]: r for r in rounds}
        assert by_n[5]["records"] == [] and by_n[5]["rc"] == 124
        assert sum(len(r["records"]) for r in rounds) > 40


class TestMatchTimelineScript:
    def setup_method(self):
        self.mod = _load_script("match_timeline")

    def test_extracts_and_folds_chaos_artifact(self, tmp_path):
        trace = match_trace_id("m2")
        artifact = {
            "scenario": "x",
            "timeline": {
                "m2": [timeline_event(EV_MIGRATE_BEGIN, "m2", ts_ns=10)],
                f"trace:{trace:016x}": [
                    timeline_event(EV_ROUTE_FLIP, f"trace:{trace:016x}",
                                   ts_ns=20)],
            },
        }
        p = tmp_path / "art.json"
        p.write_text(json.dumps(artifact))
        merged = self.mod.load_sources([], [str(p)])
        assert [e["ev"] for e in merged["m2"]] == [
            EV_MIGRATE_BEGIN, EV_ROUTE_FLIP]

    def test_desync_report_list_form(self, tmp_path):
        doc = {"match_id": "m9",
               "timeline": [timeline_event(EV_ADMIT, "m9", ts_ns=1)]}
        p = tmp_path / "rep.json"
        p.write_text(json.dumps(doc))
        merged = self.mod.load_sources([], [str(p)])
        assert [e["ev"] for e in merged["m9"]] == [EV_ADMIT]

    def test_perfetto_export_validates(self, tmp_path):
        evs = [timeline_event(EV_ADMIT, "m0", ts_ns=100),
               timeline_event(EV_ROUTE_FLIP, "m0", ts_ns=200)]
        out = tmp_path / "m0.trace.json"
        assert self.mod.export_perfetto(evs, str(out)) == []
        trace = json.loads(out.read_text())
        assert len(trace["traceEvents"]) >= 2


@needs_native
class TestFleetTopSlo:
    def test_render_shows_slo_column_and_timeline_footer(self):
        fleet_top = _load_script("fleet_top")
        ctx = drive_fleet_chaos(16, matches_per_shard=1, seed=5)
        sup = ctx["sup"]
        try:
            healthz = sup.healthz()
            metrics = json_snapshot(sup.merged_registry())
            timelines = sup.fleet_obs.timelines.to_dict()
        finally:
            sup.close()
        frame = fleet_top.render(healthz, metrics, timelines=timelines)
        assert "SLO" in frame                     # the new column
        assert "slo:" in frame                    # the verdict header
        assert "timeline" in frame                # the footer block
