"""InputQueue unit tests, parity oracles from the reference
(/root/reference/src/input_queue.rs:272-354)."""

from ggrs_tpu.core import Config, InputQueue, InputStatus, NULL_FRAME, PlayerInput


def make_queue() -> InputQueue:
    return InputQueue(Config.for_uint(8))


def test_add_input_wrong_frame():
    q = make_queue()
    assert q.add_input(PlayerInput(0, 0)) == 0
    assert q.add_input(PlayerInput(3, 0)) == NULL_FRAME  # non-sequential: dropped


def test_add_input_twice():
    q = make_queue()
    assert q.add_input(PlayerInput(0, 0)) == 0
    assert q.add_input(PlayerInput(0, 0)) == NULL_FRAME  # duplicate: dropped


def test_add_input_sequentially():
    q = make_queue()
    for i in range(10):
        q.add_input(PlayerInput(i, 0))
        assert q.last_added_frame == i
        assert q.length == i + 1


def test_input_sequentially():
    q = make_queue()
    for i in range(10):
        q.add_input(PlayerInput(i, i))
        assert q.last_added_frame == i
        assert q.length == i + 1
        value, status = q.input(i)
        assert value == i
        assert status == InputStatus.CONFIRMED


def test_delayed_inputs():
    q = make_queue()
    delay = 2
    q.set_frame_delay(delay)
    for i in range(10):
        q.add_input(PlayerInput(i, i))
        assert q.last_added_frame == i + delay
        assert q.length == i + delay + 1
        value, _status = q.input(i)
        assert value == max(0, i - delay)


def test_prediction_repeat_last():
    q = make_queue()
    q.add_input(PlayerInput(0, 7))
    # frame 1 not confirmed yet: predict repeat-last
    value, status = q.input(1)
    assert value == 7
    assert status == InputStatus.PREDICTED
    # confirm with a matching input: no misprediction recorded
    q.add_input(PlayerInput(1, 7))
    assert q.first_incorrect_frame == NULL_FRAME


def test_prediction_mismatch_recorded():
    q = make_queue()
    q.add_input(PlayerInput(0, 7))
    value, status = q.input(1)
    assert (value, status) == (7, InputStatus.PREDICTED)
    q.add_input(PlayerInput(1, 9))  # reality disagrees
    assert q.first_incorrect_frame == 1
    q.reset_prediction()
    assert q.first_incorrect_frame == NULL_FRAME


def test_prediction_without_previous_input_uses_default():
    q = make_queue()
    value, status = q.input(0)
    assert value == 0  # default input
    assert status == InputStatus.PREDICTED


def test_discard_confirmed_frames():
    q = make_queue()
    for i in range(10):
        q.add_input(PlayerInput(i, i))
    q.input(9)
    q.discard_confirmed_frames(5)
    assert q.length == 5  # frames 5..9 remain
    assert q.confirmed_input(5).input == 5


def test_confirmed_input_missing_raises():
    q = make_queue()
    q.add_input(PlayerInput(0, 0))
    try:
        q.confirmed_input(5)
    except AssertionError:
        pass
    else:
        raise AssertionError("expected missing confirmed input to raise")
