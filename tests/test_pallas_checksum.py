"""Pallas digest kernel vs the XLA lane formulas — bitwise equality.

The kernel must reproduce ``checksum._leaf_digest``'s four lanes exactly
(same mod-2^32 arithmetic, same 1-based index weights) or every desync gate
built on checksum equality would silently compare different functions.  On
CPU the kernel runs in interpreter mode; the TPU path compiles the same
program."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ggrs_tpu.ops.checksum import _as_u32_words, _leaf_digest, checksum_device
from ggrs_tpu.ops import pallas_checksum as pc


def _xla_lanes(words: jnp.ndarray) -> np.ndarray:
    """The four lanes exactly as checksum._leaf_digest computes them."""
    n = words.shape[0]
    idx = jnp.arange(1, n + 1, dtype=jnp.uint32)
    lane0 = jnp.sum(words, dtype=jnp.uint32)
    lane1 = jnp.sum(words * idx, dtype=jnp.uint32)
    lane2 = jnp.sum(words * (idx * np.uint32(40503) + jnp.uint32(1)), dtype=jnp.uint32)
    rot = (words << jnp.uint32(13)) | (words >> jnp.uint32(19))
    lane3 = jnp.sum(rot ^ (idx * np.uint32(2246822519)), dtype=jnp.uint32)
    return np.asarray(jnp.stack([lane0, lane1, lane2, lane3]))


@pytest.mark.skipif(not pc.HAVE_PALLAS, reason="pallas unavailable")
@pytest.mark.parametrize(
    "n",
    [
        1,
        100,
        pc._LANES,                      # exactly one row
        pc._BLOCK_ROWS * pc._LANES,     # exactly one block
        pc._BLOCK_ROWS * pc._LANES + 1,  # one word into the second block
        3 * pc._BLOCK_ROWS * pc._LANES - 7,  # multi-block, ragged tail
    ],
)
def test_kernel_matches_xla_lanes(n):
    words = jnp.asarray(
        np.random.default_rng(n).integers(0, 2**32, size=(n,), dtype=np.uint32)
    )
    got = np.asarray(pc.leaf_digest_pallas(words, interpret=True))
    np.testing.assert_array_equal(got, _xla_lanes(words))


@pytest.mark.skipif(not pc.HAVE_PALLAS, reason="pallas unavailable")
def test_ragged_tail_folds_at_correct_offset():
    # all-zero words: lanes 0-2 are 0, lane3 is sum(idx*B) — index-dependent,
    # so a tail folded at the wrong global offset (or dropped) would differ
    for n in (
        pc._BLOCK_ROWS * pc._LANES // 2 + 3,   # below one block: pure XLA path
        2 * pc._BLOCK_ROWS * pc._LANES + 17,   # kernel head + ragged tail
    ):
        words = jnp.zeros((n,), jnp.uint32)
        got = np.asarray(pc.leaf_digest_pallas(words, interpret=True))
        np.testing.assert_array_equal(got, _xla_lanes(words))


@pytest.mark.skipif(not pc.HAVE_PALLAS, reason="pallas unavailable")
def test_leaf_digest_routing_unchanged_when_disabled(monkeypatch):
    # default-off policy: _leaf_digest must not engage pallas unless enabled
    # AND on TPU AND the leaf is large enough
    big = jnp.asarray(
        np.random.default_rng(0).integers(
            0, 2**31, size=(pc.MIN_PALLAS_WORDS + 5,), dtype=np.int32
        )
    )
    base = np.asarray(_leaf_digest(big))
    pc.use_pallas_checksums(True)
    try:
        # on CPU the backend gate keeps the XLA path — results identical
        np.testing.assert_array_equal(np.asarray(_leaf_digest(big)), base)
    finally:
        pc.use_pallas_checksums(None)


@pytest.mark.skipif(not pc.HAVE_PALLAS, reason="pallas unavailable")
def test_words_view_of_mixed_dtypes_roundtrip():
    # the pallas path consumes the same _as_u32_words stream as XLA; a mixed
    # pytree digest must be invariant to which implementation digests leaves
    state = {
        "a": jnp.asarray(np.arange(300, dtype=np.float32)),
        "b": jnp.asarray(np.arange(77, dtype=np.uint8)),
    }
    lanes = checksum_device(state)
    assert lanes.shape == (4,)
    for leaf in jax.tree_util.tree_leaves(state):
        w = _as_u32_words(jnp.asarray(leaf))
        got = np.asarray(pc.leaf_digest_pallas(w, interpret=True))
        np.testing.assert_array_equal(got, _xla_lanes(w))
