"""The placement plane (DESIGN.md §26): multi-supervisor scheduling,
cross-host live migration, and host-death journal failover — all behind
virtual endpoints that NEVER change.

Two layers:

- scheduling/refusal unit tests over stub hosts (the ring walk, the
  capacity/p99 refusal matrix, the kill_host epoch mint);
- the cross-host chaos world (``drive_placement_fleet``: two real
  ``ShardSupervisor`` hosts sharing a journal directory behind one
  ``IngressNode``, external peers + viewers on real loopback UDP) run
  three ways — fault-free control, live migration, host kill — and
  compared.  The acceptance contracts mirror tests/test_fleet.py's §16
  migration contract, one level up:

  * survivors (matches on the untouched host) bit-identical to control:
    peer-received wire bytes, request streams, events, placement;
  * the migrated/failed-over match: peer sees a retransmission hiccup,
    never a reset — no Disconnected, no DesyncDetected (per-frame
    checksum exchange is ON), bounded frame lag — and its journal
    streams are bit-identical across incarnations and to the control
    prefix;
  * the virtual endpoint is the SAME (public address, vport) before and
    after — nothing public ever re-addresses.
"""

from __future__ import annotations

import importlib.util
import os
from pathlib import Path

import pytest

from ggrs_tpu.broadcast.journal import read_journal
from ggrs_tpu.chaos import (
    drive_placement_fleet,
    fleet_recovery_violations,
    fleet_survivor_violations,
)
from ggrs_tpu.core.errors import InvalidRequest
from ggrs_tpu.fleet import FleetTuning, PlacementService
from ggrs_tpu.fleet.ingress import IngressNode
from ggrs_tpu.fleet.supervisor import FleetError
from ggrs_tpu.obs import Registry, json_snapshot

TICKS = 48
SEED = 11
MIGRATE_AT = 10
KILL_AT = 14
H0_MATCHES = ["m0", "m1"]
H1_MATCHES = ["m2", "m3"]
WORLD = dict(
    matches_per_host=2, seed=SEED, n_spectators=2, spectate_match="m2",
)


# ----------------------------------------------------------------------
# scheduling + refusal (stub hosts: no ticking, just the policy)
# ----------------------------------------------------------------------


class _StubShard:
    def __init__(self, refusal=None):
        self._refusal = refusal

    def admission_refusal(self):
        return self._refusal


class _StubHost:
    def __init__(self, refusal=None, p99=None):
        self.shards = {"s0": _StubShard(refusal)}
        self._p99 = p99

    def healthz(self):
        return {"shards": {"s0": {"tick_p99_ms": self._p99}}}


def _service(hosts, **kw):
    return PlacementService(hosts, ingress=object(),
                            metrics=Registry(), **kw)


class TestScheduling:
    def test_ring_walk_skips_refusing_hosts(self):
        svc = _service({"h0": _StubHost(refusal="full"),
                        "h1": _StubHost()})
        for mid in ("a", "b", "c", "z9"):
            assert svc.choose_host(mid) == "h1"
        assert svc.metrics.value("ggrs_placement_refusals_total",
                                 reason="full") >= 1

    def test_dead_host_never_chosen(self):
        svc = _service({"h0": _StubHost(), "h1": _StubHost()})
        svc.kill_host("h0")
        assert svc.host_refusal("h0") == "dead"
        for mid in ("a", "b", "c"):
            assert svc.choose_host(mid) == "h1"

    def test_p99_budget_refuses_overloaded(self):
        tuning = FleetTuning(placement_p99_budget_ms=5.0)
        svc = _service({"h0": _StubHost(p99=50.0),
                        "h1": _StubHost(p99=1.0)}, tuning=tuning)
        assert svc.host_refusal("h0") == "overloaded"
        assert svc.host_refusal("h1") is None
        for mid in ("a", "b", "c"):
            assert svc.choose_host(mid) == "h1"

    def test_no_host_accepts_raises(self):
        svc = _service({"h0": _StubHost(refusal="full"),
                        "h1": _StubHost(refusal="full")})
        with pytest.raises(FleetError, match="no host accepts"):
            svc.choose_host("m0")

    def test_kill_host_mints_route_epoch(self):
        svc = _service({"h0": _StubHost(), "h1": _StubHost()})
        assert svc.route_epoch == 1
        svc.kill_host("h1")
        assert svc.route_epoch == 2
        svc.kill_host("h1")  # idempotent: no double mint
        assert svc.route_epoch == 2
        svc.kill_host("h0")
        assert svc.route_epoch == 3
        assert svc.metrics.value("ggrs_placement_hosts",
                                 state="dead") == 2

    def test_needs_at_least_one_host(self):
        with pytest.raises(InvalidRequest, match="at least one host"):
            _service({})

    def test_stale_supervisor_route_refused_at_ingress(self):
        """The §26 fence end to end in miniature: a route signed with
        the pre-kill epoch is refused once kill_host has minted — the
        exact counterexample route-flip:stale-route-write pins."""
        from ggrs_tpu.fleet.ingress import ROUTE_OP_PUT, encode_route_update

        node = IngressNode(metrics=Registry())
        try:
            svc = _service({"h0": _StubHost(), "h1": _StubHost()})
            vport = node.allocate_endpoint()
            stale_epoch = svc.route_epoch
            svc.kill_host("h1")
            assert node.apply_route_update(encode_route_update(
                ROUTE_OP_PUT, svc.route_epoch, 1, vport,
                ("127.0.0.1", 40000))) == "ok"
            assert node.apply_route_update(encode_route_update(
                ROUTE_OP_PUT, stale_epoch, 2, vport,
                ("127.0.0.1", 40666))) == "stale-epoch"
        finally:
            node.close()


# ----------------------------------------------------------------------
# the cross-host chaos world
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def control():
    ctx = drive_placement_fleet(TICKS, **WORLD)
    ctx["close"]()
    return ctx


@pytest.fixture(scope="module")
def migrated():
    endpoints = {}

    def inject(i, ctx):
        if i == MIGRATE_AT:
            endpoints["before"] = ctx["placement"].virtual_endpoint("m2")
            ctx["placement"].migrate("m2", "h0")
            endpoints["after"] = ctx["placement"].virtual_endpoint("m2")

    ctx = drive_placement_fleet(TICKS, inject=inject, **WORLD)
    ctx["close"]()
    ctx["endpoints"] = endpoints
    return ctx


@pytest.fixture(scope="module")
def host_killed():
    def inject(i, ctx):
        if i == KILL_AT:
            ctx["placement"].kill_host("h1")

    ctx = drive_placement_fleet(TICKS, inject=inject, **WORLD)
    ctx["close"]()
    return ctx


class TestControlWorld:
    def test_all_matches_live_and_placed(self, control):
        assert not control["lost"]
        assert control["healthz"]["ok"]
        for mid in H0_MATCHES:
            assert control["locations"][mid] == ("h0", "a0")
        for mid in H1_MATCHES:
            assert control["locations"][mid] == ("h1", "b0")
        for mid, frame in control["frames"].items():
            assert frame == TICKS, mid

    def test_peers_and_viewers_stream_through_ingress(self, control):
        # every peer byte arrived FROM the public address (the recv
        # recorder would have captured any other source)
        for mid, wire in control["wire"].items():
            assert wire, f"{mid}: peer heard nothing"
        for stream in control["viewer_streams"]:
            frames = [f for f, _ in stream]
            assert frames == sorted(set(frames))
            assert frames[-1] >= TICKS - 4

    def test_fault_free_world_is_clean(self, control):
        # interval-1 checksum exchange is ON: any host/peer divergence
        # would surface as DesyncDetected within one frame
        assert fleet_recovery_violations(
            control, control["match_ids"]) == []


class TestLiveMigrationCrossHost:
    """tests/test_fleet.py's §16 migration contract, one level up: the
    move crosses SUPERVISORS (export → pickle bytes → adopt → route
    flip) and the public endpoint provably never changes."""

    def test_match_moved_cross_host(self, migrated):
        assert migrated["locations"]["m2"] == ("h0", "a0")
        assert not migrated["lost"]
        assert migrated["registry"].value(
            "ggrs_placement_migrations_total", reason="manual") == 1
        assert migrated["registry"].value(
            "ggrs_ingress_route_flips_total") == 1

    def test_survivors_bit_identical_to_control(self, migrated, control):
        assert fleet_survivor_violations(
            migrated, control, ["m0", "m1", "m3"]) == []
        for mid in ("m0", "m1", "m3"):
            assert migrated["states"][mid] == control["states"][mid]
            assert (migrated["peer_states"][mid]
                    == control["peer_states"][mid])

    def test_peer_sees_hiccup_never_reset(self, migrated):
        """No Disconnected, no DesyncDetected (interval-1 checksums are
        ON: any state divergence would trip within a frame), bounded
        catch-up lag."""
        assert fleet_recovery_violations(migrated, ["m2"]) == []
        lag = migrated["peer_frames"]["m2"] - migrated["frames"]["m2"]
        assert 0 <= lag <= 8

    def test_virtual_endpoint_unchanged(self, migrated, control):
        before = migrated["endpoints"]["before"]
        after = migrated["endpoints"]["after"]
        assert before == after  # the whole point of the plane
        assert after == (migrated["public"], migrated["vports"]["m2"])
        assert migrated["vports"] == control["vports"]

    def test_journal_streams_survive_the_move(self, migrated, control):
        """The durable artifact across incarnations: the source's file
        and the adopter's file agree record for record on every frame
        both hold, and the leg's merged stream is bit-identical to the
        control journal for every frame up to the export tip."""
        c = read_journal(
            os.path.join(control["journal_dir"], "m2.000.ggjl"))
        inc0 = read_journal(
            os.path.join(migrated["journal_dir"], "m2.000.ggjl"))
        inc1 = read_journal(
            os.path.join(migrated["journal_dir"], "m2.001.ggjl"))
        assert inc0["frames"] and inc1["frames"]
        d_control = {f: rec for f, *rec in c["frames"]}
        d0 = {f: rec for f, *rec in inc0["frames"]}
        d1 = {f: rec for f, *rec in inc1["frames"]}
        overlap = set(d0) & set(d1)
        assert overlap, "incarnations share no frames (no overlap proof)"
        for f in overlap:
            assert d0[f] == d1[f], f"frame {f} differs across incarnations"
        tip = max(d0)
        merged = dict(d0)
        merged.update(d1)
        for f in range(tip + 1):
            assert merged[f] == d_control[f], \
                f"frame {f} differs from control before the export tip"
        # the adopter resumed from a checkpoint, not frame 0
        assert inc1["checkpoints"]

    def test_viewers_follow_the_move(self, migrated):
        for v, stream in enumerate(migrated["viewer_streams"]):
            frames = [f for f, _ in stream]
            assert frames == sorted(set(frames)), f"viewer {v} reset"
            assert frames[-1] >= MIGRATE_AT + 16, f"viewer {v} stalled"


class TestHostKillFailover:
    """A whole machine dies: both its matches journal-fail-over ACROSS
    hosts from replicated meta + shared-storage journals, the route
    epoch fences the dead incarnation, and nothing public changes."""

    def test_matches_failed_over_cross_host(self, host_killed):
        assert not host_killed["lost"]
        for mid in H1_MATCHES:
            assert host_killed["locations"][mid] == ("h0", "a0")
        assert host_killed["registry"].value(
            "ggrs_placement_host_failovers_total") == 2
        assert host_killed["registry"].value(
            "ggrs_ingress_route_flips_total") == 2

    def test_survivors_bit_identical_to_control(self, host_killed,
                                                control):
        assert fleet_survivor_violations(
            host_killed, control, H0_MATCHES) == []

    def test_failed_over_matches_recovered_clean(self, host_killed):
        assert fleet_recovery_violations(host_killed, H1_MATCHES) == []
        for mid in H1_MATCHES:
            lag = (host_killed["peer_frames"][mid]
                   - host_killed["frames"][mid])
            assert 0 <= lag <= 12, f"{mid}: lag {lag}"

    def test_route_epoch_fences_dead_host(self, host_killed):
        hz = host_killed["healthz"]
        assert hz["route_epoch"] == 2
        assert hz["hosts"]["h1"] == {"ok": False, "state": "dead"}
        assert hz["shards"]["h1/b0"]["state"] == "dead"
        assert hz["ok"]  # nothing lost, the survivor serves everything

    def test_virtual_endpoints_unchanged(self, host_killed, control):
        assert host_killed["vports"] == control["vports"]

    def test_viewers_ride_through_the_host_kill(self, host_killed):
        # the viewers watch m2 — a match ON the killed host
        for v, stream in enumerate(host_killed["viewer_streams"]):
            frames = [f for f, _ in stream]
            assert frames == sorted(set(frames)), f"viewer {v} reset"
            assert frames[-1] >= KILL_AT + 12, f"viewer {v} stalled"


# ----------------------------------------------------------------------
# obs: the placement healthz renders in fleet_top
# ----------------------------------------------------------------------


class TestFleetTopPlacement:
    def test_render_placement_healthz(self, host_killed):
        spec = importlib.util.spec_from_file_location(
            "fleet_top",
            Path(__file__).resolve().parents[1] / "scripts"
            / "fleet_top.py",
        )
        fleet_top = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(fleet_top)
        frame = fleet_top.render(
            host_killed["healthz"], json_snapshot(host_killed["registry"]))
        assert "INGRESS" in frame
        assert "h0/a0" in frame and "h1/b0" in frame
        assert "ingress ingress:" in frame
        assert "route_epoch=2" in frame
        # the survivor host's shard carries every live route
        assert host_killed["healthz"]["shards"]["h0/a0"][
            "ingress_routes"] == 4
