"""Speculative rollback wired into the live P2P path.

BASELINE config 3's integration contract (VERDICT round 1, item 1): a P2P
rollback is fulfilled by a branch hit with no replay dispatch; a miss falls
back to the fused replay; states stay bit-identical to a non-speculative peer
either way.  The replay loop being replaced is the reference's rollback hot
loop (/root/reference/src/sessions/p2p_session.rs:658-714).
"""

import random

import numpy as np

import jax.numpy as jnp

from ggrs_tpu.core import LoadGameState
from ggrs_tpu.games import BoxGame, boxgame_config
from ggrs_tpu.net import InMemoryNetwork
from ggrs_tpu.ops import DeviceRequestExecutor
from ggrs_tpu.parallel import SpeculativeRollback
from ggrs_tpu.sessions import SessionBuilder
from ggrs_tpu.core import Local, Remote


def _inputs_to_array(pairs):
    return jnp.asarray(np.asarray([p[0] for p in pairs], np.uint8))


def _count_bursts(executor):
    """Wrap the executor's replay dispatch with a call counter."""
    counter = {"n": 0}
    original = executor._do_burst

    def counting(pairs, saves, **kwargs):
        counter["n"] += 1
        return original(pairs, saves, **kwargs)

    executor._do_burst = counting
    return counter


def _make_2p_pair(net, spec_factory):
    """Two P2P BoxGame peers; peer A's executor gets ``spec_factory(game)``."""
    game = BoxGame(2)
    sessions, executors = [], []
    for me, other, local_handle in (("A", "B", 0), ("B", "A", 1)):
        sess = (
            SessionBuilder(boxgame_config())
            .with_clock(lambda: 0)
            .with_rng(random.Random(3 + local_handle))
            .add_player(Local(), local_handle)
            .add_player(Remote(other), 1 - local_handle)
            .start_p2p_session(net.socket(me))
        )
        spec = spec_factory(game) if me == "A" else None
        executors.append(
            DeviceRequestExecutor(
                game.advance, game.init_state(), _inputs_to_array,
                speculation=spec,
            )
        )
        sessions.append(sess)
    return game, sessions, executors


def _a_sched(i):
    return (i // 4) % 16


def _b_sched(i):
    # changes every 3 frames: repeat-last mispredicts at every transition,
    # forcing regular rollbacks
    return (i // 3) % 16


def _drive(sessions, executors, ticks, record_loads=None, drain=12):
    """Run ``ticks`` scheduled frames, then ``drain`` constant-input frames so
    repeat-last predictions become correct and both live states converge to
    the true simulation (predicted tails otherwise legitimately differ)."""
    sess_a, sess_b = sessions
    ex_a, ex_b = executors
    for i in range(ticks + drain):
        a_in = _a_sched(min(i, ticks - 1))
        b_in = _b_sched(min(i, ticks - 1))
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()
        sess_a.add_local_input(0, a_in)
        reqs_a = sess_a.advance_frame()
        if record_loads is not None:
            record_loads["n"] += sum(
                1 for r in reqs_a if isinstance(r, LoadGameState)
            )
        ex_a.run(reqs_a)
        sess_b.add_local_input(1, b_in)
        ex_b.run(sess_b.advance_frame())


def _assert_peers_identical(sessions, executors):
    """Both peers reached the same frame with bit-identical device states."""
    assert sessions[0].current_frame == sessions[1].current_frame
    ex_a, ex_b = executors
    for k in ("pos", "vel", "rot"):
        np.testing.assert_array_equal(
            np.asarray(ex_a.state[k]), np.asarray(ex_b.state[k]), err_msg=k
        )


def _oracle_spec(game):
    """K=2: branch 0 trusts the session's prediction, branch 1 knows peer B's
    actual schedule (a deterministic stand-in for a good guesser)."""

    def branch_inputs(k, frame, arr):
        if k == 0:
            return jnp.asarray(arr, jnp.uint8)
        return jnp.asarray(arr, jnp.uint8).at[1].set(np.uint8(_b_sched(frame)))

    return SpeculativeRollback(game.advance, 2, branch_inputs, max_window=8)


def _hopeless_spec(game):
    """K=2 hypotheses that never match B's schedule once it leaves 9:
    branch 1 guesses a constant B never presses mid-run; branch 0 repeats the
    prediction, which is wrong at every schedule transition."""

    def branch_inputs(k, frame, arr):
        if k == 0:
            return jnp.asarray(arr, jnp.uint8)
        return jnp.asarray(arr, jnp.uint8).at[1].set(np.uint8(9))

    return SpeculativeRollback(game.advance, 2, branch_inputs, max_window=8)


class TestSpeculativeP2P:
    def test_branch_hit_fulfills_rollback_without_replay(self):
        net = InMemoryNetwork()
        game, sessions, executors = _make_2p_pair(net, _oracle_spec)
        ex_a, ex_b = executors
        bursts = _count_bursts(ex_a)
        loads = {"n": 0}

        _drive(sessions, executors, 40, record_loads=loads)

        # rollbacks really happened, and every one was served by a branch
        assert loads["n"] > 5, "schedule transitions must cause rollbacks"
        assert ex_a.spec_hits == loads["n"]
        assert ex_a.spec_misses == 0
        assert bursts["n"] == 0, "a hit must not dispatch the replay scan"

        # speculative fulfillment is bit-identical to peer B's plain replay
        _assert_peers_identical(sessions, executors)

    def test_rollback_tick_is_one_fused_dispatch(self):
        """A speculative rollback tick whose burst ends in a saveless live
        advance must cost exactly ONE device dispatch: fulfill_and_refill is
        invoked with the live inputs fused in, and neither the plain advance
        nor advance_and_extend runs for that tick — dispatch parity with the
        plain path's single load+replay+advance burst."""
        net = InMemoryNetwork()
        game, sessions, executors = _make_2p_pair(net, _oracle_spec)
        ex_a, _ = executors
        bursts = _count_bursts(ex_a)

        calls = {"fused_live": 0, "unfused": 0, "advances": 0, "adv_ext": 0}
        spec = ex_a._spec
        orig_fulfill = spec.fulfill_and_refill
        orig_advance = ex_a._advance
        orig_adv_ext = spec.advance_and_extend

        def spy_fulfill(frame, confirmed, load_state, wc, live_inputs=None):
            calls["fused_live" if live_inputs is not None else "unfused"] += 1
            return orig_fulfill(
                frame, confirmed, load_state, wc, live_inputs=live_inputs
            )

        def spy_advance(state, inputs):
            calls["advances"] += 1
            return orig_advance(state, inputs)

        def spy_adv_ext(state, inputs):
            out = orig_adv_ext(state, inputs)
            if out is not None:  # None = no dispatch (caller advances plainly)
                calls["adv_ext"] += 1
            return out

        spec.fulfill_and_refill = spy_fulfill
        ex_a._advance = spy_advance
        spec.advance_and_extend = spy_adv_ext
        loads = {"n": 0}

        _drive(sessions, executors, 40, record_loads=loads)

        assert loads["n"] > 5
        assert calls["fused_live"] > 0, "live advance must ride the fulfill"
        assert bursts["n"] == 0
        # the separate advance program may only run on non-rollback ticks and
        # unrooted fallbacks — never once per rollback on top of the fused
        # dispatch (ticks = 40 scheduled + 12 drain; every dispatch beyond
        # one-per-tick would show up here)
        total_ticks = 52
        assert calls["fused_live"] + calls["unfused"] == loads["n"]
        assert (
            calls["advances"]
            + calls["adv_ext"]
            + calls["fused_live"]
            + calls["unfused"]
            == total_ticks
        ), "a tick must cost exactly one device dispatch"
        _assert_peers_identical(sessions, executors)

    def test_miss_falls_back_to_replay(self):
        net = InMemoryNetwork()
        game, sessions, executors = _make_2p_pair(net, _hopeless_spec)
        ex_a, ex_b = executors
        bursts = _count_bursts(ex_a)
        loads = {"n": 0}

        _drive(sessions, executors, 40, record_loads=loads)

        assert loads["n"] > 5
        assert ex_a.spec_misses > 0
        # misses dispatch the fused replay (depth-1 rollbacks use the single-
        # advance path, so bursts may be fewer than misses but states must
        # still match)
        _assert_peers_identical(sessions, executors)

    def test_sparse_saving_with_speculation_stays_correct(self):
        """Sparse saving produces rollback bursts with few (or oddly placed)
        saves — paths where speculation cannot re-anchor and must invalidate
        rather than trust a stale window (round-1 review finding)."""
        net = InMemoryNetwork()
        game = BoxGame(2)
        sessions, executors = [], []
        for me, other, local_handle in (("A", "B", 0), ("B", "A", 1)):
            sess = (
                SessionBuilder(boxgame_config())
                .with_clock(lambda: 0)
                .with_rng(random.Random(29 + local_handle))
                .with_sparse_saving_mode(True)
                .add_player(Local(), local_handle)
                .add_player(Remote(other), 1 - local_handle)
                .start_p2p_session(net.socket(me))
            )
            spec = _oracle_spec(game) if me == "A" else None
            executors.append(
                DeviceRequestExecutor(
                    game.advance, game.init_state(), _inputs_to_array,
                    speculation=spec,
                )
            )
            sessions.append(sess)

        _drive(sessions, executors, 40)
        _assert_peers_identical(sessions, executors)

    def test_speculation_under_packet_loss_mixes_hits_and_fallbacks(self):
        """Lossy network + a deterministically IMPERFECT oracle (wrong on
        every 5th frame): rollback windows containing a bad-guess frame take
        the miss/fallback + invalidate + re-anchor path, the rest hit — both
        paths must execute under loss-deepened irregular rollbacks, and the
        peers must still drain to bit-identical states."""

        def flaky_oracle(game):
            def branch_inputs(k, frame, arr):
                if k == 0:
                    return jnp.asarray(arr, jnp.uint8)
                guess = _b_sched(frame) ^ (0 if frame % 5 else 1)
                return jnp.asarray(arr, jnp.uint8).at[1].set(np.uint8(guess))

            return SpeculativeRollback(
                game.advance, 2, branch_inputs, max_window=8
            )

        net = InMemoryNetwork(loss=0.25, seed=37)
        game, sessions, executors = _make_2p_pair(net, flaky_oracle)
        ex_a, ex_b = executors

        _drive(sessions, executors, 120, drain=40)

        assert ex_a.spec_hits > 0, "clean windows must hit a branch"
        assert ex_a.spec_misses > 0, (
            "windows containing a bad-guess frame must take the fallback path"
        )
        _assert_peers_identical(sessions, executors)

    def test_four_players_eight_branches(self):
        """BASELINE config 3's exact shape: 4 players, 8-frame prediction,
        8 branches; peer 0 speculates, the other three replay."""
        net = InMemoryNetwork()
        game = BoxGame(4)
        peers = ["P0", "P1", "P2", "P3"]

        def sched(player, i):
            return ((i + player) // 3) % 16

        def branch_inputs(k, frame, arr):
            arr = jnp.asarray(arr, jnp.uint8)
            if k < 7:
                # "held buttons" style guesses on the remote lanes
                return arr.at[1:].set(np.uint8(k))
            # branch 7: the oracle for all three remotes
            vals = np.asarray(
                [sched(p, frame) for p in (1, 2, 3)], np.uint8
            )
            return arr.at[1:].set(jnp.asarray(vals))

        sessions, executors = [], []
        for me in range(4):
            b = (
                SessionBuilder(boxgame_config())
                .with_num_players(4)
                .with_max_prediction_window(8)
                .with_clock(lambda: 0)
                .with_rng(random.Random(17 + me))
            )
            for p in range(4):
                if p == me:
                    b = b.add_player(Local(), p)
                else:
                    b = b.add_player(Remote(peers[p]), p)
            sessions.append(b.start_p2p_session(net.socket(peers[me])))
            spec = (
                SpeculativeRollback(game.advance, 8, branch_inputs, max_window=8)
                if me == 0
                else None
            )
            executors.append(
                DeviceRequestExecutor(
                    game.advance, game.init_state(), _inputs_to_array,
                    speculation=spec,
                )
            )

        loads = {"n": 0}
        for i in range(48):  # 36 scheduled + 12 constant drain ticks
            for s in sessions:
                s.poll_remote_clients()
            for p, (s, ex) in enumerate(zip(sessions, executors)):
                s.add_local_input(p, sched(p, min(i, 35)))
                reqs = s.advance_frame()
                if p == 0:
                    loads["n"] += sum(
                        1 for r in reqs if isinstance(r, LoadGameState)
                    )
                ex.run(reqs)

        assert loads["n"] > 0
        assert executors[0].spec_hits > 0
        # all peers that reached the same frame agree bit-exactly
        frames = {s.current_frame for s in sessions}
        assert len(frames) == 1
        for other in (1, 2, 3):
            for k in ("pos", "vel", "rot"):
                np.testing.assert_array_equal(
                    np.asarray(executors[0].state[k]),
                    np.asarray(executors[other].state[k]),
                    err_msg=f"peer {other} {k}",
                )
