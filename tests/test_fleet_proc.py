"""Out-of-process shard tests (DESIGN.md §17): the subprocess shard
runner, supervisor RPC with heartbeats and watchdogs, and SIGKILL-grade
chaos.

The acceptance pins, mirrored by ``scripts/chaos.py --fault proc``:

* SIGKILL of a shard subprocess is detected within the heartbeat
  deadline; every match re-adopts from its durable journal onto the
  survivors; the surviving shard's peer-observed wire bytes are
  bit-identical to a fault-free control; zero orphan processes or
  leaked fds remain in the supervisor.
* SIGSTOP (a hang, not a death) escalates SIGTERM → drain deadline →
  SIGKILL before any failover — wedged ≠ dead, and a wedged process
  must be fenced off the wire before its matches are re-adopted.
* The in-process and subprocess backends pass the SAME fleet matrix
  behind one supervisor interface (parametrized here), and a
  process-backed run is bit-identical to the identical in-process
  topology under the same seeded traffic (the parity pin).
* SIGTERM runs a graceful drain: journals closed durable, a final
  GOODBYE, exit code 0.
* After a death the shard respawns under the jittered-backoff restart
  policy, bounded by the restart-storm budget.
"""

from __future__ import annotations

import os
import signal
import time

import pytest

from ggrs_tpu.broadcast.journal import read_journal
from ggrs_tpu.chaos import (
    drive_proc_fleet,
    fleet_recovery_violations,
    fleet_survivor_violations,
)
from ggrs_tpu.fleet import FleetTuning, ProcShard, SHARD_DEAD
from ggrs_tpu.net import _native
from ggrs_tpu.obs import Registry

needs_native = pytest.mark.skipif(
    _native.bank_lib() is None, reason="native session bank unavailable"
)

TICKS = 48
PER_SHARD = 2
SURVIVORS = [f"m{k}" for k in range(PER_SHARD)]              # on s0
AFFECTED = [f"m{k}" for k in range(PER_SHARD, 2 * PER_SHARD)]  # on s1

# fast deadlines so the watchdog scenarios run in test time; restarts
# off by default (the restart test opts back in)
TUNING = FleetTuning(
    heartbeat_interval_s=0.05,
    heartbeat_deadline_s=1.0,
    rpc_timeout_s=5.0,
    spawn_timeout_s=120.0,
    drain_deadline_s=0.5,
    restart_max=0,
)


@pytest.fixture(scope="module")
def control_inproc():
    ctx = drive_proc_fleet(TICKS, matches_per_shard=PER_SHARD, seed=7,
                           backend="inproc", tuning=TUNING)
    yield ctx
    ctx["sup"].close()


@pytest.fixture(scope="module")
def control_proc():
    ctx = drive_proc_fleet(TICKS, matches_per_shard=PER_SHARD, seed=7,
                           backend="proc", tuning=TUNING)
    yield ctx
    ctx["sup"].close()


# ----------------------------------------------------------------------
# backend parity: one topology, two backends, identical bytes
# ----------------------------------------------------------------------


@needs_native
class TestBackendParity:
    def test_wire_and_state_bit_identical(self, control_inproc,
                                          control_proc):
        """The same seeded traffic through a subprocess shard and the
        identical in-process topology: every peer's RECEIVED datagram
        byte sequence, final frame, and game state agree exactly."""
        for mid in control_proc["match_ids"]:
            assert (
                control_proc["wire"][mid] == control_inproc["wire"][mid]
            ), f"{mid}: peer-received wire diverged across backends"
            assert (
                control_proc["peer_states"][mid]
                == control_inproc["peer_states"][mid]
            )
            assert (
                control_proc["frames"][mid] == control_inproc["frames"][mid]
            )
        assert not control_proc["lost"] and not control_inproc["lost"]

    def test_journal_streams_bit_identical(self, control_inproc,
                                           control_proc):
        """The durable artifact agrees too: the confirmed-input stream a
        runner journals (in its own process, at supervisor-composed
        paths) matches the in-process leg's record for record."""
        for mid in AFFECTED:  # the matches that lived on the s1 backend
            a = read_journal(
                os.path.join(control_inproc["journal_dir"],
                             f"{mid}.000.ggjl"))
            b = read_journal(
                os.path.join(control_proc["journal_dir"],
                             f"{mid}.000.ggjl"))
            assert a["frames"] == b["frames"]
            assert len(b["frames"]) > 0

    def test_healthz_reports_proc_backend(self, control_proc):
        h = control_proc["healthz"]["shards"]["s1"]
        assert h["backend"] == "proc" and h["ok"] and h["pid"]
        assert h["heartbeat_age_s"] < TUNING.heartbeat_deadline_s


# ----------------------------------------------------------------------
# the same fleet matrix behind one interface (mixed backends)
# ----------------------------------------------------------------------


@needs_native
class TestKillFailoverMatrix:
    @pytest.mark.parametrize("backend", ["inproc", "proc"])
    def test_kill_s1_fails_over_identically(self, backend, control_inproc,
                                            control_proc, request):
        """``sup.kill('s1')`` — a chaos switch in-process, a REAL
        SIGKILL out-of-process — recovers every affected match from its
        journal onto the survivor, with the surviving shard
        bit-identical to control, under EITHER backend."""
        control = (control_inproc if backend == "inproc"
                   else control_proc)

        def inject(i, ctx):
            if i == TICKS // 2:
                ctx["sup"].kill("s1")

        chaos = drive_proc_fleet(
            TICKS, matches_per_shard=PER_SHARD, seed=7, backend=backend,
            tuning=TUNING, inject=inject,
        )
        try:
            assert not fleet_survivor_violations(chaos, control, SURVIVORS)
            assert not fleet_recovery_violations(
                chaos, AFFECTED, dead_shards=["s1"]
            )
            for mid in AFFECTED:
                assert chaos["locations"][mid] == "s0"
            sup = chaos["sup"]
            assert sup.shards["s1"].healthz()["state"] == SHARD_DEAD
            assert chaos["registry"].value(
                "ggrs_fleet_migrations_total", reason="failover"
            ) == len(AFFECTED)
        finally:
            chaos["sup"].close()
        if backend == "proc":
            assert chaos["sup"].shards["s1"].orphan_count() == 0


# ----------------------------------------------------------------------
# watchdog: SIGSTOP is a hang, not a death
# ----------------------------------------------------------------------


@needs_native
class TestHangWatchdog:
    def test_sigstop_escalates_sigterm_then_sigkill_then_recovers(self):
        """A SIGSTOPped runner answers nothing but is NOT dead: the
        watchdog must escalate (SIGTERM is undeliverable to a stopped
        process, so the drain deadline expires into SIGKILL) and only
        then fail the matches over — never while the process breathes."""
        t = FleetTuning(
            heartbeat_interval_s=0.05, heartbeat_deadline_s=0.4,
            rpc_timeout_s=0.3, drain_deadline_s=0.3,
            spawn_timeout_s=120.0, restart_max=0,
        )

        def inject(i, ctx):
            if i == 20:
                os.kill(ctx["sup"].shards["s1"].pid, signal.SIGSTOP)

        chaos = drive_proc_fleet(
            90, matches_per_shard=1, seed=11, backend="proc", tuning=t,
            inject=inject, tick_sleep_s=0.01,
        )
        try:
            reg = chaos["registry"]
            assert reg.value("ggrs_fleet_proc_watchdog_total",
                             shard="s1", stage="sigterm") >= 1
            assert reg.value("ggrs_fleet_proc_watchdog_total",
                             shard="s1", stage="sigkill") >= 1
            assert not fleet_recovery_violations(
                chaos, ["m1"], dead_shards=["s1"]
            )
            assert chaos["locations"]["m1"] == "s0"
            assert chaos["sup"].shards["s1"].state == SHARD_DEAD
        finally:
            chaos["sup"].close()
        assert chaos["sup"].shards["s1"].orphan_count() == 0


# ----------------------------------------------------------------------
# graceful drain + leak checks
# ----------------------------------------------------------------------


def _count_fds() -> int:
    return len(os.listdir("/proc/self/fd"))


class TestRunnerLifecycle:
    def test_sigterm_runs_graceful_drain(self, tmp_path):
        """SIGTERM → admission off, journals flushed+fsynced+CLOSED, a
        final GOODBYE, exit code 0 — the journal is readable as a clean
        artifact afterwards."""
        import functools

        from ggrs_tpu.fleet.proc import (
            proc_match_builder,
            udp_socket_factory,
        )

        shard = ProcShard("g0", capacity=2, metrics=Registry(),
                          tuning=TUNING, clock=lambda: clock[0])
        clock = [0]
        try:
            from ggrs_tpu.chaos import CrcGame, two_peer_builder
            from ggrs_tpu.net.sockets import UdpNonBlockingSocket

            peer_sock = UdpNonBlockingSocket(0)
            path = tmp_path / "g0.m0.ggjl"
            shard.admit_spec(
                "m0",
                functools.partial(
                    proc_match_builder, 301, 0,
                    ("127.0.0.1", peer_sock.local_port()),
                ),
                functools.partial(udp_socket_factory, 0),
                CrcGame,
                journal_spec=dict(path=str(path), num_players=2,
                                  input_size=2, fsync_every=1,
                                  tail_window=32),
            )
            peer = two_peer_builder(
                clock, 302, 1, ("127.0.0.1", shard.match_port("m0")),
                other_handle=0,
            ).start_p2p_session(peer_sock)
            game = CrcGame()
            from ggrs_tpu.core.errors import (
                NotSynchronized,
                PredictionThreshold,
            )

            for i in range(30):
                clock[0] += 16
                try:
                    peer.add_local_input(1, i % 7)
                    game.fulfill(peer.advance_frame())
                except (NotSynchronized, PredictionThreshold):
                    pass
                shard.add_local_input("m0", 0, i % 5)
                shard.advance_all()
            assert shard.current_frame("m0") > 10
            conn = shard._conn
            os.kill(shard.pid, signal.SIGTERM)
            shard._proc.wait(timeout=30)
            assert shard._proc.returncode == 0
            # the drain left a GOODBYE and a CLEANLY CLOSED journal
            for _ in range(50):
                if shard.poll_lifecycle() is not None or conn.goodbye:
                    break
                time.sleep(0.01)
            assert conn.goodbye is not None
            assert conn.goodbye["reason"] == "sigterm"
            assert conn.goodbye["frames"]["m0"] > 10
            parsed = read_journal(path)
            assert parsed["closed"] and not parsed["truncated"]
            assert len(parsed["frames"]) > 0
        finally:
            shard.close()
        assert shard.orphan_count() == 0

    def test_sigkill_leaves_no_orphans_or_leaked_fds(self):
        """SIGKILL-only death: the supervisor reaps the child (no
        zombie) and closes its socket end (no fd growth) — measured on
        an isolated shard so the accounting is exact."""
        fd_base = _count_fds()
        shard = ProcShard("leak0", capacity=2, metrics=Registry(),
                          tuning=TUNING)
        pid = shard.pid
        assert _count_fds() > fd_base  # the live conn holds an fd
        os.kill(pid, signal.SIGKILL)
        died = None
        for _ in range(200):
            died = shard.poll_lifecycle()
            if died == "died":
                break
            time.sleep(0.01)
        assert died == "died"
        shard.close()
        assert _count_fds() == fd_base
        assert shard.orphan_count() == 0
        assert shard.last_exit == "exit code -9"
        # reaped for real: the pid is no longer our child
        with pytest.raises(ChildProcessError):
            os.waitpid(pid, os.WNOHANG)

    def test_shutdown_rpc_closes_cleanly(self):
        shard = ProcShard("c0", capacity=2, metrics=Registry(),
                          tuning=TUNING)
        shard.close()
        assert shard.last_exit == "exit code 0"
        assert shard.orphan_count() == 0
        # idempotent
        shard.close()


# ----------------------------------------------------------------------
# restart policy: jittered backoff + storm budget
# ----------------------------------------------------------------------


@needs_native
class TestRestartPolicy:
    def test_restart_after_crash_then_storm_budget(self):
        """A killed shard respawns (capacity returns for new
        admissions); killing it repeatedly exhausts the storm budget and
        it stays dead — with every match still recovered and no
        orphans."""
        t = FleetTuning(
            heartbeat_interval_s=0.05, heartbeat_deadline_s=0.5,
            rpc_timeout_s=2.0, drain_deadline_s=0.3,
            spawn_timeout_s=120.0,
            restart_backoff_s=0.05, restart_max=2, restart_window_s=60.0,
        )
        kills = {"n": 0}

        def inject(i, ctx):
            s1 = ctx["sup"].shards["s1"]
            if i >= 20 and kills["n"] < 5 and s1.pid and s1._alive():
                kills["n"] += 1
                os.kill(s1.pid, signal.SIGKILL)

        chaos = drive_proc_fleet(
            240, matches_per_shard=1, seed=3, backend="proc", tuning=t,
            inject=inject, tick_sleep_s=0.01,
        )
        sup = chaos["sup"]
        try:
            s1 = sup.shards["s1"]
            assert kills["n"] >= 3  # the storm actually stormed
            assert s1.restarts == 2  # budget: exactly restart_max
            assert s1.state == SHARD_DEAD  # then it STAYS dead
            assert chaos["registry"].value(
                "ggrs_fleet_proc_restarts_total", shard="s1"
            ) == 2
            assert not chaos["lost"]
            assert chaos["locations"]["m1"] == "s0"
            assert not fleet_recovery_violations(
                chaos, ["m1"], dead_shards=["s1"]
            )
        finally:
            sup.close()
        assert sup.shards["s1"].orphan_count() == 0
