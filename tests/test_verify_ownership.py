"""ggrs-verify pillar 3 (static half): the thread-ownership lint.

Golden fixtures for each own/* rule plus the self-clean gate: the
session classes declare exactly the driving surface they guard.
"""

from pathlib import Path

from ggrs_tpu.analysis import lint_ownership
from ggrs_tpu.sessions import P2PSession, SpectatorSession, SyncTestSession
from ggrs_tpu.utils.ownership import ThreadOwned

REPO = Path(__file__).resolve().parents[1]


def lint_src(tmp_path, src: str):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(src)
    return lint_ownership(tmp_path, scope=("pkg/",))


OK_CLASS = """
class ThreadOwned:
    pass

class Session(ThreadOwned):
    _DRIVING_METHODS = ("advance",)

    def advance(self):
        self._check_owner()
        return 1

    def read_only(self):
        return 2
"""


class TestGoldenFixtures:
    def test_clean_class_passes(self, tmp_path):
        assert lint_src(tmp_path, OK_CLASS) == []

    def test_undeclared_fires(self, tmp_path):
        src = OK_CLASS.replace(
            '    _DRIVING_METHODS = ("advance",)\n', ""
        )
        findings = lint_src(tmp_path, src)
        assert [f.rule for f in findings] == ["own/undeclared"]

    def test_missing_guard_fires_on_unguarded_method(self, tmp_path):
        src = OK_CLASS.replace(
            "    def advance(self):\n        self._check_owner()\n",
            "    def advance(self):\n",
        )
        findings = lint_src(tmp_path, src)
        assert [f.rule for f in findings] == ["own/missing-guard"]

    def test_missing_guard_fires_on_phantom_method(self, tmp_path):
        src = OK_CLASS.replace(
            '_DRIVING_METHODS = ("advance",)',
            '_DRIVING_METHODS = ("advance", "phantom")',
        )
        findings = lint_src(tmp_path, src)
        assert [f.rule for f in findings] == ["own/missing-guard"]

    def test_unlisted_guard_fires(self, tmp_path):
        src = OK_CLASS.replace(
            "    def read_only(self):\n        return 2\n",
            "    def read_only(self):\n"
            "        self._check_owner()\n"
            "        return 2\n",
        )
        findings = lint_src(tmp_path, src)
        assert [f.rule for f in findings] == ["own/unlisted-guard"]

    def test_thread_target_fires(self, tmp_path):
        src = OK_CLASS + (
            "\n"
            "import threading\n"
            "def spawn(s):\n"
            "    return threading.Thread(target=s.advance)\n"
        )
        findings = lint_src(tmp_path, src)
        assert [f.rule for f in findings] == ["own/thread-target"]

    def test_subclass_inherits_declaration(self, tmp_path):
        src = OK_CLASS + (
            "\n"
            "class Derived(Session):\n"
            "    def helper(self):\n"
            "        return 3\n"
        )
        assert lint_src(tmp_path, src) == []

    def test_subclass_with_new_guard_must_declare(self, tmp_path):
        src = OK_CLASS + (
            "\n"
            "class Derived(Session):\n"
            "    def extra(self):\n"
            "        self._check_owner()\n"
            "        return 3\n"
        )
        findings = lint_src(tmp_path, src)
        assert [f.rule for f in findings] == ["own/undeclared"]


class TestHandOffGoldens:
    """The non-Thread escape hatches: Timer, executor.submit, and
    one-level bound-method aliasing.  The alias ALONE must stay clean —
    session_pool's same-thread hot-path alias is idiomatic — only the
    cross-thread hand-off fires."""

    def test_timer_positional_fires(self, tmp_path):
        src = OK_CLASS + (
            "\n"
            "import threading\n"
            "def arm(s):\n"
            "    return threading.Timer(0.5, s.advance)\n"
        )
        findings = lint_src(tmp_path, src)
        assert [f.rule for f in findings] == ["own/thread-target"]

    def test_timer_function_kw_fires(self, tmp_path):
        src = OK_CLASS + (
            "\n"
            "import threading\n"
            "def arm(s):\n"
            "    return threading.Timer(0.5, function=s.advance)\n"
        )
        findings = lint_src(tmp_path, src)
        assert [f.rule for f in findings] == ["own/thread-target"]

    def test_timer_with_benign_callback_is_clean(self, tmp_path):
        src = OK_CLASS + (
            "\n"
            "import threading\n"
            "def arm(s):\n"
            "    return threading.Timer(0.5, s.read_only)\n"
        )
        assert lint_src(tmp_path, src) == []

    def test_executor_submit_fires(self, tmp_path):
        src = OK_CLASS + (
            "\n"
            "def offload(pool, s):\n"
            "    return pool.submit(s.advance, 1)\n"
        )
        findings = lint_src(tmp_path, src)
        assert [f.rule for f in findings] == ["own/executor-submit"]

    def test_executor_submit_benign_is_clean(self, tmp_path):
        src = OK_CLASS + (
            "\n"
            "def offload(pool, s):\n"
            "    return pool.submit(s.read_only)\n"
        )
        assert lint_src(tmp_path, src) == []

    def test_alias_handed_to_thread_fires(self, tmp_path):
        src = OK_CLASS + (
            "\n"
            "import threading\n"
            "def spawn(s):\n"
            "    step = s.advance\n"
            "    return threading.Thread(target=step)\n"
        )
        findings = lint_src(tmp_path, src)
        assert [f.rule for f in findings] == ["own/thread-target"]
        assert "step (= ….advance)" in findings[0].detail

    def test_alias_handed_to_submit_fires(self, tmp_path):
        src = OK_CLASS + (
            "\n"
            "def offload(pool, s):\n"
            "    step = s.advance\n"
            "    return pool.submit(step)\n"
        )
        findings = lint_src(tmp_path, src)
        assert [f.rule for f in findings] == ["own/executor-submit"]

    def test_bare_alias_is_clean(self, tmp_path):
        # the same-thread hot-path alias (session_pool's
        # `add = self.host.add_local_input`) must never fire
        src = OK_CLASS + (
            "\n"
            "def hot_loop(s):\n"
            "    step = s.advance\n"
            "    for _ in range(8):\n"
            "        step()\n"
        )
        assert lint_src(tmp_path, src) == []


class TestTreeIsClean:
    def test_repo_ownership_clean(self):
        findings = lint_ownership(REPO)
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_declarations_exist_and_are_live(self):
        """The runtime classes carry the declarations the lint reads,
        and every declared name is a real attribute."""
        for cls in (P2PSession, SpectatorSession, SyncTestSession):
            declared = cls._DRIVING_METHODS
            assert declared, f"{cls.__name__} declares no driving methods"
            for name in declared:
                assert callable(getattr(cls, name)), (cls, name)
        assert ThreadOwned._DRIVING_METHODS == ()


class TestReviewRegressions:
    def test_inheritance_resolution_is_name_order_independent(self, tmp_path):
        """A subclass sorting alphabetically BEFORE its declaring base
        must still inherit the declaration (bases resolve first)."""
        src = """
class ThreadOwned:
    pass

class ZBase(ThreadOwned):
    _DRIVING_METHODS = ("step",)

    def step(self):
        self._check_owner()
        return 1

class ASub(ZBase):
    _DRIVING_METHODS = ("step",)

    def step(self):
        self._check_owner()
        return 2

class AQuiet(ZBase):
    def helper(self):
        return 3
"""
        assert lint_src(tmp_path, src) == []

    def test_thread_target_pragma_suppresses(self, tmp_path):
        src = OK_CLASS + (
            "\n"
            "import threading\n"
            "def spawn(s):\n"
            "    return threading.Thread(target=s.advance)"
            "  # ggrs-verify: allow(own/thread-target)\n"
        )
        assert lint_src(tmp_path, src) == []
