"""Pins for the kernel-batched socket datapath (native/net_batch.cpp +
ggrs_bank_pump, DESIGN.md §15).

The headline pin is WIRE PARITY: with ``native_io=True`` every attached
slot's datagrams flow socket → crossing → socket through recvmmsg/sendmmsg
with zero Python on the packet path — and the full outbound byte sequence
(content AND send order, spectator fan-out included) must be bit-identical
to the per-datagram Python shuttle under seeded loss/dup/reorder inbound
traffic.  The shuttle leg records through a wrapping socket; the batched
leg records through the NetBatch capture tee (a stage-time mirror of the
exact bytes handed to sendmmsg).

Also pinned: native_io adds ZERO extra tick crossings; unattachable
sockets (in-memory, wrapped, kill switch env) fall back to the shuttle
per slot; transient errno storms (ENOBUFS/EAGAIN) are counted as loss
without faulting the slot; a fatal errno faults exactly one slot
(BANK_ERR_IO) and the supervision layer evicts it onto the Python path.
"""

from __future__ import annotations

import errno
import random

import pytest

from ggrs_tpu.core import Local, Remote
from ggrs_tpu.core.config import Config
from ggrs_tpu.net import _native
from ggrs_tpu.net.sockets import InMemoryNetwork, UdpNonBlockingSocket
from ggrs_tpu.parallel.host_bank import HostSessionPool
from ggrs_tpu.sessions import SessionBuilder

needs_native = pytest.mark.skipif(
    _native.bank_lib() is None, reason="native session bank unavailable"
)
needs_io = pytest.mark.skipif(
    _native.net_lib() is None,
    reason="kernel-batched socket datapath unavailable",
)


class RecordingUdpSocket:
    """Wraps a UdpNonBlockingSocket, recording every raw datagram sent —
    the shuttle leg's capture side.  Deliberately exposes no ``fileno``,
    so a native_io pool cannot attach it (also the wrapped-socket
    fallback fixture)."""

    def __init__(self, inner: UdpNonBlockingSocket):
        self.inner = inner
        self.sent = []

    def send_datagram(self, data: bytes, addr) -> None:
        self.sent.append((addr, bytes(data)))
        self.inner.send_datagram(data, addr)

    def send_to(self, msg, addr) -> None:
        self.send_datagram(msg.encode(), addr)

    def receive_all_datagrams(self):
        return self.inner.receive_all_datagrams()

    def receive_all_messages(self):
        return self.inner.receive_all_messages()

    def local_port(self) -> int:
        return self.inner.local_port()


class FaultingUdpSocket:
    """Peer-side socket: real UDP underneath, with InMemoryNetwork-style
    seeded loss/duplication/reordering applied to sends (staged per tick,
    flushed by the driver).  All three rng draws happen unconditionally so
    the fault schedule is a pure function of the send sequence — identical
    across the two parity legs."""

    def __init__(self, inner: UdpNonBlockingSocket, seed: int,
                 loss=0.0, duplicate=0.0, reorder=0.0):
        self.inner = inner
        self._rng = random.Random(seed)
        self.loss, self.duplicate, self.reorder = loss, duplicate, reorder
        self._staged = []

    def send_to(self, msg, addr) -> None:
        payload = msg.encode()
        rng = self._rng
        drop = rng.random() < self.loss
        dup = rng.random() < self.duplicate
        swap = rng.random() < self.reorder
        if drop:
            return
        self._staged.append((addr, payload))
        if dup:
            self._staged.append((addr, payload))
        if swap and len(self._staged) >= 2:
            self._staged[-1], self._staged[-2] = (
                self._staged[-2], self._staged[-1]
            )

    def flush(self) -> None:
        for addr, payload in self._staged:
            self.inner.send_datagram(payload, addr)
        self._staged.clear()

    def receive_all_datagrams(self):
        return self.inner.receive_all_datagrams()

    def receive_all_messages(self):
        return self.inner.receive_all_messages()


def fulfill(requests):
    for r in requests:
        if type(r).__name__ == "SaveGameState":
            r.cell.save(r.frame, None, None)


def _builder(cfg, clock, seed, me, other_addr):
    return (
        SessionBuilder(cfg)
        .with_clock(lambda: clock[0])
        .with_rng(random.Random(seed))
        .add_player(Local(), me)
        .add_player(Remote(other_addr), 1 - me)
    )


def run_udp_leg(native_io: bool, seed: int, ticks: int, n_matches: int,
                faults: dict, n_viewers: int = 0, metrics=None):
    """One parity leg over real loopback UDP: ``n_matches`` host slots in
    the pool (2-player, one out-of-pool peer each, inbound traffic passed
    through a seeded fault stage), optionally ``n_viewers`` real spectator
    sessions per match attached through the hub.  Returns the per-slot
    outbound capture as (role-label, bytes) pairs in exact send order."""
    from ggrs_tpu.core.errors import NotSynchronized, PredictionThreshold

    cfg = Config.for_uint(16)
    clock = [0]
    pool = HostSessionPool(native_io=native_io, metrics=metrics)
    hub = None
    if n_viewers:
        from ggrs_tpu.broadcast import SpectatorHub

        hub = SpectatorHub(pool, rng=random.Random(9000 + seed))
    peers = []
    peer_socks = []
    viewers = []
    host_socks = []
    labels = []  # per match: addr -> role label
    for m in range(n_matches):
        raw = UdpNonBlockingSocket(0)
        host_sock = raw if native_io else RecordingUdpSocket(raw)
        host_port = raw.local_port()
        peer_inner = UdpNonBlockingSocket(0)
        peer_addr = ("127.0.0.1", peer_inner.local_port())
        peer_sock = FaultingUdpSocket(peer_inner, seed * 101 + m, **faults)
        pool.add_session(
            _builder(cfg, clock, 3 + 5 * m, 0, peer_addr), host_sock
        )
        peer = _builder(
            cfg, clock, 4 + 5 * m, 1, ("127.0.0.1", host_port)
        ).start_p2p_session(peer_sock)
        peers.append(peer)
        peer_socks.append(peer_sock)
        host_socks.append(host_sock)
        labels.append({peer_addr: "peer"})
    # viewers attach AFTER every session is registered (attach finalizes
    # the pool) but before the first tick confirms frame 0
    for m in range(n_matches):
        host_port = (
            host_socks[m].local_port()
        )
        for v in range(n_viewers):
            vsock = UdpNonBlockingSocket(0)
            vaddr = ("127.0.0.1", vsock.local_port())
            viewer = (
                SessionBuilder(cfg)
                .with_clock(lambda: clock[0])
                .with_rng(random.Random(7000 + 13 * m + v))
            ).start_spectator_session(
                ("127.0.0.1", host_port), vsock
            )
            viewers.append(viewer)
            labels[m][vaddr] = f"viewer{v}"
            hub.attach(m, vaddr)
    assert pool.native_active, "native bank did not engage"
    if native_io:
        assert pool.native_io_active, "batched datapath did not attach"
        for m in range(n_matches):
            pool._io_set_capture(m)

    sent = [[] for _ in range(n_matches)]

    def sched(i, m):
        return ((i + 2 * m) // (2 + m % 3)) % 16

    for i in range(ticks):
        clock[0] += 16
        for m, peer in enumerate(peers):
            peer.add_local_input(1, sched(i, m))
            fulfill(peer.advance_frame())
            # the peer's faulted sends reach the host before its crossing
            peer_socks[m].flush()
        for m in range(n_matches):
            pool.add_local_input(m, 0, sched(i, m))
        reqs = pool.advance_all()
        for r in reqs:
            fulfill(r)
        for viewer in viewers:
            try:
                fulfill(viewer.advance_frame())
            except (NotSynchronized, PredictionThreshold):
                pass
        if native_io:
            for m in range(n_matches):
                sent[m].extend(pool._io_drain_capture(m))
    if not native_io:
        for m in range(n_matches):
            sent[m] = list(host_socks[m].sent)
    # rewrite addresses (ephemeral ports differ between legs) to roles
    out = []
    for m in range(n_matches):
        out.append([
            (labels[m].get(addr, f"?{addr}"), data) for addr, data in sent[m]
        ])
    return dict(
        sent=out,
        frames=[pool.current_frame(m) for m in range(n_matches)],
        crossings=pool.crossings,
        pool=pool,
        viewers=viewers,
    )


@needs_io
class TestWireParity:
    @pytest.mark.parametrize("seed", [1, 23])
    def test_two_peer_matches_under_faults(self, seed):
        """The headline pin: the batched datapath's full wire byte
        sequence — content and send order — bit-identical to the Python
        shuttle under seeded loss/dup/reorder inbound traffic."""
        faults = dict(loss=0.05, duplicate=0.03, reorder=0.03)
        ticks, n_matches = 200, 3
        a = run_udp_leg(False, seed, ticks, n_matches, faults)
        b = run_udp_leg(True, seed, ticks, n_matches, faults)
        for m in range(n_matches):
            assert a["sent"][m] == b["sent"][m], (
                f"match {m}: wire bytes diverged "
                f"(shuttle {len(a['sent'][m])} datagrams, "
                f"batched {len(b['sent'][m])})"
            )
            assert a["frames"][m] == b["frames"][m]
        assert all(f >= ticks - 64 for f in b["frames"]), (
            "a batched session stalled short of the horizon"
        )

    @pytest.mark.parametrize("faults",
                             [dict(), dict(loss=0.04, duplicate=0.02,
                                           reorder=0.03)])
    def test_both_sides_in_pool_parity(self, faults):
        """One pool hosting BOTH peers of every match (the capacity-bench
        topology): the pump must pre-drain every attached socket before
        any slot flushes, or slot j would see slot i's tick-T datagrams
        one tick early (mid-crossing) and the wire bytes would diverge
        from the shuttle's drain-all-then-cross order."""
        cfg = Config.for_uint(16)
        ticks, n_matches = 150, 2

        def leg(native_io):
            clock = [0]
            pool = HostSessionPool(native_io=native_io)
            raws = []
            socks = []
            for m in range(n_matches):
                raws.extend(UdpNonBlockingSocket(0) for _ in range(2))
            for k, raw in enumerate(raws):
                m, me = divmod(k, 2)
                other = raws[2 * m + (1 - me)].local_port()
                sock = raw if native_io else RecordingUdpSocket(raw)
                socks.append(sock)
                pool.add_session(
                    _builder(cfg, clock, 3 + 7 * m + me, me,
                             ("127.0.0.1", other)),
                    sock,
                )
            assert pool.native_active
            if native_io:
                assert pool.native_io_active
                for i in range(2 * n_matches):
                    pool._io_set_capture(i)
            sent = [[] for _ in range(2 * n_matches)]
            rng = random.Random(99)
            for i in range(ticks):
                # jittered clock steps (seeded identically across legs)
                # drive retry/quality/keep-alive timers through varied
                # phases — the faults dict selects the jitter profile
                clock[0] += 16 if not faults else rng.choice((5, 16, 40))
                for idx in range(2 * n_matches):
                    pool.add_local_input(
                        idx, idx % 2, ((i + idx) // (2 + idx % 3)) % 16
                    )
                for reqs in pool.advance_all():
                    fulfill(reqs)
                if native_io:
                    for idx in range(2 * n_matches):
                        sent[idx].extend(
                            data for _, data in pool._io_drain_capture(idx)
                        )
            if not native_io:
                for idx in range(2 * n_matches):
                    sent[idx] = [data for _, data in socks[idx].sent]
            frames = [pool.current_frame(i) for i in range(2 * n_matches)]
            return sent, frames

        sent_a, frames_a = leg(False)
        sent_b, frames_b = leg(True)
        for idx in range(2 * n_matches):
            assert sent_a[idx] == sent_b[idx], (
                f"slot {idx}: in-pool wire bytes diverged (shuttle "
                f"{len(sent_a[idx])} vs batched {len(sent_b[idx])})"
            )
        assert frames_a == frames_b
        assert all(f >= ticks - 64 for f in frames_b)

    def test_spectator_fanout_parity(self):
        """Fan-out rides the batched path too: per-viewer deferral (the
        one-tick-late flush order) must hold natively, and the captured
        stream — remote and viewer datagrams interleaved — must match the
        shuttle byte-for-byte."""
        faults = dict(loss=0.03, duplicate=0.02, reorder=0.02)
        ticks, n_matches = 150, 2
        a = run_udp_leg(False, 7, ticks, n_matches, faults, n_viewers=2)
        b = run_udp_leg(True, 7, ticks, n_matches, faults, n_viewers=2)
        for m in range(n_matches):
            assert a["sent"][m] == b["sent"][m], (
                f"match {m}: fan-out wire bytes diverged"
            )
        # the viewers actually followed the broadcast on the batched leg
        assert all(v.current_frame > ticks - 80 for v in b["viewers"]), (
            "a viewer stalled on the batched leg"
        )
        # and fan-out datagrams really went through the NetBatch
        st = b["pool"].io_stats()
        assert st["send_datagrams"] > ticks * n_matches

    def test_zero_extra_crossings_and_syscall_shape(self):
        """native_io must not add crossings: exactly one pump crossing per
        tick, and the syscall counters show the batching (≤ a couple of
        recvmmsg/sendmmsg per slot-tick vs one syscall per datagram)."""
        from ggrs_tpu.obs import Registry

        ticks, n_matches = 80, 2
        leg = run_udp_leg(True, 5, ticks, n_matches, dict(),
                          metrics=Registry())
        pool = leg["pool"]
        assert leg["crossings"] == ticks
        st = pool.io_stats()
        assert st["recv_datagrams"] > 0 and st["send_datagrams"] > 0
        # one drain loop + one flush per slot per tick, with slack for
        # multi-batch drains
        assert st["recv_calls"] <= 2 * ticks * n_matches
        assert st["send_calls"] <= 2 * ticks * n_matches
        # the shuttle would have paid ~one syscall per datagram
        assert st["recv_calls"] + st["send_calls"] < (
            st["recv_datagrams"] + st["send_datagrams"]
        )
        # the scrape surfaced the same counters through the registry
        reg = pool.metrics
        assert (reg.value("ggrs_io_syscalls_total", kind="recvmmsg") or 0) \
            == st["recv_calls"]
        assert (reg.value("ggrs_io_datagrams_total", dir="out") or 0) \
            == st["send_datagrams"]


@needs_io
class TestErrnoStorms:
    def _make(self, n_matches=2):
        cfg = Config.for_uint(16)
        clock = [0]
        pool = HostSessionPool(native_io=True)
        peers = []
        for m in range(n_matches):
            host_sock = UdpNonBlockingSocket(0)
            peer_sock = UdpNonBlockingSocket(0)
            peer_addr = ("127.0.0.1", peer_sock.local_port())
            pool.add_session(
                _builder(cfg, clock, 1 + m, 0, peer_addr), host_sock
            )
            peers.append(_builder(
                cfg, clock, 100 + m, 1, ("127.0.0.1", host_sock.local_port())
            ).start_p2p_session(peer_sock))
        assert pool.native_active and pool.native_io_active
        return pool, peers, clock

    def _tick(self, pool, peers, clock, i):
        clock[0] += 16
        for m, peer in enumerate(peers):
            peer.add_local_input(1, (i + m) % 16)
            fulfill(peer.advance_frame())
            pool.add_local_input(m, 0, (i + m) % 16)
        for r in pool.advance_all():
            fulfill(r)

    def test_transient_storm_counts_as_loss(self):
        """An ENOBUFS/EAGAIN storm drops datagrams (counted) but never
        faults the slot — the protocol's redundant sends ride it out."""
        pool, peers, clock = self._make()
        for i in range(30):
            self._tick(pool, peers, clock, i)
        pool.inject_socket_errno(0, errno.ENOBUFS, 10)
        for i in range(30, 45):
            self._tick(pool, peers, clock, i)
        pool.inject_socket_errno(0, errno.EAGAIN, 10)
        for i in range(45, 120):
            self._tick(pool, peers, clock, i)
        assert pool.slot_state(0) == "native", "transient storm faulted slot"
        assert pool.io_state(0) == "native"
        st = pool.io_stats()
        assert st["send_errors"] >= 20
        assert pool.current_frame(0) > 80, "storm stalled the match"
        assert pool.current_frame(1) > 80

    def test_fatal_errno_faults_one_slot_and_evicts(self):
        """A fatal errno (EPERM — the firewall/seccomp class the Python
        path raises on) faults exactly the storm's slot with BANK_ERR_IO;
        supervision evicts it onto the Python socket path and the match
        resumes, while the other slot never leaves the bank."""
        pool, peers, clock = self._make()
        for i in range(30):
            self._tick(pool, peers, clock, i)
        pool.inject_socket_errno(0, errno.EPERM, 1)
        for i in range(30, 90):
            self._tick(pool, peers, clock, i)
        assert pool.slot_state(0) == "evicted"
        assert any(
            f.code == _native.BANK_ERR_IO for f in pool.fault_log(0)
        ), "fault log missing BANK_ERR_IO"
        assert pool.slot_state(1) == "native", "blast radius exceeded 1 slot"
        assert pool.current_frame(1) > 70
        # the evicted slot resumed on the Python path and kept advancing
        assert pool.current_frame(0) > 40
        # eviction detached the batched datapath for that slot...
        assert pool.io_state(0) == "python"
        assert pool.io_state(1) == "native"
        # ...without regressing the io totals: the detached slot's final
        # counter snapshot stays in the aggregate
        st = pool.io_stats()
        assert st["recv_calls"] > 30 and st["send_calls"] > 30


@needs_native
class TestFallback:
    def test_in_memory_sockets_stay_on_shuttle(self):
        """native_io over an InMemoryNetwork: no fd to attach — every slot
        stays on the Python shuttle and the pool behaves exactly as
        before (the native bank itself still engages)."""
        clock = [0]
        net = InMemoryNetwork(latency_ticks=1)
        pool = HostSessionPool(native_io=True)
        names = ("X", "Y")
        cfg = Config.for_uint(16)
        for me in (0, 1):
            b = (
                SessionBuilder(cfg)
                .with_clock(lambda: clock[0])
                .with_rng(random.Random(me))
                .add_player(Local(), me)
                .add_player(Remote(names[1 - me]), 1 - me)
            )
            pool.add_session(b, net.socket(names[me]))
        assert pool.native_active
        assert not pool.native_io_active
        assert pool.io_state(0) == "python"
        for i in range(40):
            clock[0] += 16
            for idx in range(2):
                pool.add_local_input(idx, idx, (i + idx) % 16)
            for reqs in pool.advance_all():
                fulfill(reqs)
            net.tick()
        assert pool.current_frame(0) > 20
        stats = pool.io_stats()
        assert all(stats[k] == 0 for k in _native.IO_STAT_FIELDS)
        # in-memory sockets have no fd: the gen-2 batched drain must not
        # have touched them either
        assert stats["drain"]["datagrams"] == 0

    def test_wrapped_socket_stays_on_shuttle(self):
        """A socket without fileno (any wrapper) is not attachable: the
        slot silently keeps the shuttle — fallback is per slot, never an
        error."""
        if _native.net_lib() is None:
            pytest.skip("io datapath unavailable")
        cfg = Config.for_uint(16)
        clock = [0]
        pool = HostSessionPool(native_io=True)
        host_sock = RecordingUdpSocket(UdpNonBlockingSocket(0))
        peer_sock = UdpNonBlockingSocket(0)
        pool.add_session(
            _builder(cfg, clock, 1, 0,
                     ("127.0.0.1", peer_sock.local_port())),
            host_sock,
        )
        peer = _builder(
            cfg, clock, 2, 1, ("127.0.0.1", host_sock.local_port())
        ).start_p2p_session(peer_sock)
        assert pool.native_active
        assert not pool.native_io_active
        for i in range(40):
            clock[0] += 16
            peer.add_local_input(1, i % 16)
            fulfill(peer.advance_frame())
            pool.add_local_input(0, 0, i % 16)
            for reqs in pool.advance_all():
                fulfill(reqs)
        assert pool.current_frame(0) > 20
        assert len(host_sock.sent) > 0  # sends rode the Python path

    def test_env_kill_switch(self, monkeypatch):
        """GGRS_TPU_NO_NATIVE_IO=1 forces the shuttle even on attachable
        sockets (the recvmmsg-unavailable / operator-override fallback)."""
        monkeypatch.setenv("GGRS_TPU_NO_NATIVE_IO", "1")
        assert _native.net_lib() is None
        cfg = Config.for_uint(16)
        clock = [0]
        pool = HostSessionPool(native_io=True)
        host_sock = UdpNonBlockingSocket(0)
        peer_sock = UdpNonBlockingSocket(0)
        pool.add_session(
            _builder(cfg, clock, 1, 0,
                     ("127.0.0.1", peer_sock.local_port())),
            host_sock,
        )
        peer = _builder(
            cfg, clock, 2, 1, ("127.0.0.1", host_sock.local_port())
        ).start_p2p_session(peer_sock)
        assert pool.native_active
        assert not pool.native_io_active
        for i in range(30):
            clock[0] += 16
            peer.add_local_input(1, i % 16)
            fulfill(peer.advance_frame())
            pool.add_local_input(0, 0, i % 16)
            for reqs in pool.advance_all():
                fulfill(reqs)
        assert pool.current_frame(0) > 15
