"""Speculative branch execution (vmap) and batched sessions (shard_map).

Runs on the virtual 8-device CPU mesh set up in conftest.py."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ggrs_tpu.games import BoxGame
from ggrs_tpu.parallel import (
    BatchedSessions,
    build_speculation_programs,
    make_mesh,
    make_mesh2d,
)


def _random_inputs(shape, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 16, size=shape).astype(np.uint8)


class TestSpeculation:
    def setup_method(self):
        self.game = BoxGame(2)
        self.spec = build_speculation_programs(self.game.advance, num_branches=4)

    def _branch_inputs(self, w, seed=0):
        """[K, W, P] input windows; branch 2 will be 'correct'."""
        inputs = _random_inputs((4, w, 2), seed=seed)
        return jnp.asarray(inputs)

    def test_matching_branch_selected(self):
        w = 6
        base = self.game.init_state()
        inputs_kw = self._branch_inputs(w, seed=3)
        branches = self.spec.speculate_window(base, inputs_kw)
        confirmed = inputs_kw[2]  # branch 2 guessed right
        state, idx, found = self.spec.resolve(branches, inputs_kw, confirmed)
        assert bool(found)
        assert int(idx) == 2
        # selected state equals a plain replay under the confirmed inputs
        replayed = self.spec.replay_window(base, confirmed)
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(
                np.asarray(state[k]), np.asarray(replayed[k])
            )

    def test_no_match_reports_not_found(self):
        w = 4
        base = self.game.init_state()
        inputs_kw = self._branch_inputs(w, seed=5)
        branches = self.spec.speculate_window(base, inputs_kw)
        confirmed = jnp.full((w, 2), 255, jnp.uint8)  # matches no branch
        _, _, found = self.spec.resolve(branches, inputs_kw, confirmed)
        assert not bool(found)

    def test_branches_diverge(self):
        w = 8
        base = self.game.init_state()
        inputs_kw = self._branch_inputs(w, seed=7)
        branches = self.spec.speculate_window(base, inputs_kw)
        pos = np.asarray(branches["pos"])  # [K, P, 2]
        assert not np.array_equal(pos[0], pos[1])

    def test_collapse_picks_branch(self):
        base = self.game.init_state()
        inputs_kw = self._branch_inputs(3, seed=9)
        branches = self.spec.speculate_window(base, inputs_kw)
        picked = self.spec.collapse(branches, jnp.int32(1))
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(
                np.asarray(picked[k]), np.asarray(branches[k])[1]
            )


class TestBatchedSessions:
    def test_virtual_mesh_has_8_devices(self):
        assert len(jax.devices()) == 8

    def test_batched_matches_single_session(self):
        game = BoxGame(2)
        mesh = make_mesh(8)
        B, n = 16, 30
        batch = BatchedSessions(
            game.advance,
            game.init_state(),
            jnp.zeros((2,), jnp.uint8),
            batch_size=B,
            mesh=mesh,
            check_distance=2,
        )
        inputs = _random_inputs((B, n, 2), seed=11)
        stats = batch.run_ticks(inputs)
        assert stats["mismatches"] == 0
        assert batch.current_frame == n

        # session 5 must equal an independent forward NumPy simulation
        live = batch.live_states()
        s_np = game.init_state_np()
        for i in range(n):
            s_np = game.advance_np(s_np, inputs[5, i])
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(np.asarray(live[k])[5], s_np[k])

    def test_uneven_batch_rejected(self):
        game = BoxGame(2)
        with pytest.raises(AssertionError):
            BatchedSessions(
                game.advance,
                game.init_state(),
                jnp.zeros((2,), jnp.uint8),
                batch_size=9,
                mesh=make_mesh(8),
            )

    def test_2d_host_mesh_matches_1d_mesh_bitwise(self):
        """The multi-host shape: a (2 hosts × 4 chips) mesh must produce
        bit-identical states and the same global stats as the flat 8-chip
        mesh — moving to multi-host is a mesh swap, not a program change."""
        game = BoxGame(2)
        B, n = 16, 24
        inputs = _random_inputs((B, n, 2), seed=23)
        results = []
        for mesh in (make_mesh(8), make_mesh2d(2, 4)):
            batch = BatchedSessions(
                game.advance,
                game.init_state(),
                jnp.zeros((2,), jnp.uint8),
                batch_size=B,
                mesh=mesh,
                check_distance=2,
            )
            stats = batch.run_ticks(inputs)
            assert stats["mismatches"] == 0
            results.append(batch.live_states())
        flat, two_d = results
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(
                np.asarray(flat[k]), np.asarray(two_d[k]), err_msg=k
            )

    def test_distributed_mesh_single_process_degenerate_form(self):
        """make_distributed_mesh on one process: a (1, n_devices) mesh
        running the identical program — the virtual-mesh gate for the
        multi-host launch recipe (its two-host form differs only in
        jax.distributed initialization, documented in its docstring)."""
        from ggrs_tpu.parallel import make_distributed_mesh

        mesh = make_distributed_mesh()
        assert mesh.devices.shape == (1, len(jax.devices()))
        assert mesh.axis_names == ("hosts", "sessions")

        game = BoxGame(2)
        B, n = 16, 12
        inputs = _random_inputs((B, n, 2), seed=5)
        results = []
        for m in (make_mesh(8), mesh):
            batch = BatchedSessions(
                game.advance, game.init_state(), jnp.zeros((2,), jnp.uint8),
                batch_size=B, mesh=m, check_distance=2,
            )
            stats = batch.run_ticks(inputs)
            assert stats["mismatches"] == 0
            results.append(batch.live_states())
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(
                np.asarray(results[0][k]), np.asarray(results[1][k])
            )

    def test_2d_mesh_detects_corruption_across_hosts(self):
        """The psum/pmin health reduction must cross BOTH mesh axes: corrupt
        a session owned by the second host row and read the global stats."""
        game = BoxGame(2)
        B = 16
        batch = BatchedSessions(
            game.advance,
            game.init_state(),
            jnp.zeros((2,), jnp.uint8),
            batch_size=B,
            mesh=make_mesh2d(2, 4),
            check_distance=2,
        )
        batch.run_ticks(_random_inputs((B, 10, 2), seed=3))
        ring_len = batch._programs.ring.length
        slot = 8 % ring_len
        states = batch._carry["ring"]["states"]
        # session 12 lives in the second host row (sessions are host-major)
        states["pos"] = states["pos"].at[12, slot, 0, 0].add(1)
        stats = batch.run_ticks(_random_inputs((B, 5, 2), seed=4))
        assert stats["mismatches"] >= 1
        assert stats["first_bad"] == 9

    def test_corruption_in_one_session_detected_globally(self):
        game = BoxGame(2)
        B = 8
        batch = BatchedSessions(
            game.advance,
            game.init_state(),
            jnp.zeros((2,), jnp.uint8),
            batch_size=B,
            mesh=make_mesh(8),
            check_distance=2,
        )
        batch.run_ticks(_random_inputs((B, 10, 2), seed=1))
        # corrupt session 3's saved frame-8 slot (loaded by the next tick)
        ring_len = batch._programs.ring.length
        slot = 8 % ring_len
        states = batch._carry["ring"]["states"]
        states["pos"] = states["pos"].at[3, slot, 0, 0].add(1)
        stats = batch.run_ticks(_random_inputs((B, 5, 2), seed=2))
        assert stats["mismatches"] >= 1
        assert stats["first_bad"] == 9
