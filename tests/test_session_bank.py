"""Parity pin for the native session bank (native/session_bank.cpp via
parallel/host_bank.py): the pooled one-crossing-per-tick path must be
indistinguishable — bit-identical wire bytes, frames, request lists, and
events — from B independent Python sessions driven with identical seeded
traffic, including loss/duplication/reordering.  Mirrors the role
tests/test_native_sync.py and tests/test_native_endpoint.py play one layer
down.

Also pinned here: the one-crossing-per-tick invariant (a crossing-count
test), the Python fallback's identical behavior when the native bank is
unavailable, and the bank's disconnect handling.
"""

from __future__ import annotations

import random

import pytest

from ggrs_tpu.core import Local, Remote
from ggrs_tpu.core.config import Config
from ggrs_tpu.core.types import Disconnected, NetworkInterrupted
from ggrs_tpu.net import InMemoryNetwork, _native
from ggrs_tpu.parallel.host_bank import HostSessionPool
from ggrs_tpu.sessions import SessionBuilder

needs_native = pytest.mark.skipif(
    _native.bank_lib() is None, reason="native session bank unavailable"
)


class RecordingSocket:
    """Wraps a FakeSocket, recording every (addr, wire bytes) sent."""

    def __init__(self, inner):
        self.inner = inner
        self.sent = []

    def send_to(self, msg, addr):
        self.sent.append((addr, msg.encode()))
        self.inner.send_to(msg, addr)

    def receive_all_datagrams(self):
        return self.inner.receive_all_datagrams()

    def receive_all_messages(self):
        return self.inner.receive_all_messages()


def two_peer_builders(net, clock, n_matches, input_delay=0, bits=16):
    """2·n_matches sessions (n_matches 2-peer matches) over ``net``; the
    SAME construction for the bank and the reference sessions."""
    out = []
    for m in range(n_matches):
        names = (f"A{m}", f"B{m}")
        for me in (0, 1):
            b = (
                SessionBuilder(Config.for_uint(bits))
                .with_clock(lambda: clock[0])
                .with_rng(random.Random(3 + 5 * m + me))
                .with_input_delay(input_delay)
                .add_player(Local(), me)
                .add_player(Remote(names[1 - me]), 1 - me)
            )
            out.append((b, RecordingSocket(net.socket(names[me]))))
    return out


def four_peer_builders(net, clock):
    """One 4-peer match: 4 sessions, 3 remote endpoints each."""
    names = [f"N{h}" for h in range(4)]
    out = []
    for h in range(4):
        b = (
            SessionBuilder(Config.for_uint(16))
            .with_num_players(4)
            .with_clock(lambda: clock[0])
            .with_rng(random.Random(40 + h))
        )
        for o in range(4):
            b = b.add_player(Local() if o == h else Remote(names[o]), o)
        out.append((b, RecordingSocket(net.socket(names[h]))))
    return out


def fulfill_saves(requests):
    for r in requests:
        if type(r).__name__ == "SaveGameState":
            r.cell.save(r.frame, None, None)


def assert_requests_equal(py_reqs, bank_reqs, context):
    assert len(py_reqs) == len(bank_reqs), (
        f"{context}: request count {len(py_reqs)} != {len(bank_reqs)}"
    )
    for a, b in zip(py_reqs, bank_reqs):
        assert type(a).__name__ == type(b).__name__, (context, py_reqs, bank_reqs)
        if type(a).__name__ == "AdvanceFrame":
            assert a.inputs == b.inputs, (context, a.inputs, b.inputs)
        else:
            assert a.frame == b.frame, (context, a.frame, b.frame)


def run_parity(builders_fn, faults, ticks, local_of, sched):
    """Drive the bank and the per-session Python reference with identical
    traffic on identically-seeded fault networks; compare everything."""
    clock = [0]
    net_bank = InMemoryNetwork(**faults)
    net_py = InMemoryNetwork(**faults)
    bank_builders = builders_fn(net_bank, clock)
    py_builders = builders_fn(net_py, clock)

    pool = HostSessionPool()
    for b, s in bank_builders:
        pool.add_session(b, s)
    py_sessions = [b.start_p2p_session(s) for b, s in py_builders]
    assert pool.native_active, "native bank did not engage"

    n = len(py_sessions)
    for i in range(ticks):
        clock[0] += 16
        for idx in range(n):
            py_sessions[idx].add_local_input(local_of(idx), sched(i, idx))
            pool.add_local_input(idx, local_of(idx), sched(i, idx))
        py_reqs = []
        for s in py_sessions:
            r = s.advance_frame()
            fulfill_saves(r)
            py_reqs.append(r)
        bank_reqs = pool.advance_all()
        for r in bank_reqs:
            fulfill_saves(r)
        net_bank.tick()
        net_py.tick()
        for idx in range(n):
            ps = py_builders[idx][1].sent
            bs = bank_builders[idx][1].sent
            assert ps == bs, (
                f"tick {i} session {idx}: wire bytes diverged "
                f"(py {len(ps)} datagrams, bank {len(bs)})"
            )
            assert_requests_equal(
                py_reqs[idx], bank_reqs[idx], f"tick {i} session {idx}"
            )
            assert py_sessions[idx].events() == pool.events(idx), (
                f"tick {i} session {idx}: events diverged"
            )
            assert py_sessions[idx].current_frame == pool.current_frame(idx)
            assert (
                py_sessions[idx]._sync_layer.last_confirmed_frame
                == pool.last_confirmed_frame(idx)
            )
    assert all(pool.current_frame(i) >= ticks - 64 for i in range(n)), (
        "a pooled session stalled short of the horizon"
    )
    return pool


@needs_native
class TestCrossCoreParityFuzz:
    @pytest.mark.parametrize("seed", [1, 7, 23])
    def test_two_peer_matches_under_faults(self, seed):
        """The headline pin: 3 matches, seeded loss/dup/reorder, 300 ticks,
        bit-identical wire bytes / requests / events / frames."""
        run_parity(
            lambda net, clock: two_peer_builders(net, clock, n_matches=3),
            dict(seed=seed, loss=0.05, duplicate=0.03, reorder=0.03,
                 latency_ticks=1),
            ticks=300,
            local_of=lambda idx: idx % 2,
            sched=lambda i, idx: ((i + 2 * idx) // (2 + idx % 3)) % 16,
        )

    def test_two_peer_matches_faultless(self):
        run_parity(
            lambda net, clock: two_peer_builders(net, clock, n_matches=2),
            dict(latency_ticks=1),
            ticks=200,
            local_of=lambda idx: idx % 2,
            sched=lambda i, idx: ((i + idx) // 2) % 16,
        )

    def test_four_peer_match_under_faults(self):
        """Multi-endpoint sessions: 4 peers, 3 endpoints each."""
        run_parity(
            four_peer_builders,
            dict(seed=99, loss=0.04, duplicate=0.02, reorder=0.04,
                 latency_ticks=1),
            ticks=250,
            local_of=lambda idx: idx,
            sched=lambda i, idx: ((i * 7 + idx) // 3) % 16,
        )

    def test_input_delay(self):
        run_parity(
            lambda net, clock: two_peer_builders(
                net, clock, n_matches=2, input_delay=2
            ),
            dict(seed=5, loss=0.03, duplicate=0.02, reorder=0.02,
                 latency_ticks=1),
            ticks=200,
            local_of=lambda idx: idx % 2,
            sched=lambda i, idx: ((i + idx) // (2 + idx % 2)) % 16,
        )

    def test_blackout_exercises_retry_and_interrupt_timers(self):
        """A 60-tick total blackout mid-run: the 200 ms retry timer
        resends the pending window, prediction-threshold skips stall both
        paths identically, NetworkInterrupted fires at 500 ms of silence,
        NetworkResumed on the first packet after restore — all bit-identical
        (the steady-traffic fuzz never reaches these timers)."""
        clock = [0]
        net_bank = InMemoryNetwork(latency_ticks=1)
        net_py = InMemoryNetwork(latency_ticks=1)
        bank_builders = two_peer_builders(net_bank, clock, n_matches=2)
        py_builders = two_peer_builders(net_py, clock, n_matches=2)
        pool = HostSessionPool()
        for b, s in bank_builders:
            pool.add_session(b, s)
        py_sessions = [b.start_p2p_session(s) for b, s in py_builders]
        assert pool.native_active

        n = len(py_sessions)
        interrupted = resumed = 0
        for i in range(260):
            clock[0] += 16
            if i == 100:
                net_bank.loss = net_py.loss = 1.0
            if i == 160:
                net_bank.loss = net_py.loss = 0.0
            for idx in range(n):
                py_sessions[idx].add_local_input(idx % 2, (i + idx) % 16)
                pool.add_local_input(idx, idx % 2, (i + idx) % 16)
            py_reqs = []
            for s in py_sessions:
                r = s.advance_frame()
                fulfill_saves(r)
                py_reqs.append(r)
            bank_reqs = pool.advance_all()
            for r in bank_reqs:
                fulfill_saves(r)
            net_bank.tick()
            net_py.tick()
            for idx in range(n):
                assert (
                    py_builders[idx][1].sent == bank_builders[idx][1].sent
                ), f"tick {i} session {idx}: wire divergence"
                assert_requests_equal(
                    py_reqs[idx], bank_reqs[idx], f"tick {i} s{idx}"
                )
                pe = py_sessions[idx].events()
                assert pe == pool.events(idx), f"tick {i} s{idx} events"
                interrupted += sum(
                    isinstance(e, NetworkInterrupted) for e in pe
                )
                resumed += sum(
                    type(e).__name__ == "NetworkResumed" for e in pe
                )
        assert interrupted >= n, "blackout never tripped the interrupt timer"
        assert resumed >= n, "recovery never emitted NetworkResumed"
        assert all(pool.current_frame(i) >= 150 for i in range(n))


@needs_native
class TestOneCrossingPerTick:
    def test_crossing_count_is_exactly_ticks(self):
        """THE tentpole invariant: B sessions' whole protocol + sync
        mechanism steps in exactly ONE ctypes crossing per pool tick."""
        clock = [0]
        net = InMemoryNetwork(latency_ticks=1)
        pool = HostSessionPool()
        for b, s in two_peer_builders(net, clock, n_matches=4):
            pool.add_session(b, s)
        assert pool.native_active
        TICKS = 50
        for i in range(TICKS):
            clock[0] += 16
            for idx in range(len(pool)):
                pool.add_local_input(idx, idx % 2, (i + idx) % 16)
            for reqs in pool.advance_all():
                fulfill_saves(reqs)
            net.tick()
        assert pool.crossings == TICKS

    def test_scrapes_add_zero_tick_crossings(self):
        """The obs budget (DESIGN.md §12): a metrics scrape per pool tick
        costs exactly one SEPARATE ``ggrs_bank_stats`` crossing for the
        whole bank — the tick crossing count is untouched, repeat scrapes
        and ``network_stats`` reads within a tick hit the cache."""
        from ggrs_tpu.core.errors import StatsUnavailable
        from ggrs_tpu.obs import Registry

        clock = [0]
        net = InMemoryNetwork(latency_ticks=1)
        pool = HostSessionPool(metrics=Registry())
        for b, s in two_peer_builders(net, clock, n_matches=4):
            pool.add_session(b, s)
        assert pool.native_active
        TICKS = 50
        for i in range(TICKS):
            clock[0] += 16
            for idx in range(len(pool)):
                pool.add_local_input(idx, idx % 2, (i + idx) % 16)
            for reqs in pool.advance_all():
                fulfill_saves(reqs)
            pool.scrape()            # one stats crossing...
            pool.scrape()            # ...and the repeat is cached
            if i % 5 == 0:
                try:
                    pool.network_stats(0, 1)  # rides the same cache
                except StatsUnavailable:
                    pass  # under a second of elapsed clock (parity raise)
            net.tick()
        assert pool.crossings == TICKS, "scraping perturbed the tick path"
        assert pool.stat_crossings == TICKS
        assert pool.metrics.value("ggrs_pool_ticks_total") == TICKS
        assert pool.metrics.value(
            "ggrs_pool_crossings_total", kind="stats"
        ) == TICKS


class TestFallback:
    def test_fallback_behaves_like_plain_sessions(self, monkeypatch):
        """With the native bank unavailable the pool must drive ordinary
        P2PSessions — same wire bytes, frames, and requests as using
        P2PSession directly."""
        monkeypatch.setattr(_native, "bank_lib", lambda: None)
        clock = [0]
        faults = dict(seed=11, loss=0.05, duplicate=0.03, reorder=0.03,
                      latency_ticks=1)
        net_pool = InMemoryNetwork(**faults)
        net_ref = InMemoryNetwork(**faults)
        pool_builders = two_peer_builders(net_pool, clock, n_matches=2)
        ref_builders = two_peer_builders(net_ref, clock, n_matches=2)

        pool = HostSessionPool()
        for b, s in pool_builders:
            pool.add_session(b, s)
        refs = [b.start_p2p_session(s) for b, s in ref_builders]
        assert not pool.native_active
        assert pool.crossings == 0

        for i in range(150):
            clock[0] += 16
            for idx in range(len(refs)):
                refs[idx].add_local_input(idx % 2, (i + idx) % 16)
                pool.add_local_input(idx, idx % 2, (i + idx) % 16)
            ref_reqs = []
            for s in refs:
                r = s.advance_frame()
                fulfill_saves(r)
                ref_reqs.append(r)
            pool_reqs = pool.advance_all()
            for r in pool_reqs:
                fulfill_saves(r)
            net_pool.tick()
            net_ref.tick()
            for idx in range(len(refs)):
                assert (
                    ref_builders[idx][1].sent == pool_builders[idx][1].sent
                ), f"tick {i} session {idx}: fallback wire divergence"
                assert_requests_equal(
                    ref_reqs[idx], pool_reqs[idx], f"tick {i} s{idx}"
                )
                assert refs[idx].events() == pool.events(idx)
                assert refs[idx].current_frame == pool.current_frame(idx)
        assert pool.crossings == 0  # no native crossings on the fallback

    def test_ineligible_shapes_fall_back(self):
        """Session shapes outside the bank's mechanism must use the Python
        sessions even when the native library is present."""
        from ggrs_tpu.core.types import DesyncDetection

        def make(builder_tweak):
            clock = [0]
            net = InMemoryNetwork()
            pool = HostSessionPool()
            names = ("X", "Y")
            for me in (0, 1):
                b = (
                    SessionBuilder(Config.for_uint(16))
                    .with_clock(lambda: clock[0])
                    .with_rng(random.Random(me))
                    .add_player(Local(), me)
                    .add_player(Remote(names[1 - me]), 1 - me)
                )
                b = builder_tweak(b)
                pool.add_session(b, net.socket(names[me]))
            return pool

        assert not make(lambda b: b.with_sparse_saving_mode(True)).native_active
        assert not make(lambda b: b.with_max_prediction_window(0)).native_active
        assert not make(
            lambda b: b.with_desync_detection_mode(DesyncDetection.on(100))
        ).native_active
        assert not make(lambda b: b.with_sync_handshake(True)).native_active

    def test_empty_pool_is_a_noop(self):
        pool = HostSessionPool()
        assert not pool.native_active
        assert pool.advance_all() == []

    def test_observables_readable_before_first_tick(self, monkeypatch):
        """A P2PSession's state is readable right after construction; the
        pool's accessors must finalize lazily rather than crash (both
        paths)."""
        for native in (False, True):
            if not native:
                monkeypatch.setattr(_native, "bank_lib", lambda: None)
            net = InMemoryNetwork()
            pool = HostSessionPool()
            names = ("X", "Y")
            for me in (0, 1):
                b = (
                    SessionBuilder(Config.for_uint(16))
                    .with_clock(lambda: 0)
                    .with_rng(random.Random(me))
                    .add_player(Local(), me)
                    .add_player(Remote(names[1 - me]), 1 - me)
                )
                pool.add_session(b, net.socket(names[me]))
            assert pool.current_frame(0) == 0
            assert pool.last_confirmed_frame(1) == -1
            assert pool.frames_ahead(0) == 0
            assert pool.events(0) == []
            monkeypatch.undo()

    def test_mixed_timebases_fall_back(self):
        """A frozen test clock pooled with a real-time clock cannot share
        the bank's single per-tick clock read: per-session fallback."""
        from ggrs_tpu.net.protocol import monotonic_ms

        net = InMemoryNetwork()
        pool = HostSessionPool()
        names = ("X", "Y")
        clocks = (lambda: 0, monotonic_ms)
        for me in (0, 1):
            b = (
                SessionBuilder(Config.for_uint(16))
                .with_clock(clocks[me])
                .with_rng(random.Random(me))
                .add_player(Local(), me)
                .add_player(Remote(names[1 - me]), 1 - me)
            )
            pool.add_session(b, net.socket(names[me]))
        assert not pool.native_active

    def test_variable_size_inputs_fall_back(self):
        clock = [0]
        net = InMemoryNetwork()
        pool = HostSessionPool()
        names = ("X", "Y")
        for me in (0, 1):
            b = (
                SessionBuilder(Config.for_bytes())
                .with_clock(lambda: clock[0])
                .with_rng(random.Random(me))
                .add_player(Local(), me)
                .add_player(Remote(names[1 - me]), 1 - me)
            )
            pool.add_session(b, net.socket(names[me]))
        assert not pool.native_active
        # and it actually runs
        for i in range(20):
            clock[0] += 16
            pool.add_local_input(0, 0, bytes([i % 7]))
            pool.add_local_input(1, 1, bytes([i % 5, 1]))
            for reqs in pool.advance_all():
                fulfill_saves(reqs)
        assert pool.current_frame(0) > 10


class TestHostedPool:
    def test_bank_feeds_batched_executor(self):
        """The full two-crossings-per-tick stack: HostSessionPool request
        lists straight into a BatchedRequestExecutor, states advancing and
        matching a per-session NumPy replay of the same inputs."""
        import numpy as np

        from ggrs_tpu.games import BoxGame, boxgame_config
        from ggrs_tpu.parallel import BatchedRequestExecutor, HostedPool

        game = BoxGame(2)
        clock = [0]
        net = InMemoryNetwork(latency_ticks=1)
        host = HostSessionPool()
        n_matches = 3
        for m in range(n_matches):
            names = (f"A{m}", f"B{m}")
            for me in (0, 1):
                b = (
                    SessionBuilder(boxgame_config())
                    .with_clock(lambda: clock[0])
                    .with_rng(random.Random(7 * m + me))
                    .add_player(Local(), me)
                    .add_player(Remote(names[1 - me]), 1 - me)
                )
                host.add_session(b, net.socket(names[me]))

        executor = BatchedRequestExecutor(
            game.advance, game.init_state(),
            lambda pairs: np.asarray([p[0] for p in pairs], np.uint8),
            batch_size=len(host), ring_length=10, max_burst=9,
            with_checksums=False,
        )
        executor.warmup(np.zeros((2,), np.uint8))
        hosted = HostedPool(host, executor)

        def sched(i, idx):
            return ((i + idx) // (2 + idx % 3)) % 16

        TICKS = 60
        for i in range(TICKS):
            clock[0] += 16
            hosted.tick([
                (idx, idx % 2, sched(i, idx)) for idx in range(len(host))
            ])
            net.tick()
        hosted.block_until_ready()
        for idx in range(len(host)):
            assert host.current_frame(idx) >= TICKS - 16
        # every session's live device state exists and has the right shape
        st = executor.live_state(0)
        assert set(st) == set(game.init_state_np())

    def test_size_mismatch_refused(self):
        from ggrs_tpu.games import BoxGame, boxgame_config
        from ggrs_tpu.parallel import BatchedRequestExecutor, HostedPool
        import numpy as np

        game = BoxGame(2)
        host = HostSessionPool()
        net = InMemoryNetwork()
        b = (
            SessionBuilder(boxgame_config())
            .with_rng(random.Random(0))
            .add_player(Local(), 0)
            .add_player(Remote("peer"), 1)
        )
        host.add_session(b, net.socket("me"))
        executor = BatchedRequestExecutor(
            game.advance, game.init_state(),
            lambda pairs: np.asarray([p[0] for p in pairs], np.uint8),
            batch_size=4, ring_length=10, max_burst=9,
        )
        with pytest.raises(ValueError):
            HostedPool(host, executor)


@needs_native
class TestOutputBufferGrowth:
    def test_undersized_buffer_recovers_without_poisoning(self):
        """kErrBufferTooSmall is a grow-and-fetch, not a poisoned pool: the
        tick's output is retained natively (a stalled peer's whole-window
        retransmit volley must not kill all B matches)."""
        import ctypes

        clock = [0]
        net = InMemoryNetwork(latency_ticks=1)
        pool = HostSessionPool()
        for b, s in two_peer_builders(net, clock, n_matches=2):
            pool.add_session(b, s)
        assert pool.native_active

        def tick(i):
            clock[0] += 16
            for idx in range(len(pool)):
                pool.add_local_input(idx, idx % 2, (i + idx) % 16)
            out = pool.advance_all()
            for reqs in out:
                fulfill_saves(reqs)
            net.tick()
            return out

        for i in range(10):
            tick(i)
        # sabotage: shrink the output buffer below any tick's record size
        pool._out_buf = ctypes.create_string_buffer(8)
        out = tick(10)  # grow-and-fetch path
        assert len(out) == len(pool)
        assert len(pool._out_buf) > 8
        for i in range(11, 30):
            tick(i)  # and the pool keeps running, not poisoned
        assert all(pool.current_frame(i) >= 20 for i in range(len(pool)))


@needs_native
class TestDisconnect:
    def test_silent_peer_disconnects_and_session_continues(self):
        """A peer that goes silent: NetworkInterrupted then Disconnected
        fire from the bank's timers, the disconnect rollback erases its
        predictions, and the session keeps advancing on dummy inputs.
        (Reactions apply one pool tick late on the native path — a
        documented divergence — so this asserts behavior, not bit parity.)
        """
        clock = [0]
        net = InMemoryNetwork(latency_ticks=1)
        pool = HostSessionPool()
        names = ("L", "R")
        b = (
            SessionBuilder(Config.for_uint(16))
            .with_clock(lambda: clock[0])
            .with_rng(random.Random(1))
            .with_disconnect_timeout(400)
            .with_disconnect_notify_delay(100)
            .add_player(Local(), 0)
            .add_player(Remote(names[1]), 1)
        )
        pool.add_session(b, net.socket(names[0]))
        assert pool.native_active

        peer_b = (
            SessionBuilder(Config.for_uint(16))
            .with_clock(lambda: clock[0])
            .with_rng(random.Random(2))
            .with_disconnect_timeout(400)
            .with_disconnect_notify_delay(100)
            .add_player(Local(), 1)
            .add_player(Remote(names[0]), 0)
        )
        peer = peer_b.start_p2p_session(net.socket(names[1]))

        events = []
        state = [0]

        def tick(i, drive_peer):
            clock[0] += 16
            if drive_peer:
                peer.add_local_input(1, i % 16)
                fulfill_saves(peer.advance_frame())
            pool.add_local_input(0, 0, i % 16)
            for reqs in pool.advance_all():
                fulfill_saves(reqs)
            events.extend(pool.events(0))
            net.tick()

        for i in range(40):
            tick(i, drive_peer=True)
        frame_at_silence = pool.current_frame(0)
        for i in range(40, 120):
            tick(i, drive_peer=False)

        kinds = [type(e).__name__ for e in events]
        assert "NetworkInterrupted" in kinds
        assert "Disconnected" in kinds
        # after the disconnect the session runs free on dummy inputs
        assert pool.current_frame(0) > frame_at_silence + 40
