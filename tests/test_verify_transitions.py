"""ggrs-model's static half: the transition-conformance lint.

Golden fixtures for each model/* rule (firing and non-firing) over a
toy machine spec, plus the self-clean gate: every setter site in the
live fleet layer performs an edge of its declared table.
"""

from pathlib import Path

from ggrs_tpu.analysis import MACHINE_SPECS, lint_transitions
from ggrs_tpu.analysis.conformance import (
    MachineSpec,
    parse_transition_table,
)

REPO = Path(__file__).resolve().parents[1]

SPEC = MachineSpec(
    name="toy",
    table_path="pkg/mod.py",
    table_name="TOY_TRANSITIONS",
    prefix="TOY_",
    setter_kind="attr",
    setter_name="state",
    dst_arg=0,
    scan=("pkg/mod.py",),
)

HEADER = '''
TOY_IDLE = "idle"
TOY_BUSY = "busy"
TOY_TRANSITIONS = (
    (TOY_IDLE, TOY_BUSY),
    (TOY_BUSY, TOY_IDLE),
)
'''


def lint_src(tmp_path, body: str, header: str = HEADER):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(header + body)
    return lint_transitions(tmp_path, specs=(SPEC,))


def rules_of(findings):
    return [f.rule for f in findings]


class TestTableParsing:
    def test_missing_file(self, tmp_path):
        findings = lint_transitions(tmp_path, specs=(SPEC,))
        assert rules_of(findings) == ["model/table-missing"]

    def test_missing_table(self, tmp_path):
        findings = lint_src(tmp_path, "", header='TOY_IDLE = "idle"\n')
        assert rules_of(findings) == ["model/table-missing"]

    def test_table_entry_with_undeclared_constant(self, tmp_path):
        bad = HEADER.replace("(TOY_BUSY, TOY_IDLE),",
                             "(TOY_BUSY, TOY_GONE),")
        findings = lint_src(tmp_path, "", header=bad)
        assert "model/unknown-state" in rules_of(findings)

    def test_parse_live_tables(self):
        for spec in MACHINE_SPECS:
            table, findings = parse_transition_table(REPO, spec)
            assert findings == [], (spec.name, findings)
            assert table is not None and len(table.edges) >= 4


class TestSiteResolution:
    def test_pragma_site_on_declared_edge_is_clean(self, tmp_path):
        assert lint_src(tmp_path, '''
class Toy:
    def go(self):
        # ggrs-model: transitions(idle->busy)
        self.state = TOY_BUSY
''') == []

    def test_pragma_declaring_unlisted_edge_fires(self, tmp_path):
        findings = lint_src(tmp_path, '''
class Toy:
    def go(self):
        # ggrs-model: transitions(busy->busy2)
        self.state = TOY_BUSY
''')
        assert "model/unknown-state" in rules_of(findings)

    def test_pragma_dst_mismatch_fires(self, tmp_path):
        findings = lint_src(tmp_path, '''
class Toy:
    def go(self):
        # ggrs-model: transitions(idle->busy)
        self.state = TOY_IDLE
''')
        assert rules_of(findings) == ["model/transition-unlisted"]

    def test_guard_inference_clean(self, tmp_path):
        assert lint_src(tmp_path, '''
class Toy:
    def go(self):
        if self.state == TOY_IDLE:
            self.state = TOY_BUSY
''') == []

    def test_guard_inference_unlisted_edge_fires(self, tmp_path):
        findings = lint_src(tmp_path, '''
TOY_DEAD = "dead"

class Toy:
    def go(self):
        if self.state == TOY_IDLE:
            self.state = TOY_DEAD
''')
        assert rules_of(findings) == ["model/transition-unlisted"]

    def test_else_branch_never_infers(self, tmp_path):
        # inferring idle from the ELSE of `== TOY_IDLE` would invert the
        # guard; the site must be undeclared instead
        findings = lint_src(tmp_path, '''
class Toy:
    def go(self):
        if self.state == TOY_IDLE:
            pass
        else:
            self.state = TOY_BUSY
''')
        assert rules_of(findings) == ["model/transition-undeclared"]

    def test_bare_site_is_undeclared(self, tmp_path):
        findings = lint_src(tmp_path, '''
class Toy:
    def go(self):
        self.state = TOY_BUSY
''')
        assert rules_of(findings) == ["model/transition-undeclared"]

    def test_init_sites_are_exempt(self, tmp_path):
        assert lint_src(tmp_path, '''
class Toy:
    def __init__(self):
        self.state = TOY_IDLE
''') == []

    def test_reflexive_pragma_edge_is_fine(self, tmp_path):
        assert lint_src(tmp_path, '''
class Toy:
    def refresh(self):
        # ggrs-model: transitions(busy->busy)
        self.state = TOY_BUSY
''') == []

    def test_allow_pragma_suppresses(self, tmp_path):
        assert lint_src(tmp_path, '''
class Toy:
    def go(self):
        self.state = TOY_BUSY  # ggrs-verify: allow(model/transition-undeclared)
''') == []


class TestTreeIsClean:
    def test_live_fleet_layer_conforms(self):
        assert lint_transitions(REPO) == []
