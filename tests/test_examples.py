"""Smoke tests: every example driver must run headless end-to-end.

The reference exercises its session wiring in tests mirroring the examples;
without these, a broken example ships silently (round-1 review finding).
Each example is executed as a real subprocess (its own jax import, CLI
parsing, UDP sockets) with a small frame budget.
"""

import os
import select
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
EXAMPLES = REPO / "examples"


def wait_for_line(proc, needle: str, timeout: float = 120.0) -> bool:
    """Wait until ``proc`` prints a stdout line containing ``needle``.
    Non-invasive readiness signal (a port-bind probe could steal the port
    out from under the child for a microsecond and crash its own bind)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if proc.poll() is not None:
            return False  # child exited before signalling ready
        ready, _, _ = select.select([proc.stdout], [], [], 0.5)
        if ready:
            line = proc.stdout.readline()
            if needle in line:
                return True
    return False


def run_example(args, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # examples are single-device
    proc = subprocess.run(
        [sys.executable, *args],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    assert proc.returncode == 0, (
        f"{args} failed:\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    return proc.stdout


class TestExampleSmoke:
    def test_synctest_host_session(self):
        out = run_example(
            [
                EXAMPLES / "ex_game_synctest.py",
                "--frames", "100",
                "--check-distance", "3",
            ]
        )
        assert "no desyncs" in out

    def test_synctest_device_session(self):
        run_example(
            [
                EXAMPLES / "ex_game_synctest.py",
                "--frames", "100",
                "--check-distance", "3",
                "--device-session",
            ]
        )

    def test_p2p_both_peers(self):
        out = run_example(
            [EXAMPLES / "ex_game_p2p.py", "--both", "--frames", "120"]
        )
        assert "done" in out

    def test_p2p_with_spectator(self):
        """Host + second peer + spectator as three real processes over UDP.

        The spectator starts FIRST and the host waits for its socket: the
        host streams from frame 0 with no handshake (fork delta #4), so a
        spectator that is still importing jax while the host runs ahead
        trips the 128-pending-input overflow force-disconnect
        (/root/reference/src/network/protocol.rs:441-445) by design.
        """
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        spec = subprocess.Popen(
            [
                sys.executable, EXAMPLES / "ex_game_spectator.py",
                "--local-port", "9999",
                "--host", "127.0.0.1:7777",
                "--frames", "100",
            ],
            cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        assert wait_for_line(
            spec, "[spectator] listening"
        ), "spectator never signalled ready"
        host = subprocess.Popen(
            [
                sys.executable, EXAMPLES / "ex_game_p2p.py",
                "--local-port", "7777",
                "--players", "local", "127.0.0.1:8888",
                "--spectators", "127.0.0.1:9999",
                "--frames", "240",
            ],
            cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        peer = subprocess.Popen(
            [
                sys.executable, EXAMPLES / "ex_game_p2p.py",
                "--local-port", "8888",
                "--players", "127.0.0.1:7777", "local",
                "--frames", "240",
            ],
            cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        try:
            results = [p.communicate(timeout=300) for p in (host, peer, spec)]
        except subprocess.TimeoutExpired:
            for p in (host, peer, spec):
                p.kill()
            pytest.fail("example trio timed out")
        for p, (out, err) in zip((host, peer, spec), results):
            assert p.returncode == 0, f"rc={p.returncode}\n{out}\n{err}"
        # the spectator must actually have followed the full frame budget,
        # not bailed early on a disconnect
        spec_out = results[2][0]
        assert "[spectator] done" in spec_out, spec_out

    def test_server_massed_hosting(self):
        out = run_example(
            [
                EXAMPLES / "ex_game_server.py",
                "--matches", "4",
                "--frames", "80",
            ]
        )
        assert "SERVER-EXAMPLE-OK" in out
