"""Desync detection over the wire with device-resident state.

The r3 perf redesign made save checksums lazy (``DeviceChecksum`` handles
that materialize only when the desync exchange reports one).  These tests
close the loop the unit tests can't: two live P2P peers fulfilled by device
executors — one speculating — exchange real checksum reports through the
session's interval machinery, and synchronized simulations must produce ZERO
DesyncDetected events (while a deliberately corrupted peer must produce
one).  Reference flow: /root/reference/src/sessions/p2p_session.rs:904-975.
"""

import random

import numpy as np

from ggrs_tpu.core import DesyncDetected, DesyncDetection, Local, Remote
from ggrs_tpu.games import BoxGame, boxgame_config
from ggrs_tpu.net import InMemoryNetwork
from ggrs_tpu.ops import DeviceRequestExecutor
from ggrs_tpu.parallel import SpeculativeRollback
from ggrs_tpu.sessions import SessionBuilder


def _to_arr(pairs):
    return np.asarray([p[0] for p in pairs], np.uint8)


def _b_sched(i):
    return (i // 3) % 16  # transitions force regular rollbacks


def _make_pair(interval=10, speculate=True):
    game = BoxGame(2)
    net = InMemoryNetwork()
    sessions, executors = [], []
    for me, other, local_handle in (("A", "B", 0), ("B", "A", 1)):
        sess = (
            SessionBuilder(boxgame_config())
            .with_clock(lambda: 0)
            .with_rng(random.Random(41 + local_handle))
            .with_desync_detection_mode(DesyncDetection.on(interval))
            .add_player(Local(), local_handle)
            .add_player(Remote(other), 1 - local_handle)
            .start_p2p_session(net.socket(me))
        )
        spec = None
        if speculate and me == "A":
            def branch_inputs(k, frame, arr):
                out = np.array(arr, np.uint8, copy=True)
                if k:
                    out[1] = np.uint8(_b_sched(frame))
                return out

            spec = SpeculativeRollback(game.advance, 2, branch_inputs, max_window=8)
        executors.append(
            DeviceRequestExecutor(game.advance, game.init_state(), _to_arr,
                                  speculation=spec)
        )
        sessions.append(sess)
    return game, sessions, executors


def _drive(sessions, executors, ticks):
    events = [[], []]
    for i in range(ticks):
        for p, (s, ex) in enumerate(zip(sessions, executors)):
            s.poll_remote_clients()
            s.add_local_input(p, (i // 4) % 16 if p == 0 else _b_sched(i))
            ex.run(s.advance_frame())
            events[p].extend(s.events())
    return events


class TestDeviceExecutorDesyncExchange:
    def test_synchronized_peers_report_no_desync(self):
        """Lazy device checksums materialize at the send interval, cross the
        wire as u128s, and compare equal — for both the speculating peer
        (whose save cells are filled from branch trajectories) and the
        replaying peer."""
        game, sessions, executors = _make_pair(interval=10, speculate=True)
        events = _drive(sessions, executors, 80)
        for p in (0, 1):
            desyncs = [e for e in events[p] if isinstance(e, DesyncDetected)]
            assert desyncs == [], f"peer {p} saw false desyncs: {desyncs}"
        # the exchange really happened: both peers sent interval checksums
        for s in sessions:
            assert s._last_sent_checksum_frame >= 10

    def test_corrupted_peer_is_detected(self):
        """Corrupt peer B's live state mid-run: the checksum exchange must
        surface DesyncDetected with crossed checksums (the device analog of
        the reference's frame-200 desync test)."""
        import jax.numpy as jnp

        game, sessions, executors = _make_pair(interval=5, speculate=False)
        _drive(sessions, executors, 30)
        # nudge B's simulation off-course (bit-level corruption)
        ex_b = executors[1]
        ex_b._state = {**ex_b.state, "pos": ex_b.state["pos"] + jnp.int32(1)}
        events = _drive(sessions, executors, 60)
        desyncs = [
            e
            for p in (0, 1)
            for e in events[p]
            if isinstance(e, DesyncDetected)
        ]
        assert desyncs, "corruption must surface as DesyncDetected"
        assert any(e.local_checksum != e.remote_checksum for e in desyncs)
