"""The multi-host TCP fleet link, end to end (DESIGN.md §25).

A real ``ProcShard(tcp=True)``: the runner subprocess dials the
supervisor's listener over AF_INET, completes the HMAC handshake, and
serves the same RPC plane the socketpair backend does.  The scenarios
here pin the liveness split the §25 model proves:

- a severed link (full or half-open) RESUMES inside the reconnect
  window with zero failovers — ``poll_lifecycle`` never says "died";
- a runner that cannot return before the window closes is confirmed
  dead WITHOUT being signalled (a remote host's process is not ours to
  kill) — and when it resurrects, the bumped epoch fences it at
  handshake, loudly, with the refusal counted;
- adoption (``ShardRunner --tcp host:port``) works for externally
  launched runners, the multi-host deployment shape.

The adversarial handshake matrix (wrong token, replay, slowloris,
garbage) lives in test_fleet_rpc.py; the data-plane bit-identity legs
live in scripts/chaos.py --fault net.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import pytest

from ggrs_tpu.fleet import FleetTuning, ShardSupervisor
from ggrs_tpu.fleet.proc import PROC_EXITED, PROC_RUNNING, ProcShard
from ggrs_tpu.fleet.transport import LINK_RECONNECTING, LINK_UP
from ggrs_tpu.obs import Registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TUNING = FleetTuning(
    heartbeat_interval_s=0.05,
    heartbeat_deadline_s=1.0,
    rpc_timeout_s=5.0,
    spawn_timeout_s=120.0,
    drain_deadline_s=0.5,
    restart_max=3,
    link_auth_token="e2e-token",
    link_reconnect_window_s=2.0,
    link_backoff_s=0.01,
    link_handshake_timeout_s=1.0,
)


def _poll_until(shard, pred, timeout=10.0, expect=(None,)):
    """Drive poll_lifecycle until ``pred(shard)``; every intermediate
    verdict must be in ``expect`` (the zero-failover assertions ride
    this: expect=(None,) means "died" is an instant failure)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        r = shard.poll_lifecycle()
        assert r in expect, f"unexpected lifecycle verdict {r!r}"
        if pred(shard):
            return
        time.sleep(0.02)
    raise AssertionError("condition not reached before timeout")


@pytest.fixture
def shard():
    s = ProcShard("s0", tuning=TUNING, metrics=Registry(), tcp=True)
    yield s
    # belt and braces: reap anything a scenario left stopped/alive
    for p in s._all_procs:
        if p.poll() is None:
            try:
                os.kill(p.pid, signal.SIGCONT)
            except (OSError, ProcessLookupError):
                pass
            p.kill()
            p.wait(timeout=10)
    s.close()


class TestTcpSpawn:
    def test_spawn_serves_over_tcp(self, shard):
        assert shard._status == PROC_RUNNING
        info = shard.link_info()
        assert info["state"] == LINK_UP and info["epoch"] == 1
        assert shard.watchdog_stage() == "ok"
        h = shard.healthz()
        assert h["link"]["state"] == "up"
        assert h["ok"] and h["pid"] == shard.pid
        # heartbeats flow over the TCP conn
        _poll_until(shard,
                    lambda s: (s.heartbeat_age_s() or 99) < 1.0)

    def test_sever_resumes_with_zero_failovers(self, shard):
        shard.chaos_sever_link()
        # the whole excursion must stay failover-free: expect=(None,).
        # On loopback the redial can land before a poll observes the
        # transient "reconnecting" state, so wait for the resume itself
        # (reconnects counter), not for the transient.
        _poll_until(shard,
                    lambda s: s.link_info()["reconnects"] >= 1
                    and s.link_info()["state"] == LINK_UP)
        info = shard.link_info()
        assert info["reconnects"] == 1 and info["window_expiries"] == 0
        assert info["epoch"] == 1  # same incarnation, same token
        assert shard.watchdog_stage() == "ok"
        # the conn still serves rpcs after the resume
        assert shard._call("ping") is not None

    def test_half_open_sever_resumes(self, shard):
        # supervisor stops sending but keeps its read side: the runner
        # sees EOF, we do not — its epoch-current resume IS the signal
        shard.chaos_sever_link("wr")
        _poll_until(shard,
                    lambda s: s.link_info()["reconnects"] == 1)
        assert shard.link_info()["state"] == LINK_UP

    def test_window_expiry_confirms_death_without_kill(self, shard):
        pid = shard.pid
        os.kill(pid, signal.SIGSTOP)  # cannot redial
        try:
            shard.chaos_sever_link()
            deadline = time.monotonic() + 15
            died = None
            while time.monotonic() < deadline:
                died = shard.poll_lifecycle()
                if died is not None:
                    break
                time.sleep(0.02)
            assert died == "died"
            assert shard._status == PROC_EXITED
            assert "fenced" in (shard.last_exit or "")
            assert shard.link_info()["window_expiries"] == 1
            # epoch bumped at down(): the stale incarnation is fenced
            assert shard.link_info()["epoch"] == 2
            # the liveness split: the process was NOT signalled — on a
            # real remote host it would not be ours to kill
            os.kill(pid, 0)  # still exists (stopped)
        finally:
            os.kill(pid, signal.SIGCONT)

    def test_resurrected_stale_runner_fenced_at_handshake(self, shard):
        """The §25 acceptance bit: kill the link, let the window
        expire, respawn a fresh incarnation — then the old runner
        (SIGCONT'd back to life) redials with its stale epoch and must
        be refused with HS_REFUSED_FENCE, then exit of its own accord
        (never double-driven)."""
        old_pid = shard.pid
        old_proc = shard._proc
        os.kill(old_pid, signal.SIGSTOP)
        shard.chaos_sever_link()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if shard.poll_lifecycle() is not None:
                break
            time.sleep(0.02)
        assert shard._status == PROC_EXITED
        # resurrect the old incarnation, then respawn the new one; the
        # spawn's wait_for_runner pump judges the stale redial
        os.kill(old_pid, signal.SIGCONT)
        assert shard.try_respawn()
        assert shard._status == PROC_RUNNING
        assert shard.pid != old_pid
        assert shard.link_info()["epoch"] == 3  # expire +1, respawn +1
        # the old runner must notice the fence and exit nonzero, and
        # the refusal must be counted (it may need a few pump rounds)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            shard.poll_lifecycle()
            if (old_proc.poll() is not None
                    and shard.link_info()["refusals"].get("fence")):
                break
            time.sleep(0.02)
        assert shard.link_info()["refusals"].get("fence", 0) >= 1
        assert old_proc.poll() == 1  # fenced exit, not a crash
        # and the NEW incarnation is untouched by the old one's redials
        assert shard.link_info()["state"] == LINK_UP
        assert shard._call("ping") is not None


class TestTcpAdoption:
    def test_adopt_external_runner(self):
        shard = ProcShard("s9", tuning=TUNING, metrics=Registry(),
                          tcp=True, spawn=False)
        proc = None
        try:
            host, port = shard._link.address
            env = dict(
                os.environ,
                GGRS_FLEET_LINK_AUTH_TOKEN=TUNING.link_auth_token,
                GGRS_FLEET_LINK_SHARD="s9",
            )
            proc = subprocess.Popen(
                [sys.executable, os.path.join(REPO, "scripts",
                                              "shard_runner.py"),
                 "--tcp", f"{host}:{port}"],
                env=env, cwd=REPO,
            )
            shard.adopt_tcp(timeout=120.0)
            assert shard._status == PROC_RUNNING
            assert shard.link_info()["state"] == LINK_UP
            assert shard.pid == proc.pid
        finally:
            shard.close()
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)

    def test_wrong_token_runner_never_adopted(self):
        shard = ProcShard("s9", tuning=TUNING, metrics=Registry(),
                          tcp=True, spawn=False)
        proc = None
        try:
            host, port = shard._link.address
            env = dict(
                os.environ,
                GGRS_FLEET_LINK_AUTH_TOKEN="not-the-token",
                GGRS_FLEET_LINK_SHARD="s9",
            )
            proc = subprocess.Popen(
                [sys.executable, os.path.join(REPO, "scripts",
                                              "shard_runner.py"),
                 "--tcp", f"{host}:{port}"],
                env=env, cwd=REPO,
            )
            # the runner is refused at handshake and exits nonzero;
            # pump enough to judge its attempt
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                shard._link.pump()
                if proc.poll() is not None:
                    break
                time.sleep(0.02)
            assert proc.poll() == 1
            assert shard._link.refusals.get("auth", 0) >= 1
            assert shard._status == PROC_EXITED  # never adopted
        finally:
            shard.close()
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)


class TestSupervisorTcp:
    def test_tcp_shards_must_be_proc_backed(self):
        with pytest.raises(ValueError, match="tcp_shards"):
            ShardSupervisor(("a", "b"), tuning=TUNING,
                            tcp_shards=("a",))

    def test_healthz_carries_link_state(self):
        sup = ShardSupervisor(
            ("s0", "s1"), capacity=4, metrics=Registry(),
            tuning=TUNING, proc_shards=("s1",), tcp_shards=("s1",),
        )
        try:
            h = sup.healthz()
            assert h["proc"]["s1"]["link"]["state"] == "up"
            assert h["shards"]["s1"]["link"]["epoch"] == 1
            # non-tcp shards have no link block
            assert h["shards"]["s0"].get("link") is None
        finally:
            sup.close()

    def test_fleet_top_renders_link_column(self):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            from fleet_top import render
        finally:
            sys.path.pop(0)
        from ggrs_tpu.obs.exporters import json_snapshot
        sup = ShardSupervisor(
            ("s0", "s1"), capacity=4, metrics=Registry(),
            tuning=TUNING, proc_shards=("s1",), tcp_shards=("s1",),
        )
        try:
            out = render(sup.healthz(), json_snapshot(sup.metrics))
            assert "LINK" in out
            assert "up/e1" in out  # state/epoch for the tcp shard
        finally:
            sup.close()
