"""Unit tests for the device primitives: checksum, state ring, fused replay."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ggrs_tpu.ops import (
    CHECKSUM_LANES,
    DeviceStateRing,
    build_replay_programs,
    checksum_device,
    checksum_to_u128,
    pytree_checksum,
)


class TestChecksum:
    def test_shape_and_dtype(self):
        cs = checksum_device({"a": jnp.arange(7), "b": jnp.ones((2, 3))})
        assert cs.shape == (CHECKSUM_LANES,)
        assert cs.dtype == jnp.uint32

    def test_deterministic(self):
        state = {"x": jnp.arange(100, dtype=jnp.int32), "y": jnp.float32(3.5)}
        assert pytree_checksum(state) == pytree_checksum(state)

    def test_empty_pytree(self):
        # regression (ADVICE r5): _INIT_LANES holds ints above int32 max and
        # jnp.asarray's int32 default raised OverflowError on the leafless path
        cs = checksum_device({})
        assert cs.shape == (CHECKSUM_LANES,)
        assert cs.dtype == jnp.uint32
        assert pytree_checksum({}) == pytree_checksum({})
        assert pytree_checksum({}) != pytree_checksum({"a": jnp.arange(2)})

    def test_sensitive_to_values(self):
        a = jnp.arange(16, dtype=jnp.int32)
        assert pytree_checksum(a) != pytree_checksum(a.at[3].add(1))

    def test_sensitive_to_position(self):
        # same multiset of words, different order
        a = jnp.asarray([1, 2, 3, 4], jnp.uint32)
        b = jnp.asarray([4, 3, 2, 1], jnp.uint32)
        assert pytree_checksum(a) != pytree_checksum(b)

    def test_float_bitcast_not_rounded(self):
        # two floats equal under fp-tolerance but not bitwise must differ
        a = jnp.float32(1.0)
        b = jnp.float32(1.0 + 1.2e-7)
        assert pytree_checksum(a) != pytree_checksum(b)

    @pytest.mark.parametrize("dtype", [jnp.uint8, jnp.int16, jnp.int32, jnp.float32])
    def test_small_dtypes_supported(self, dtype):
        x = jnp.arange(5).astype(dtype)
        assert isinstance(pytree_checksum(x), int)

    def test_u128_composition(self):
        lanes = np.asarray([1, 2, 3, 4], np.uint32)
        v = checksum_to_u128(lanes)
        assert v == 1 | (2 << 32) | (3 << 64) | (4 << 96)
        assert 0 <= v < (1 << 128)

    def test_jittable_inside_scan(self):
        def body(c, _):
            return c + 1, checksum_device({"s": c})

        _, css = jax.lax.scan(body, jnp.int32(0), None, length=4)
        assert css.shape == (4, CHECKSUM_LANES)
        # different states digest differently
        assert not np.array_equal(np.asarray(css[0]), np.asarray(css[1]))


class TestDeviceStateRing:
    def _mk(self, length=4):
        ring = DeviceStateRing(length)
        template = {"a": jnp.zeros((3,), jnp.int32), "b": jnp.zeros((), jnp.float32)}
        return ring, ring.init(template)

    def test_init_frames_null(self):
        ring, buf = self._mk()
        assert np.all(np.asarray(buf["frames"]) == -1)

    def test_save_load_roundtrip(self):
        ring, buf = self._mk()
        state = {"a": jnp.asarray([1, 2, 3], jnp.int32), "b": jnp.float32(7.5)}
        cs = checksum_device(state)
        buf = ring.save(buf, jnp.int32(5), state, cs)
        got = ring.load(buf, jnp.int32(5))
        assert np.array_equal(np.asarray(got["a"]), [1, 2, 3])
        assert float(got["b"]) == 7.5
        assert int(ring.frame_at(buf, jnp.int32(5))) == 5
        assert np.array_equal(
            np.asarray(ring.load_checksum(buf, jnp.int32(5))), np.asarray(cs)
        )

    def test_ring_wraparound_overwrites(self):
        ring, buf = self._mk(length=4)
        s = lambda v: {"a": jnp.full((3,), v, jnp.int32), "b": jnp.float32(v)}
        for f in range(6):  # frames 4,5 overwrite slots 0,1
            buf = ring.save(buf, jnp.int32(f), s(f), checksum_device(s(f)))
        assert int(ring.frame_at(buf, jnp.int32(4))) == 4
        got = ring.load(buf, jnp.int32(4))
        assert np.all(np.asarray(got["a"]) == 4)
        # frame 0's slot now holds frame 4 — frame_at exposes the overwrite
        assert int(ring.frame_at(buf, jnp.int32(0))) == 4


class _CounterGame:
    """Trivial deterministic game: state {count, acc}; input (1,) int32."""

    @staticmethod
    def advance(state, inp):
        return {
            "count": state["count"] + 1,
            "acc": state["acc"] * 3 + inp[0],
        }

    @staticmethod
    def init():
        return {"count": jnp.int32(0), "acc": jnp.int32(0)}


class TestReplayPrograms:
    def _run(self, n_ticks, d=2, ring_len=9):
        progs = build_replay_programs(_CounterGame.advance, ring_len, d)
        carry = progs.init_carry(_CounterGame.init(), jnp.zeros((1,), jnp.int32))
        inputs = jnp.arange(n_ticks, dtype=jnp.int32).reshape(n_ticks, 1)
        w = min(progs.warmup_ticks, n_ticks)
        carry = progs.run_warmup(carry, inputs[:w])
        if n_ticks > w:
            carry = progs.run_steady(carry, inputs[w:])
        return progs, carry

    def test_warmup_advances_frames(self):
        progs, carry = self._run(3, d=2)
        assert int(carry["frame"]) == 3
        assert int(carry["mismatches"]) == 0

    def test_steady_matches_plain_simulation(self):
        n = 40
        progs, carry = self._run(n, d=3)
        # plain forward simulation of the same inputs
        state = _CounterGame.init()
        for i in range(n):
            state = _CounterGame.advance(state, jnp.asarray([i], jnp.int32))
        live = jax.device_get(carry["live"])
        assert int(live["count"]) == int(state["count"]) == n
        assert int(live["acc"]) == int(state["acc"])
        assert int(carry["mismatches"]) == 0

    def test_nondeterminism_detected(self):
        # a game whose advance depends on how many times it has been called
        # (hidden Python-side state) is exactly what synctest must catch —
        # emulate via a frame-independent RNG-free trick: advance uses
        # state["count"] *squared* only when count is the live pass; instead
        # we corrupt determinism by making advance read the ring slot parity
        # through its own input history — simplest honest case: flip a value
        # in the saved ring between ticks and watch the compare fire.
        progs = build_replay_programs(_CounterGame.advance, 5, 2)
        carry = progs.init_carry(_CounterGame.init(), jnp.zeros((1,), jnp.int32))
        inputs = jnp.ones((3, 1), jnp.int32)
        carry = progs.run_warmup(carry, inputs)
        # corrupt the first-seen history for frame 2 → next steady tick's
        # resimulation of frame 2 must mismatch
        carry["hist"] = carry["hist"].at[2].set(jnp.uint32(0xDEAD))
        carry = progs.run_steady(carry, jnp.ones((1, 1), jnp.int32))
        assert int(carry["mismatches"]) >= 1
        assert int(carry["first_bad"]) == 2

    def test_requests_per_tick_accounting(self):
        progs, _ = self._run(2, d=2)
        assert progs.warmup_ticks == 3

    def test_check_distance_one_still_detects(self):
        # at d=1 the reference's scheme has nothing to compare (each frame is
        # resimulated exactly once); our live-advance digest makes even d=1
        # meaningful — corrupting the saved state a rollback reloads must be
        # caught on the next tick
        progs = build_replay_programs(_CounterGame.advance, 4, 1)
        carry = progs.init_carry(_CounterGame.init(), jnp.zeros((1,), jnp.int32))
        inputs = jnp.ones((6, 1), jnp.int32)
        carry = progs.run_warmup(carry, inputs[: progs.warmup_ticks])
        carry = progs.run_steady(carry, inputs[progs.warmup_ticks :])
        assert int(carry["mismatches"]) == 0
        frame = int(carry["frame"])  # next steady tick reloads frame-1
        slot = (frame - 1) % 4
        carry["ring"]["states"]["acc"] = (
            carry["ring"]["states"]["acc"].at[slot].add(1)
        )
        carry = progs.run_steady(carry, jnp.ones((1, 1), jnp.int32))
        assert int(carry["mismatches"]) >= 1
        assert int(carry["first_bad"]) == frame


class TestDigestPathEquivalence:
    """checksum_device routes small states through one concatenated
    reduction and large states through per-leaf offset sums; both must
    produce identical lanes (lane_sums' chunk-additivity contract)."""

    def test_concat_and_offset_sum_paths_agree(self):
        from ggrs_tpu.ops import checksum as cs

        rng = np.random.default_rng(3)
        # total words straddle the fuse threshold from both sides
        big = {
            "a": jnp.asarray(rng.integers(0, 2**31, size=(3000,), dtype=np.int64)),
            "b": jnp.asarray(rng.integers(0, 255, size=(2500,), dtype=np.uint8)),
            "c": jnp.asarray(rng.random((700,)).astype(np.float32)),
        }
        small = {k: v[:50] for k, v in big.items()}
        for state in (big, small):
            words = [
                cs._as_u32_words(jnp.asarray(l))
                for l in jax.tree_util.tree_leaves(state)
            ]
            concat_lanes = cs.lane_sums(jnp.concatenate(words))
            acc = jnp.zeros((4,), jnp.uint32)
            off = 0
            for w in words:
                acc = acc + cs.lane_sums(w, off)
                off += w.shape[0]
            np.testing.assert_array_equal(np.asarray(concat_lanes), np.asarray(acc))
            np.testing.assert_array_equal(
                np.asarray(cs._digest_words(words)), np.asarray(concat_lanes)
            )

    def test_leaf_structure_still_distinguished(self):
        # same concatenated words, different leaf boundaries -> the structure
        # salt must keep the digests distinct
        a = {"a": jnp.asarray([1, 2], jnp.uint32)}
        b = {"a": jnp.asarray([1], jnp.uint32), "b": jnp.asarray([2], jnp.uint32)}
        assert pytree_checksum(a) != pytree_checksum(b)
