"""Pins for the gen-2 socket datapath (DESIGN.md §23): the one-crossing
batched inbound drain (``ggrs_net_recv_table``), the shared dispatch
socket (one fd + SO_REUSEPORT siblings serving many slots, native
(ip,port)->slot demux), and GSO spectator fan-out (``UDP_SEGMENT``
segmented sends with sendmmsg fallback).

The headline pins:

* INBOUND PARITY — the batched drain and the dispatch demux deliver a
  bit-identical host tick stream to the per-slot reference drain under
  seeded loss/dup/reorder over real loopback UDP (observed through the
  host's outbound bytes: any inbound divergence changes what the session
  sends).
* CROSSING BUDGET — the drain is ONE extra crossing per pool tick; the
  tick itself stays one.
* FD FLOOR — dispatch mode's fd count is O(1) in B.
* FAULT ISOLATION — a fatal errno on the shared fd faults exactly the
  owning slot(s); co-tenants keep running (§9).
* PER-FEATURE DEGRADATION — recv-table, dispatch-reuseport, and GSO each
  fall back independently, never all-or-nothing.
"""

from __future__ import annotations

import ctypes
import errno
import os
import random
import socket as pysocket
import struct

import numpy as np
import pytest

from ggrs_tpu.core import Local, Remote
from ggrs_tpu.core.config import Config
from ggrs_tpu.net import _native
from ggrs_tpu.net.sockets import DispatchHub, UdpNonBlockingSocket
from ggrs_tpu.parallel.host_bank import HostSessionPool
from ggrs_tpu.sessions import SessionBuilder

needs_io = pytest.mark.skipif(
    _native.net_lib() is None,
    reason="kernel-batched socket datapath unavailable",
)
needs_gen2 = pytest.mark.skipif(
    _native.net_lib() is None
    or not hasattr(_native.net_lib(), "ggrs_net_recv_table"),
    reason="gen-2 datapath unavailable",
)


def _ip(host: str) -> int:
    return int.from_bytes(pysocket.inet_aton(host), "little")


def _fd_tab(rows):
    return b"".join(struct.pack("<ii", fd, slot) for fd, slot in rows)


def _route_tab(rows):
    rows = sorted(rows, key=lambda r: (r[0] << 16) | r[1])
    return b"".join(
        struct.pack("<IHHi", ip, port, 0, slot) for ip, port, slot in rows
    )


def _recv_table(lib, fd_rows, route_rows, max_recs=256, slab_cap=1 << 16):
    """Direct one-shot drain; returns (records, slab, stats, fatals)."""
    recs = ctypes.create_string_buffer(max_recs * _native.NET_RECV_STRIDE)
    slab = ctypes.create_string_buffer(slab_cap)
    stats = (ctypes.c_uint64 * _native.NET_RECV_TABLE_STATS)()
    fatal = (ctypes.c_int32 * 64)()
    n_fatal = ctypes.c_int32(0)
    n = lib.ggrs_net_recv_table(
        _fd_tab(fd_rows), len(fd_rows),
        _route_tab(route_rows), len(route_rows),
        recs, max_recs, slab, slab_cap,
        stats, fatal, 32, ctypes.byref(n_fatal),
    )
    assert n >= 0, f"recv_table failed: {n}"
    out = []
    for k in range(n):
        slot, fd_idx, ip, port, _pad, off, ln = struct.unpack_from(
            "<iiIHHII", recs, k * _native.NET_RECV_STRIDE
        )
        out.append((slot, fd_idx, ip, port, slab[off:off + ln]))
    fatals = [
        (fatal[2 * k], fatal[2 * k + 1]) for k in range(n_fatal.value)
    ]
    return out, list(stats), fatals


def fulfill(requests):
    for r in requests:
        if type(r).__name__ == "SaveGameState":
            r.cell.save(r.frame, None, None)


# ----------------------------------------------------------------------
# ggrs_net_recv_table: direct native units
# ----------------------------------------------------------------------


@needs_gen2
class TestRecvTableUnit:
    def _bound(self):
        s = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_DGRAM)
        s.bind(("127.0.0.1", 0))
        s.setblocking(False)
        return s

    def test_slot_bound_fds_drain_in_order(self):
        lib = _native.net_lib()
        rx_a, rx_b, tx = self._bound(), self._bound(), self._bound()
        try:
            for i in range(3):
                tx.sendto(bytes([i]) * (5 + i), rx_a.getsockname())
            tx.sendto(b"bbbb", rx_b.getsockname())
            recs, stats, fatals = _recv_table(
                lib, [(rx_a.fileno(), 7), (rx_b.fileno(), 9)], []
            )
            assert fatals == []
            a = [r for r in recs if r[0] == 7]
            b = [r for r in recs if r[0] == 9]
            assert [r[4] for r in a] == [bytes([i]) * (5 + i)
                                         for i in range(3)]
            assert [r[4] for r in b] == [b"bbbb"]
            src_ip, src_port = tx.getsockname()
            assert all(r[3] == src_port and r[2] == _ip("127.0.0.1")
                       for r in recs)
            assert stats[1] == 4  # datagrams
            assert stats[0] >= 2  # one recvmmsg call per fd minimum
        finally:
            for s in (rx_a, rx_b, tx):
                s.close()

    def test_dispatch_routes_and_unroutable_drop(self):
        lib = _native.net_lib()
        rx, tx_a, tx_b, tx_x = (self._bound() for _ in range(4))
        try:
            dst = rx.getsockname()
            tx_a.sendto(b"from-a", dst)
            tx_b.sendto(b"from-b", dst)
            tx_x.sendto(b"from-nobody", dst)
            routes = [
                (_ip("127.0.0.1"), tx_a.getsockname()[1], 3),
                (_ip("127.0.0.1"), tx_b.getsockname()[1], 5),
            ]
            recs, stats, fatals = _recv_table(
                lib, [(rx.fileno(), -1)], routes
            )
            assert fatals == []
            got = {r[0]: r[4] for r in recs}
            assert got == {3: b"from-a", 5: b"from-b"}
            assert stats[2] == 1  # the unclaimed source was dropped
        finally:
            for s in (rx, tx_a, tx_b, tx_x):
                s.close()

    def test_backpressure_stops_before_losing_datagrams(self):
        lib = _native.net_lib()
        rx, tx = self._bound(), self._bound()
        try:
            for i in range(6):
                tx.sendto(bytes([i]) * 8, rx.getsockname())
            # room for only 2 records: the clamp must stop BEFORE the
            # recvmmsg so the rest stay queued in the kernel
            recs, stats, _ = _recv_table(
                lib, [(rx.fileno(), 0)], [], max_recs=2
            )
            assert [r[4] for r in recs] == [bytes([i]) * 8
                                            for i in range(2)]
            assert stats[3] >= 1  # backpressure_stops
            recs2, _, _ = _recv_table(lib, [(rx.fileno(), 0)], [])
            assert [r[4] for r in recs2] == [bytes([i]) * 8
                                             for i in range(2, 6)]
        finally:
            rx.close()
            tx.close()

    def test_fatal_fd_reports_index_and_drains_others(self):
        lib = _native.net_lib()
        rx, tx = self._bound(), self._bound()
        try:
            tx.sendto(b"alive", rx.getsockname())
            recs, _, fatals = _recv_table(
                lib, [(10_000, 1), (rx.fileno(), 2)], []
            )
            assert [(r[0], r[4]) for r in recs] == [(2, b"alive")]
            assert len(fatals) == 1
            assert fatals[0][0] == 0  # the bogus fd's TABLE index
            assert fatals[0][1] == errno.EBADF
        finally:
            rx.close()
            tx.close()

    def test_bad_args_refused(self):
        lib = _native.net_lib()
        stats = (ctypes.c_uint64 * _native.NET_RECV_TABLE_STATS)()
        fatal = (ctypes.c_int32 * 8)()
        n_fatal = ctypes.c_int32(0)
        rc = lib.ggrs_net_recv_table(
            b"", -1, b"", 0, None, 0, None, 0,
            stats, fatal, 4, ctypes.byref(n_fatal),
        )
        assert rc == _native.NET_ERR_BAD_ARGS


# ----------------------------------------------------------------------
# send-table gen 2: dispatch-flag fault isolation + GSO coalescing
# ----------------------------------------------------------------------


@needs_gen2
class TestSendTableGen2:
    def _send(self, lib, rows, payload, inject=None):
        desc = np.empty(len(rows), np.dtype(list(_native.NET_SEND_FIELDS)))
        for k, row in enumerate(rows):
            desc[k] = row
        stats = (ctypes.c_uint64 * _native.NET_SEND_STATS)()
        fatal = (ctypes.c_int32 * 32)()
        if inject is not None:
            lib.ggrs_net_inject_table_errno(*inject)
        try:
            rc = lib.ggrs_net_send_table(
                desc.ctypes.data, len(rows), payload, len(payload),
                stats, fatal, 16,
            )
        finally:
            lib.ggrs_net_inject_table_errno(0, 0, 0)
        fatals = [(fatal[2 * k], fatal[2 * k + 1])
                  for k in range(max(rc, 0))]
        return rc, list(stats), fatals

    def test_dispatch_flag_isolates_fatal_record(self):
        """A fatal errno on a kSendFlagDispatch record reports the record
        and CONTINUES the run — co-tenants on the shared fd still flush.
        The same fault without the flag abandons the fd's run (gen-1
        whole-fd semantics, unchanged)."""
        lib = _native.net_lib()
        tx = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_DGRAM)
        rx = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_DGRAM)
        rx.bind(("127.0.0.1", 0))
        rx.settimeout(2.0)
        ip, port = _ip("127.0.0.1"), rx.getsockname()[1]
        payload = b"aaaa" + b"bbbb" + b"cccc"
        disp = _native.NET_SEND_FLAG_DISPATCH
        rows = [
            (tx.fileno(), ip, port, disp, 0, 4),
            (tx.fileno(), ip, port, disp, 4, 4),
            (tx.fileno(), ip, port, disp, 8, 4),
        ]
        try:
            # inject EPERM (fatal) on the middle record only
            rc, stats, fatals = self._send(
                lib, rows, payload, inject=(errno.EPERM, 1, 1)
            )
            assert fatals == [(1, errno.EPERM)]
            assert stats[0] == 2
            assert sorted(rx.recv(64) for _ in range(2)) == \
                [b"aaaa", b"cccc"]
            # same rows without the dispatch flag: the run is abandoned
            # at the fault (gen-1 per-slot-fd semantics)
            plain = [(fd, i, p, 0, o, ln)
                     for fd, i, p, _f, o, ln in rows]
            rc, stats, fatals = self._send(
                lib, plain, payload, inject=(errno.EPERM, 1, 1)
            )
            assert fatals == [(1, errno.EPERM)]
            assert stats[0] == 1  # only the record before the fault
            assert rx.recv(64) == b"aaaa"
        finally:
            tx.close()
            rx.close()

    def test_gso_parity_and_counters(self):
        """Same-destination equal-size runs arrive bit-identical whether
        GSO is forced off (per-datagram sendmmsg) or on (one UDP_SEGMENT
        send) — and the gso counters fire only when it engages."""
        lib = _native.net_lib()
        tx = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_DGRAM)
        rx = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_DGRAM)
        rx.bind(("127.0.0.1", 0))
        rx.settimeout(2.0)
        ip, port = _ip("127.0.0.1"), rx.getsockname()[1]
        n, size = 5, 32
        payload = b"".join(bytes([0x40 + i]) * size for i in range(n))
        rows = [(tx.fileno(), ip, port, 0, i * size, size)
                for i in range(n)]
        want = [payload[i * size:(i + 1) * size] for i in range(n)]
        try:
            legs = {}
            for mode in (0, -1):
                lib.ggrs_net_set_gso(mode)
                try:
                    rc, stats, fatals = self._send(lib, rows, payload)
                finally:
                    lib.ggrs_net_set_gso(-1)
                assert rc == 0 and fatals == []
                assert stats[0] == n
                got = [rx.recv(256) for _ in range(n)]
                assert got == want, f"gso mode {mode} changed the bytes"
                legs[mode] = stats
            assert legs[0][3] == 0  # forced off: no gso sends
            if lib.ggrs_net_gso_supported():
                assert legs[-1][3] >= 1  # one segmented send…
                assert legs[-1][4] == n  # …covering every record
        finally:
            tx.close()
            rx.close()


# ----------------------------------------------------------------------
# pool-level: inbound parity fuzz across the three drain modes
# ----------------------------------------------------------------------


class FaultyTapPeerSocket:
    """Peer-side socket: seeded loss/dup/reorder applied to sends (the
    fault schedule is a pure function of the send sequence, identical
    across legs) and a tape of every datagram RECEIVED — the host's
    outbound bytes as observed on the wire."""

    def __init__(self, inner: UdpNonBlockingSocket, seed: int,
                 loss=0.0, duplicate=0.0, reorder=0.0):
        self.inner = inner
        self._rng = random.Random(seed)
        self.loss, self.duplicate, self.reorder = loss, duplicate, reorder
        self._staged = []
        self.tape = []

    def send_to(self, msg, addr) -> None:
        payload = msg.encode()
        rng = self._rng
        drop = rng.random() < self.loss
        dup = rng.random() < self.duplicate
        swap = rng.random() < self.reorder
        if drop:
            return
        self._staged.append((addr, payload))
        if dup:
            self._staged.append((addr, payload))
        if swap and len(self._staged) >= 2:
            self._staged[-1], self._staged[-2] = (
                self._staged[-2], self._staged[-1]
            )

    def flush(self) -> None:
        for addr, payload in self._staged:
            self.inner.send_datagram(payload, addr)
        self._staged.clear()

    def receive_all_datagrams(self):
        got = self.inner.receive_all_datagrams()
        self.tape.extend(data for _, data in got)
        return got

    def receive_all_messages(self):
        return self.inner.receive_all_messages()


def run_inbound_leg(mode: str, seed: int, ticks: int, n_matches: int,
                    faults: dict):
    """One leg of the inbound parity fuzz.  ``mode``:

    * ``reference`` — per-slot sockets, batched drain disabled
      (``GGRS_TPU_NO_RECV_TABLE``): the pinned per-slot Python drain.
    * ``batched``   — per-slot sockets through ``ggrs_net_recv_table``.
    * ``dispatch``  — one DispatchHub port for every slot, native demux.
    * ``dispatch-reference`` — the hub WITHOUT the native drain (the
      Python claims demux): the per-feature fallback leg.
    """
    env = {}
    if mode in ("reference", "dispatch-reference"):
        env["GGRS_TPU_NO_RECV_TABLE"] = "1"
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        cfg = Config.for_uint(16)
        clock = [0]
        pool = HostSessionPool()
        hub = (
            DispatchHub(siblings=1)
            if mode.startswith("dispatch") else None
        )
        peers, peer_socks = [], []
        for m in range(n_matches):
            host_sock = hub.view() if hub else UdpNonBlockingSocket(0)
            host_port = host_sock.local_port()
            peer_inner = UdpNonBlockingSocket(0)
            peer_addr = ("127.0.0.1", peer_inner.local_port())
            peer_sock = FaultyTapPeerSocket(
                peer_inner, seed * 101 + m, **faults
            )
            pool.add_session(
                SessionBuilder(cfg)
                .with_clock(lambda: clock[0])
                .with_rng(random.Random(3 + 5 * m))
                .add_player(Local(), 0)
                .add_player(Remote(peer_addr), 1),
                host_sock,
            )
            peer = (
                SessionBuilder(cfg)
                .with_clock(lambda: clock[0])
                .with_rng(random.Random(4 + 5 * m))
                .add_player(Local(), 1)
                .add_player(Remote(("127.0.0.1", host_port)), 0)
            ).start_p2p_session(peer_sock)
            peers.append(peer)
            peer_socks.append(peer_sock)
        for i in range(ticks):
            clock[0] += 16
            for m, peer in enumerate(peers):
                peer.add_local_input(1, (i + 2 * m) % 16)
                fulfill(peer.advance_frame())
                peer_socks[m].flush()
            for m in range(n_matches):
                pool.add_local_input(m, 0, (i + 2 * m) % 16)
            for reqs in pool.advance_all():
                fulfill(reqs)
        # final peer drain so the tape includes the last tick's sends
        for sock in peer_socks:
            sock.receive_all_datagrams()
        return dict(
            tapes=[s.tape for s in peer_socks],
            frames=[pool.current_frame(m) for m in range(n_matches)],
            crossings=pool.crossings,
            drain_crossings=pool.drain_crossings,
            stats=pool.io_stats(),
        )
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@needs_gen2
class TestInboundParity:
    @pytest.mark.parametrize("seed", [2, 31])
    def test_all_modes_bit_identical_under_faults(self, seed):
        """The headline pin: batched drain, native dispatch demux, and
        the hub's Python fallback demux all deliver the same inbound to
        the sessions as the per-slot reference drain — observed through
        the host's outbound wire bytes, which any inbound divergence
        would change."""
        faults = dict(loss=0.05, duplicate=0.03, reorder=0.03)
        ticks, n_matches = 140, 2
        ref = run_inbound_leg("reference", seed, ticks, n_matches, faults)
        assert ref["drain_crossings"] == 0  # the kill switch held
        for mode in ("batched", "dispatch", "dispatch-reference"):
            leg = run_inbound_leg(mode, seed, ticks, n_matches, faults)
            for m in range(n_matches):
                assert leg["tapes"][m] == ref["tapes"][m], (
                    f"{mode}: match {m} wire bytes diverged "
                    f"(ref {len(ref['tapes'][m])} datagrams, "
                    f"{mode} {len(leg['tapes'][m])})"
                )
            assert leg["frames"] == ref["frames"]
            if mode != "dispatch-reference":
                assert leg["stats"]["drain"]["datagrams"] > 0, (
                    f"{mode}: the batched drain never engaged"
                )
        assert all(f >= ticks - 64 for f in ref["frames"])

    def test_crossing_budget(self):
        """The drain is ONE crossing per pool tick and the tick stays
        one: crossings == ticks, drain_crossings == ticks."""
        ticks = 60
        leg = run_inbound_leg("batched", 5, ticks, 2, {})
        assert leg["crossings"] == ticks
        assert leg["drain_crossings"] == ticks
        assert leg["stats"]["drain"]["recv_calls"] >= ticks

    def test_dispatch_fd_floor_is_constant_in_b(self):
        """The dispatch mode's whole point: B slots, O(1) fds."""
        cfg = Config.for_uint(16)
        for b in (2, 6):
            clock = [0]
            pool = HostSessionPool()
            hub = DispatchHub(siblings=1)
            peer_ports = []
            for m in range(b):
                peer = UdpNonBlockingSocket(0)
                peer_ports.append(peer)
                pool.add_session(
                    SessionBuilder(cfg)
                    .with_clock(lambda: clock[0])
                    .with_rng(random.Random(m))
                    .add_player(Local(), 0)
                    .add_player(
                        Remote(("127.0.0.1", peer.local_port())), 1
                    ),
                    hub.view(),
                )
            for i in range(3):
                clock[0] += 16
                for m in range(b):
                    pool.add_local_input(m, 0, i)
                for reqs in pool.advance_all():
                    fulfill(reqs)
            n_fds = len(hub.filenos())
            assert n_fds == (2 if hub.reuseport else 1)
            assert pool._drain_n_fds == n_fds, (
                "drain plan fd count must equal the hub's fds, not B"
            )
            assert pool._drain_n_routes == b
            hub.close()
            for p in peer_ports:
                p.close()


# ----------------------------------------------------------------------
# §9 supervision through the shared fd
# ----------------------------------------------------------------------


@needs_gen2
class TestDispatchFaultIsolation:
    def test_shared_fd_fatal_evicts_only_the_owner(self):
        """A fatal send errno on ONE dispatch record faults exactly the
        owning slot; co-tenants on the same fd stay native and keep
        advancing."""
        lib = _native.net_lib()
        cfg = Config.for_uint(16)
        clock = [0]
        pool = HostSessionPool()
        hub = DispatchHub()
        n = 3
        peers, peer_socks = [], []
        for m in range(n):
            view = hub.view()
            peer_sock = UdpNonBlockingSocket(0)
            pool.add_session(
                SessionBuilder(cfg)
                .with_clock(lambda: clock[0])
                .with_rng(random.Random(3 + 5 * m))
                .add_player(Local(), 0)
                .add_player(
                    Remote(("127.0.0.1", peer_sock.local_port())), 1
                ),
                view,
            )
            peer = (
                SessionBuilder(cfg)
                .with_clock(lambda: clock[0])
                .with_rng(random.Random(4 + 5 * m))
                .add_player(Local(), 1)
                .add_player(Remote(("127.0.0.1", hub.local_port())), 0)
            ).start_p2p_session(peer_sock)
            peers.append(peer)
            peer_socks.append(peer_sock)

        def tick(i):
            clock[0] += 16
            for m, peer in enumerate(peers):
                peer.add_local_input(1, (i + m) % 16)
                fulfill(peer.advance_frame())
                pool.add_local_input(m, 0, (i + m) % 16)
            for reqs in pool.advance_all():
                fulfill(reqs)

        for i in range(20):
            tick(i)
        assert all(pool.slot_state(m) == "native" for m in range(n))
        # fatal errno on the FIRST outbound record of the next flush:
        # its owner (one slot) faults; the run continues for co-tenants
        lib.ggrs_net_inject_table_errno(errno.EPERM, 0, 1)
        try:
            tick(20)
        finally:
            lib.ggrs_net_inject_table_errno(0, 0, 0)
        states = [pool.slot_state(m) for m in range(n)]
        assert states.count("native") == n - 1, (
            f"exactly one slot must fault, got {states}"
        )
        before = [pool.current_frame(m) for m in range(n)]
        for i in range(21, 90):
            tick(i)
        states = [pool.slot_state(m) for m in range(n)]
        assert states.count("native") == n - 1, (
            f"blast radius exceeded one slot: {states}"
        )
        bad = next(m for m in range(n) if states[m] != "native")
        assert states[bad] == "evicted"
        for m in range(n):
            # co-tenants AND the evicted slot (Python path) keep playing
            assert pool.current_frame(m) > before[m], (
                f"slot {m} stalled after the shared-fd fault"
            )
        # the starvation regression: the native drain keeps reading the
        # SHARED fd after the eviction, so the evicted slot's inbound
        # must be delivered to its view (never dropped as unroutable)
        # and the slot must keep pace far past the prediction window
        assert pool.io_stats()["drain"]["unroutable"] == 0, (
            "evicted co-tenant's datagrams were dropped as unroutable"
        )
        assert pool.current_frame(bad) > 60, (
            f"evicted slot starved at frame {pool.current_frame(bad)}"
        )
        hub.close()


# ----------------------------------------------------------------------
# per-feature degradation + the capability matrix
# ----------------------------------------------------------------------


@needs_gen2
class TestDegradation:
    def _mini_pool(self, n=2, dispatch=False, siblings=0):
        cfg = Config.for_uint(16)
        clock = [0]
        pool = HostSessionPool()
        hub = DispatchHub(siblings=siblings) if dispatch else None
        peers, peer_socks = [], []
        for m in range(n):
            host_sock = hub.view() if hub else UdpNonBlockingSocket(0)
            host_port = host_sock.local_port()
            peer_sock = UdpNonBlockingSocket(0)
            pool.add_session(
                SessionBuilder(cfg)
                .with_clock(lambda: clock[0])
                .with_rng(random.Random(3 + 5 * m))
                .add_player(Local(), 0)
                .add_player(
                    Remote(("127.0.0.1", peer_sock.local_port())), 1
                ),
                host_sock,
            )
            peer = (
                SessionBuilder(cfg)
                .with_clock(lambda: clock[0])
                .with_rng(random.Random(4 + 5 * m))
                .add_player(Local(), 1)
                .add_player(Remote(("127.0.0.1", host_port)), 0)
            ).start_p2p_session(peer_sock)
            peers.append(peer)
            peer_socks.append(peer_sock)
        return pool, clock, peers, hub

    def _run(self, pool, clock, peers, ticks=40):
        for i in range(ticks):
            clock[0] += 16
            for m, peer in enumerate(peers):
                peer.add_local_input(1, (i + m) % 16)
                fulfill(peer.advance_frame())
            for m in range(len(peers)):
                pool.add_local_input(m, 0, (i + m) % 16)
            for reqs in pool.advance_all():
                fulfill(reqs)

    def test_no_recv_table_env_forces_reference_drain(self, monkeypatch):
        monkeypatch.setenv("GGRS_TPU_NO_RECV_TABLE", "1")
        pool, clock, peers, _ = self._mini_pool()
        self._run(pool, clock, peers)
        s = pool.io_stats()
        assert not s["capabilities"]["recv_table"]
        assert s["drain"]["crossings"] == 0
        assert pool.current_frame(0) > 20  # the fallback still plays

    def test_no_gso_env_forces_per_datagram_sends(self, monkeypatch):
        monkeypatch.setenv("GGRS_TPU_NO_GSO", "1")
        pool, clock, peers, _ = self._mini_pool()
        try:
            self._run(pool, clock, peers)
            s = pool.io_stats()
            assert not s["capabilities"]["gso"]
            assert s["drain"]["datagrams"] > 0  # recv-table unaffected
            assert s["gso"] == {"gso_sends": 0, "gso_segments": 0}
        finally:
            lib = _native.net_lib()
            if lib is not None and hasattr(lib, "ggrs_net_set_gso"):
                lib.ggrs_net_set_gso(-1)  # global posture: restore

    def test_missing_reuseport_runs_single_fd(self, monkeypatch):
        # a kernel without SO_REUSEPORT: the hub silently runs one fd —
        # dispatch still works, just without sibling spreading
        import ggrs_tpu.net.sockets as sockets_mod

        monkeypatch.delattr(
            sockets_mod._socket, "SO_REUSEPORT", raising=False
        )
        hub = DispatchHub(siblings=3)
        try:
            assert not hub.reuseport
            assert len(hub.filenos()) == 1
        finally:
            hub.close()
        pool, clock, peers, hub = self._mini_pool(dispatch=True,
                                                  siblings=3)
        try:
            self._run(pool, clock, peers)
            assert pool.current_frame(0) > 20
            assert len(hub.filenos()) == 1
            s = pool.io_stats()
            assert s["capabilities"]["dispatch"]
            assert not s["capabilities"]["reuseport"]
        finally:
            hub.close()

    def test_capability_matrix_reports_dispatch(self):
        pool, clock, peers, hub = self._mini_pool(dispatch=True,
                                                  siblings=1)
        try:
            self._run(pool, clock, peers, ticks=10)
            caps = pool.io_capabilities()
            assert caps["dispatch"] and caps["recv_table"]
            assert set(caps) == {
                "native_io", "recv_table", "send_table", "dispatch",
                "reuseport", "gso", "gro", "gro_active",
                "parallel_decode", "decode_backend",
            }
        finally:
            hub.close()


# ----------------------------------------------------------------------
# GSO spectator fan-out: pool-level viewer-stream parity
# ----------------------------------------------------------------------


@needs_gen2
class TestGsoFanoutParity:
    def test_viewer_streams_identical_with_and_without_gso(self):
        """The spectator fan-out bytes every viewer observes must be
        bit-identical whether the flush rides GSO segmented sends or the
        per-datagram reference — and the drain keeps viewer inbound
        (acks) flowing either way."""
        from ggrs_tpu.broadcast import SpectatorHub
        from ggrs_tpu.core.errors import (
            NotSynchronized,
            PredictionThreshold,
        )

        def leg(no_gso: bool, no_fastpath: bool = False):
            saved = {
                k: os.environ.get(k)
                for k in ("GGRS_TPU_NO_GSO", "GGRS_TPU_NO_FASTPATH")
            }
            if no_gso:
                os.environ["GGRS_TPU_NO_GSO"] = "1"
            if no_fastpath:
                os.environ["GGRS_TPU_NO_FASTPATH"] = "1"
            try:
                cfg = Config.for_uint(16)
                clock = [0]
                pool = HostSessionPool()
                shub = SpectatorHub(pool, rng=random.Random(77))
                host_sock = UdpNonBlockingSocket(0)
                host_port = host_sock.local_port()
                peer_sock = UdpNonBlockingSocket(0)
                pool.add_session(
                    SessionBuilder(cfg)
                    .with_clock(lambda: clock[0])
                    .with_rng(random.Random(3))
                    .add_player(Local(), 0)
                    .add_player(
                        Remote(("127.0.0.1", peer_sock.local_port())), 1
                    ),
                    host_sock,
                )
                peer = (
                    SessionBuilder(cfg)
                    .with_clock(lambda: clock[0])
                    .with_rng(random.Random(4))
                    .add_player(Local(), 1)
                    .add_player(Remote(("127.0.0.1", host_port)), 0)
                ).start_p2p_session(peer_sock)
                viewers, tapes = [], []
                for v in range(3):
                    vsock_inner = UdpNonBlockingSocket(0)
                    vsock = FaultyTapPeerSocket(vsock_inner, 50 + v)
                    vaddr = ("127.0.0.1", vsock_inner.local_port())
                    viewer = (
                        SessionBuilder(cfg)
                        .with_clock(lambda: clock[0])
                        .with_rng(random.Random(7000 + v))
                    ).start_spectator_session(
                        ("127.0.0.1", host_port), vsock
                    )
                    shub.attach(0, vaddr)
                    viewers.append(viewer)
                    tapes.append(vsock)
                for i in range(80):
                    clock[0] += 16
                    peer.add_local_input(1, i % 16)
                    fulfill(peer.advance_frame())
                    pool.add_local_input(0, 0, i % 16)
                    for reqs in pool.advance_all():
                        fulfill(reqs)
                    for sock in tapes:
                        sock.flush()
                    for viewer in viewers:
                        try:
                            fulfill(viewer.advance_frame())
                        except (NotSynchronized, PredictionThreshold):
                            pass
                for sock in tapes:
                    sock.receive_all_datagrams()
                return dict(
                    tapes=[s.tape for s in tapes],
                    frames=[v.current_frame for v in viewers],
                    gso=pool.io_stats()["gso"],
                )
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
                lib = _native.net_lib()
                if lib is not None and hasattr(lib, "ggrs_net_set_gso"):
                    lib.ggrs_net_set_gso(-1)

        on = leg(no_gso=False)
        off = leg(no_gso=True)
        ref = leg(no_gso=True, no_fastpath=True)  # per-datagram send_raw
        assert on["tapes"] == off["tapes"] == ref["tapes"], (
            "viewer streams diverged across GSO/send-table modes"
        )
        assert on["frames"] == off["frames"] == ref["frames"]
        assert any(f > 40 for f in on["frames"]), "viewers never synced"
        assert off["gso"]["gso_sends"] == 0
        lib = _native.net_lib()
        if lib.ggrs_net_gso_supported():
            assert on["gso"]["gso_sends"] > 0, (
                "GSO never engaged on the fan-out path"
            )
