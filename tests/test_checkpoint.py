"""Durable checkpoint/resume for the device sessions.

The reference's checkpoint machinery is in-memory only (SURVEY §5); the
device sessions add disk persistence: a resumed session must be bit-exactly
indistinguishable from one that never stopped — same live states, same
desync verdicts — including across a mesh-shape change for batched sessions
(preemptible-TPU resume may land on a different topology)."""

import numpy as np
import pytest

import jax.numpy as jnp

from ggrs_tpu.core.errors import InvalidRequest
from ggrs_tpu.games import BoxGame, ChipVM
from ggrs_tpu.parallel import BatchedSessions, make_mesh, make_mesh2d
from ggrs_tpu.sessions import DeviceSyncTestSession


def _inputs(n, players, seed):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, 16, size=(n, players)).astype(np.uint8))


class TestDeviceSynctestCheckpoint:
    def test_resume_is_bit_exact(self, tmp_path):
        game = BoxGame(2)
        path = str(tmp_path / "sess.npz")

        def fresh():
            return DeviceSyncTestSession(
                game.advance, game.init_state(), jnp.zeros((2,), jnp.uint8),
                check_distance=3, max_prediction=8,
            )

        head, tail = _inputs(20, 2, seed=1), _inputs(15, 2, seed=2)

        a = fresh()
        a.run_ticks(head)
        a.save_checkpoint(path)
        a.run_ticks(tail)

        b = fresh()
        b.load_checkpoint(path)
        assert b.current_frame == 20
        b.run_ticks(tail)

        sa, sb = a.live_state(), b.live_state()
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(np.asarray(sa[k]), np.asarray(sb[k]))

    def test_extensionless_path_round_trips(self, tmp_path):
        """np.savez silently appends .npz; save/load must agree on the name
        whichever form the caller used (review finding, round 3)."""
        game = BoxGame(2)
        path = str(tmp_path / "ckpt")  # no extension
        a = DeviceSyncTestSession(
            game.advance, game.init_state(), jnp.zeros((2,), jnp.uint8),
            check_distance=2,
        )
        a.run_ticks(_inputs(6, 2, seed=9))
        a.save_checkpoint(path)
        b = DeviceSyncTestSession(
            game.advance, game.init_state(), jnp.zeros((2,), jnp.uint8),
            check_distance=2,
        )
        b.load_checkpoint(path)
        assert b.current_frame == 6

    def test_wrong_config_rejected(self, tmp_path):
        game = BoxGame(2)
        path = str(tmp_path / "sess.npz")
        a = DeviceSyncTestSession(
            game.advance, game.init_state(), jnp.zeros((2,), jnp.uint8),
            check_distance=3,
        )
        a.run_ticks(_inputs(8, 2, seed=3))
        a.save_checkpoint(path)

        b = DeviceSyncTestSession(
            game.advance, game.init_state(), jnp.zeros((2,), jnp.uint8),
            check_distance=2,
        )
        with pytest.raises((InvalidRequest, ValueError)):
            b.load_checkpoint(path)


class TestBatchedCheckpoint:
    def test_resume_across_mesh_shapes(self, tmp_path):
        """Save on the flat 8-chip mesh, resume on the 2-D (2, 4) mesh: the
        preemptible-resume scenario where topology changes under the job."""
        vm = ChipVM(2)
        B = 16
        path = str(tmp_path / "batch.npz")

        def fresh(mesh):
            return BatchedSessions(
                vm.advance, vm.init_state(), jnp.zeros((2,), jnp.uint8),
                batch_size=B, mesh=mesh, check_distance=2, max_prediction=4,
            )

        rng = np.random.default_rng(7)
        head = jnp.asarray(rng.integers(0, 256, size=(B, 10, 2), dtype=np.uint8))
        tail = jnp.asarray(rng.integers(0, 256, size=(B, 8, 2), dtype=np.uint8))

        a = fresh(make_mesh(8))
        assert a.run_ticks(head)["mismatches"] == 0
        a.save_checkpoint(path)
        assert a.run_ticks(tail)["mismatches"] == 0

        b = fresh(make_mesh2d(2, 4))
        b.load_checkpoint(path)
        assert b.current_frame == 10
        assert b.run_ticks(tail)["mismatches"] == 0

        la, lb = a.live_states(), b.live_states()
        for k in ("mem", "regs", "pc"):
            np.testing.assert_array_equal(np.asarray(la[k]), np.asarray(lb[k]))

    def test_wrong_batch_size_rejected(self, tmp_path):
        vm = ChipVM(2)
        path = str(tmp_path / "batch.npz")
        a = BatchedSessions(
            vm.advance, vm.init_state(), jnp.zeros((2,), jnp.uint8),
            batch_size=16, mesh=make_mesh(8), check_distance=2,
        )
        a.save_checkpoint(path)
        b = BatchedSessions(
            vm.advance, vm.init_state(), jnp.zeros((2,), jnp.uint8),
            batch_size=8, mesh=make_mesh(8), check_distance=2,
        )
        with pytest.raises((InvalidRequest, ValueError)):
            b.load_checkpoint(path)
