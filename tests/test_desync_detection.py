"""Tests for the reference desync-detection path in ``sessions/p2p.py``
(p2p_session.rs:904-975) — interval scheduling, checksum compare, and
event emission under lossy traffic — previously pinned only indirectly.

The driver is ``ggrs_tpu.chaos.drive_desync_forensics``: two Python
``P2PSession`` peers with ``DesyncDetection.on(interval)`` where peer B's
saves carry perturbed checksums from ``fault_frame`` on (the classic
nondeterminism bug, seeded at a known frame).
"""

from __future__ import annotations

from ggrs_tpu.chaos import drive_desync_forensics
from ggrs_tpu.core.types import DesyncDetected
from ggrs_tpu.net.protocol import MAX_CHECKSUM_HISTORY_SIZE

# far past any driven frame: the "no fault" sentinel
NEVER = 1 << 40


class TestIntervalScheduling:
    def test_reports_land_on_the_interval_grid(self):
        """With interval K the session sends checksum reports for frames
        K, 2K, 3K, ... (reference: frame_to_send starts at the interval
        and advances by it) — and a clean run emits no events."""
        run = drive_desync_forensics(120, fault_frame=NEVER, interval=3,
                                     seed=1)
        for side in (0, 1):
            frames = sorted(run[("a", "b")[side]]._local_checksum_history)
            assert frames, "no checksum reports were ever scheduled"
            assert all(f % 3 == 0 and f > 0 for f in frames)
            # consecutive grid points: the scheduler never skips one
            assert frames == list(range(frames[0], frames[-1] + 3, 3))
        assert not run["desyncs"][0] and not run["desyncs"][1]

    def test_remote_history_mirrors_the_grid(self):
        """What each peer accumulates from the other's reports sits on the
        same grid (the compare consumes pending_checksums; the forensic
        window keeps them) — held by the attached flight recorder, with
        the session-local store staying empty (one store, never both)."""
        run = drive_desync_forensics(120, fault_frame=NEVER, interval=4,
                                     seed=2)
        hist = run["recorders"][0].remote_checksums
        assert len(hist) == 1
        frames = next(iter(hist.values())).frames()
        assert frames and all(f % 4 == 0 for f in frames)
        assert not run["a"]._remote_checksum_history

    def test_remote_history_without_recorder(self):
        """No recorder attached: the window falls back to the session's
        own store and reports still bisect."""
        from ggrs_tpu.chaos import two_peer_builder
        from ggrs_tpu.core.types import DesyncDetection
        from ggrs_tpu.net import InMemoryNetwork

        clock = [0]
        net = InMemoryNetwork(latency_ticks=1, seed=21)
        sessions = [
            two_peer_builder(clock, 60 + me, me, ("B", "A")[me])
            .with_desync_detection_mode(DesyncDetection.on(1))
            .start_p2p_session(net.socket(("A", "B")[me]))
            for me in (0, 1)
        ]
        for i in range(120):
            clock[0] += 16
            for me, s in enumerate(sessions):
                s.add_local_input(me, i % 16)
                for r in s.advance_frame():
                    if type(r).__name__ == "SaveGameState":
                        cs = r.frame + (500 if me == 1 and r.frame >= 30
                                        else 0)
                        r.cell.save(r.frame, r.frame, cs)
                s.events()
            net.tick()
        assert sessions[0]._remote_checksum_history
        assert sessions[0].desync_reports
        assert sessions[0].desync_reports[0].first_divergent_frame == 30

    def test_local_history_pruned_to_max(self):
        """The local checksum history stays bounded by
        MAX_CHECKSUM_HISTORY_SIZE (reference: p2p_session.rs:966-975)."""
        run = drive_desync_forensics(
            MAX_CHECKSUM_HISTORY_SIZE + 120, fault_frame=NEVER, interval=1,
            seed=3,
        )
        hist = run["a"]._local_checksum_history
        assert 0 < len(hist) <= MAX_CHECKSUM_HISTORY_SIZE
        # pruning keeps the newest window
        sent = run["a"]._last_sent_checksum_frame
        assert max(hist) == sent


class TestChecksumCompare:
    def test_divergence_detected_on_both_ends(self):
        """A state divergence at frame F with interval 1 fires
        DesyncDetected on BOTH peers, first at exactly frame F, carrying
        the two differing checksums."""
        run = drive_desync_forensics(160, fault_frame=40, interval=1,
                                     seed=4)
        for side in (0, 1):
            events = run["desyncs"][side]
            assert events, f"peer {side} never detected the desync"
            first = min(events, key=lambda e: e.frame)
            assert first.frame == 40
            assert first.local_checksum != first.remote_checksum
            assert isinstance(first, DesyncDetected)

    def test_detection_lands_on_next_grid_point(self):
        """With interval K, a fault between grid points is first detected
        at the next reported frame (the interval is the detection
        granularity)."""
        run = drive_desync_forensics(200, fault_frame=42, interval=4,
                                     seed=5)
        assert min(e.frame for e in run["desyncs"][0]) == 44
        assert min(e.frame for e in run["desyncs"][1]) == 44

    def test_agreeing_frames_never_fire(self):
        """Every detected frame is at or after the fault — frames before
        it agreed and must not fire (false positives page humans)."""
        run = drive_desync_forensics(200, fault_frame=60, interval=1,
                                     seed=6)
        for side in (0, 1):
            assert all(e.frame >= 60 for e in run["desyncs"][side])


class TestUnderLossAndReorder:
    def test_detection_survives_faulty_transport(self):
        """Checksum reports ride the unreliable channel (no retransmit):
        loss/dup/reorder may delay detection past the fault frame but must
        not break it, and must never produce a pre-fault detection."""
        run = drive_desync_forensics(
            400, fault_frame=50, interval=2, seed=7,
            fault_cfg=dict(latency_ticks=1, loss=0.05, duplicate=0.03,
                           reorder=0.05, seed=77),
        )
        for side in (0, 1):
            events = run["desyncs"][side]
            assert events, f"peer {side} lost the desync to packet loss"
            assert min(e.frame for e in events) >= 50

    def test_clean_under_faulty_transport(self):
        """Loss and reordering alone (no state fault) never fabricate a
        desync."""
        run = drive_desync_forensics(
            300, fault_frame=NEVER, interval=2, seed=8,
            fault_cfg=dict(latency_ticks=1, loss=0.08, duplicate=0.05,
                           reorder=0.08, seed=78),
        )
        assert not run["desyncs"][0] and not run["desyncs"][1]
