"""Satellite pins for the socket-layer datapath changes (DESIGN.md §15):

- ``receive_all_datagrams`` drains through ONE persistent buffer
  (``recvfrom_into``) instead of allocating 4 KiB per datagram — a burst
  of N datagrams must come back intact and order-preserved (the buffer is
  reused, so any aliasing bug corrupts earlier entries);
- ``send_datagram`` is the raw sibling of ``send_to`` (no Message
  wrapper, no re-encode) on both the UDP socket and the in-memory fake;
- the oversized-packet warning fires once per (addr, size-class) while
  the counter keeps counting every oversized datagram;
- per-socket syscall accounting (``io_syscalls``) matches the
  datagram-plus-probe arithmetic the host_bank_io bench relies on.
"""

from __future__ import annotations

import logging
import random

from ggrs_tpu.net.messages import KeepAlive, Message, RawMessage
from ggrs_tpu.net.sockets import (
    IDEAL_MAX_UDP_PACKET_SIZE,
    InMemoryNetwork,
    UdpNonBlockingSocket,
)


def _pair():
    a = UdpNonBlockingSocket(0)
    b = UdpNonBlockingSocket(0)
    return a, b, ("127.0.0.1", a.local_port()), ("127.0.0.1", b.local_port())


class TestPersistentReceiveBuffer:
    def test_burst_intact_and_order_preserved(self):
        """N datagrams of varying sizes, one drain: every payload intact
        (the persistent buffer must not alias earlier returns) and in
        send order."""
        a, b, _, addr_b = _pair()
        try:
            rng = random.Random(7)
            payloads = [
                bytes(rng.randrange(256) for _ in range(rng.randrange(1, 900)))
                for _ in range(50)
            ]
            for p in payloads:
                a.send_datagram(p, addr_b)
            got = b.receive_all_datagrams()
            assert [d for _, d in got] == payloads
            assert all(src[0] == "127.0.0.1" for src, _ in got)
            # the follow-up drain is empty, not a repeat
            assert b.receive_all_datagrams() == []
        finally:
            a.close()
            b.close()

    def test_truncation_matches_recv_buffer_size(self):
        """Datagrams above the 4096-byte receive buffer truncate (the
        recvfrom contract the persistent buffer must preserve)."""
        a, b, _, addr_b = _pair()
        try:
            a.send_datagram(b"\xab" * 6000, addr_b)
            got = b.receive_all_datagrams()
            assert len(got) == 1
            assert got[0][1] == b"\xab" * 4096
        finally:
            a.close()
            b.close()

    def test_syscall_accounting(self):
        """Each datagram is one recvfrom; the EAGAIN probe is one more —
        the per-socket counter the io bench sums."""
        a, b, _, addr_b = _pair()
        try:
            base = b.io_syscalls
            for i in range(5):
                a.send_datagram(bytes([i]), addr_b)
            assert len(b.receive_all_datagrams()) == 5
            assert b.io_syscalls - base == 6  # 5 datagrams + 1 probe
            sends = a.io_syscalls
            assert sends >= 5
        finally:
            a.close()
            b.close()


class TestSendDatagram:
    def test_raw_send_equals_wrapped_send(self):
        """send_datagram(bytes) puts the same wire bytes out as
        send_to(RawMessage(bytes)) — the bank/hub path stops paying the
        wrapper + re-encode for already-encoded datagrams."""
        a, b, _, addr_b = _pair()
        try:
            wire = Message(0x1234, KeepAlive()).encode()
            a.send_datagram(wire, addr_b)
            a.send_to(RawMessage(wire), addr_b)
            got = [d for _, d in b.receive_all_datagrams()]
            assert got == [wire, wire]
        finally:
            a.close()
            b.close()

    def test_fake_socket_send_datagram_parity(self):
        """FakeSocket.send_datagram rides the same fault-injection path
        (and the same rng stream) as send_to."""
        wire = Message(0x4242, KeepAlive()).encode()
        net_a = InMemoryNetwork(seed=3, loss=0.3, duplicate=0.2, reorder=0.2)
        net_b = InMemoryNetwork(seed=3, loss=0.3, duplicate=0.2, reorder=0.2)
        sa, sb = net_a.socket("S"), net_b.socket("S")
        net_a.socket("D")
        net_b.socket("D")
        for _ in range(50):
            sa.send_datagram(wire, "D")
            sb.send_to(RawMessage(wire), "D")
        got_a = net_a._receive_raw("D")
        got_b = net_b._receive_raw("D")
        assert got_a == got_b
        assert 0 < len(got_a) < 70  # faults actually fired

    def test_oversized_warning_rate_limited(self, caplog):
        """One warning per (addr, size-class); the obs counter still
        counts every oversized datagram."""
        from ggrs_tpu.net import sockets as sockets_mod

        a, b, _, addr_b = _pair()
        c = UdpNonBlockingSocket(0)
        addr_c = ("127.0.0.1", c.local_port())
        try:
            counter = sockets_mod._OBS_OVERSIZED
            base = counter.value
            big = b"x" * (IDEAL_MAX_UDP_PACKET_SIZE + 100)   # class 1
            bigger = b"y" * (IDEAL_MAX_UDP_PACKET_SIZE + 700)  # class 2
            with caplog.at_level(logging.WARNING, logger="ggrs_tpu.net.sockets"):
                for _ in range(4):
                    a.send_datagram(big, addr_b)       # 4 sends, 1 warning
                a.send_datagram(bigger, addr_b)        # new class: warns
                a.send_datagram(big, addr_c)           # new addr: warns
                a.send_datagram(b"z" * 10, addr_b)     # small: never warns
            warnings = [
                r for r in caplog.records
                if "larger than ideal" in r.getMessage()
            ]
            assert len(warnings) == 3
            assert counter.value - base == 6
        finally:
            a.close()
            b.close()
            c.close()
