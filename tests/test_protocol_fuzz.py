"""Session-level adversarial fuzz (VERDICT r3 item 5).

The codec and message layers are property-tested in isolation
(tests/test_compression.py, tests/test_messages.py); this module attacks the
layer above: arbitrary and mutated datagrams flowing through a live
``PeerProtocol`` and a polled P2P session.  The reference hardens
decode-of-arbitrary-bytes at the codec (compression.rs:205-213) and drops
undecodable datagrams at the socket (udp_socket.rs:70-72); our contract is
stronger — no exception may escape, session state stays consistent, and
memory stays bounded, no matter what bytes arrive.
"""

from __future__ import annotations

import random

import pytest

pytest.importorskip("hypothesis")  # fuzz-only dep: absent on lean CI images

from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

from ggrs_tpu.core.config import Config
from ggrs_tpu.core.frame_info import PlayerInput
from ggrs_tpu.core.types import DesyncDetection, Local, Remote
from ggrs_tpu.net.messages import (
    ConnectionStatus,
    InputMessage,
    Message,
)
from ggrs_tpu.net.protocol import PENDING_OUTPUT_SIZE, PeerProtocol
from ggrs_tpu.net.sockets import InMemoryNetwork
from ggrs_tpu.sessions.builder import SessionBuilder
from ggrs_tpu.games.boxgame import boxgame_config

FUZZ_SETTINGS = settings(
    max_examples=120,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)


def make_proto(seed: int = 7) -> PeerProtocol:
    return PeerProtocol(
        config=Config.for_uint(bits=8),
        handles=[1],
        peer_addr="B",
        num_players=2,
        local_players=1,
        max_prediction=8,
        disconnect_timeout_ms=2000,
        disconnect_notify_start_ms=500,
        fps=60,
        desync_detection=DesyncDetection.off(),
        clock=lambda: 0,
        rng=random.Random(seed),
    )


def realistic_input_message(rng: random.Random) -> bytes:
    """A well-formed InputMessage with randomized fields, as mutation
    seed material."""
    statuses = [
        ConnectionStatus(rng.random() < 0.2, rng.randrange(-1, 100))
        for _ in range(2)
    ]
    body = InputMessage(
        peer_connect_status=statuses,
        disconnect_requested=rng.random() < 0.05,
        start_frame=rng.randrange(-1, 50),
        ack_frame=rng.randrange(-1, 50),
        bytes=bytes(rng.randrange(256) for _ in range(rng.randrange(0, 24))),
    )
    return Message(rng.randrange(1, 1 << 16), body).encode()


def checked_pump(proto: PeerProtocol, datagrams) -> None:
    """Feed datagrams then poll; nothing may raise, and bounded-memory
    invariants must hold."""
    status = [ConnectionStatus(), ConnectionStatus()]
    for data in datagrams:
        proto.handle_datagram(bytes(data))
    proto.poll(status)
    # memory bounds: the pending window and event queue cannot be grown by
    # inbound garbage; the recv ring is bounded by construction
    assert proto._core.pending_len() <= PENDING_OUTPUT_SIZE + 1
    assert len(proto._event_queue) <= 4096


class TestArbitraryDatagrams:
    @FUZZ_SETTINGS
    @given(st.lists(st.binary(min_size=0, max_size=96), max_size=24))
    def test_random_bytes_never_crash(self, blobs):
        proto = make_proto()
        checked_pump(proto, blobs)

    @FUZZ_SETTINGS
    @given(
        st.integers(0, 2**32 - 1),
        st.lists(
            st.tuples(st.integers(0, 400), st.integers(0, 255)), max_size=12
        ),
    )
    def test_mutated_real_messages_never_crash(self, seed, flips):
        """Start from well-formed wire bytes, then flip bytes — the
        highest-yield corruption class (passes length prefixes and tag
        checks more often than pure noise)."""
        rng = random.Random(seed)
        proto = make_proto()
        datagrams = []
        for _ in range(6):
            data = bytearray(realistic_input_message(rng))
            for pos, val in flips:
                if data:
                    data[pos % len(data)] ^= val
            datagrams.append(bytes(data))
        checked_pump(proto, datagrams)

    @FUZZ_SETTINGS
    @given(st.integers(0, 2**32 - 1), st.integers(1, 40))
    def test_truncations_and_splices_never_crash(self, seed, cut):
        rng = random.Random(seed)
        proto = make_proto()
        a = realistic_input_message(rng)
        b = realistic_input_message(rng)
        datagrams = [
            a[: cut % (len(a) + 1)],            # truncated
            a + b[: cut % (len(b) + 1)],        # trailing garbage
            b[cut % len(b):],                   # missing header
            a[: len(a) // 2] + b[len(b) // 2:],  # spliced halves
        ]
        checked_pump(proto, datagrams)

    def test_huge_claimed_lengths_do_not_allocate(self):
        """Length prefixes claiming enormous payloads must be rejected
        before any allocation of that size (memory-amplification)."""
        proto = make_proto()
        # InputMessage header + uvarint byte-length claiming ~2^60 bytes
        evil = bytes.fromhex("aabb00") + b"\x00" + b"\x00" + b"\x00\x00" + (
            b"\xff\xff\xff\xff\xff\xff\xff\xff\x0f"
        )
        checked_pump(proto, [evil] * 8)


class TestFuzzedLiveSession:
    def drive_session_under_attack(self, mutate, require_liveness=True) -> None:
        """Two honest peers + an attacker spoofing peer B's address into
        peer A's socket.  Nothing may raise, and memory stays bounded.

        With ``require_liveness`` the match must also keep advancing —
        right for injected *garbage*, which can never decode to a valid
        message.  Mutated-but-valid protocol messages are a different
        contract: the wire carries no authentication (the reference fork
        does not even verify the magic, p2p_session.rs:433-440), so a
        spoofed valid disconnect/status message MAY legitimately
        disconnect a player; the required outcome then is a *clean*
        protocol disconnect, never a crash or corruption."""
        net = InMemoryNetwork()
        sessions = []
        for me, other, h in (("A", "B", 0), ("B", "A", 1)):
            sessions.append(
                SessionBuilder(boxgame_config())
                .with_clock(lambda: 0)
                .with_rng(random.Random(21 + h))
                .add_player(Local(), h)
                .add_player(Remote(other), 1 - h)
                .start_p2p_session(net.socket(me))
            )
        attacker = net.socket("EVIL")
        rng = random.Random(5)
        state = [0, 0]
        for i in range(120):
            # attacker spoofs B→A traffic every tick
            for data in mutate(rng):
                q = net._queues["A"]
                q.append((net._tick, "B", bytes(data)))
            for s in sessions:
                s.poll_remote_clients()
            for h, s in enumerate(sessions):
                s.add_local_input(h, (i + h) % 16)
                for r in s.advance_frame():
                    k = type(r).__name__
                    if k == "SaveGameState":
                        r.cell.save(r.frame, state[h], None)
                    elif k == "LoadGameState":
                        state[h] = r.cell.data()
        frames = [s.current_frame for s in sessions]
        if require_liveness:
            assert all(f == 120 for f in frames), frames
        else:
            disconnected = any(
                st.disconnected
                for s in sessions
                for st in s.local_connect_status
            )
            # either the match survived, or the spoofed control data caused
            # a CLEAN disconnect (attacked peer keeps simulating; the stalled
            # peer sits at its prediction threshold awaiting a timeout)
            assert all(f == 120 for f in frames) or (
                disconnected and max(frames) == 120
            ), (frames, [s.local_connect_status for s in sessions])
        _ = attacker  # the spoof path uses the queue directly

    def test_session_survives_random_garbage(self):
        def mutate(rng):
            return [
                bytes(rng.randrange(256) for _ in range(rng.randrange(0, 64)))
                for _ in range(2)
            ]

        self.drive_session_under_attack(mutate)

    def test_session_survives_mutated_protocol_traffic(self):
        def mutate(rng):
            out = []
            for _ in range(2):
                data = bytearray(realistic_input_message(rng))
                for _ in range(rng.randrange(0, 4)):
                    data[rng.randrange(len(data))] ^= rng.randrange(1, 256)
                out.append(bytes(data))
            return out

        self.drive_session_under_attack(mutate, require_liveness=False)


class TestFuzzedHandshake:
    def pump_pair(self, net, protos, socks, ticks, clock_now):
        status = [ConnectionStatus(), ConnectionStatus()]
        for _ in range(ticks):
            net.tick()
            for me in protos:
                p = protos[me]
                for _, data in socks[me].receive_all_datagrams():
                    p.handle_datagram(data)
                p.poll(status)
                p.send_all_messages(socks[me])

    def test_handshake_survives_truncated_and_reordered_probes(self):
        """Opt-in sync handshake under attack: truncated / duplicated /
        reordered Sync packets plus spoofed garbage must not crash it or
        complete it spuriously; the honest exchange still synchronizes."""
        net = InMemoryNetwork(seed=3, duplicate=0.3, reorder=0.4)
        clock_now = [0]
        protos, socks = {}, {}
        for me, other, h in (("A", "B", 0), ("B", "A", 1)):
            protos[me] = PeerProtocol(
                config=Config.for_uint(bits=8),
                handles=[1 - h],
                peer_addr=other,
                num_players=2,
                local_players=1,
                max_prediction=8,
                disconnect_timeout_ms=2000,
                disconnect_notify_start_ms=500,
                fps=60,
                desync_detection=DesyncDetection.off(),
                clock=lambda: clock_now[0],
                rng=random.Random(33 + h),
                sync_required=True,
            )
            socks[me] = net.socket(me)
        rng = random.Random(12)
        # interleave hostile packets with the honest handshake
        for step in range(40):
            clock_now[0] += 250  # past the sync retry interval
            q = net._queues["A"]
            q.append((net._tick, "B", bytes(
                rng.randrange(256) for _ in range(rng.randrange(0, 12))
            )))
            # truncated SyncReply-shaped bytes
            q.append((net._tick, "B", b"\xaa\xbb\x07"))
            self.pump_pair(net, protos, socks, 1, clock_now)
            if all(p.is_running() for p in protos.values()):
                break
        assert all(p.is_running() for p in protos.values()), (
            protos["A"]._state, protos["B"]._state
        )
