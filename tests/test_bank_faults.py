"""Fault isolation for the supervised session bank (DESIGN.md §9): one bad
peer degrades one match, never the pool.

The chaos scenarios drive faults through the pool's REAL tick path — raw
datagrams spliced into a slot's inbound routing, simulated native slot
errors on the ctrl-op channel, peer blackouts — and pin the headline:

* blast radius = 1 slot (or 0 for malformed datagrams, which are dropped
  before any state advance);
* the surviving slots' wire bytes, request lists, and events stay
  BIT-IDENTICAL to a fault-free control run;
* the crossing count stays exactly one ``ggrs_bank_tick`` per pool tick
  (plus a one-off harvest crossing per eviction);
* an evicted slot resumes the same match on the Python fallback from its
  last committed frame, bit-consistent with what its peer already holds.

Each in-bank match lives on its OWN ``InMemoryNetwork`` so no fault-rng
stream couples matches; the targeted slot's peer is an external
``P2PSession`` so the survivors' traffic is provably independent of the
fault.  The driver is ``ggrs_tpu.chaos.drive_chaos`` — the SAME harness
``scripts/chaos.py`` fronts, so the CLI and this suite exercise one code
path.
"""

from __future__ import annotations

import random

import pytest

from ggrs_tpu.chaos import (
    MALFORMED_BURST,
    blast_radius_violations,
    drive_chaos,
    fulfill,
    two_peer_builder as builder,
)
from ggrs_tpu.core import Local, Remote
from ggrs_tpu.core.config import Config
from ggrs_tpu.net import InMemoryNetwork, _native
from ggrs_tpu.parallel.host_bank import (
    HostSessionPool,
    SLOT_DEAD,
    SLOT_EVICTED,
    SLOT_NATIVE,
)
from ggrs_tpu.sessions import SessionBuilder

needs_native = pytest.mark.skipif(
    _native.bank_lib() is None, reason="native session bank unavailable"
)


def assert_survivors_identical(faulted, control, survivors):
    """The acceptance pin: surviving slots stay bank-resident and
    bit-identical to the fault-free control run — wire bytes, request
    lists, and events — with one crossing per pool tick."""
    violations = blast_radius_violations(faulted, control, survivors)
    assert not violations, violations


@needs_native
class TestBlastRadius:
    """B=9 banked sessions; each fault class touches at most the target."""

    def test_simulated_native_slot_error_quarantines_one_slot(self):
        control = drive_chaos(220)

        def inject(i, ctx):
            if i == 60:
                ctx["pool"].inject_slot_error(ctx["target"])

        run = drive_chaos(220, inject=inject)
        target = run["target"]
        survivors = [i for i in range(len(run["states"])) if i != target]
        assert run["states"][target] == SLOT_EVICTED
        assert all(run["states"][i] == SLOT_NATIVE for i in survivors)
        assert_survivors_identical(run, control, survivors)
        # the one-crossing invariant holds for the survivors; eviction cost
        # exactly one extra harvest crossing, once
        assert run["pool"].crossings == 220
        assert run["pool"].harvests == 1
        # the evicted slot resumed the SAME match: both sides kept advancing
        assert run["pool"].current_frame(target) > 180
        assert run["ext"].current_frame > 180
        codes = [f.code for f in run["pool"].fault_log(target)]
        assert _native.BANK_ERR_INJECTED in codes

    def test_forced_desync_class_fault_quarantines_one_slot(self):
        """A desync-class invariant violation (the errors the pre-supervision
        bank raised as pool-wide AssertionErrors) now costs one slot."""
        control = drive_chaos(220)

        def inject(i, ctx):
            if i == 60:
                ctx["pool"].inject_slot_error(
                    ctx["target"], _native.BANK_ERR_SYNC
                )

        run = drive_chaos(220, inject=inject)
        target = run["target"]
        survivors = [i for i in range(len(run["states"])) if i != target]
        assert run["states"][target] == SLOT_EVICTED
        assert all(run["states"][i] == SLOT_NATIVE for i in survivors)
        assert_survivors_identical(run, control, survivors)
        assert run["pool"].current_frame(target) > 180

    def test_peer_blackout_retires_only_the_target(self):
        """The target's peer goes silent for good: interrupt → disconnect →
        (retire_dead_matches) the dead match is retired.  Everyone else is
        bit-identical to the control run."""
        control = drive_chaos(260, retire=True)
        run = drive_chaos(260, retire=True, ext_alive=lambda i: i < 80)
        target = run["target"]
        survivors = [i for i in range(len(run["states"])) if i != target]
        assert run["states"][target] == SLOT_DEAD
        assert all(run["states"][i] == SLOT_NATIVE for i in survivors)
        assert_survivors_identical(run, control, survivors)
        kinds = [type(e).__name__ for e in run["events"][target]]
        assert "NetworkInterrupted" in kinds
        assert "Disconnected" in kinds
        assert run["pool"].crossings == 260
        # dead slot: request lists went (and stay) empty
        assert run["reqs"][target][-1] == []

    def test_malformed_datagram_burst_is_dropped_radius_zero(self):
        """Truncated/corrupted datagrams are dropped at the native parse
        before ANY state advance (the Python path's WireError handling):
        blast radius 0 — even the targeted slot stays bit-identical, no
        quarantine, and the bank is never invalidated."""
        control = drive_chaos(200)

        def inject(i, ctx):
            if 50 <= i < 60:
                for junk in MALFORMED_BURST:
                    ctx["pool"].inject_datagram(ctx["target"], "X", junk)

        run = drive_chaos(200, inject=inject)
        all_slots = list(range(len(run["states"])))
        assert all(run["states"][i] == SLOT_NATIVE for i in all_slots)
        assert run["pool"].fault_log(run["target"]) == []
        # radius zero: the TARGET too is bit-identical to control
        assert_survivors_identical(run, control, all_slots)
        # and the pool was never invalidated
        assert run["pool"].current_frame(run["target"]) > 180

    def test_malformed_fuzz_never_invalidates_the_bank(self):
        """Seeded random junk through the bank's inbound routing: whatever
        valid-looking packets it accidentally forms behave as the protocol
        defines, but the bank must never be invalidated, never quarantine
        the slot, and the OTHER slots must stay bit-identical."""
        control = drive_chaos(200)
        rng = random.Random(1234)
        junk = [
            bytes(rng.randrange(256) for _ in range(rng.randrange(0, 40)))
            for _ in range(300)
        ]

        def inject(i, ctx):
            if 40 <= i < 140:
                for _ in range(3):
                    ctx["pool"].inject_datagram(
                        ctx["target"], "X", junk[(i * 3) % len(junk)]
                    )

        run = drive_chaos(200, inject=inject)
        target = run["target"]
        survivors = [i for i in range(len(run["states"])) if i != target]
        assert all(run["states"][i] == SLOT_NATIVE for i in survivors)
        assert run["states"][target] == SLOT_NATIVE  # junk is not a fault
        assert_survivors_identical(run, control, survivors)
        assert run["pool"].current_frame(target) > 180
        assert run["ext"].current_frame > 180


@needs_native
class TestEviction:
    def test_eviction_is_bit_consistent_with_the_peer(self):
        """After eviction the peer's stored view of the evicted side's
        inputs must equal the evicted session's own record — across input
        delay and seeded loss/dup/reorder (the pending-window + delta-base
        adoption working end to end)."""
        for delay, faults in [
            (0, None),
            (2, None),
            (0, dict(seed=5, loss=0.1, duplicate=0.05, reorder=0.05,
                     latency_ticks=1)),
        ]:
            clock = [0]
            net = InMemoryNetwork(**(faults or {"latency_ticks": 1}))
            pool = HostSessionPool()
            b = (
                SessionBuilder(Config.for_uint(16))
                .with_clock(lambda: clock[0])
                .with_rng(random.Random(1))
                .with_input_delay(delay)
                .add_player(Local(), 0)
                .add_player(Remote("R"), 1)
            )
            pool.add_session(b, net.socket("L"))
            peer = (
                SessionBuilder(Config.for_uint(16))
                .with_clock(lambda: clock[0])
                .with_rng(random.Random(2))
                .with_input_delay(delay)
                .add_player(Local(), 1)
                .add_player(Remote("L"), 0)
            ).start_p2p_session(net.socket("R"))
            assert pool.native_active

            def tick(i):
                clock[0] += 16
                peer.add_local_input(1, (i * 3) % 16)
                fulfill(peer.advance_frame())
                pool.add_local_input(0, 0, (i * 7) % 16)
                for reqs in pool.advance_all():
                    fulfill(reqs)
                net.tick()

            for i in range(50):
                tick(i)
            pool.inject_slot_error(0)
            for i in range(50, 300):
                tick(i)
            assert pool.slot_state(0) == SLOT_EVICTED
            sess = pool.session(0)
            horizon = peer._sync_layer.last_confirmed_frame
            checked = 0
            for f in range(max(0, horizon - 60), horizon):
                theirs = peer._sync_layer.confirmed_input(0, f).input
                ours = sess._sync_layer.confirmed_input(0, f).input
                assert theirs == ours, (delay, faults, f, theirs, ours)
                checked += 1
            assert checked >= 50
            assert pool.current_frame(0) > 280 and peer.current_frame > 280

    def test_in_bank_peer_survives_its_matchmates_eviction(self):
        """Both sides of a match in the bank; one faults and evicts; the
        match continues across the native/evicted seam."""
        clock = [0]
        net = InMemoryNetwork(latency_ticks=1)
        pool = HostSessionPool()
        for me, name, other in ((0, "L", "R"), (1, "R", "L")):
            pool.add_session(builder(clock, 10 + me, me, other),
                             net.socket(name))
        assert pool.native_active

        def tick(i):
            clock[0] += 16
            for idx in range(2):
                pool.add_local_input(idx, idx, (i + idx) % 16)
            for reqs in pool.advance_all():
                fulfill(reqs)
            net.tick()

        for i in range(40):
            tick(i)
        pool.inject_slot_error(0)
        for i in range(40, 240):
            tick(i)
        assert pool.slot_state(0) == SLOT_EVICTED
        assert pool.slot_state(1) == SLOT_NATIVE
        assert pool.current_frame(0) > 200
        assert pool.current_frame(1) > 200
        assert pool.crossings == 240

    def test_missing_input_for_evicted_slot_raises_before_the_crossing(self):
        """A missing staged input for an EVICTED session must raise in the
        pre-crossing validation — raising after the native crossing would
        lose the healthy slots' request lists for the tick."""
        from ggrs_tpu.core.errors import InvalidRequest

        clock = [0]
        net = InMemoryNetwork(latency_ticks=1)
        pool = HostSessionPool()
        for me, name, other in ((0, "L", "R"), (1, "R", "L")):
            pool.add_session(builder(clock, 10 + me, me, other),
                             net.socket(name))
        assert pool.native_active

        def tick(i, include=(0, 1)):
            clock[0] += 16
            for idx in include:
                pool.add_local_input(idx, idx, (i + idx) % 16)
            for reqs in pool.advance_all():
                fulfill(reqs)
            net.tick()

        for i in range(40):
            tick(i)
        pool.inject_slot_error(0)
        for i in range(40, 60):
            tick(i)
        assert pool.slot_state(0) == SLOT_EVICTED
        crossings = pool.crossings
        clock[0] += 16
        pool.add_local_input(1, 1, 3)  # slot 0's input deliberately missing
        with pytest.raises(InvalidRequest):
            pool.advance_all()
        assert pool.crossings == crossings, (
            "validation must fire BEFORE the native crossing"
        )
        # and the pool is not poisoned: stage properly and keep going
        pool.add_local_input(0, 0, 3)
        for reqs in pool.advance_all():
            fulfill(reqs)
        assert pool.slot_state(1) == SLOT_NATIVE

    def test_eviction_falls_back_to_previous_committed_frame(self):
        """The suppressed-save fault class: a fault tick can raise the
        confirmed watermark and then have its own save op suppressed, so
        the watermark cell was never fulfilled.  Eviction must resume from
        watermark-1 (whose inputs the harvest keeps) instead of dying."""
        clock = [0]
        net = InMemoryNetwork(latency_ticks=1)
        pool = HostSessionPool()
        pool.add_session(builder(clock, 1, 0, "R"), net.socket("L"))
        peer = builder(clock, 2, 1, "L").start_p2p_session(net.socket("R"))
        assert pool.native_active

        def tick(i):
            clock[0] += 16
            peer.add_local_input(1, (i * 3) % 16)
            fulfill(peer.advance_frame())
            pool.add_local_input(0, 0, (i * 7) % 16)
            for reqs in pool.advance_all():
                fulfill(reqs)
            net.tick()

        for i in range(60):
            tick(i)
        # simulate the unfulfilled watermark save, then fault the slot (the
        # injection freezes the slot's tick, so the watermark cannot move
        # between the clobber and the eviction's harvest)
        w = pool._harvest(0)["last_confirmed"]
        assert w > 1
        pool._mirrors[0].saved_states.get_cell(w).save(w + 10 ** 6, None, None)
        pool.inject_slot_error(0)
        for i in range(60, 220):
            tick(i)
        assert pool.slot_state(0) == SLOT_EVICTED, pool.fault_log(0)
        assert any(
            f"resuming from frame {w - 1}" in f.detail
            for f in pool.fault_log(0)
        ), pool.fault_log(0)
        assert pool.current_frame(0) > 180 and peer.current_frame > 180
        # and the resumed stream stays bit-consistent with the peer
        sess = pool.session(0)
        horizon = peer._sync_layer.last_confirmed_frame
        for f in range(max(0, horizon - 40), horizon):
            assert (
                peer._sync_layer.confirmed_input(0, f).input
                == sess._sync_layer.confirmed_input(0, f).input
            )

    def test_unrecoverable_slot_goes_dead_after_bounded_retries(self):
        """Fault before anything is committed (no confirmed frame): eviction
        cannot resume, retries back off, the slot dies — and the pool keeps
        serving the other slots."""
        from ggrs_tpu.parallel.host_bank import EVICT_MAX_ATTEMPTS

        clock = [0]
        net = InMemoryNetwork()  # no latency: still nothing confirmed at t0
        pool = HostSessionPool()
        for me, name, other in ((0, "L", "R"), (1, "R", "L")):
            pool.add_session(builder(clock, 20 + me, me, other),
                             net.socket(name))
        assert pool.native_active
        pool.inject_slot_error(0)  # fires on the very first tick

        def tick(i):
            clock[0] += 16
            for idx in range(2):
                pool.add_local_input(idx, idx, i % 16)
            for reqs in pool.advance_all():
                fulfill(reqs)
            net.tick()

        tick(0)
        assert pool.slot_state(0) == "quarantined"
        # past the 2000 ms disconnect timeout so the healthy slot sheds its
        # dead peer and runs free on dummy inputs
        for i in range(1, 200):
            tick(i)
        assert pool.slot_state(0) == SLOT_DEAD
        attempts = [
            f for f in pool.fault_log(0) if "eviction attempt" in f.detail
        ]
        assert len(attempts) == EVICT_MAX_ATTEMPTS
        assert pool.current_frame(1) > 60

    def test_eviction_feeds_the_batched_executor(self):
        """HostedPool end to end: the evicted slot's Load-leading request
        list parses through BatchedRequestExecutor's grammar and its device
        lane keeps advancing."""
        import numpy as np

        from ggrs_tpu.games import BoxGame, boxgame_config
        from ggrs_tpu.parallel import BatchedRequestExecutor, HostedPool

        game = BoxGame(2)
        clock = [0]
        net = InMemoryNetwork(latency_ticks=1)
        host = HostSessionPool()
        n_matches = 2
        for m in range(n_matches):
            names = (f"A{m}", f"B{m}")
            for me in (0, 1):
                b = (
                    SessionBuilder(boxgame_config())
                    .with_clock(lambda: clock[0])
                    .with_rng(random.Random(7 * m + me))
                    .add_player(Local(), me)
                    .add_player(Remote(names[1 - me]), 1 - me)
                )
                host.add_session(b, net.socket(names[me]))
        executor = BatchedRequestExecutor(
            game.advance, game.init_state(),
            lambda pairs: np.asarray([p[0] for p in pairs], np.uint8),
            batch_size=len(host), ring_length=10, max_burst=9,
            with_checksums=False,
        )
        executor.warmup(np.zeros((2,), np.uint8))
        hosted = HostedPool(host, executor)

        TICKS = 120
        for i in range(TICKS):
            clock[0] += 16
            if i == 40:
                host.inject_slot_error(1)
            hosted.tick([
                (idx, idx % 2, (i + idx) % 16) for idx in range(len(host))
            ])
            net.tick()
        hosted.block_until_ready()
        assert host.slot_state(1) == SLOT_EVICTED
        for idx in range(len(host)):
            assert host.current_frame(idx) >= TICKS - 24
        st = executor.live_state(1)
        assert set(st) == set(game.init_state_np())


class TestFallbackIsolation:
    def test_python_fallback_contains_slot_faults(self, monkeypatch):
        """With the native bank unavailable, a session whose tick raises is
        marked dead; the other sessions keep ticking."""
        monkeypatch.setattr(_native, "bank_lib", lambda: None)
        clock = [0]
        net = InMemoryNetwork(latency_ticks=1)
        pool = HostSessionPool()

        class FaultySocket:
            def __init__(self, inner):
                self.inner = inner
                self.explode = False

            def send_to(self, msg, addr):
                if self.explode:
                    raise OSError("wire cut")
                self.inner.send_to(msg, addr)

            def receive_all_datagrams(self):
                return self.inner.receive_all_datagrams()

            def receive_all_messages(self):
                return self.inner.receive_all_messages()

        faulty = FaultySocket(net.socket("A0"))
        pool.add_session(builder(clock, 1, 0, "B0"), faulty)
        pool.add_session(builder(clock, 2, 1, "A0"), net.socket("B0"))
        for m in range(1, 3):
            names = (f"A{m}", f"B{m}")
            for me in (0, 1):
                pool.add_session(
                    builder(clock, 3 + 2 * m + me, me, names[1 - me]),
                    net.socket(names[me]),
                )
        assert not pool.native_active

        def tick(i):
            clock[0] += 16
            for idx in range(len(pool)):
                pool.add_local_input(idx, idx % 2, (i + idx) % 16)
            for reqs in pool.advance_all():
                fulfill(reqs)
            net.tick()

        for i in range(30):
            tick(i)
        faulty.explode = True
        for i in range(30, 120):
            tick(i)
        assert pool.slot_state(0) == SLOT_DEAD
        assert pool.fault_log(0)
        for idx in range(2, len(pool)):
            assert pool.slot_state(idx) == SLOT_NATIVE
            assert pool.current_frame(idx) > 100

    def test_handshake_pool_converges_on_fallback(self, monkeypatch):
        """Handshake sessions (bank-ineligible, always fallback) must keep
        polling while NotSynchronized is raised, or in-pool peers can never
        answer each other's sync probes and advance_all livelocks."""
        from ggrs_tpu.core.errors import NotSynchronized

        monkeypatch.setattr(_native, "bank_lib", lambda: None)
        clock = [0]
        net = InMemoryNetwork()
        pool = HostSessionPool()
        for me, name, other in ((0, "L", "R"), (1, "R", "L")):
            b = builder(clock, 30 + me, me, other).with_sync_handshake(True)
            pool.add_session(b, net.socket(name))
        assert not pool.native_active

        synced_at = None
        for i in range(100):
            clock[0] += 16
            for idx in range(2):
                pool.add_local_input(idx, idx, i % 16)
            try:
                reqs = pool.advance_all()
            except NotSynchronized:
                continue
            for r in reqs:
                fulfill(r)
            synced_at = i
            break
        assert synced_at is not None, "handshake never completed (livelock)"

    def test_missing_input_still_raises_contract_error(self, monkeypatch):
        """GgrsError is a caller bug, not a slot fault — both paths."""
        from ggrs_tpu.core.errors import InvalidRequest

        monkeypatch.setattr(_native, "bank_lib", lambda: None)
        net = InMemoryNetwork()
        pool = HostSessionPool()
        clock = [0]
        pool.add_session(builder(clock, 1, 0, "Y"), net.socket("X"))
        pool.add_session(builder(clock, 2, 1, "X"), net.socket("Y"))
        with pytest.raises(InvalidRequest):
            pool.advance_all()
        assert pool.slot_state(0) == SLOT_NATIVE


@needs_native
@pytest.mark.slow
class TestSoak:
    def test_bank_soak_under_combined_faults(self):
        """≥5k ticks under loss+dup+reorder+latency plus a mid-run blackout
        window: honest traffic must NEVER fault a slot (zero quarantines,
        zero deaths) and every session converges.  The fault-free control
        leg pins the same at zero-fault conditions."""
        for faults, blackout in (
            (dict(seed=9, loss=0.05, duplicate=0.03, reorder=0.03,
                  latency_ticks=2), (2000, 2090)),
            (dict(latency_ticks=1), None),  # fault-free control leg
        ):
            clock = [0]
            nets = []
            pool = HostSessionPool()
            for m in range(2):
                net = InMemoryNetwork(**faults)
                nets.append(net)
                names = (f"A{m}", f"B{m}")
                for me in (0, 1):
                    pool.add_session(
                        builder(clock, 3 + 5 * m + me, me, names[1 - me]),
                        net.socket(names[me]),
                    )
            assert pool.native_active

            TICKS = 5200
            for i in range(TICKS):
                clock[0] += 16
                if blackout is not None:
                    if i == blackout[0]:
                        for net in nets:
                            net.loss = 1.0
                    elif i == blackout[1]:
                        for net in nets:
                            net.loss = faults["loss"]
                for idx in range(len(pool)):
                    pool.add_local_input(idx, idx % 2, (i * 3 + idx) % 16)
                for reqs in pool.advance_all():
                    fulfill(reqs)
                for idx in range(len(pool)):
                    pool.events(idx)  # drain
                for net in nets:
                    net.tick()

            for idx in range(len(pool)):
                assert pool.slot_state(idx) == SLOT_NATIVE, (
                    f"slot {idx} faulted under honest traffic: "
                    f"{pool.fault_log(idx)}"
                )
                # frames advance at most 1/tick, so the blackout window is
                # never regained — the bound is ticks minus the blackout
                # plus prediction-stall slack
                slack = (blackout[1] - blackout[0] if blackout else 0) + 64
                assert pool.current_frame(idx) >= TICKS - slack, (
                    f"slot {idx} failed to converge"
                )
            assert pool.crossings == TICKS
            assert pool.harvests == 0
