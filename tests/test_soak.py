"""Soak tier (VERDICT r4 item 6): the failure modes this hunts — ring /
watermark drift, unbounded queue growth under asymmetric loss, checksum-
history aliasing after frame wrap — only surface at 10^5+ frames, a horizon
the reference's tests never reach (/root/reference/tests/test_p2p_session.rs
runs hundreds of frames).

The harnesses live in bench.py (``p2p_soak`` / ``pool_soak``) and are shared
verbatim with the recorded `bench.py soak` metrics, so the test tier and the
bench line certify the same behavior.  Tiers:

  - test_p2p_soak_100k_frames: two peers over the seeded fault net for 1e5
    frames with desync detection on; bit-exact convergence at every settled
    frame, bounded send queues / event queues / checksum history / digest
    backlog, bounded RSS growth.  Crosses the 128-slot input-queue ring
    ~780x and the 32-entry checksum history cap ~60x.
  - test_pool_soak_wraparound: 8 pooled sessions (4 matches) for 2e4 device
    ticks — ~156 input-ring wraps per queue.  (The bench-side run extends
    this to 1e5 ticks off the tunnel.)

Both are marked ``soak`` — deselect with ``-m "not soak"`` when iterating.
"""

from __future__ import annotations

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from bench import p2p_soak, pool_soak  # noqa: E402

pytestmark = pytest.mark.soak


def _bounded_growth_invariants(sessions, digests) -> None:
    for s in sessions:
        for ep in s._remote_endpoints:
            assert ep._core.pending_len() <= 128 + 16, "send queue grew"
            assert len(ep.pending_checksums) <= 32, (
                "checksum history grew past its cap"
            )
        assert len(s._event_queue) <= 100, "session event queue grew"
    for d in digests:
        assert len(d) < 1200, "digest backlog grew (stalled peer?)"


def test_p2p_soak_100k_frames():
    stats = p2p_soak(100_000, periodic=_bounded_growth_invariants)
    # convergence and horizon asserts live inside the harness; pin the
    # test-tier extras here
    assert stats["desyncs"] == 0
    assert stats["compared"] > 50_000
    assert stats["rss_drift_mb"] < 64.0, (
        f"RSS grew {stats['rss_drift_mb']:.0f} MiB in the second half"
    )


def test_pool_soak_wraparound():
    stats = pool_soak(20_000)
    assert stats["sessions"] == 8
    assert stats["ring_wraps"] >= 156
