"""ChipVM: the emulator-style workload — JAX/NumPy parity and batched use."""

import numpy as np

import jax
import jax.numpy as jnp

from ggrs_tpu.games.chipvm import ChipVM
from ggrs_tpu.parallel import BatchedSessions, make_mesh
from ggrs_tpu.sessions import DeviceSyncTestSession


def _inputs(n, players, seed):
    return np.random.default_rng(seed).integers(0, 256, (n, players)).astype(np.uint8)


class TestChipVM:
    def test_jax_matches_numpy_oracle(self):
        vm = ChipVM(2)
        n = 50
        ins = _inputs(n, 2, seed=3)
        s_j, s_n = vm.init_state(), vm.init_state_np()
        adv = jax.jit(vm.advance)
        for i in range(n):
            s_j = adv(s_j, jnp.asarray(ins[i]))
            s_n = vm.advance_np(s_n, ins[i])
        np.testing.assert_array_equal(np.asarray(s_j["mem"]), s_n["mem"])
        np.testing.assert_array_equal(np.asarray(s_j["regs"]), s_n["regs"])
        assert int(s_j["pc"]) == int(s_n["pc"])

    def test_state_evolves(self):
        vm = ChipVM(2)
        s = vm.init_state()
        s2 = vm.advance(s, jnp.asarray([3, 7], jnp.uint8))
        assert not np.array_equal(np.asarray(s["mem"]), np.asarray(s2["mem"]))

    def test_device_synctest_clean(self):
        vm = ChipVM(2)
        sess = DeviceSyncTestSession(
            vm.advance, vm.init_state(), jnp.zeros((2,), jnp.uint8), check_distance=4
        )
        sess.run_ticks(_inputs(60, 2, seed=5))

    def test_batched_sessions_shard(self):
        vm = ChipVM(2)
        B = 16
        batch = BatchedSessions(
            vm.advance,
            vm.init_state(),
            jnp.zeros((2,), jnp.uint8),
            batch_size=B,
            mesh=make_mesh(8),
            check_distance=2,
        )
        stats = batch.run_ticks(_inputs(12, 2, 7)[None].repeat(B, 0))
        assert stats["mismatches"] == 0
