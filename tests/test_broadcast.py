"""Broadcast subsystem pins (DESIGN.md §13): native spectator fan-out
parity against the per-session Python relay, hub-aware bank admission,
the zero-extra-crossings budget for fan-out + journaling, dynamic viewer
lifecycle, and supervision interplay (eviction keeps viewers fed; a
chaos-killed slot recovers from the journal with survivors untouched).
"""

from __future__ import annotations

import random

import pytest

from ggrs_tpu.chaos import blast_radius_violations, drive_broadcast
from ggrs_tpu.core import Local, Remote
from ggrs_tpu.core.config import Config
from ggrs_tpu.core.types import Disconnected, Spectator
from ggrs_tpu.net import InMemoryNetwork, _native
from ggrs_tpu.parallel.host_bank import (
    HostSessionPool,
    SLOT_EVICTED,
    SLOT_NATIVE,
    _bank_eligible,
)
from ggrs_tpu.sessions import SessionBuilder

needs_broadcast = pytest.mark.skipif(
    _native.broadcast_lib() is None,
    reason="native broadcast bank unavailable",
)

FAULTS = dict(loss=0.05, duplicate=0.03, reorder=0.03, latency_ticks=1)


@needs_broadcast
class TestFanOutParityFuzz:
    @pytest.mark.parametrize("seed", [1, 7, 23])
    def test_hub_spectator_stream_bit_identical(self, seed):
        """The headline pin: a hub-fanned spectator's observed
        frame/input stream — and the host's entire wire byte sequence —
        is bit-identical to the Python ``P2PSession`` +
        ``SpectatorSession`` baseline under seeded loss/dup/reorder."""
        base = drive_broadcast(
            250, use_hub=False, seed=seed, fault_cfg=dict(FAULTS, seed=seed)
        )
        hubd = drive_broadcast(
            250, use_hub=True, seed=seed, fault_cfg=dict(FAULTS, seed=seed)
        )
        assert hubd["host_wire"] == base["host_wire"], (
            "host wire bytes diverged from the per-session baseline"
        )
        assert hubd["viewer_streams"] == base["viewer_streams"]
        assert hubd["viewer_frames"] == base["viewer_frames"]
        assert hubd["reqs"][0] == base["reqs"][0]
        assert hubd["viewer_frames"][0][-1] > 200, "viewer stalled"

    def test_multi_viewer_fan_out(self):
        """8 viewers on one match, each with an independent ack window:
        every stream matches the single-viewer reference content."""
        ctx = drive_broadcast(150, use_hub=True, seed=3, n_spectators=8,
                              fault_cfg=dict(FAULTS, seed=3))
        streams = ctx["viewer_streams"]
        assert len(streams) == 8
        # all viewers see the same (frame -> inputs) mapping
        maps = [dict(s) for s in streams]
        reference = maps[0]
        assert reference, "no viewer received anything"
        for k, m in enumerate(maps[1:], 1):
            shared = set(reference) & set(m)
            assert shared, f"viewer {k} received nothing in common"
            for f in shared:
                assert m[f] == reference[f], f"viewer {k} diverged at {f}"
        assert all(ctx["viewer_frames"][k][-1] > 100 for k in range(8))


@needs_broadcast
class TestAdmission:
    def _spectator_builder(self, clock, rng_seed):
        return (
            SessionBuilder(Config.for_uint(16))
            .with_clock(lambda: clock[0])
            .with_rng(random.Random(rng_seed))
            .add_player(Local(), 0)
            .add_player(Remote("P"), 1)
            .add_player(Spectator("V"), 2)
        )

    def test_bank_eligible_is_hub_aware(self):
        clock = [0]
        b = self._spectator_builder(clock, 1)
        assert not _bank_eligible(b)                      # hubless: refuse
        assert _bank_eligible(b, hub_active=True)         # hub: admit

    def test_hubless_spectator_match_falls_back_and_runs(self):
        """The pre-broadcast behavior is preserved verbatim for hubless
        callers: the match lands on the Python session (which relays to
        its spectators itself) and still runs."""
        clock = [0]
        net = InMemoryNetwork()
        pool = HostSessionPool()
        pool.add_session(self._spectator_builder(clock, 1), net.socket("H"))
        assert not pool.native_active
        peer = (
            SessionBuilder(Config.for_uint(16))
            .with_clock(lambda: clock[0])
            .with_rng(random.Random(2))
            .add_player(Local(), 1)
            .add_player(Remote("H"), 0)
        ).start_p2p_session(net.socket("P"))
        for i in range(30):
            clock[0] += 16
            peer.add_local_input(1, i % 16)
            for r in peer.advance_frame():
                if type(r).__name__ == "SaveGameState":
                    r.cell.save(r.frame, None, None)
            pool.add_local_input(0, 0, i % 16)
            for reqs in pool.advance_all():
                for r in reqs:
                    if type(r).__name__ == "SaveGameState":
                        r.cell.save(r.frame, None, None)
        assert pool.current_frame(0) > 20

    def test_hub_makes_spectator_match_native(self):
        from ggrs_tpu.broadcast import SpectatorHub

        clock = [0]
        net = InMemoryNetwork()
        pool = HostSessionPool()
        SpectatorHub(pool, rng=random.Random(9))
        pool.add_session(self._spectator_builder(clock, 1), net.socket("H"))
        assert pool.native_active


@needs_broadcast
class TestCrossingBudget:
    def test_fanout_and_journal_add_zero_crossings(self, tmp_path):
        """THE acceptance pin: a bank-hosted match with 8 native-fanned
        spectators plus an attached journal still runs in the PR 1 + PR 3
        crossing budget — one bank crossing per pool tick plus one stats
        crossing per scrape, nothing more."""
        ctx = drive_broadcast(
            120, use_hub=True, seed=5, n_spectators=8,
            journal_path=tmp_path / "match.ggjl", scrape_every=1,
        )
        pool = ctx["pool"]
        assert pool.crossings == 120, "fan-out perturbed the tick budget"
        assert pool.stat_crossings == 120
        assert pool.harvests == 0
        assert ctx["journal"].next_frame > 100, "journal received no frames"
        # the fan-out actually happened (counters, not just silence)
        reg = ctx["registry"]
        total = sum(
            child.value
            for fam in reg.families() if fam.name == "ggrs_fanout_datagrams_total"
            for _, child in fam.samples()
        )
        assert total > 100 * 8, "native fan-out sent almost nothing"


@needs_broadcast
class TestViewerLifecycle:
    def test_dynamic_attach_before_frame0_and_detach(self):
        from ggrs_tpu.broadcast import SpectatorHub
        from ggrs_tpu.core.errors import (
            InvalidRequest,
            NotSynchronized,
            PredictionThreshold,
        )

        clock = [0]
        net = InMemoryNetwork(latency_ticks=1)
        cfg = Config.for_uint(16)
        hb = (
            SessionBuilder(cfg)
            .with_clock(lambda: clock[0])
            .with_rng(random.Random(1))
            .add_player(Local(), 0)
            .add_player(Remote("P"), 1)
        )
        peer = (
            SessionBuilder(cfg)
            .with_clock(lambda: clock[0])
            .with_rng(random.Random(2))
            .add_player(Local(), 1)
            .add_player(Remote("H"), 0)
        ).start_p2p_session(net.socket("P"))
        viewer = (
            SessionBuilder(cfg)
            .with_clock(lambda: clock[0])
            .with_rng(random.Random(3))
        ).start_spectator_session("H", net.socket("V"))
        pool = HostSessionPool()
        hub = SpectatorHub(pool, rng=random.Random(4))
        pool.add_session(hb, net.socket("H"))
        assert pool.native_active
        hub.attach(0, "V")  # dynamic join before frame 0
        assert len(hub.spectators(0)) == 1

        def tick(i):
            clock[0] += 16
            peer.add_local_input(1, i % 16)
            for r in peer.advance_frame():
                if type(r).__name__ == "SaveGameState":
                    r.cell.save(r.frame, None, None)
            pool.add_local_input(0, 0, i % 16)
            for reqs in pool.advance_all():
                for r in reqs:
                    if type(r).__name__ == "SaveGameState":
                        r.cell.save(r.frame, None, None)
            try:
                viewer.advance_frame()
            except (NotSynchronized, PredictionThreshold):
                pass
            net.tick()

        for i in range(40):
            tick(i)
        assert viewer.current_frame > 20, "dynamic viewer never followed"
        # late joins are refused (the journal is the catch-up story)
        with pytest.raises(InvalidRequest):
            hub.attach(0, "LATE")
        frozen = viewer.current_frame
        hub.detach(0, "V")
        for i in range(40, 90):
            tick(i)
        assert pool.current_frame(0) > 70, "detach perturbed the match"
        assert viewer.current_frame <= frozen + 12, (
            "detached viewer kept receiving the stream"
        )

    def test_late_attach_refused_on_virgin_slot(self, tmp_path):
        """A slot that never had a spectator or journal keeps its fan-out
        cursor at 0 while the watermark discard eats the early inputs —
        a mid-match attach (viewer OR journal tap) must be refused, not
        admitted and then fault the whole slot."""
        from ggrs_tpu.broadcast import MatchJournal, SpectatorHub
        from ggrs_tpu.core.errors import InvalidRequest

        clock = [0]
        net = InMemoryNetwork(latency_ticks=1)
        cfg = Config.for_uint(16)
        hb = (
            SessionBuilder(cfg)
            .with_clock(lambda: clock[0])
            .with_rng(random.Random(1))
            .add_player(Local(), 0)
            .add_player(Remote("P"), 1)
        )
        peer = (
            SessionBuilder(cfg)
            .with_clock(lambda: clock[0])
            .with_rng(random.Random(2))
            .add_player(Local(), 1)
            .add_player(Remote("H"), 0)
        ).start_p2p_session(net.socket("P"))
        pool = HostSessionPool()
        hub = SpectatorHub(pool, rng=random.Random(3))
        pool.add_session(hb, net.socket("H"))
        assert pool.native_active
        for i in range(60):
            clock[0] += 16
            peer.add_local_input(1, i % 16)
            for r in peer.advance_frame():
                if type(r).__name__ == "SaveGameState":
                    r.cell.save(r.frame, None, None)
            pool.add_local_input(0, 0, i % 16)
            for reqs in pool.advance_all():
                for r in reqs:
                    if type(r).__name__ == "SaveGameState":
                        r.cell.save(r.frame, None, None)
            net.tick()
        with pytest.raises(InvalidRequest):
            hub.attach(0, "LATE")
        with pytest.raises(InvalidRequest):
            hub.attach_journal(0, MatchJournal(
                tmp_path / "late.ggjl", 2, cfg.native_input_size
            ))
        # the refusals left the slot untouched
        for i in range(60, 80):
            clock[0] += 16
            peer.add_local_input(1, i % 16)
            for r in peer.advance_frame():
                if type(r).__name__ == "SaveGameState":
                    r.cell.save(r.frame, None, None)
            pool.add_local_input(0, 0, i % 16)
            for reqs in pool.advance_all():
                for r in reqs:
                    if type(r).__name__ == "SaveGameState":
                        r.cell.save(r.frame, None, None)
            net.tick()
        assert pool.slot_state(0) == SLOT_NATIVE
        assert pool.current_frame(0) > 60

    def test_stuck_viewer_disconnects_match_unharmed(self):
        """A viewer that never acks: the 128-unacked rule fires natively,
        the hub surfaces Disconnected and detaches the viewer via ctrl
        op, and the match itself never misses a frame."""
        from ggrs_tpu.broadcast import SpectatorHub

        clock = [0]
        net = InMemoryNetwork(latency_ticks=1)
        cfg = Config.for_uint(16)
        hb = (
            SessionBuilder(cfg)
            .with_clock(lambda: clock[0])
            .with_rng(random.Random(1))
            .add_player(Local(), 0)
            .add_player(Remote("P"), 1)
            .add_player(Spectator("MUTE"), 2)
        )
        peer = (
            SessionBuilder(cfg)
            .with_clock(lambda: clock[0])
            .with_rng(random.Random(2))
            .add_player(Local(), 1)
            .add_player(Remote("H"), 0)
        ).start_p2p_session(net.socket("P"))
        # "MUTE" never drains its socket: it acks nothing, ever
        pool = HostSessionPool()
        hub = SpectatorHub(pool, rng=random.Random(4))
        pool.add_session(hb, net.socket("H"))
        assert pool.native_active
        for i in range(180):
            clock[0] += 16
            peer.add_local_input(1, i % 16)
            for r in peer.advance_frame():
                if type(r).__name__ == "SaveGameState":
                    r.cell.save(r.frame, None, None)
            pool.add_local_input(0, 0, i % 16)
            for reqs in pool.advance_all():
                for r in reqs:
                    if type(r).__name__ == "SaveGameState":
                        r.cell.save(r.frame, None, None)
            net.tick()
        events = hub.events(0)
        assert any(isinstance(e, Disconnected) for e in events), (
            "stuck viewer never surfaced Disconnected"
        )
        assert not hub.spectators(0)[0]["running"]
        assert pool.slot_state(0) == SLOT_NATIVE
        assert pool.current_frame(0) > 150, "stuck viewer stalled the match"


@needs_broadcast
class TestSupervisionInterplay:
    def test_eviction_keeps_viewer_fed(self):
        """A native fault mid-match: the slot evicts to the Python relay
        and the viewer KEEPS receiving the stream across the transition
        (the fan-out window rides the harvest's pending dumps)."""
        def inject(i, ctx):
            if i == 80:
                ctx["pool"].inject_slot_error(0)

        ctx = drive_broadcast(240, use_hub=True, seed=11, inject=inject)
        assert ctx["states"][0] == SLOT_EVICTED
        frames = ctx["viewer_frames"][0]
        assert frames[-1] > frames[80] + 100, (
            "viewer stalled after the host slot evicted"
        )

    def test_chaos_kill_recovers_from_journal_survivors_untouched(
        self, tmp_path
    ):
        """The acceptance scenario: kill a NATIVE slot mid-match with its
        harvest unavailable (dead native state) — the slot recovers from
        the journal tail, the match and its viewer continue, and the
        unrelated in-bank matches are bit-identical to a fault-free
        control leg."""
        def inject(i, ctx):
            if i == 100:
                ctx["pool"].inject_slot_error(0)

        control = drive_broadcast(
            300, use_hub=True, seed=17, n_side_matches=2,
            journal_path=tmp_path / "control.ggjl",
        )
        chaos = drive_broadcast(
            300, use_hub=True, seed=17, n_side_matches=2,
            journal_path=tmp_path / "chaos.ggjl",
            inject=inject, sabotage_harvest=True,
        )
        assert chaos["states"][0] == SLOT_EVICTED
        assert any(
            "journal tail" in f.detail
            for f in chaos["pool"].fault_log(0)
        ), "recovery did not come from the journal"
        # the journal stays a VALID artifact across the eviction: the
        # evicted relay's tap re-encodes with the session config, so the
        # post-eviction frames parse and extend well past the kill tick
        from ggrs_tpu.broadcast import read_journal

        chaos["journal"].close()
        parsed = read_journal(tmp_path / "chaos.ggjl")
        assert not parsed["truncated"]
        assert parsed["frames"][-1][0] > 200
        # the recovered match keeps pace with its external peer
        assert chaos["frames"][0] > chaos["peer_frame"] - 20
        assert chaos["viewer_frames"][0][-1] > 250
        # survivors: bit-identical wire/requests/events vs control
        violations = []
        for idx in range(1, 5):
            if chaos["states"][idx] != SLOT_NATIVE:
                violations.append(f"slot {idx} left native")
            for field in ("reqs", "events"):
                if chaos[field][idx] != control[field][idx]:
                    violations.append(f"slot {idx}: {field} diverged")
        for k in range(4):
            if chaos["side_wire"][k] != control["side_wire"][k]:
                violations.append(f"side socket {k}: wire diverged")
        assert not violations, violations


@needs_broadcast
@pytest.mark.slow
class TestBroadcastSoak:
    def test_long_fanout_soak_under_faults(self, tmp_path):
        """Slow soak (run with ``-m slow``): 2.5k ticks of hub fan-out to
        8 viewers under loss/dup/reorder with a journal attached — no
        quarantine, no viewer left behind, journal contiguous."""
        ctx = drive_broadcast(
            2500, use_hub=True, seed=29, n_spectators=8,
            fault_cfg=dict(seed=29, loss=0.03, duplicate=0.02,
                           reorder=0.02, latency_ticks=1),
            journal_path=tmp_path / "soak.ggjl", journal_fsync=256,
            scrape_every=16,
        )
        assert ctx["states"][0] == SLOT_NATIVE, "soak quarantined the slot"
        assert ctx["pool"].crossings == 2500
        assert all(f[-1] > 2300 for f in ctx["viewer_frames"])
        journal = ctx["journal"]
        journal.close()
        from ggrs_tpu.broadcast import read_journal

        parsed = read_journal(tmp_path / "soak.ggjl")
        assert not parsed["gaps"] and parsed["closed"]
        assert len(parsed["frames"]) > 2300


@needs_broadcast
class TestMetricsObservability:
    def test_spectator_gauges_and_digest(self, tmp_path):
        ctx = drive_broadcast(
            100, use_hub=True, seed=2, n_spectators=2,
            journal_path=tmp_path / "m.ggjl", scrape_every=5,
        )
        reg = ctx["registry"]
        assert reg.value("ggrs_spectators_attached", slot="0") == 2
        assert (reg.value("ggrs_journal_frames_total") or 0) > 80
        assert (reg.value("ggrs_fanout_bytes_total", slot="0") or 0) > 0
        lag0 = reg.value("ggrs_spectator_catchup_lag", slot="0",
                         spectator="0")
        assert lag0 is not None and lag0 < 30
        digest = ctx["hub"].metrics_digest()
        assert "viewers live" in digest and "journal:" in digest
