"""ggrs-verify pillar 2: the determinism lint.

Golden fixtures per rule — a snippet that MUST fire and a sibling that
MUST NOT — plus pragma suppression, baseline split semantics, and the
self-clean gate (the repo tree passes modulo the committed baseline).
"""

from pathlib import Path

from ggrs_tpu.analysis import (
    DETERMINISM_RULES,
    load_baseline,
    lint_determinism,
)
from ggrs_tpu.analysis.baseline import Baseline, write_baseline
from ggrs_tpu.analysis.determinism import lint_source
from ggrs_tpu.analysis.report import Finding

REPO = Path(__file__).resolve().parents[1]
BASELINE = REPO / "ggrs_tpu/analysis/determinism_baseline.json"


def rules_of(src: str, scope: str = "sim"):
    return sorted({f.rule for f in lint_source(src, "x.py", scope)})


# ----------------------------------------------------------------------
# one firing + one non-firing golden per rule
# ----------------------------------------------------------------------


class TestWallClock:
    def test_fires(self):
        assert rules_of(
            "import time\n"
            "def f():\n"
            "    return time.monotonic()\n"
        ) == ["det/wall-clock"]
        assert rules_of(
            "import datetime\n"
            "def f():\n"
            "    return datetime.datetime.now()\n"
        ) == ["det/wall-clock"]

    def test_injected_clock_does_not_fire(self):
        assert rules_of(
            "def f(clock):\n"
            "    return clock()\n"
            "def g(self):\n"
            "    return self._clock()\n"
        ) == []

    def test_time_ns_variants_fire(self):
        assert rules_of(
            "import time\n"
            "def f():\n"
            "    return time.perf_counter_ns() + time.time_ns()\n"
        ) == ["det/wall-clock"]


class TestUnseededRng:
    def test_module_level_rng_fires(self):
        assert rules_of(
            "import random\n"
            "def f():\n"
            "    return random.randint(0, 3)\n"
        ) == ["det/unseeded-rng"]

    def test_noarg_random_fires(self):
        assert rules_of(
            "import random\n"
            "def f():\n"
            "    return random.Random()\n"
        ) == ["det/unseeded-rng"]

    def test_seeded_random_does_not_fire(self):
        assert rules_of(
            "import random\n"
            "def f(seed):\n"
            "    rng = random.Random(seed)\n"
            "    return rng.randint(0, 3)\n"
        ) == []

    def test_entropy_sources_fire(self):
        assert rules_of(
            "import os, uuid\n"
            "def f():\n"
            "    return os.urandom(8), uuid.uuid4()\n"
        ) == ["det/unseeded-rng"]
        assert rules_of(
            "import numpy as np\n"
            "def f():\n"
            "    return np.random.rand(3)\n"
        ) == ["det/unseeded-rng"]


class TestSetIteration:
    def test_for_over_set_fires(self):
        assert rules_of(
            "def f(xs):\n"
            "    for x in set(xs):\n"
            "        yield x\n"
        ) == ["det/set-iteration"]

    def test_comprehension_and_list_fire(self):
        assert rules_of(
            "def f(xs):\n"
            "    return [x for x in {1, 2}] + list(frozenset(xs))\n"
        ) == ["det/set-iteration"]

    def test_sorted_set_does_not_fire(self):
        assert rules_of(
            "def f(xs):\n"
            "    for x in sorted(set(xs)):\n"
            "        yield x\n"
            "    return sorted({1, 2})\n"
        ) == []

    def test_membership_does_not_fire(self):
        assert rules_of(
            "def f(xs, x):\n"
            "    s = set(xs)\n"
            "    return x in s\n"
        ) == []


class TestHashOrder:
    def test_builtin_hash_fires(self):
        assert rules_of(
            "def f(s):\n"
            "    return hash(s)\n"
        ) == ["det/hash-order"]

    def test_sort_key_id_fires(self):
        assert rules_of(
            "def f(xs):\n"
            "    xs.sort(key=id)\n"
            "    return sorted(xs, key=id)\n"
        ) == ["det/hash-order"]

    def test_crc_does_not_fire(self):
        assert rules_of(
            "import zlib\n"
            "def f(b):\n"
            "    return zlib.crc32(b)\n"
        ) == []


class TestJitFloatReduce:
    def test_sum_in_jit_fires(self):
        assert rules_of(
            "import jax\n"
            "@jax.jit\n"
            "def f(xs):\n"
            "    return sum(xs)\n"
        ) == ["det/jit-float-reduce"]

    def test_sum_outside_jit_does_not_fire(self):
        assert rules_of(
            "def f(xs):\n"
            "    return sum(xs)\n"
        ) == []

    def test_jnp_sum_in_jit_does_not_fire(self):
        assert rules_of(
            "import jax, jax.numpy as jnp\n"
            "@jax.jit\n"
            "def f(xs):\n"
            "    return jnp.sum(xs)\n"
        ) == []


class TestPickleProtocol:
    def test_unpinned_fires(self):
        assert rules_of(
            "import pickle\n"
            "def f(x):\n"
            "    return pickle.dumps(x)\n",
            scope="bundle",
        ) == ["det/pickle-protocol"]

    def test_highest_protocol_fires(self):
        assert rules_of(
            "import pickle\n"
            "def f(x):\n"
            "    return pickle.dumps(x, protocol=pickle.HIGHEST_PROTOCOL)\n",
            scope="bundle",
        ) == ["det/pickle-protocol"]

    def test_pinned_does_not_fire(self):
        assert rules_of(
            "import pickle\n"
            "PROTO = 4\n"
            "def f(x):\n"
            "    return pickle.dumps(x, protocol=4), "
            "pickle.dumps(x, protocol=PROTO)\n",
            scope="bundle",
        ) == []

    def test_loads_does_not_fire(self):
        assert rules_of(
            "import pickle\n"
            "def f(b):\n"
            "    return pickle.loads(b)\n",
            scope="bundle",
        ) == []


class TestScopesAndPragmas:
    def test_bundle_scope_allows_wall_clock(self):
        src = "import time\ndef f():\n    return time.monotonic()\n"
        assert rules_of(src, scope="sim") == ["det/wall-clock"]
        assert rules_of(src, scope="bundle") == []

    def test_allow_pragma_suppresses(self):
        assert rules_of(
            "def f(s):\n"
            "    return hash(s)  # ggrs-verify: allow(det/hash-order)\n"
        ) == []

    def test_allow_pragma_is_rule_specific(self):
        assert rules_of(
            "def f(s):\n"
            "    return hash(s)  # ggrs-verify: allow(det/wall-clock)\n"
        ) == ["det/hash-order"]


# ----------------------------------------------------------------------
# baseline semantics + the self-clean gate
# ----------------------------------------------------------------------


def F(rule, path, line, detail):
    return Finding(rule, path, line, detail)


class TestBaseline:
    def test_split_absorbs_up_to_count(self):
        f1 = F("det/wall-clock", "a.py", 10, "time.time() ...")
        f2 = F("det/wall-clock", "a.py", 20, "time.time() ...")
        f3 = F("det/wall-clock", "a.py", 30, "time.time() ...")
        base = Baseline({f1.key(): 2})
        new, legacy = base.split([f1, f2, f3])
        assert len(legacy) == 2 and len(new) == 1

    def test_line_moves_do_not_invalidate(self):
        f_old = F("det/hash-order", "a.py", 5, "builtin hash() ...")
        f_moved = F("det/hash-order", "a.py", 99, "builtin hash() ...")
        base = Baseline.from_findings([f_old])
        new, legacy = base.split([f_moved])
        assert new == [] and legacy == [f_moved]

    def test_roundtrip(self, tmp_path):
        # keys are rule::path::detail — version 2 splits them back into
        # a per-file grouping on disk and must reassemble losslessly
        base = Baseline({
            "det/wall-clock::a.py::time.time() read": 2,
            "det/hash-order::b.py::builtin hash()": 1,
            "det/wall-clock::a.py::burned down": 0,
        })
        path = tmp_path / "b.json"
        write_baseline(path, base)
        loaded = load_baseline(path)
        assert loaded.counts == {
            "det/wall-clock::a.py::time.time() read": 2,
            "det/hash-order::b.py::builtin hash()": 1,
        }

    def test_on_disk_format_is_per_file_v2(self, tmp_path):
        import json
        path = tmp_path / "b.json"
        write_baseline(path, Baseline({
            "det/wall-clock::a.py::time.time() read": 2,
        }))
        data = json.loads(path.read_text())
        assert data["version"] == 2
        assert data["files"] == {"a.py": [
            {"rule": "det/wall-clock", "detail": "time.time() read",
             "count": 2},
        ]}

    def test_v1_baseline_is_rejected_with_guidance(self, tmp_path):
        # a flat v1 total could hide a violation MOVING between files;
        # the loader refuses it and points at --baseline-update
        import json
        import pytest
        path = tmp_path / "b.json"
        path.write_text(json.dumps({"version": 1, "entries": []}))
        with pytest.raises(ValueError, match="--baseline-update"):
            load_baseline(path)

    def test_missing_file_is_empty(self, tmp_path):
        assert load_baseline(tmp_path / "nope.json").counts == {}


class TestScope:
    def test_rollback_visible_files_are_in_sim_scope(self):
        """session_pool.py (the host-session driver: rollback-visible
        despite living in parallel/) and broadcast/journal.py (replay
        source of truth) must be linted at sim strictness."""
        from ggrs_tpu.analysis.determinism import DET_SCOPE
        sim_files = {p for s, p in DET_SCOPE if s == "sim"}
        assert "ggrs_tpu/parallel/session_pool.py" in sim_files
        assert "ggrs_tpu/broadcast/journal.py" in sim_files


class TestTreeIsClean:
    def test_repo_has_no_new_determinism_findings(self):
        findings = lint_determinism(REPO)
        new, _legacy = load_baseline(BASELINE).split(findings)
        assert new == [], "\n".join(f.render() for f in new)

    def test_baseline_is_not_stale(self):
        """Every baseline entry still matches a real finding — burned-
        down violations must leave the baseline too (run
        scripts/ggrs_verify.py --baseline-update)."""
        findings = lint_determinism(REPO)
        live = Baseline.from_findings(findings).counts
        base = load_baseline(BASELINE).counts
        stale = {
            k: n for k, n in base.items() if live.get(k, 0) < n
        }
        assert not stale, f"stale baseline entries: {stale}"

    def test_rule_catalog_matches_emitted_rules(self):
        assert set(DETERMINISM_RULES) >= {
            f.rule for f in lint_determinism(REPO)
        }


class TestJaxRandomIsFunctional:
    def test_keyed_jax_random_does_not_fire(self):
        assert rules_of(
            "import jax\n"
            "def f(key):\n"
            "    return jax.random.uniform(key, (3,))\n"
        ) == []


class TestReviewRegressions:
    def test_pickle_dump_positional_protocol_not_flagged(self):
        assert rules_of(
            "import pickle\n"
            "def f(x, fh):\n"
            "    pickle.dump(x, fh, 4)\n",
            scope="bundle",
        ) == []

    def test_pickle_dumps_positional_protocol_not_flagged(self):
        assert rules_of(
            "import pickle\n"
            "def f(x):\n"
            "    return pickle.dumps(x, 4)\n",
            scope="bundle",
        ) == []

    def test_pickle_dump_without_protocol_fires(self):
        assert rules_of(
            "import pickle\n"
            "def f(x, fh):\n"
            "    pickle.dump(x, fh)\n",
            scope="bundle",
        ) == ["det/pickle-protocol"]

    def test_from_imported_nondeterminism_fires(self):
        assert rules_of(
            "from time import perf_counter, monotonic as mono\n"
            "from random import random\n"
            "def f():\n"
            "    return perf_counter() + mono() + random()\n"
        ) == ["det/unseeded-rng", "det/wall-clock"]

    def test_module_alias_import_fires(self):
        assert rules_of(
            "import time as t\n"
            "def f():\n"
            "    return t.monotonic()\n"
        ) == ["det/wall-clock"]

    def test_from_import_of_datetime_fires(self):
        assert rules_of(
            "from datetime import datetime\n"
            "def f():\n"
            "    return datetime.now()\n"
        ) == ["det/wall-clock"]

    def test_default_protocol_fires(self):
        assert rules_of(
            "import pickle\n"
            "def f(x):\n"
            "    return pickle.dumps(x, protocol=pickle.DEFAULT_PROTOCOL)\n",
            scope="bundle",
        ) == ["det/pickle-protocol"]

    def test_protocol_minus_one_fires(self):
        assert rules_of(
            "import pickle\n"
            "def f(x):\n"
            "    return pickle.dumps(x, -1)\n",
            scope="bundle",
        ) == ["det/pickle-protocol"]

    def test_protocol_none_fires(self):
        assert rules_of(
            "import pickle\n"
            "def f(x):\n"
            "    return pickle.dumps(x, protocol=None)\n",
            scope="bundle",
        ) == ["det/pickle-protocol"]
