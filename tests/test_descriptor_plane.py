"""Pins for the descriptor-plane quiet path (DESIGN.md §21).

Three tentpole surfaces, each pinned against the reference decoder
(``GGRS_TPU_NO_FASTPATH=1`` / per-call staging — the unchanged
semantics):

* **batched input staging** — ``HostSessionPool.stage_inputs`` routes all
  B local inputs through ONE ``ggrs_bank_stage_inputs`` crossing; wire
  bytes, requests, events and frames must be bit-identical to per-call
  ``add_local_input`` staging, the crossing budget must stay one tick +
  one stats crossing, and unconsumed staged inputs must survive into the
  harvest (eviction/export re-feed them);
* **request descriptor tables** — ``advance_all`` returns a lazy
  ``RequestPlan`` whose fast slots materialize pooled requests only on
  demand, while ``BatchedRequestExecutor`` consumes the flat descriptor
  columns directly (zero request objects): the device state must stay
  bit-identical to the materialized path under seeded loss/dup/reorder
  (which forces rollback-resim descriptors through ``_fill_resim``);
* **batched outbound** — non-attached fd-backed sockets flush the whole
  tick through one ``ggrs_net_send_table`` crossing; the peer-observed
  byte stream must match the per-datagram reference leg over real
  loopback UDP.

Plus the §21 satellite: the per-slot staging router (``_stagers``) is
precomputed at finalize and rebuilt on supervision transitions instead of
re-validating handle→slot mappings per call.
"""

from __future__ import annotations

import ctypes
import random
import socket as pysocket
import struct

import numpy as np
import pytest

from ggrs_tpu.core import Local, Remote
from ggrs_tpu.core.config import Config
from ggrs_tpu.core.errors import InvalidRequest
from ggrs_tpu.net import InMemoryNetwork, _native
from ggrs_tpu.parallel.host_bank import HostSessionPool, RequestPlan
from ggrs_tpu.sessions import SessionBuilder

from test_session_bank import (  # noqa: E402  (pytest rootdir path)
    assert_requests_equal,
    fulfill_saves,
    needs_native,
    two_peer_builders,
)

needs_io = pytest.mark.skipif(
    _native.net_lib() is None,
    reason="kernel-batched socket datapath unavailable",
)


def _drive_pair(faults, ticks, n_matches=3, fault_at=None,
                scrape_every=0):
    """Two identically-seeded pools: pool A stages through the batched
    ``stage_inputs`` API, pool B through per-call ``add_local_input``.
    Compares requests, events, frames and wire bytes every tick; returns
    both pools."""
    clock = [0]
    net_a = InMemoryNetwork(**faults)
    net_b = InMemoryNetwork(**faults)
    builders_a = two_peer_builders(net_a, clock, n_matches)
    builders_b = two_peer_builders(net_b, clock, n_matches)
    pool_a, pool_b = HostSessionPool(), HostSessionPool()
    for b, s in builders_a:
        pool_a.add_session(b, s)
    for b, s in builders_b:
        pool_b.add_session(b, s)
    assert pool_a.native_active and pool_b.native_active
    n = len(builders_a)
    for i in range(ticks):
        clock[0] += 16
        pool_a.stage_inputs(
            [(idx, idx % 2, (i + idx) % 16) for idx in range(n)]
        )
        for idx in range(n):
            pool_b.add_local_input(idx, idx % 2, (i + idx) % 16)
        if fault_at is not None and i == fault_at:
            pool_a.inject_slot_error(0)
            pool_b.inject_slot_error(0)
        reqs_a = pool_a.advance_all()
        reqs_b = pool_b.advance_all()
        if scrape_every and i % scrape_every == 0:
            pool_a.scrape()
            pool_b.scrape()
        for idx in range(n):
            assert_requests_equal(
                reqs_b[idx], reqs_a[idx], f"tick {i} slot {idx}"
            )
            fulfill_saves(reqs_a[idx])
            fulfill_saves(reqs_b[idx])
        net_a.tick()
        net_b.tick()
        for idx in range(n):
            assert pool_a.events(idx) == pool_b.events(idx)
            assert pool_a.current_frame(idx) == pool_b.current_frame(idx)
            sa = builders_a[idx][1].sent
            sb = builders_b[idx][1].sent
            assert sa == sb, f"tick {i} slot {idx}: wire bytes diverged"
    return pool_a, pool_b


@needs_native
class TestBatchedStagingParity:
    @pytest.mark.parametrize("seed", [5, 31])
    def test_fuzzed_traffic_bit_identical(self, seed):
        """Batched native staging vs per-call staging: bit-identical wire
        bytes / requests / events / frames under seeded loss/dup/reorder,
        and the staged path actually went native (no inline dicts)."""
        rng = random.Random(seed)
        faults = dict(
            loss=0.08, duplicate=0.04, reorder=0.15,
            seed=rng.randrange(1 << 30),
        )
        pool_a, _ = _drive_pair(faults, ticks=180)
        assert pool_a.fast_slot_ticks > 0
        assert all(not m.staged_inputs for m in pool_a._mirrors), (
            "batched staging leaked into the inline dicts"
        )

    def test_crossing_budget_with_batched_staging(self):
        """stage_inputs is its OWN crossing (like the harvest): the tick
        budget stays exactly one tick + one stats crossing per pool
        tick."""
        pool_a, _ = _drive_pair(dict(), ticks=60, scrape_every=1)
        assert pool_a.crossings == 60
        assert pool_a.stat_crossings == 60
        assert pool_a.harvests == 0

    def test_eviction_with_native_staged_inputs(self):
        """A slot faulted while its inputs sit in the NATIVE staging
        buffer: the harvest's staged tail re-feeds them to the evicted
        session — bit-identical to the inline-staged reference leg."""
        pool_a, pool_b = _drive_pair(
            dict(latency_ticks=1), ticks=80, n_matches=2, fault_at=30
        )
        assert pool_a.slot_state(0) == "evicted"
        assert pool_b.slot_state(0) == "evicted"
        assert pool_a.current_frame(0) > 31, "evicted slot never resumed"

    def test_missing_input_raises_before_crossing(self):
        clock = [0]
        net = InMemoryNetwork()
        builders = two_peer_builders(net, clock, 2)
        pool = HostSessionPool()
        for b, s in builders:
            pool.add_session(b, s)
        assert pool.native_active
        # stage only the first slot's input
        pool.stage_inputs([(0, 0, 3)])
        with pytest.raises(InvalidRequest, match="Missing local input"):
            pool.advance_all()

    def test_inline_staging_wins_over_stale_native(self):
        """Both mechanisms used for one slot in one tick: the inline dict
        wins and the native copy is dropped ON BOTH SIDES — the next
        all-native tick must not resurrect stale bytes."""
        clock = [0]
        net_a, net_b = InMemoryNetwork(), InMemoryNetwork()
        builders_a = two_peer_builders(net_a, clock, 1)
        builders_b = two_peer_builders(net_b, clock, 1)
        pool_a, pool_b = HostSessionPool(), HostSessionPool()
        for b, s in builders_a:
            pool_a.add_session(b, s)
        for b, s in builders_b:
            pool_b.add_session(b, s)
        # finalize BOTH pools at the same clock: endpoint timer seeds are
        # drawn at finalize time, and a one-tick skew shifts the quality
        # report schedule between the legs
        assert pool_a.native_active and pool_b.native_active
        n = len(builders_a)
        for i in range(30):
            clock[0] += 16
            if i == 5:
                # stage a WRONG value natively, then override inline with
                # the reference value: inline must win
                pool_a.stage_inputs(
                    [(idx, idx % 2, 15) for idx in range(n)]
                )
                for idx in range(n):
                    pool_a.add_local_input(idx, idx % 2, (i + idx) % 16)
            else:
                pool_a.stage_inputs(
                    [(idx, idx % 2, (i + idx) % 16) for idx in range(n)]
                )
            for idx in range(n):
                pool_b.add_local_input(idx, idx % 2, (i + idx) % 16)
            for idx, (ra, rb) in enumerate(
                zip(pool_a.advance_all(), pool_b.advance_all())
            ):
                assert_requests_equal(rb, ra, f"tick {i} slot {idx}")
                fulfill_saves(ra)
                fulfill_saves(rb)
            net_a.tick()
            net_b.tick()
            for idx in range(n):
                assert builders_a[idx][1].sent == builders_b[idx][1].sent

    def test_export_bundle_carries_native_staged_inputs(self):
        """Inputs staged natively but not yet consumed (no advance_all)
        ride the harvest's staged tail into the export bundle."""
        clock = [0]
        net = InMemoryNetwork()
        builders = two_peer_builders(net, clock, 1)
        pool = HostSessionPool()
        for b, s in builders:
            pool.add_session(b, s)
        assert pool.native_active
        n = len(builders)
        for i in range(10):
            clock[0] += 16
            pool.stage_inputs(
                [(idx, idx % 2, (i + idx) % 16) for idx in range(n)]
            )
            for reqs in pool.advance_all():
                fulfill_saves(reqs)
            net.tick()
        # stage for the NEXT tick, then export before advancing
        pool.stage_inputs([(idx, idx % 2, 7) for idx in range(n)])
        cfg = builders[0][0]._config
        for idx in range(n):
            bundle = pool.export_resume_state(idx)
            staged = bundle["staged_inputs"]
            assert staged == {idx % 2: cfg.input_encode(7)}, (
                f"slot {idx}: staged tail missing from the bundle"
            )


@needs_native
class TestRequestPlan:
    def _pool(self, n_matches=2):
        clock = [0]
        net = InMemoryNetwork()
        builders = two_peer_builders(net, clock, n_matches)
        pool = HostSessionPool()
        for b, s in builders:
            pool.add_session(b, s)
        assert pool.native_active
        return pool, builders, net, clock

    def _tick(self, pool, net, clock, i, fulfill=True):
        clock[0] += 16
        n = len(pool)
        pool.stage_inputs(
            [(idx, idx % 2, (i + idx) % 16) for idx in range(n)]
        )
        plan = pool.advance_all()
        if fulfill:
            for reqs in plan:
                fulfill_saves(reqs)
        net.tick()
        return plan

    def test_fast_slots_materialize_lazily(self):
        pool, builders, net, clock = self._pool()
        plan = None
        for i in range(20):
            plan = self._tick(pool, net, clock, i)
        assert isinstance(plan, RequestPlan)
        # a steady-state tick: every live slot deferred
        plan = self._tick(pool, net, clock, 20, fulfill=False)
        assert all(lst is None for lst in plan.lists), (
            "quiet slots were materialized at plan build"
        )
        # indexing materializes exactly that slot; requests_for is the
        # same surface
        reqs = plan[0]
        assert plan.lists[0] is reqs and plan.lists[1] is None
        assert pool.requests_for(0) is reqs
        for reqs in plan:
            fulfill_saves(reqs)

    def test_stale_plan_raises(self):
        pool, builders, net, clock = self._pool()
        n = len(pool)

        def quiet_tick(fulfill=True):
            # constant inputs: repeat-last predictions are always right,
            # so skipping one tick's save fulfillment cannot be loaded
            # back by a later rollback
            clock[0] += 16
            pool.stage_inputs([(idx, idx % 2, 7) for idx in range(n)])
            plan = pool.advance_all()
            if fulfill:
                for reqs in plan:
                    fulfill_saves(reqs)
            net.tick()
            return plan

        for _ in range(10):
            quiet_tick()
        plan = quiet_tick(fulfill=False)
        assert plan.lists[0] is None  # still deferred
        quiet_tick()
        with pytest.raises(InvalidRequest, match="stale"):
            plan[0]

    def test_plan_counters(self):
        pool, builders, net, clock = self._pool()
        for i in range(30):
            self._tick(pool, net, clock, i)
        assert pool.plan_ticks == 30
        assert pool.fast_slot_ticks > 0
        # tick 0 (frame-0 double save) is kReqOther → eager for all slots
        assert pool.desc_slow_slots >= len(pool)


@needs_native
class TestExecutorDescriptorParity:
    @pytest.mark.parametrize("faults", [
        dict(),
        dict(loss=0.08, duplicate=0.04, reorder=0.15, seed=77),
    ])
    def test_device_state_bit_identical(self, faults):
        """HostedPool with the bulk raw-input converter (descriptor
        consumption, zero request objects) vs the materialized reference:
        live device state and ring frame tags bit-identical after a
        faulted-traffic run (rollback resims included)."""
        import jax

        from ggrs_tpu.games import BoxGame
        from ggrs_tpu.parallel import BatchedRequestExecutor, HostedPool

        game = BoxGame(2)

        def to_arr(pairs):
            return np.asarray([p[0] for p in pairs], np.uint8)

        def raw_to_arr(blobs, statuses):
            # Config.for_uint(16): u16le blobs; values are 0..15 → byte 0
            return blobs[:, :, 0]

        def build(vector):
            clock = [0]
            net = InMemoryNetwork(**faults)
            builders = two_peer_builders(net, clock, 4)
            host = HostSessionPool()
            for b, s in builders:
                host.add_session(b, s)
            ex = BatchedRequestExecutor(
                game.advance, game.init_state(), to_arr,
                batch_size=len(builders), ring_length=10, max_burst=9,
                with_checksums=False,
                raw_inputs_to_array=raw_to_arr if vector else None,
            )
            ex.warmup(np.zeros((2,), np.uint8))
            return clock, net, host, ex, HostedPool(host, ex)

        ca, na, ha, ea, pa = build(True)
        cb, nb, hb, eb, pb = build(False)
        assert ha.native_active and hb.native_active
        n = len(ha)
        for i in range(150):
            ca[0] += 16
            cb[0] += 16
            items = [(idx, idx % 2, (i + idx) % 16) for idx in range(n)]
            pa.tick(items)
            pb.tick(items)
            na.tick()
            nb.tick()
        for la, lb in zip(
            jax.tree_util.tree_leaves(jax.device_get(ea.live_states)),
            jax.tree_util.tree_leaves(jax.device_get(eb.live_states)),
        ):
            np.testing.assert_array_equal(la, lb)
        np.testing.assert_array_equal(ea._host_frames, eb._host_frames)
        assert ha.fast_slot_ticks > 0
        # descriptor consumption means the plan's fast slots were never
        # materialized by the executor
        plan = ha._plan
        deferred = [
            i for i in range(n)
            if plan.live_l[i] and plan.lists[i] is None
        ]
        assert deferred, "executor materialized every fast slot"


@needs_native
class TestFlushFaultSuppression:
    def test_faulted_fast_slot_never_reaches_the_device(self):
        """A fast slot whose batched outbound flush fails fatally must be
        suppressed on the DEVICE too: pruned from the plan's quiet
        columns and routed through the eager rows, so the executor sees
        its (empty or supervise-replaced) list instead of dispatching the
        stale quiet program for a slot the pool just quarantined."""
        import jax
        import numpy as np

        from ggrs_tpu.games import BoxGame
        from ggrs_tpu.parallel import BatchedRequestExecutor, HostedPool

        class BombSocket:
            """FakeSocket wrapper whose batched flush explodes on cue.
            Exposes send_datagram_batch (so the slot takes the batched
            tier) but no fileno (so it never takes the native table)."""

            def __init__(self, inner):
                self.inner = inner
                self.explode = False

            def send_to(self, msg, addr):
                self.inner.send_to(msg, addr)

            def send_datagram(self, data, addr):
                if self.explode:
                    raise OSError("boom")
                self.inner.send_datagram(data, addr)

            def send_datagram_batch(self, items):
                if self.explode:
                    raise OSError("boom")
                self.inner.send_datagram_batch(items)

            def receive_all_datagrams(self):
                return self.inner.receive_all_datagrams()

            def receive_all_messages(self):
                return self.inner.receive_all_messages()

        game = BoxGame(2)

        def build(vector):
            clock = [0]
            net = InMemoryNetwork()
            builders = two_peer_builders(net, clock, 2)
            host = HostSessionPool()
            bombs = []
            for b, s in builders:
                sock = BombSocket(s.inner)  # unwrap the RecordingSocket
                bombs.append(sock)
                host.add_session(b, sock)
            ex = BatchedRequestExecutor(
                game.advance, game.init_state(),
                lambda pairs: np.asarray([p[0] for p in pairs], np.uint8),
                batch_size=len(builders), ring_length=10, max_burst=9,
                with_checksums=False,
                raw_inputs_to_array=(
                    (lambda blobs, statuses: blobs[:, :, 0])
                    if vector else None
                ),
            )
            ex.warmup(np.zeros((2,), np.uint8))
            return clock, net, host, ex, HostedPool(host, ex), bombs

        legs = [build(True), build(False)]
        assert all(leg[2].native_active for leg in legs)
        n = len(legs[0][2])
        for i in range(60):
            for clock, net, host, ex, hosted, bombs in legs:
                clock[0] += 16
                if i == 30:
                    bombs[0].explode = True  # fatal mid-run flush fault
                hosted.tick(
                    [(idx, idx % 2, (i + idx) % 16) for idx in range(n)]
                )
                net.tick()
        (ca, na, ha, ea, pa, _), (cb, nb, hb, eb, pb, _) = legs
        assert ha.fast_slot_ticks > 0
        assert ha.slot_state(0) != "native"  # the fault took slot 0 out
        assert ha.slot_state(0) == hb.slot_state(0)
        # the faulted slot's device history — suppression tick included —
        # must match the materialized reference leg bit-for-bit
        for x, y in zip(
            jax.tree_util.tree_leaves(jax.device_get(ea.live_states)),
            jax.tree_util.tree_leaves(jax.device_get(eb.live_states)),
        ):
            np.testing.assert_array_equal(x, y)

    def test_reference_leg_send_fault_keeps_staged_inputs(self):
        """The reference decoder branch (GGRS_TPU_NO_FASTPATH) with
        NATIVE staging: a send fault on an advanced tick must rebuild
        the inline staged dict from the decoded advance (the bank's
        copy was consumed by the trailing advance), so eviction stays
        fed instead of raising Missing-local-input."""
        import os

        class Bomb:
            """Single-shot: the FIRST send after arming fails, so the
            native slot faults but the evicted session's own resume
            sends succeed."""

            def __init__(self, inner):
                self.inner = inner
                self.explode = False

            def send_to(self, msg, addr):
                if self.explode:
                    self.explode = False
                    raise OSError("boom")
                self.inner.send_to(msg, addr)

            def receive_all_datagrams(self):
                return self.inner.receive_all_datagrams()

            def receive_all_messages(self):
                return self.inner.receive_all_messages()

        prev = os.environ.get("GGRS_TPU_NO_FASTPATH")
        os.environ["GGRS_TPU_NO_FASTPATH"] = "1"
        try:
            clock = [0]
            net = InMemoryNetwork()
            builders = two_peer_builders(net, clock, 1)
            pool = HostSessionPool()
            bombs = []
            for b, s in builders:
                sock = Bomb(s.inner)
                bombs.append(sock)
                pool.add_session(b, sock)
            assert pool.native_active and not pool._vectorized
            n = len(pool)
            for i in range(40):
                clock[0] += 16
                pool.stage_inputs(
                    [(idx, idx % 2, (i + idx) % 16) for idx in range(n)]
                )
                if i == 20:
                    bombs[0].explode = True
                for reqs in pool.advance_all():
                    fulfill_saves(reqs)
                net.tick()
            # pre-fix, the reconstructed dict was missing and the
            # same-tick eviction's session raised Missing-local-input
            # out of advance_all; post-fix the eviction consumed the
            # rebuilt inputs and the fallback session keeps advancing
            assert pool.slot_state(0) == "evicted"
            assert pool.current_frame(0) > 21
        finally:
            if prev is None:
                os.environ.pop("GGRS_TPU_NO_FASTPATH", None)
            else:
                os.environ["GGRS_TPU_NO_FASTPATH"] = prev


@needs_native
class TestStagerRouter:
    def test_foreign_handle_raises(self):
        clock = [0]
        net = InMemoryNetwork()
        builders = two_peer_builders(net, clock, 1)
        pool = HostSessionPool()
        for b, s in builders:
            pool.add_session(b, s)
        assert pool.native_active
        with pytest.raises(InvalidRequest, match="local player"):
            pool.add_local_input(0, 1, 3)  # handle 1 is slot 0's REMOTE

    def test_router_rebuilt_on_transitions(self):
        """The per-slot stager is precomputed and swapped on supervision
        transitions: after eviction the dispatch goes to the evicted
        session; after death it drops."""
        clock = [0]
        net = InMemoryNetwork()
        builders = two_peer_builders(net, clock, 2)
        pool = HostSessionPool()
        for b, s in builders:
            pool.add_session(b, s)
        assert pool.native_active
        n = len(pool)

        def tick(i):
            clock[0] += 16
            for idx in range(n):
                if pool.slot_state(idx) not in ("dead", "migrated"):
                    pool.add_local_input(idx, idx % 2, (i + idx) % 16)
            for reqs in pool.advance_all():
                fulfill_saves(reqs)
            net.tick()

        for i in range(8):
            tick(i)
        native_stager = pool._stagers[0]
        pool.inject_slot_error(0)
        for i in range(8, 30):
            tick(i)
        assert pool.slot_state(0) == "evicted"
        assert pool._stagers[0] is not native_stager
        assert (
            pool._stagers[0].__self__ is pool._evicted[0]
        ), "evicted slot's stager is not the session's add_local_input"
        # a released slot drops inputs silently (nothing ticks for it)
        pool.release_slot(1)
        pool.add_local_input(1, 1, 9)  # must not raise


@needs_io
class TestSendTable:
    def test_order_content_and_fatal_isolation(self):
        """ggrs_net_send_table direct unit: datagrams arrive in record
        order per fd; a fatal record (bogus fd) reports its index+errno
        while OTHER fds' runs still flush."""
        lib = _native.net_lib()
        tx_a = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_DGRAM)
        tx_b = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_DGRAM)
        rx = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_DGRAM)
        rx.bind(("127.0.0.1", 0))
        rx.settimeout(2.0)
        port = rx.getsockname()[1]
        ip = int.from_bytes(pysocket.inet_aton("127.0.0.1"), "little")
        payload = b"".join(
            bytes([i]) * (10 + i) for i in range(6)
        )
        offs = np.cumsum([0] + [10 + i for i in range(5)])
        bogus_fd = 10_000  # EBADF: deterministic fatal
        rows = [
            # fd A: two datagrams, then fd BOGUS, then fd B: three
            (tx_a.fileno(), 0, 10),
            (tx_a.fileno(), 1, 11),
            (bogus_fd, 2, 12),
            (tx_b.fileno(), 3, 13),
            (tx_b.fileno(), 4, 14),
        ]
        desc = np.empty(len(rows), np.dtype(list(_native.NET_SEND_FIELDS)))
        for k, (fd, idx, _ln) in enumerate(rows):
            desc[k] = (fd, ip, port, 0, offs[idx], 10 + idx)
        stats3 = (ctypes.c_uint64 * _native.NET_SEND_STATS)()
        fatal = (ctypes.c_int32 * 32)()
        rc = lib.ggrs_net_send_table(
            desc.ctypes.data, len(rows), payload, len(payload),
            stats3, fatal, 16,
        )
        assert rc == 1, f"expected exactly one fatal record, got {rc}"
        assert fatal[0] == 2  # the bogus-fd record's index
        assert fatal[1] != 0  # its errno (EBADF)
        got = [rx.recv(2048) for _ in range(4)]
        want = [
            payload[offs[i] : offs[i] + 10 + i] for i in (0, 1, 3, 4)
        ]
        assert sorted(got) == sorted(want)
        # per-fd order is preserved (different fds may interleave)
        a_got = [g for g in got if g in want[:2]]
        b_got = [g for g in got if g in want[2:]]
        assert a_got == want[:2] and b_got == want[2:]
        assert int(stats3[0]) == 4
        for s in (tx_a, tx_b, rx):
            s.close()

    def test_pool_outbound_rides_send_table_bit_identical(self):
        """A non-attached UDP pool's outbound goes through the one-
        crossing send table (descriptor leg) — the peer-observed byte
        stream must equal the per-datagram reference leg
        (GGRS_TPU_NO_FASTPATH)."""
        import os

        from ggrs_tpu.net.sockets import UdpNonBlockingSocket

        cfg = Config.for_uint(16)

        class TeeSocket:
            """Records every datagram the peer RECEIVES (the pool's
            outbound as observed on the wire) without stealing them."""

            def __init__(self, inner):
                self.inner = inner
                self.tape = []

            def send_to(self, msg, addr):
                self.inner.send_to(msg, addr)

            def send_datagram(self, data, addr):
                self.inner.send_datagram(data, addr)

            def receive_all_datagrams(self):
                got = self.inner.receive_all_datagrams()
                self.tape.extend(data for _, data in got)
                return got

            def receive_all_messages(self):
                return self.inner.receive_all_messages()

        def leg(fastpath: bool):
            prev = os.environ.pop("GGRS_TPU_NO_FASTPATH", None)
            if not fastpath:
                os.environ["GGRS_TPU_NO_FASTPATH"] = "1"
            try:
                clock = [0]
                pool = HostSessionPool()
                host_sock = UdpNonBlockingSocket(0)
                peer_inner = UdpNonBlockingSocket(0)
                peer_sock = TeeSocket(peer_inner)
                peer_addr = ("127.0.0.1", peer_inner.local_port())
                host_addr = ("127.0.0.1", host_sock.local_port())
                b = (
                    SessionBuilder(cfg)
                    .with_clock(lambda: clock[0])
                    .with_rng(random.Random(11))
                    .add_player(Local(), 0)
                    .add_player(Remote(peer_addr), 1)
                )
                pool.add_session(b, host_sock)
                peer = (
                    SessionBuilder(cfg)
                    .with_clock(lambda: clock[0])
                    .with_rng(random.Random(12))
                    .add_player(Local(), 1)
                    .add_player(Remote(host_addr), 0)
                ).start_p2p_session(peer_sock)
                assert pool.native_active
                if fastpath:
                    assert pool._send_fds[0] is not None, (
                        "send table did not engage for a plain UDP socket"
                    )
                for i in range(120):
                    clock[0] += 16
                    # the peer polls first (loopback delivery of last
                    # tick's pool sends is already complete), then the
                    # pool ticks — the same lockstep both legs
                    peer.add_local_input(1, i % 16)
                    fulfill_saves(peer.advance_frame())
                    pool.stage_inputs([(0, 0, i % 16)])
                    for reqs in pool.advance_all():
                        fulfill_saves(reqs)
                return list(peer_sock.tape), pool.current_frame(0)
            finally:
                os.environ.pop("GGRS_TPU_NO_FASTPATH", None)
                if prev is not None:
                    os.environ["GGRS_TPU_NO_FASTPATH"] = prev

        ref_stream, ref_frame = leg(False)
        fast_stream, fast_frame = leg(True)
        assert fast_stream == ref_stream, (
            f"peer-observed streams diverged ({len(fast_stream)} vs "
            f"{len(ref_stream)} datagrams)"
        )
        assert fast_frame == ref_frame >= 100
