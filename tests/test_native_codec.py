"""C++ codec vs Python codec: byte-for-byte parity, round-trips, hardening.

The native library is compiled on first use; if no toolchain is available
these tests are skipped (the pure-Python codec remains the wire
implementation either way)."""

import numpy as np
import pytest

from ggrs_tpu.net import _native
from ggrs_tpu.net.compression import (
    CodecError,
    decode,
    decode_py,
    encode,
    encode_py,
)

pytestmark = pytest.mark.skipif(
    not _native.available(), reason="native codec unavailable (no g++?)"
)


def _cases(seed, n_cases=200):
    rng = np.random.default_rng(seed)
    for _ in range(n_cases):
        ref_len = int(rng.integers(0, 12))
        reference = bytes(rng.integers(0, 256, ref_len, dtype=np.uint8))
        n = int(rng.integers(0, 12))
        if rng.random() < 0.5 and ref_len > 0:
            sizes = [ref_len] * n  # same-size fast path
        else:
            sizes = [int(rng.integers(0, 20)) for _ in range(n)]
        inputs = [
            bytes(rng.integers(0, 256, s, dtype=np.uint8)) for s in sizes
        ]
        # bias toward repeated inputs: the codec's favorable case
        if n >= 2 and rng.random() < 0.5:
            inputs = [inputs[0]] * n
        yield reference, inputs


class TestParity:
    def test_encode_bytes_identical(self):
        for reference, inputs in _cases(1):
            assert _native.encode(reference, inputs) == encode_py(
                reference, inputs
            ), (reference, inputs)

    def test_cross_roundtrips(self):
        for reference, inputs in _cases(2):
            blob_py = encode_py(reference, inputs)
            blob_cc = _native.encode(reference, inputs)
            if len(reference) == 0 and not all(len(i) == len(reference) for i in inputs):
                pass  # size table present; both must carry it identically
            assert _native.decode(reference, blob_py) == inputs
            assert decode_py(reference, blob_cc) == inputs

    def test_dispatcher_uses_native(self):
        reference = b"\x01\x02"
        inputs = [b"\x01\x02", b"\x03\x04"]
        assert decode(reference, encode(reference, inputs)) == inputs


class TestHardening:
    def test_garbage_never_crashes(self):
        rng = np.random.default_rng(3)
        for _ in range(500):
            data = bytes(
                rng.integers(0, 256, int(rng.integers(0, 64)), dtype=np.uint8)
            )
            reference = bytes(rng.integers(0, 256, int(rng.integers(0, 4)), dtype=np.uint8))
            try:
                out_cc = _native.decode(reference, data)
                err_cc = None
            except CodecError as e:
                out_cc, err_cc = None, e
            if err_cc is None and out_cc is None:
                # packet exceeded the native resource caps; the dispatcher
                # would fall back to Python, so there is nothing to compare
                continue
            try:
                out_py = decode_py(reference, data)
                err_py = None
            except CodecError as e:
                out_py, err_py = None, e
            # both sides must agree on accept/reject, and on the value
            assert (err_cc is None) == (err_py is None), (reference, data, err_cc, err_py)
            if err_cc is None:
                assert out_cc == out_py, (reference, data)

    def test_huge_zero_run_bounded(self):
        # header varint requesting a multi-GB zero run must be rejected,
        # not allocated (python parity: MAX_DECODED_BYTES)
        from ggrs_tpu.net.wire import Writer

        w = Writer()
        w.u8(0)
        inner = Writer()
        inner.uvarint(((1 << 40) << 1) | 1)
        w.bytes(inner.finish())
        blob = w.finish()
        with pytest.raises(CodecError):
            _native.decode(b"\x01", blob)
        with pytest.raises(CodecError):
            decode_py(b"\x01", blob)

    def test_overflowing_size_delta_rejected(self):
        # svarint decoding to INT64_MAX must not overflow the C++ size math
        from ggrs_tpu.net.wire import Writer

        w = Writer()
        w.u8(1)
        w.uvarint(1)
        w.svarint((1 << 63) - 1)
        w.bytes(b"")
        blob = w.finish()
        with pytest.raises(CodecError):
            _native.decode(b"\x01", blob)
        with pytest.raises(CodecError):
            decode_py(b"\x01", blob)
