"""C++ codec vs Python codec: byte-for-byte parity, round-trips, hardening.

The native library is compiled on first use; if no toolchain is available
these tests are skipped (the pure-Python codec remains the wire
implementation either way)."""

import numpy as np
import pytest

from ggrs_tpu.net import _native
from ggrs_tpu.net.compression import (
    CodecError,
    decode,
    decode_py,
    encode,
    encode_py,
)

pytestmark = pytest.mark.skipif(
    not _native.available(), reason="native codec unavailable (no g++?)"
)


def _cases(seed, n_cases=200):
    rng = np.random.default_rng(seed)
    for _ in range(n_cases):
        ref_len = int(rng.integers(0, 12))
        reference = bytes(rng.integers(0, 256, ref_len, dtype=np.uint8))
        n = int(rng.integers(0, 12))
        if rng.random() < 0.5 and ref_len > 0:
            sizes = [ref_len] * n  # same-size fast path
        else:
            sizes = [int(rng.integers(0, 20)) for _ in range(n)]
        inputs = [
            bytes(rng.integers(0, 256, s, dtype=np.uint8)) for s in sizes
        ]
        # bias toward repeated inputs: the codec's favorable case
        if n >= 2 and rng.random() < 0.5:
            inputs = [inputs[0]] * n
        yield reference, inputs


class TestParity:
    def test_encode_bytes_identical(self):
        for reference, inputs in _cases(1):
            assert _native.encode(reference, inputs) == encode_py(
                reference, inputs
            ), (reference, inputs)

    def test_cross_roundtrips(self):
        for reference, inputs in _cases(2):
            blob_py = encode_py(reference, inputs)
            blob_cc = _native.encode(reference, inputs)
            if len(reference) == 0 and not all(len(i) == len(reference) for i in inputs):
                pass  # size table present; both must carry it identically
            assert _native.decode(reference, blob_py) == inputs
            assert decode_py(reference, blob_cc) == inputs

    def test_dispatcher_uses_native(self):
        reference = b"\x01\x02"
        inputs = [b"\x01\x02", b"\x03\x04"]
        assert decode(reference, encode(reference, inputs)) == inputs


class TestHardening:
    def test_garbage_never_crashes(self):
        rng = np.random.default_rng(3)
        for _ in range(500):
            data = bytes(
                rng.integers(0, 256, int(rng.integers(0, 64)), dtype=np.uint8)
            )
            reference = bytes(rng.integers(0, 256, int(rng.integers(0, 4)), dtype=np.uint8))
            try:
                out_cc = _native.decode(reference, data)
                err_cc = None
            except CodecError as e:
                out_cc, err_cc = None, e
            if err_cc is None and out_cc is None:
                # packet exceeded the native resource caps; the dispatcher
                # would fall back to Python, so there is nothing to compare
                continue
            try:
                out_py = decode_py(reference, data)
                err_py = None
            except CodecError as e:
                out_py, err_py = None, e
            # both sides must agree on accept/reject, and on the value
            assert (err_cc is None) == (err_py is None), (reference, data, err_cc, err_py)
            if err_cc is None:
                assert out_cc == out_py, (reference, data)

    def test_huge_zero_run_bounded(self):
        # header varint requesting a multi-GB zero run must be rejected,
        # not allocated (python parity: MAX_DECODED_BYTES)
        from ggrs_tpu.net.wire import Writer

        w = Writer()
        w.u8(0)
        inner = Writer()
        inner.uvarint(((1 << 40) << 1) | 1)
        w.bytes(inner.finish())
        blob = w.finish()
        with pytest.raises(CodecError):
            _native.decode(b"\x01", blob)
        with pytest.raises(CodecError):
            decode_py(b"\x01", blob)

    def test_overflowing_size_delta_rejected(self):
        # svarint decoding to INT64_MAX must not overflow the C++ size math
        from ggrs_tpu.net.wire import Writer

        w = Writer()
        w.u8(1)
        w.uvarint(1)
        w.svarint((1 << 63) - 1)
        w.bytes(b"")
        blob = w.finish()
        with pytest.raises(CodecError):
            _native.decode(b"\x01", blob)
        with pytest.raises(CodecError):
            decode_py(b"\x01", blob)


# ---------------------------------------------------------------------------
# message framing fast path (ggrs_msg_encode / ggrs_msg_decode)
# ---------------------------------------------------------------------------


def _py_encode(msg):
    """The pure-Python Writer path, native fast path disabled."""
    import ggrs_tpu.net.messages as M

    fresh = M.Message(magic=msg.magic, body=msg.body)  # bypass memoization
    orig = _native.msg_encode
    _native.msg_encode = lambda m: None
    try:
        return fresh.encode()
    finally:
        _native.msg_encode = orig


def _py_decode(data):
    import ggrs_tpu.net.messages as M

    orig = _native.msg_decode
    _native.msg_decode = lambda d: None
    try:
        return M.Message.decode(data)
    finally:
        _native.msg_decode = orig


def _random_messages(seed, n_cases=300):
    import ggrs_tpu.net.messages as M

    rng = np.random.default_rng(seed)

    def frame():
        return int(rng.integers(-1, 1 << 20))

    for _ in range(n_cases):
        magic = int(rng.integers(0, 1 << 16))
        kind = int(rng.integers(0, 8))
        if kind == 0:
            statuses = [
                M.ConnectionStatus(
                    disconnected=bool(rng.integers(0, 2)), last_frame=frame()
                )
                for _ in range(int(rng.integers(0, 8)))
            ]
            body = M.InputMessage(
                peer_connect_status=statuses,
                disconnect_requested=bool(rng.integers(0, 2)),
                start_frame=frame(),
                ack_frame=frame(),
                bytes=bytes(
                    rng.integers(0, 256, int(rng.integers(0, 64)), dtype=np.uint8)
                ),
            )
        elif kind == 1:
            body = M.InputAck(ack_frame=frame())
        elif kind == 2:
            body = M.QualityReport(
                frame_advantage=int(rng.integers(-(1 << 15), 1 << 15)),
                ping=int(rng.integers(0, 1 << 62)),
            )
        elif kind == 3:
            body = M.QualityReply(pong=int(rng.integers(0, 1 << 62)))
        elif kind == 4:
            body = M.ChecksumReport(
                checksum=int(rng.integers(0, 1 << 62)) << 64
                | int(rng.integers(0, 1 << 62)),
                frame=frame(),
            )
        elif kind == 5:
            body = M.KeepAlive()
        elif kind == 6:
            body = M.SyncRequest(random=int(rng.integers(1, 1 << 32)))
        else:
            body = M.SyncReply(random=int(rng.integers(1, 1 << 32)))
        yield M.Message(magic=magic, body=body)


class TestMessageFraming:
    def test_encode_bytes_identical(self):
        for msg in _random_messages(11):
            import ggrs_tpu.net.messages as M

            fresh = M.Message(magic=msg.magic, body=msg.body)
            native_bytes = _native.msg_encode(fresh)
            assert native_bytes is not None
            assert native_bytes == _py_encode(msg), msg

    def test_decode_matches_python(self):
        for msg in _random_messages(12):
            data = _py_encode(msg)
            got = _native.msg_decode(data)
            assert got is not None
            want = _py_decode(data)
            assert got == want, msg

    def test_dispatcher_roundtrip(self):
        # the public Message.encode/decode (native-first) round-trips
        import ggrs_tpu.net.messages as M

        for msg in _random_messages(13, n_cases=100):
            fresh = M.Message(magic=msg.magic, body=msg.body)
            assert M.Message.decode(fresh.encode()) == fresh

    def test_garbage_agreement(self):
        """Arbitrary bytes: native and Python decoders agree — both raise
        WireError or both produce the same message (native may defer to
        Python via the fallback, which is agreement by construction)."""
        from ggrs_tpu.net.wire import WireError

        rng = np.random.default_rng(14)
        for _ in range(500):
            data = bytes(
                rng.integers(0, 256, int(rng.integers(0, 40)), dtype=np.uint8)
            )
            try:
                want = _py_decode(data)
                want_err = None
            except WireError as e:
                want, want_err = None, e
            try:
                got = _native.msg_decode(data)
            except WireError:
                assert want_err is not None, (data, want)
                continue
            if got is None:
                continue  # fallback: the dispatcher would use Python
            assert want_err is None, (data, "py raised, native accepted")
            assert got == want, data

    def test_truncated_real_messages_agree(self):
        """Every prefix of a real message: same accept/reject behavior."""
        from ggrs_tpu.net.wire import WireError

        for msg in _random_messages(15, n_cases=40):
            data = _py_encode(msg)
            for cut in range(len(data)):
                prefix = data[:cut]
                try:
                    want = _py_decode(prefix)
                    want_err = False
                except WireError:
                    want_err = True
                try:
                    got = _native.msg_decode(prefix)
                except WireError:
                    assert want_err, (msg, cut)
                    continue
                if got is None:
                    continue
                assert not want_err and got == want, (msg, cut)

    def test_out_of_range_fields_fall_back_to_python_semantics(self):
        """ctypes silently truncates out-of-range struct fields, so msg_encode
        must range-check and return None (Python semantics) instead of
        emitting divergent bytes."""
        import struct

        import ggrs_tpu.net.messages as M

        # huge svarint: Python encodes it (unbounded zigzag); native must
        # defer, and the public encode must produce the Python bytes
        big = M.Message(magic=1, body=M.InputAck(ack_frame=2**63))
        assert _native.msg_encode(big) is None
        assert big.encode() == _py_encode(big)

        # i16 overflow: Python raises struct.error; native must not succeed
        bad_adv = M.Message(
            magic=1, body=M.QualityReport(frame_advantage=40000, ping=0)
        )
        assert _native.msg_encode(bad_adv) is None
        with pytest.raises(struct.error):
            _py_encode(bad_adv)

        # negative nonce: Python raises ValueError; native must defer
        neg = M.Message(magic=1, body=M.SyncRequest(random=-1))
        assert _native.msg_encode(neg) is None
        with pytest.raises(ValueError):
            _py_encode(neg)
