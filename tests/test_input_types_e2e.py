"""Variable-size and structured inputs, plus predictor strategies, through
the FULL pipeline: synctest rollbacks and the two-peer wire path (codec
variable-size framing, per-player length prefixes).

Parity analog of the reference's enum-input suite
(/root/reference/tests/test_synctest_session_enum.rs:1-25) and its
variable-size codec path (/root/reference/src/network/compression.rs:26-53).
"""

import enum
import random
import struct

from ggrs_tpu.core import (
    AdvanceFrame,
    Config,
    LoadGameState,
    Local,
    PredictCustom,
    PredictDefault,
    Remote,
    SaveGameState,
)
from ggrs_tpu.net import InMemoryNetwork
from ggrs_tpu.sessions import SessionBuilder


# ---------------------------------------------------------------------------
# a deterministic host game over arbitrary (hashable-encodable) inputs
# ---------------------------------------------------------------------------


class FoldGame:
    """State folds every player's encoded input bytes into an integer
    accumulator — sensitive to content, length, AND order, so any wire or
    rollback corruption of variable-size inputs shows up."""

    def __init__(self, encode) -> None:
        self.frame = 0
        self.acc = 0
        self._encode = encode

    def snapshot(self):
        return (self.frame, self.acc)

    def restore(self, snap):
        self.frame, self.acc = snap

    def advance(self, inputs) -> None:
        for value, _status in inputs:
            data = self._encode(value)
            self.acc = (self.acc * 33 + len(data) + 7) & 0xFFFFFFFF
            for b in data:
                self.acc = (self.acc * 131 + b + 1) & 0xFFFFFFFF
        self.frame += 1

    def handle_requests(self, requests) -> None:
        for request in requests:
            if isinstance(request, LoadGameState):
                self.restore(request.cell.load())
            elif isinstance(request, SaveGameState):
                assert self.frame == request.frame
                request.cell.save(request.frame, self.snapshot(), self.acc)
            elif isinstance(request, AdvanceFrame):
                self.advance(request.inputs)


def run_synctest(config, schedules, ticks=30, check_distance=3):
    """Drive a synctest session with per-player input schedules."""
    sess = (
        SessionBuilder(config)
        .with_num_players(len(schedules))
        .with_check_distance(check_distance)
        .start_synctest_session()
    )
    game = FoldGame(config.input_encode)
    for i in range(ticks):
        for handle, sched in enumerate(schedules):
            sess.add_local_input(handle, sched(i))
        game.handle_requests(sess.advance_frame())
    return game


def run_p2p_pair(
    config,
    sched_a,
    sched_b,
    ticks=60,
    drain=20,
    count_loads=False,
    drain_sched=None,
):
    """Two peers over the in-memory net; returns both games (+ A's Load count).

    The drain phase must feed inputs the configured predictor predicts
    correctly so the unconfirmed tail converges (repeat-last: repeat the last
    scheduled input — the default; other predictors: pass ``drain_sched``)."""
    net = InMemoryNetwork()
    sessions = []
    for me, other, local_handle in (("A", "B", 0), ("B", "A", 1)):
        sessions.append(
            SessionBuilder(config)
            .with_clock(lambda: 0)
            .with_rng(random.Random(61 + local_handle))
            .add_player(Local(), local_handle)
            .add_player(Remote(other), 1 - local_handle)
            .start_p2p_session(net.socket(me))
        )
    sess_a, sess_b = sessions
    game_a, game_b = FoldGame(config.input_encode), FoldGame(config.input_encode)
    loads = 0
    for i in range(ticks + drain):
        if i < ticks:
            a_in, b_in = sched_a(i), sched_b(i)
        elif drain_sched is not None:
            a_in, b_in = drain_sched(i)
        else:
            a_in, b_in = sched_a(ticks - 1), sched_b(ticks - 1)
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()
        sess_a.add_local_input(0, a_in)
        reqs = sess_a.advance_frame()
        loads += sum(1 for r in reqs if isinstance(r, LoadGameState))
        game_a.handle_requests(reqs)
        sess_b.add_local_input(1, b_in)
        game_b.handle_requests(sess_b.advance_frame())
    assert game_a.frame == game_b.frame
    return (game_a, game_b, loads) if count_loads else (game_a, game_b)


# ---------------------------------------------------------------------------
# variable-size bytes inputs
# ---------------------------------------------------------------------------


def bytes_sched_a(i):
    # genuinely varying lengths, including empty
    return [b"", b"x", b"hello", b"\x00\x01\x02\x03"][i % 4]


def bytes_sched_b(i):
    return bytes(range(i % 7))  # length 0..6 varying per frame


class TestVariableSizeBytes:
    def test_synctest_rollbacks_with_varying_lengths(self):
        game = run_synctest(Config.for_bytes(), [bytes_sched_a, bytes_sched_b])
        assert game.frame == 30

    def test_p2p_wire_path_converges(self):
        game_a, game_b = run_p2p_pair(
            Config.for_bytes(), bytes_sched_a, bytes_sched_b
        )
        assert game_a.acc == game_b.acc

    def test_p2p_oracle_value(self):
        # the converged accumulator equals a plain replay of the true inputs
        config = Config.for_bytes()
        game_a, game_b = run_p2p_pair(config, bytes_sched_a, bytes_sched_b)
        oracle = FoldGame(config.input_encode)
        from ggrs_tpu.core import InputStatus

        for i in range(game_a.frame):
            j = min(i, 59)
            oracle.advance(
                [
                    (bytes_sched_a(j), InputStatus.CONFIRMED),
                    (bytes_sched_b(j), InputStatus.CONFIRMED),
                ]
            )
        assert game_a.acc == oracle.acc


# ---------------------------------------------------------------------------
# struct (tuple) inputs
# ---------------------------------------------------------------------------


def struct_sched_a(i):
    return (i * 7 - 100, i % 256)


def struct_sched_b(i):
    return (-i, (i * 3) % 256)


class TestStructInputs:
    FMT = "<hB"  # (int16 stick, uint8 buttons)

    def test_synctest(self):
        game = run_synctest(
            Config.for_struct(self.FMT), [struct_sched_a, struct_sched_b]
        )
        assert game.frame == 30

    def test_p2p_converges(self):
        game_a, game_b = run_p2p_pair(
            Config.for_struct(self.FMT), struct_sched_a, struct_sched_b
        )
        assert game_a.acc == game_b.acc


# ---------------------------------------------------------------------------
# enum inputs (the reference's enum suite, serde analog: custom codec)
# ---------------------------------------------------------------------------


class Direction(enum.Enum):
    NONE = 0
    UP = 1
    DOWN = 2
    LEFT = 3
    RIGHT = 4


def enum_config(predictor=None) -> Config:
    from ggrs_tpu.core import PredictRepeatLast

    return Config(
        input_default=lambda: Direction.NONE,
        input_encode=lambda d: struct.pack("<B", d.value),
        input_decode=lambda b: Direction(struct.unpack("<B", b)[0]),
        predictor=predictor if predictor is not None else PredictRepeatLast(),
    )


class TestEnumInputs:
    def test_synctest_with_delay(self):
        # reference: test_synctest_session_enum.rs drives enum inputs with
        # input delay through the full rollback pipeline
        sess = (
            SessionBuilder(enum_config())
            .with_check_distance(2)
            .with_input_delay(2)
            .start_synctest_session()
        )
        game = FoldGame(enum_config().input_encode)
        dirs = list(Direction)
        for i in range(25):
            sess.add_local_input(0, dirs[i % 5])
            sess.add_local_input(1, dirs[(i * 2) % 5])
            game.handle_requests(sess.advance_frame())
        assert game.frame == 25

    def test_p2p_converges(self):
        dirs = list(Direction)
        game_a, game_b = run_p2p_pair(
            enum_config(),
            lambda i: dirs[i % 5],
            lambda i: dirs[(i * 3) % 5],
        )
        assert game_a.acc == game_b.acc


# ---------------------------------------------------------------------------
# predictor strategies through misprediction -> rollback
# ---------------------------------------------------------------------------


class TestPredictorStrategies:
    def test_predict_default_rolls_back_and_converges(self):
        # PredictDefault guesses 0 for unconfirmed frames; B's nonzero inputs
        # make every not-yet-confirmed frame a misprediction -> rollbacks
        config = Config.for_uint(32, predictor=PredictDefault())
        game_a, game_b, loads = run_p2p_pair(
            config,
            lambda i: 5,
            lambda i: 7,
            count_loads=True,
            # drain with the default input (0): PredictDefault is then right,
            # so the unconfirmed tail converges
            drain_sched=lambda i: (0, 0),
        )
        assert loads > 10, "constant nonzero inputs must mispredict every tick"
        assert game_a.acc == game_b.acc

    def test_predict_custom_perfect_predictor_never_rolls_back(self):
        # B's input increments each frame; a +1 custom predictor is always
        # right, so A never rolls back at all
        config = Config.for_uint(32, predictor=PredictCustom(lambda prev: prev + 1))
        game_a, game_b, loads = run_p2p_pair(
            config,
            lambda i: i,
            lambda i: i,
            ticks=40,
            drain=0,
            count_loads=True,
        )
        assert loads == 0, "a perfect predictor must eliminate rollbacks"

    def test_predict_custom_wrong_predictor_converges(self):
        config = Config.for_uint(32, predictor=PredictCustom(lambda prev: prev ^ 0xFF))
        game_a, game_b, loads = run_p2p_pair(
            config,
            lambda i: i % 3,
            lambda i: i % 4,
            count_loads=True,
            # drain by alternating v -> v^0xFF: the custom predictor is then
            # exact and the tail converges
            drain_sched=lambda i: ((i % 2) * 0xFF, (i % 2) * 0xFF),
        )
        assert loads > 0
        assert game_a.acc == game_b.acc
