"""Spectator session tests (parity with
/root/reference/tests/test_p2p_spectator_session.rs, plus catch-up and
too-far-behind coverage the reference lacks)."""

import pytest

from ggrs_tpu.core import (
    AdvanceFrame,
    Local,
    PredictionThreshold,
    Remote,
    Spectator,
    SpectatorTooFarBehind,
)
from ggrs_tpu.net import InMemoryNetwork
from ggrs_tpu.sessions import SessionBuilder

from stubs import GameStub, stub_config


def make_host_pair_and_spectator(net, catchup_speed=1, max_frames_behind=10):
    clock = lambda: 0
    sess1 = (
        SessionBuilder(stub_config())
        .with_clock(clock)
        .add_player(Local(), 0)
        .add_player(Remote("B"), 1)
        .add_player(Spectator("SPEC"), 2)
        .start_p2p_session(net.socket("A"))
    )
    sess2 = (
        SessionBuilder(stub_config())
        .with_clock(clock)
        .add_player(Remote("A"), 0)
        .add_player(Local(), 1)
        .start_p2p_session(net.socket("B"))
    )
    spec = (
        SessionBuilder(stub_config())
        .with_clock(clock)
        .with_catchup_speed(catchup_speed)
        .with_max_frames_behind(max_frames_behind)
        .start_spectator_session("A", net.socket("SPEC"))
    )
    return sess1, sess2, spec


def test_spectator_follows_host():
    net = InMemoryNetwork()
    sess1, sess2, spec = make_host_pair_and_spectator(net)
    stub1, stub2, stub_spec = GameStub(), GameStub(), GameStub()

    spec_frames = 0
    for i in range(60):
        sess1.poll_remote_clients()
        sess2.poll_remote_clients()
        sess1.add_local_input(0, i)
        stub1.handle_requests(sess1.advance_frame())
        sess2.add_local_input(1, i)
        stub2.handle_requests(sess2.advance_frame())

        try:
            requests = spec.advance_frame()
        except PredictionThreshold:
            continue  # host input not here yet: wait
        for r in requests:
            assert isinstance(r, AdvanceFrame)
        stub_spec.handle_requests(requests)
        spec_frames += len(requests)

    assert spec_frames > 0
    # the spectator's replay must match the hosts' simulation exactly
    assert stub_spec.gs.frame == spec_frames
    reference = GameStub()
    for i in range(spec_frames):
        reference.gs.advance([(i, None), (i, None)])
    assert stub_spec.gs.state == reference.gs.state


def test_spectator_waits_before_first_input():
    net = InMemoryNetwork()
    _sess1, _sess2, spec = make_host_pair_and_spectator(net)
    with pytest.raises(PredictionThreshold):
        spec.advance_frame()


def test_spectator_catches_up():
    """With catchup_speed > 1 the spectator advances multiple frames per tick
    once it falls behind (reference: p2p_spectator_session.rs:103-129)."""
    net = InMemoryNetwork()
    sess1, sess2, spec = make_host_pair_and_spectator(
        net, catchup_speed=2, max_frames_behind=5
    )
    stub1, stub2, stub_spec = GameStub(), GameStub(), GameStub()

    # run hosts ahead without letting the spectator advance
    for i in range(20):
        sess1.poll_remote_clients()
        sess2.poll_remote_clients()
        sess1.add_local_input(0, i)
        stub1.handle_requests(sess1.advance_frame())
        sess2.add_local_input(1, i)
        stub2.handle_requests(sess2.advance_frame())
    spec.poll_remote_clients()
    assert spec.frames_behind_host() > 5

    saw_catchup = False
    for _ in range(30):
        try:
            requests = spec.advance_frame()
        except PredictionThreshold:
            break
        if len(requests) == 2:
            saw_catchup = True
        stub_spec.handle_requests(requests)
    assert saw_catchup
