"""EcsWorld (config 4 workload): parity, 16-frame rollback, gameplay sanity."""

import numpy as np

import jax
import jax.numpy as jnp

from ggrs_tpu.games import EcsWorld
from ggrs_tpu.sessions import DeviceSyncTestSession


def _inputs(n, players, seed):
    return np.random.default_rng(seed).integers(0, 16, (n, players)).astype(np.uint8)


class TestEcsWorld:
    def test_jax_matches_numpy_oracle(self):
        world = EcsWorld(4, entities_per_player=8)
        n = 40
        ins = _inputs(n, 4, seed=2)
        s_j, s_n = world.init_state(), world.init_state_np()
        adv = jax.jit(world.advance)
        for i in range(n):
            s_j = adv(s_j, jnp.asarray(ins[i]))
            s_n = world.advance_np(s_n, ins[i])
        for k in ("pos", "vel", "health", "rally"):
            np.testing.assert_array_equal(np.asarray(s_j[k]), s_n[k], err_msg=k)

    def test_units_move_toward_rally(self):
        world = EcsWorld(2, entities_per_player=4)
        s = world.init_state()
        # player 0 holds "right": rally (and then units) must move
        inputs = jnp.asarray([8, 0], jnp.uint8)
        s2 = s
        for _ in range(30):
            s2 = world.advance(s2, inputs)
        assert not np.array_equal(np.asarray(s["pos"]), np.asarray(s2["pos"]))
        assert int(s2["rally"][0, 0]) != int(s["rally"][0, 0])

    def test_16_frame_rollback_synctest(self):
        # BASELINE config 4: ECS world, 4 players, 16-frame rollback window
        world = EcsWorld(4, entities_per_player=8)
        sess = DeviceSyncTestSession(
            world.advance,
            world.init_state(),
            jnp.zeros((4,), jnp.uint8),
            check_distance=16,
            max_prediction=16,
        )
        sess.run_ticks(_inputs(80, 4, seed=9))
        assert sess.current_frame == 80

    def test_contact_and_respawn_invariants(self):
        world = EcsWorld(2, entities_per_player=4)
        s = world.init_state_np()
        # drive both players' rallies to the center so units collide
        inputs = np.asarray([0, 0], np.uint8)
        s["rally"] = np.asarray(
            [[512 << 16, 512 << 16], [512 << 16, 512 << 16]], np.int32
        )
        took_damage = False
        for _ in range(600):
            s = world.advance_np(s, inputs)
            assert np.all(s["health"] >= 1) and np.all(s["health"] <= 100)
            if np.any(s["health"] < 100):
                took_damage = True
        assert took_damage, "units never made contact"
