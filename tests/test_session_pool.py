"""BatchedRequestExecutor: massed fulfillment of live sessions' requests.

Oracle: a pool of B sessions fulfilled by ONE BatchedRequestExecutor must be
bit-identical to the same B sessions each fulfilled by its own
``ops.DeviceRequestExecutor`` (which is itself equivalence-tested against the
host path).  Covers heterogeneous ticks — different rollback depths per
session in the same dispatch — plus desync checksum fulfillment and sparse
saving.
"""

import random

import numpy as np

import jax

from ggrs_tpu.core import DesyncDetection, Local, Remote
from ggrs_tpu.games import BoxGame, boxgame_config
from ggrs_tpu.net import InMemoryNetwork
from ggrs_tpu.ops import DeviceRequestExecutor, ExecutorPrograms
from ggrs_tpu.parallel import BatchedRequestExecutor
from ggrs_tpu.sessions import SessionBuilder


def _to_arr(pairs):
    return np.asarray([p[0] for p in pairs], np.uint8)


def _make_matches(n_matches, seed, sparse=False, desync_interval=0):
    """n_matches 2-peer BoxGame matches over one in-memory net.  Returns
    (sessions, schedules): flat lists, session 2*m is match m's peer A."""
    net = InMemoryNetwork()
    sessions, schedules = [], []
    for m in range(n_matches):
        names = (f"A{m}", f"B{m}")
        for me in (0, 1):
            b = (
                SessionBuilder(boxgame_config())
                .with_clock(lambda: 0)
                .with_rng(random.Random(seed + 7 * m + me))
                .with_sparse_saving_mode(sparse)
            )
            if desync_interval:
                b = b.with_desync_detection_mode(
                    DesyncDetection(True, desync_interval)
                )
            b = b.add_player(Local(), me).add_player(
                Remote(names[1 - me]), 1 - me
            )
            sessions.append(b.start_p2p_session(net.socket(names[me])))
            # per-session input schedule; offsets differ per match so the
            # pool sees heterogeneous rollback depths in one tick
            schedules.append(
                lambda i, m=m, me=me: ((i + 2 * m + me) // (2 + m % 3)) % 16
            )
    return sessions, schedules


def _drive(sessions, schedules, fulfill, ticks, drain=14):
    for i in range(ticks + drain):
        for s in sessions:
            s.poll_remote_clients()
        all_reqs = []
        for handle_owner, (s, sched) in enumerate(zip(sessions, schedules)):
            s.add_local_input(handle_owner % 2, sched(min(i, ticks - 1)))
            all_reqs.append(s.advance_frame())
        fulfill(all_reqs)


def _run_pool(n_matches, ticks, seed, sparse=False, desync_interval=0):
    sessions, schedules = _make_matches(
        n_matches, seed, sparse=sparse, desync_interval=desync_interval
    )
    game = BoxGame(2)
    B = len(sessions)
    pool = BatchedRequestExecutor(
        game.advance, game.init_state(), _to_arr,
        batch_size=B, ring_length=10, max_burst=9,
    )
    pool.warmup(np.zeros((2,), np.uint8))
    _drive(sessions, schedules, pool.run, ticks)
    states = [pool.live_state(b) for b in range(B)]
    frames = [s.current_frame for s in sessions]
    events = [list(s.events()) for s in sessions]
    return states, frames, events, pool


def _run_individual(n_matches, ticks, seed, sparse=False, desync_interval=0):
    sessions, schedules = _make_matches(
        n_matches, seed, sparse=sparse, desync_interval=desync_interval
    )
    game = BoxGame(2)
    programs = ExecutorPrograms(game.advance)
    executors = [
        DeviceRequestExecutor(
            game.advance, game.init_state(), _to_arr, programs=programs
        )
        for _ in sessions
    ]

    def fulfill(all_reqs):
        for ex, reqs in zip(executors, all_reqs):
            ex.run(reqs)

    _drive(sessions, schedules, fulfill, ticks)
    states = [jax.device_get(ex.state) for ex in executors]
    frames = [s.current_frame for s in sessions]
    events = [list(s.events()) for s in sessions]
    return states, frames, events


def _assert_states_equal(got, want, label):
    for b, (g, w) in enumerate(zip(got, want)):
        for k in w:
            np.testing.assert_array_equal(
                np.asarray(g[k]), np.asarray(w[k]),
                err_msg=f"{label}: session {b} key {k}",
            )


class TestBatchedRequestExecutor:
    def test_pool_matches_individual_executors(self):
        """4 matches (8 sessions) with different rollback cadences: pooled
        fulfillment must be bit-identical to per-session executors."""
        pool_states, pool_frames, _, _ = _run_pool(4, 40, seed=11)
        ind_states, ind_frames, _ = _run_individual(4, 40, seed=11)
        assert pool_frames == ind_frames
        _assert_states_equal(pool_states, ind_states, "pool-vs-individual")

    def test_peers_converge_within_each_match(self):
        states, frames, _, _ = _run_pool(3, 36, seed=23)
        for m in range(3):
            assert frames[2 * m] == frames[2 * m + 1]
            for k in states[0]:
                np.testing.assert_array_equal(
                    np.asarray(states[2 * m][k]),
                    np.asarray(states[2 * m + 1][k]),
                    err_msg=f"match {m} key {k}",
                )

    def test_sparse_saving_through_the_pool(self):
        pool_states, pool_frames, _, _ = _run_pool(2, 36, seed=31, sparse=True)
        ind_states, ind_frames, _ = _run_individual(2, 36, seed=31, sparse=True)
        assert pool_frames == ind_frames
        _assert_states_equal(pool_states, ind_states, "sparse")

    def test_desync_detection_rides_lazy_ring_checksums(self):
        """With desync detection on, sessions exchange checksums the pool
        serves lazily from the digest ring — no DesyncDetected events for
        honest peers, and the checksum values match the individual path."""
        _, _, events, _ = _run_pool(2, 40, seed=43, desync_interval=8)
        for evs in events:
            assert not any(
                type(e).__name__ == "EvDesyncDetected" for e in evs
            ), evs

    def test_ring_accessors_validate_frames(self):
        import pytest

        states, frames, _, pool = _run_pool(1, 20, seed=5)
        # a recent frame is retrievable and consistent with its checksum
        f = frames[0] - 1
        st = pool.ring_state(0, f)
        assert set(st) == set(states[0])
        cs = pool.ring_checksum(0, f)
        assert isinstance(cs, int) and cs > 0
        # a frame that has rolled out of the ring is refused
        with pytest.raises(RuntimeError):
            pool.ring_state(0, max(0, f - 50))

    def test_pool_sharded_over_virtual_mesh(self):
        """The same pooled fulfillment sharded over the 8-device virtual
        mesh: bit-identical to the unsharded pool (sessions are independent —
        no collectives, linear scaling)."""
        import jax as _jax

        from ggrs_tpu.parallel import make_mesh

        if len(_jax.devices()) < 8:
            import pytest

            pytest.skip("needs the 8-device virtual mesh")

        sessions, schedules = _make_matches(4, seed=11)
        game = BoxGame(2)
        pool = BatchedRequestExecutor(
            game.advance, game.init_state(), _to_arr,
            batch_size=8, ring_length=10, max_burst=9,
            mesh=make_mesh(8),
        )
        pool.warmup(np.zeros((2,), np.uint8))
        _drive(sessions, schedules, pool.run, 40)
        states = [pool.live_state(b) for b in range(8)]
        frames = [s.current_frame for s in sessions]

        ind_states, ind_frames, _ = _run_individual(4, 40, seed=11)
        assert frames == ind_frames
        _assert_states_equal(states, ind_states, "sharded-pool")

    def test_undersized_ring_fails_loudly(self):
        """A pool whose ring_length can't cover the sessions' prediction
        window must raise at parse time — the device gather would otherwise
        silently load a newer frame that aliased into the slot.  Rollback
        depth must exceed ring_length for staleness to be possible (each
        rollback re-saves its whole window), so delay delivery to deepen the
        prediction tail."""
        import pytest

        net = InMemoryNetwork(latency_ticks=4)
        sessions = []
        for me, other, h in (("A", "B", 0), ("B", "A", 1)):
            sessions.append(
                SessionBuilder(boxgame_config())
                .with_clock(lambda: 0)
                .with_rng(random.Random(11 + h))
                .add_player(Local(), h)
                .add_player(Remote(other), 1 - h)
                .start_p2p_session(net.socket(me))
            )
        game = BoxGame(2)
        pool = BatchedRequestExecutor(
            game.advance, game.init_state(), _to_arr,
            batch_size=2, ring_length=3, max_burst=9,
        )
        pool.warmup(np.zeros((2,), np.uint8))
        with pytest.raises(RuntimeError, match="too small"):
            for i in range(40):
                net.tick()
                for s in sessions:
                    s.poll_remote_clients()
                reqs = []
                for h, s in enumerate(sessions):
                    s.add_local_input(h, (i // 2) % 16)
                    reqs.append(s.advance_frame())
                pool.run(reqs)
        # the aborted tick left fulfilled cells pointing at slots it never
        # wrote — the pool must refuse ALL further use, not serve stale state
        with pytest.raises(RuntimeError, match="invalidated"):
            pool.run([[] for _ in range(2)])
        with pytest.raises(RuntimeError, match="invalidated"):
            pool.ring_state(0, 0)

    def test_spectator_follows_through_the_pool(self):
        """The pool serves ANY session emitting the request grammar: a
        spectator (advance-only requests, sometimes none while waiting on the
        host) shares the batch with its two P2P peers and tracks their
        simulation bit-exactly."""
        from ggrs_tpu.core import PredictionThreshold, Spectator

        net = InMemoryNetwork()
        clock = lambda: 0
        host = (
            SessionBuilder(boxgame_config())
            .with_clock(clock)
            .with_rng(random.Random(7))
            .add_player(Local(), 0)
            .add_player(Remote("B"), 1)
            .add_player(Spectator("SPEC"), 2)
            .start_p2p_session(net.socket("A"))
        )
        peer = (
            SessionBuilder(boxgame_config())
            .with_clock(clock)
            .with_rng(random.Random(8))
            .add_player(Remote("A"), 0)
            .add_player(Local(), 1)
            .start_p2p_session(net.socket("B"))
        )
        spec = (
            SessionBuilder(boxgame_config())
            .with_clock(clock)
            .start_spectator_session("A", net.socket("SPEC"))
        )
        game = BoxGame(2)
        pool = BatchedRequestExecutor(
            game.advance, game.init_state(), _to_arr,
            batch_size=3, ring_length=10, max_burst=9,
        )
        pool.warmup(np.zeros((2,), np.uint8))

        for i in range(60):
            host.poll_remote_clients()
            peer.poll_remote_clients()
            host.add_local_input(0, (min(i, 45) // 4) % 16)
            reqs = [host.advance_frame()]
            peer.add_local_input(1, (min(i, 45) // 3) % 16)
            reqs.append(peer.advance_frame())
            try:
                reqs.append(spec.advance_frame())
            except PredictionThreshold:
                reqs.append([])  # still waiting on host input
            pool.run(reqs)

        assert spec.current_frame > 40, "spectator never followed"
        # the spectator's live state after advancing frame f equals the
        # host's save of frame f+1 (saves label the pre-advance frame, the
        # spectator counts completed advances)
        f = spec.current_frame
        want = pool.ring_state(0, f + 1)
        got = pool.live_state(2)
        for k in want:
            np.testing.assert_array_equal(
                np.asarray(got[k]), np.asarray(want[k]), err_msg=k
            )

    def test_disconnect_mid_match_through_the_pool(self):
        """One pooled match loses a player mid-run (manual disconnect_player,
        the reference's p2p_session.rs:485-511): the surviving peer rolls
        back to the disconnect frame with dummy inputs and keeps simulating;
        the OTHER pooled match must be completely unaffected — bit-identical
        to running it alone."""
        sessions, schedules = _make_matches(2, seed=17)
        game = BoxGame(2)
        pool = BatchedRequestExecutor(
            game.advance, game.init_state(), _to_arr,
            batch_size=4, ring_length=10, max_burst=9,
        )
        pool.warmup(np.zeros((2,), np.uint8))

        for i in range(50):
            for s in sessions:
                s.poll_remote_clients()
            reqs = []
            for h, (s, sched) in enumerate(zip(sessions, schedules)):
                if h == 1 and i >= 30:
                    reqs.append([])  # match 0's peer B went away
                    continue
                if h == 0 and i == 32:
                    s.disconnect_player(1)  # survivor drops the silent peer
                s.add_local_input(h % 2, sched(min(i, 39)))
                reqs.append(s.advance_frame())
            pool.run(reqs)

        # the survivor kept advancing past the disconnect with dummy inputs
        assert sessions[0].current_frame > 35
        # match 1 (sessions 2,3) is unaffected: its peers still agree
        assert sessions[2].current_frame == sessions[3].current_frame
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(
                np.asarray(pool.live_state(2)[k]),
                np.asarray(pool.live_state(3)[k]),
                err_msg=f"match 1 {k}",
            )

    def test_lockstep_and_input_delay_through_the_pool(self):
        """A lockstep match (max_prediction=0: no saves, no rollbacks —
        fork delta #3) and an input-delay match share one pool with a
        default match; all three shapes normalize into the same program."""
        net = InMemoryNetwork()
        clock = lambda: 0
        sessions = []
        variants = [
            lambda b: b.with_max_prediction_window(0),  # lockstep
            lambda b: b.with_input_delay(2),
            lambda b: b,
        ]
        for m, variant in enumerate(variants):
            names = (f"A{m}", f"B{m}")
            for me in (0, 1):
                b = (
                    SessionBuilder(boxgame_config())
                    .with_clock(clock)
                    .with_rng(random.Random(71 + 3 * m + me))
                )
                b = variant(b)
                b = b.add_player(Local(), me).add_player(
                    Remote(names[1 - me]), 1 - me
                )
                sessions.append(b.start_p2p_session(net.socket(names[me])))
        game = BoxGame(2)
        pool = BatchedRequestExecutor(
            game.advance, game.init_state(), _to_arr,
            batch_size=6, ring_length=10, max_burst=9,
        )
        pool.warmup(np.zeros((2,), np.uint8))

        for i in range(50):
            for s in sessions:
                s.poll_remote_clients()
            reqs = []
            for h, s in enumerate(sessions):
                s.add_local_input(h % 2, (min(i, 39) // (2 + h // 2)) % 16)
                reqs.append(s.advance_frame())
            pool.run(reqs)

        for m in range(3):
            a, b = sessions[2 * m], sessions[2 * m + 1]
            # deterministic fixture (fixed clock, seeded rng, in-memory net):
            # both peers reach the same frame exactly
            assert a.current_frame == b.current_frame, (
                m, a.current_frame, b.current_frame
            )
            for k in ("pos", "vel", "rot"):
                np.testing.assert_array_equal(
                    np.asarray(pool.live_state(2 * m)[k]),
                    np.asarray(pool.live_state(2 * m + 1)[k]),
                    err_msg=f"match {m} {k}",
                )

    def test_one_dispatch_per_tick(self):
        """The pool's whole point: a tick with B heterogeneous request lists
        costs exactly one program dispatch (zero when all-empty)."""
        sessions, schedules = _make_matches(3, seed=3)
        game = BoxGame(2)
        pool = BatchedRequestExecutor(
            game.advance, game.init_state(), _to_arr,
            batch_size=6, ring_length=10, max_burst=9,
        )
        pool.warmup(np.zeros((2,), np.uint8))
        calls = {"n": 0}
        real_tick = pool._tick

        def counting(carry, desc):
            calls["n"] += 1
            return real_tick(carry, desc)

        pool._tick = counting
        _drive(sessions, schedules, pool.run, 20, drain=0)
        assert calls["n"] == 20
        pool.run([[] for _ in range(6)])
        assert calls["n"] == 20, "an all-empty tick must not dispatch"
