"""SpeculativeRollback: branch trajectories replace the rollback replay."""

import numpy as np

import jax
import jax.numpy as jnp

from ggrs_tpu.games import BoxGame
from ggrs_tpu.parallel import SpeculativeRollback


def _mk(game, K=4):
    # hypotheses: player 0 is local (real inputs), player 1 remote with K
    # candidate held-button guesses
    candidates = jnp.asarray([0, 1, 4, 8], jnp.uint8)

    def branch_inputs(k, frame, local_inputs):
        return jnp.asarray(
            [jnp.asarray(local_inputs)[0], candidates[k]], jnp.uint8
        )

    return SpeculativeRollback(game.advance, K, branch_inputs, max_window=8)


class TestSpeculativeRollback:
    def test_hit_matches_replay_bitwise(self):
        game = BoxGame(2)
        state = game.init_state()
        spec = _mk(game)
        spec.root(10, state)

        local = [np.uint8(1), np.uint8(9), np.uint8(5)]
        remote_actual = 4  # matches candidate index 2 every frame
        for li in local:
            spec.extend(jnp.asarray([li, 0], jnp.uint8))

        confirmed = [
            jnp.asarray([li, remote_actual], jnp.uint8) for li in local
        ]
        traj = spec.resolve(10, confirmed)
        assert traj is not None and len(traj) == 3

        # ground truth: plain replay under the confirmed inputs
        truth = state
        for c in confirmed:
            truth = game.advance(truth, c)
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(
                np.asarray(traj[-1][k]), np.asarray(truth[k]), err_msg=k
            )

    def test_miss_returns_none(self):
        game = BoxGame(2)
        spec = _mk(game)
        spec.root(0, game.init_state())
        spec.extend(jnp.asarray([1, 0], jnp.uint8))
        confirmed = [jnp.asarray([1, 15], jnp.uint8)]  # 15 is no candidate
        assert spec.resolve(0, confirmed) is None

    def test_wrong_root_or_window_returns_none(self):
        game = BoxGame(2)
        spec = _mk(game)
        spec.root(5, game.init_state())
        spec.extend(jnp.asarray([0, 0], jnp.uint8))
        conf = [jnp.asarray([0, 0], jnp.uint8)]
        assert spec.resolve(4, conf) is None  # wrong anchor
        assert spec.resolve(5, conf * 3) is None  # window longer than traj

    def test_intermediate_states_fulfill_saves(self):
        # the resolved per-step states must equal the replay's intermediate
        # frames — that is what fulfills the rollback's Save requests
        game = BoxGame(2)
        state = game.init_state()
        spec = _mk(game)
        spec.root(0, state)
        seq = [
            jnp.asarray([2, 1], jnp.uint8),
            jnp.asarray([3, 1], jnp.uint8),
        ]
        for c in seq:
            spec.extend(c)  # local matches; remote candidate 1 == actual 1
        traj = spec.resolve(0, seq)
        assert traj is not None
        truth = state
        for step, c in enumerate(seq):
            truth = game.advance(truth, c)
            for k in ("pos", "vel", "rot"):
                np.testing.assert_array_equal(
                    np.asarray(traj[step][k]), np.asarray(truth[k])
                )

    def test_max_window_caps_extension(self):
        game = BoxGame(2)
        spec = _mk(game)
        spec.root(0, game.init_state())
        for _ in range(12):
            spec.extend(jnp.asarray([0, 0], jnp.uint8))
        assert spec.window == 8
