"""Input plane (DESIGN.md §27): variable-size varrec inputs end to end,
pluggable + device-batched prediction, and the lockstep tier.

Three pins, one per subsystem:

* **Varrec**: the ``[u16 len][payload][zero pad]`` envelope is injective
  and canonical (codec unit tests), and an enum/Vec-shaped command-stream
  game (``games.rtscmd``) rides it through synctest rollbacks, the
  two-peer wire path, the native session bank (bit-identical to the
  Python reference under seeded loss/dup/reorder — wire, requests, AND
  journal), and the journal file format round trip.
* **Prediction**: confirmed streams are bit-identical with the device
  plane on or off (predict/batched.py's correctness contract), and
  ACROSS strategies — prediction only ever fills unconfirmed frames, so
  the confirmed stream is predictor-independent.
* **Lockstep**: a ``max_prediction == 0`` session never emits
  SaveGameState/LoadGameState and never advances past the confirmed
  frontier, while folding to the same confirmed output as a rollback
  pair; ``HostSessionPool.demote_to_lockstep`` moves a healthy native
  slot onto that tier mid-run with zero blast radius on its neighbours
  (mirrors analysis/machines.py's ``lockstep:head`` model entry).
"""

import random

import pytest

from ggrs_tpu.broadcast.journal import JournalTap, MatchJournal, read_journal
from ggrs_tpu.chaos import (
    RecordingSocket,
    blast_radius_violations,
    fulfill,
    req_summary,
    two_peer_builder,
)
from ggrs_tpu.core import (
    AdvanceFrame,
    Config,
    InputStatus,
    InvalidRequest,
    LoadGameState,
    Local,
    Remote,
    SaveGameState,
    SessionState,
    Synchronized,
    Synchronizing,
)
from ggrs_tpu.core.varrec import (
    VARREC_HEADER_BYTES,
    envelope_pack,
    envelope_size,
    envelope_split,
    envelope_unpack,
)
from ggrs_tpu.fleet import PoolShard
from ggrs_tpu.games import RtsCmd, RtsCmdGame, decode_commands, encode_commands
from ggrs_tpu.net import InMemoryNetwork, _native
from ggrs_tpu.obs.registry import Registry
from ggrs_tpu.parallel.host_bank import (
    SLOT_EVICTED,
    SLOT_NATIVE,
    HostSessionPool,
)
from ggrs_tpu.predict import (
    BatchedDefault,
    BatchedRepeatLast,
    DevicePredictionPlane,
    PredictDefault,
    PredictRepeatLast,
)
from ggrs_tpu.sessions import SessionBuilder

from test_input_types_e2e import FoldGame, run_p2p_pair, run_synctest

needs_native = pytest.mark.skipif(
    _native.bank_lib() is None, reason="native session bank unavailable"
)

FUZZ = dict(loss=0.08, duplicate=0.05, reorder=0.1, latency_ticks=1)


# ---------------------------------------------------------------------------
# deterministic command schedules (enum/Vec-shaped: 0-3 orders per frame)
# ---------------------------------------------------------------------------


def _commands(rng, units):
    out = []
    for _ in range(rng.randrange(0, 4)):
        kind = rng.randrange(3)
        if kind == 0:
            out.append(("move", rng.randrange(units),
                        rng.randrange(-2, 3), rng.randrange(-2, 3)))
        elif kind == 1:
            out.append(("gather", rng.randrange(units)))
        else:
            out.append(("build", rng.randrange(64), rng.randrange(64)))
    return tuple(out)


def cmd_sched(slot, i, units=4):
    return _commands(random.Random(9000 + slot * 613 + i), units)


def ext_sched(slot, i, units=4):
    return _commands(random.Random(40000 + slot * 821 + i), units)


# ---------------------------------------------------------------------------
# the pool harness: B varrec matches, each against an external reference
# peer on its own fault-isolated network (the chaos-suite topology, over
# command streams instead of uint16)
# ---------------------------------------------------------------------------


def drive_varrec_pool(
    ticks,
    n_matches,
    predictor_factory=None,
    plane=False,
    no_native=False,
    seed=0,
    fault_cfg=None,
    journals=False,
    tmp_path=None,
    leg="",
    inject=None,
    frame_keyed=False,
):
    """Identical arguments (modulo the native/plane switches under test)
    must produce bit-comparable observables: per-slot wire bytes, request
    summaries, events, journal records, and final game checksums.

    ``frame_keyed`` feeds each slot's local input by the slot's CURRENT
    FRAME instead of the tick index (how a real driver samples input when
    a frame is consumed).  Required by the demotion/eviction legs: a slot
    adopted onto the per-session tier resumes behind the tick counter, so
    a tick-keyed schedule would land different commands on each frame
    than the control leg — a different game, not a comparable one."""
    game = RtsCmd(num_players=2, num_units=4, max_cmds=4)
    base = seed * 1000
    clock = [0]
    nets, socks, exts, ext_games = [], [], [], []
    pool = HostSessionPool(metrics=Registry(enabled=False))
    cfg0 = None
    for m in range(n_matches):
        fc = dict(fault_cfg or {"latency_ticks": 1})
        fc.setdefault("seed", base + 100 + m)
        net = InMemoryNetwork(**fc)
        nets.append(net)
        names = (f"A{m}", f"B{m}")
        predictor = predictor_factory() if predictor_factory else None
        cfg = game.config(predictor=predictor)
        if cfg0 is None:
            cfg0 = cfg
        builder = (
            SessionBuilder(cfg)
            .with_clock(lambda: clock[0])
            .with_rng(random.Random(base + 3 + 5 * m))
            .add_player(Local(), 0)
            .add_player(Remote(names[1]), 1)
        )
        sock = RecordingSocket(net.socket(names[0]))
        socks.append(sock)
        pool.add_session(builder, sock)
        # the external peer is the per-session Python reference in EVERY
        # leg: scalar repeat-last, never pooled, never predicted-for by
        # the plane — its wire bytes must not depend on the leg switches
        ext = (
            SessionBuilder(game.config())
            .with_clock(lambda: clock[0])
            .with_rng(random.Random(base + 4000 + m))
            .add_player(Remote(names[0]), 0)
            .add_player(Local(), 1)
            .start_p2p_session(net.socket(names[1]))
        )
        exts.append(ext)
        ext_games.append(RtsCmdGame(game))
    if no_native:
        import os

        os.environ["GGRS_TPU_NO_NATIVE"] = "1"
        try:
            native = pool.native_active
        finally:
            os.environ.pop("GGRS_TPU_NO_NATIVE", None)
    else:
        native = pool.native_active
    journal_list = []
    if journals:
        for m in range(n_matches):
            journal = MatchJournal(
                tmp_path / f"{leg or ('n' if native else 'p')}-{m}.journal",
                num_players=2,
                input_size=cfg0.native_input_size,
                tail_window=4 * ticks + 16,
            )
            journal_list.append(journal)
            if native:
                pool.set_confirmed_stream(m, journal)
            else:
                pool._sessions[m].adopt_spectator_endpoint(
                    JournalTap.ADDR, JournalTap(journal, cfg0)
                )
    plane_obj = None
    if plane:
        plane_obj = DevicePredictionPlane(cfg0, capacity=n_matches)
        pool.attach_prediction_plane(plane_obj)
    slot_games = [RtsCmdGame(game) for _ in range(n_matches)]
    reqs_log = [[] for _ in range(n_matches)]
    events_log = [[] for _ in range(n_matches)]
    ctx = dict(pool=pool, exts=exts, nets=nets, clock=clock,
               games=slot_games, target=n_matches - 1, seed=seed)
    last_fed = [-1] * n_matches
    ext_fed = [-1] * n_matches
    for i in range(ticks):
        clock[0] += 16
        if inject is not None:
            inject(i, ctx)
        for m, ext in enumerate(exts):
            if frame_keyed:
                frame = ext.current_frame
                if frame != ext_fed[m]:
                    ext.add_local_input(1, ext_sched(m, frame))
                    ext_fed[m] = frame
            else:
                ext.add_local_input(1, ext_sched(m, i))
            ext_games[m].handle_requests(ext.advance_frame())
        for m in range(n_matches):
            if frame_keyed:
                frame = pool.current_frame(m)
                if frame != last_fed[m]:
                    pool.add_local_input(m, 0, cmd_sched(m, frame))
                    last_fed[m] = frame
            else:
                pool.add_local_input(m, 0, cmd_sched(m, i))
        for m, reqs in enumerate(pool.advance_all()):
            slot_games[m].handle_requests(reqs)
            reqs_log[m].append(req_summary(reqs))
        for m in range(n_matches):
            events_log[m].extend(pool.events(m))
        for net in nets:
            net.tick()
    ctx.update(
        native=native,
        wire=[s.sent for s in socks],
        reqs=reqs_log,
        events=events_log,
        states=[pool.slot_state(m) for m in range(n_matches)],
        frames=[pool.current_frame(m) for m in range(n_matches)],
        checksums=[g.checksum() for g in slot_games],
        ext_checksums=[g.checksum() for g in ext_games],
        journals=journal_list,
        plane=plane_obj,
    )
    return ctx


def assert_legs_identical(a, b, journals=False):
    assert a["wire"] == b["wire"], "wire bytes diverged"
    assert a["reqs"] == b["reqs"], "request streams diverged"
    assert a["events"] == b["events"], "event streams diverged"
    assert a["frames"] == b["frames"]
    assert a["checksums"] == b["checksums"]
    assert a["ext_checksums"] == b["ext_checksums"]
    if journals:
        for ja, jb in zip(a["journals"], b["journals"]):
            assert list(ja.tail) == list(jb.tail), "journal records diverged"
            assert ja.next_frame == jb.next_frame
            assert ja.next_frame > 0, "journal never saw a confirmed frame"


# ---------------------------------------------------------------------------
# varrec envelope codec
# ---------------------------------------------------------------------------


class TestVarrecEnvelope:
    def test_round_trip_all_lengths(self):
        for n in range(17):
            payload = bytes(range(n))
            env = envelope_pack(payload, 16)
            assert len(env) == envelope_size(16) == 16 + VARREC_HEADER_BYTES
            assert envelope_unpack(env) == payload
            assert envelope_split(env) == (payload, bytes(16 - n))

    def test_empty_payload_is_all_zero_envelope(self):
        # the native core's blank input IS the default record
        assert envelope_pack(b"", 8) == bytes(envelope_size(8))

    def test_nonzero_padding_rejected(self):
        env = bytearray(envelope_pack(b"ab", 8))
        env[-1] = 1
        with pytest.raises(ValueError):
            envelope_unpack(bytes(env))
        # the raw splitter is the lenient inverse (wire decode path)
        payload, padding = envelope_split(bytes(env))
        assert payload == b"ab" and padding[-1] == 1

    def test_capacity_errors(self):
        with pytest.raises(ValueError):
            envelope_pack(b"abc", 2)
        with pytest.raises(ValueError):
            envelope_size(0)
        with pytest.raises(ValueError):
            envelope_size(0x10000)

    def test_injective_over_distinct_payloads(self):
        seen = set()
        for payload in (b"", b"\x00", b"\x00\x00", b"a", b"ab", b"ba"):
            seen.add(envelope_pack(payload, 4))
        assert len(seen) == 6

    def test_config_for_varrec_round_trip(self):
        cfg = RtsCmd(max_cmds=4).config()
        cmds = (("move", 1, -2, 2), ("gather", 3), ("build", 7, 9))
        blob = cfg.input_encode(cmds)
        assert len(blob) == cfg.native_input_size == envelope_size(16)
        assert cfg.input_decode(blob) == cmds
        assert cfg.input_encode(cfg.input_default()) == bytes(len(blob))

    def test_for_varrec_rejects_nonempty_default(self):
        with pytest.raises(ValueError):
            Config.for_varrec(8, default=lambda: b"x")


# ---------------------------------------------------------------------------
# the command-stream game: encode/decode + JAX-vs-NumPy oracle
# ---------------------------------------------------------------------------


class TestRtsCmdGame:
    def test_encode_decode_round_trip(self):
        for slot in range(4):
            for i in range(32):
                cmds = cmd_sched(slot, i)
                assert decode_commands(encode_commands(cmds)) == cmds

    def test_jax_advance_matches_numpy_oracle(self):
        import numpy as np

        game = RtsCmd(num_players=2, num_units=4, max_cmds=4)
        s_np = game.init_state_np()
        s_jx = game.init_state()
        for i in range(24):
            streams = [cmd_sched(0, i), ext_sched(0, i)]
            s_np = game.advance_np(s_np, streams)
            s_jx = game.advance(s_jx, game.envelopes_np(streams))
        for k in s_np:
            assert np.array_equal(np.asarray(s_jx[k]), s_np[k]), k


# ---------------------------------------------------------------------------
# varrec through the session pipeline (python path)
# ---------------------------------------------------------------------------


class TestVarrecSessions:
    def test_synctest_rollback_round_trip(self):
        cfg = RtsCmd(max_cmds=4).config()
        game = run_synctest(
            cfg, [lambda i: cmd_sched(0, i), lambda i: ext_sched(0, i)]
        )
        assert game.frame > 0 and game.acc != 0

    def test_p2p_pair_converges(self):
        cfg = RtsCmd(max_cmds=4).config()
        game_a, game_b = run_p2p_pair(
            cfg, lambda i: cmd_sched(0, i), lambda i: ext_sched(0, i)
        )
        assert game_a.acc == game_b.acc
        assert game_a.frame == game_b.frame > 0


# ---------------------------------------------------------------------------
# pluggable prediction: plane on/off and cross-strategy parity
# ---------------------------------------------------------------------------


class TestPredictorParity:
    def test_plane_on_off_bit_identical(self):
        for fault in (None, dict(FUZZ)):
            off = drive_varrec_pool(
                40, 4, predictor_factory=BatchedRepeatLast, fault_cfg=fault
            )
            on = drive_varrec_pool(
                40, 4, predictor_factory=BatchedRepeatLast, fault_cfg=fault,
                plane=True,
            )
            # batched strategies are never native-eligible: both legs run
            # the fallback path, where the plane hooks
            assert not off["native"] and not on["native"]
            assert_legs_identical(off, on)
            stats = on["plane"].stats()
            assert stats["ticks"] == 40 and stats["registered"] == 4
            assert stats["hits"] > 0, "plane never served a prediction"

    def test_batched_default_plane_parity(self):
        off = drive_varrec_pool(
            40, 4, predictor_factory=BatchedDefault,
            fault_cfg=dict(FUZZ),
        )
        on = drive_varrec_pool(
            40, 4, predictor_factory=BatchedDefault,
            fault_cfg=dict(FUZZ), plane=True,
        )
        assert_legs_identical(off, on)
        assert on["plane"].stats()["hits"] > 0

    def test_confirmed_stream_is_predictor_independent(self, tmp_path):
        """Prediction only fills unconfirmed frames: whatever the
        strategy (and however differently it mispredicts under fuzz),
        the confirmed stream — journal records, final game state, frame
        count — must be identical."""
        legs = [
            drive_varrec_pool(
                40, 4, predictor_factory=factory, fault_cfg=dict(FUZZ),
                no_native=True, journals=True, tmp_path=tmp_path, leg=name,
            )
            for name, factory in [
                ("repeat", None),
                ("default", PredictDefault),
                ("brepeat", BatchedRepeatLast),
                ("bdefault", BatchedDefault),
            ]
        ]
        ref = legs[0]
        assert ref["journals"][0].next_frame > 0
        for leg in legs[1:]:
            for ja, jb in zip(ref["journals"], leg["journals"]):
                assert list(ja.tail) == list(jb.tail)
                assert ja.next_frame == jb.next_frame
            # frame CADENCE is predictor-independent too; the head game
            # states are not compared — they include speculative frames
            # simulated from strategy-specific predictions
            assert ref["frames"] == leg["frames"]


# ---------------------------------------------------------------------------
# the acceptance leg: B=64 device-batched pool vs per-session reference
# ---------------------------------------------------------------------------


class TestBatchedPoolAcceptance:
    def test_b64_plane_bit_identical_to_reference(self, tmp_path):
        fault = dict(loss=0.05, duplicate=0.03, reorder=0.05,
                     latency_ticks=1)
        ref = drive_varrec_pool(
            25, 64, predictor_factory=BatchedRepeatLast, fault_cfg=fault,
            journals=True, tmp_path=tmp_path, leg="ref",
        )
        dev = drive_varrec_pool(
            25, 64, predictor_factory=BatchedRepeatLast, fault_cfg=fault,
            journals=True, tmp_path=tmp_path, leg="dev", plane=True,
        )
        assert_legs_identical(ref, dev, journals=True)
        stats = dev["plane"].stats()
        assert stats["registered"] == 64
        assert stats["hits"] > 0


# ---------------------------------------------------------------------------
# varrec on the native session bank
# ---------------------------------------------------------------------------


@needs_native
class TestNativeVarrecBank:
    def test_native_matches_python_reference_under_fuzz(self, tmp_path):
        nat = drive_varrec_pool(
            50, 8, fault_cfg=dict(FUZZ), journals=True, tmp_path=tmp_path,
        )
        ref = drive_varrec_pool(
            50, 8, fault_cfg=dict(FUZZ), journals=True, tmp_path=tmp_path,
            no_native=True,
        )
        assert nat["native"] and not ref["native"]
        assert_legs_identical(nat, ref, journals=True)

    def test_journal_file_round_trips_commands(self, tmp_path):
        """The journal's joined-input records split back into per-player
        varrec envelopes whose payloads decode to the original command
        tuples — the on-disk resume format carries variable-size inputs
        losslessly."""
        run = drive_varrec_pool(
            30, 2, journals=True, tmp_path=tmp_path, leg="rt",
        )
        isize = RtsCmd(max_cmds=4).config().native_input_size
        for m, journal in enumerate(run["journals"]):
            journal.close()
            parsed = read_journal(journal.path)
            frames = parsed["frames"]
            assert not parsed["truncated"] and len(frames) > 0
            for frame, flags, joined in frames:
                assert flags == b"\x00\x00"
                for player in range(2):
                    env = joined[player * isize:(player + 1) * isize]
                    sched = cmd_sched if player == 0 else ext_sched
                    assert decode_commands(envelope_unpack(env)) == \
                        sched(m, frame)


# ---------------------------------------------------------------------------
# lockstep tier: session-level semantics
# ---------------------------------------------------------------------------


class TraceFold(FoldGame):
    """FoldGame recording the accumulator after every simulated frame;
    the LAST write per frame is the settled (confirmed) value."""

    def __init__(self, encode):
        super().__init__(encode)
        self.trace = {}

    def advance(self, inputs):
        super().advance(inputs)
        self.trace[self.frame] = self.acc


def _drive_pair(cfg, max_prediction, ticks, fault_cfg=None):
    net = InMemoryNetwork(**(fault_cfg or {"latency_ticks": 1}))
    clock = [0]
    sessions, games, raw = [], [], []
    for me, other, handle in (("A", "B", 0), ("B", "A", 1)):
        builder = (
            SessionBuilder(cfg)
            .with_clock(lambda: clock[0])
            .with_rng(random.Random(17 + handle))
            .add_player(Local(), handle)
            .add_player(Remote(other), 1 - handle)
        )
        if max_prediction is not None:
            builder.with_max_prediction_window(max_prediction)
        sessions.append(builder.start_p2p_session(net.socket(me)))
        games.append(TraceFold(cfg.input_encode))
        raw.append([])
    # inputs are keyed by FRAME, not tick: a lockstep session stalls at
    # pipeline fill, so tick-keyed schedules would land on different
    # frames than the rollback leg and the confirmed streams would be
    # different games, not comparable ones
    last_fed = [-1, -1]
    for _ in range(ticks):
        clock[0] += 16
        for handle, (sess, game) in enumerate(zip(sessions, games)):
            sess.poll_remote_clients()
            frame = sess.current_frame
            if frame != last_fed[handle]:
                sess.add_local_input(handle, cmd_sched(handle, frame))
                last_fed[handle] = frame
            reqs = sess.advance_frame()
            raw[handle].extend(reqs)
            game.handle_requests(reqs)
        net.tick()
    return sessions, games, raw


class TestLockstepSession:
    def test_never_saves_never_loads_never_predicts(self):
        cfg = RtsCmd(max_cmds=4).config()
        sessions, games, raw = _drive_pair(cfg, 0, 40)
        for sess, game, reqs in zip(sessions, games, raw):
            assert sess.in_lockstep_mode()
            assert not any(
                isinstance(r, (SaveGameState, LoadGameState)) for r in reqs
            ), "lockstep session emitted save/load work"
            advances = [r for r in reqs if isinstance(r, AdvanceFrame)]
            assert advances, "lockstep pair never advanced"
            for adv in advances:
                for _value, status in adv.inputs:
                    assert status is InputStatus.CONFIRMED
            # never past the confirmed frontier
            assert game.frame <= sess.confirmed_frame() + 1

    def test_confirmed_output_matches_rollback_pair(self):
        cfg = RtsCmd(max_cmds=4).config()
        _, lock_games, _ = _drive_pair(cfg, 0, 40)
        _, roll_games, _ = _drive_pair(cfg, None, 48)
        for lock, roll in zip(lock_games, roll_games):
            assert lock.frame > 10
            assert roll.frame >= lock.frame
            for frame, acc in lock.trace.items():
                assert roll.trace[frame] == acc, (
                    f"frame {frame}: lockstep fold diverged from the "
                    "rollback pair's settled value"
                )

    def test_pool_rejects_demotion_on_fallback(self):
        run = drive_varrec_pool(3, 2, no_native=True)
        with pytest.raises(InvalidRequest):
            run["pool"].demote_to_lockstep(0)


# ---------------------------------------------------------------------------
# lockstep tier: pool demotion (load shedding)
# ---------------------------------------------------------------------------


@needs_native
class TestLockstepDemotion:
    DEMOTE_AT = 25

    def _inject(self, i, ctx):
        if i == self.DEMOTE_AT:
            ctx["resume_frame"] = ctx["pool"].demote_to_lockstep(
                ctx["target"]
            )

    def test_demotion_mid_run(self, tmp_path):
        run = drive_varrec_pool(
            60, 3, journals=True, tmp_path=tmp_path, leg="demo",
            inject=self._inject, frame_keyed=True,
        )
        control = drive_varrec_pool(
            60, 3, journals=True, tmp_path=tmp_path, leg="ctl",
            frame_keyed=True,
        )
        target = run["target"]
        assert run["states"][target] == SLOT_EVICTED
        assert run["pool"].in_lockstep(target)
        assert run["pool"].lockstep_slots() == {target: self.DEMOTE_AT}
        # survivors: zero blast radius (bank-resident, bit-identical)
        assert blast_radius_violations(run, control) == []
        # the demoted match kept running past its resume point
        assert run["frames"][target] > run["resume_frame"] > 0

    def test_demoted_slot_never_saves_or_loads(self, tmp_path):
        run = drive_varrec_pool(
            60, 3, journals=True, tmp_path=tmp_path, leg="nl",
            inject=self._inject, frame_keyed=True,
        )
        post = [
            r
            for tick in run["reqs"][run["target"]][self.DEMOTE_AT:]
            for r in tick
        ]
        loads = [r for r in post if r[0] == "LoadGameState"]
        assert len(loads) == 1, (
            "expected exactly the one-time adoption load, got "
            f"{len(loads)}"
        )
        assert not any(r[0] == "SaveGameState" for r in post)
        advances = [r for r in post if r[0] == "adv"]
        assert advances, "demoted slot never advanced"
        for adv in advances:
            for _value, status in adv[1]:
                assert status is InputStatus.CONFIRMED, (
                    "lockstep tier advanced on a predicted input"
                )

    def test_demoted_confirmed_stream_matches_control(self, tmp_path):
        run = drive_varrec_pool(
            60, 3, journals=True, tmp_path=tmp_path, leg="cs",
            inject=self._inject, frame_keyed=True,
        )
        control = drive_varrec_pool(
            60, 3, journals=True, tmp_path=tmp_path, leg="csc",
            frame_keyed=True,
        )
        target = run["target"]
        tail_run = list(run["journals"][target].tail)
        tail_ctl = list(control["journals"][target].tail)
        assert len(tail_run) > self.DEMOTE_AT, (
            "journal stalled at demotion"
        )
        assert tail_run == tail_ctl[: len(tail_run)], (
            "demoted slot's confirmed stream diverged from the rollback "
            "control"
        )

    def test_demote_is_one_way_and_native_only(self, tmp_path):
        run = drive_varrec_pool(
            40, 2, inject=lambda i, ctx: (
                ctx["pool"].demote_to_lockstep(0) if i == 10 else None
            ),
        )
        with pytest.raises(InvalidRequest):
            run["pool"].demote_to_lockstep(0)  # already on the tier

    def test_shard_demote_match(self):
        clock = [0]
        shard = PoolShard("s0", capacity=4, metrics=Registry())
        peers, nets, peer_reqs = [], [], []
        for k in range(2):
            net = InMemoryNetwork(latency_ticks=1, seed=50 + k)
            nets.append(net)
            shard.admit(
                f"m{k}",
                two_peer_builder(clock, 70 + 2 * k, 0, f"P{k}"),
                net.socket(f"H-m{k}"),
            )
            peers.append(
                two_peer_builder(
                    clock, 71 + 2 * k, 1, f"H-m{k}", other_handle=0
                ).start_p2p_session(net.socket(f"P{k}"))
            )
            peer_reqs.append([])

        def tick(i):
            clock[0] += 16
            for k, peer in enumerate(peers):
                peer.add_local_input(1, (i * 3 + k) % 16)
                fulfill(peer.advance_frame())
                shard.add_local_input(f"m{k}", 0, (i * 7 + k) % 16)
            for reqs in shard.advance_all().values():
                fulfill(reqs)
            for net in nets:
                net.tick()

        for i in range(20):
            tick(i)
        assert shard.lockstep_matches() == []
        resume = shard.demote_match("m1")
        assert resume > 0
        assert shard.lockstep_matches() == ["m1"]
        before = shard.pool.current_frame(shard._matches["m1"])
        for i in range(20, 40):
            tick(i)
        assert shard.pool.current_frame(shard._matches["m1"]) > before
        assert shard.live_matches() == 2
        with pytest.raises(InvalidRequest):
            shard.demote_match("nope")


# ---------------------------------------------------------------------------
# varrec eviction adoption (fault path) — the OTHER road onto the
# per-session tier must also carry variable-size inputs losslessly
# ---------------------------------------------------------------------------


@needs_native
class TestVarrecEviction:
    def test_fault_eviction_adopts_varrec_match(self, tmp_path):
        def inject(i, ctx):
            if i == 20:
                ctx["pool"].inject_slot_error(ctx["target"])

        run = drive_varrec_pool(
            50, 3, journals=True, tmp_path=tmp_path, leg="ev",
            inject=inject, frame_keyed=True,
        )
        control = drive_varrec_pool(
            50, 3, journals=True, tmp_path=tmp_path, leg="evc",
            frame_keyed=True,
        )
        target = run["target"]
        assert run["states"][target] == SLOT_EVICTED
        assert not run["pool"].in_lockstep(target), (
            "fault eviction must not be tagged as a lockstep demotion"
        )
        assert blast_radius_violations(run, control) == []
        tail_run = list(run["journals"][target].tail)
        tail_ctl = list(control["journals"][target].tail)
        assert len(tail_run) > 20
        assert tail_run == tail_ctl[: len(tail_run)]


# ---------------------------------------------------------------------------
# sync-handshake decision pin (DESIGN.md §27): default sessions start
# Running and the handshake vocabulary stays dormant
# ---------------------------------------------------------------------------


class TestSyncHandshakeDefault:
    def test_default_run_emits_no_handshake_events(self):
        cfg = RtsCmd(max_cmds=4).config()
        net = InMemoryNetwork(latency_ticks=1)
        clock = [0]
        sessions = []
        for me, other, handle in (("A", "B", 0), ("B", "A", 1)):
            sessions.append(
                SessionBuilder(cfg)
                .with_clock(lambda: clock[0])
                .with_rng(random.Random(5 + handle))
                .add_player(Local(), handle)
                .add_player(Remote(other), 1 - handle)
                .start_p2p_session(net.socket(me))
            )
        # the vocabulary survives (callers may still match on it) ...
        assert Synchronizing is not None and Synchronized is not None
        events = []
        for sess in sessions:
            # ... but a default build starts Running: no handshake phase
            assert sess.current_state() is SessionState.RUNNING
        for i in range(30):
            clock[0] += 16
            for handle, sess in enumerate(sessions):
                sess.poll_remote_clients()
                sess.add_local_input(handle, cmd_sched(handle, i))
                fulfill(sess.advance_frame())
                events.extend(sess.events())
            net.tick()
        assert not any(
            isinstance(e, (Synchronizing, Synchronized)) for e in events
        ), "default session produced handshake events"
