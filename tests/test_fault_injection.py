"""P2P convergence under injected network faults, and the spectator
pending-overflow disconnect.

The in-memory network's loss/duplication/reordering/latency knobs are the
README's claimed improvement over the reference's loopback-UDP-only testing;
these tests prove sessions converge bit-exactly under each fault class and
under all of them combined.  The overflow disconnect matches
/root/reference/src/network/protocol.rs:441-445.
"""

import random

import pytest

from ggrs_tpu.core import Disconnected, Local, Remote, Spectator
from ggrs_tpu.net import InMemoryNetwork
from ggrs_tpu.sessions import SessionBuilder

from stubs import GameStub, stub_config


class FakeClock:
    def __init__(self) -> None:
        self.now = 0

    def __call__(self) -> int:
        return self.now


def make_pair(net, clock, input_delay=0):
    sessions = []
    for me, other, local_handle in (("A", "B", 0), ("B", "A", 1)):
        b = (
            SessionBuilder(stub_config())
            .with_clock(clock)
            .with_rng(random.Random(41 + local_handle))
        )
        if input_delay:
            b = b.with_input_delay(input_delay)
        sessions.append(
            b.add_player(Local(), local_handle)
            .add_player(Remote(other), 1 - local_handle)
            .start_p2p_session(net.socket(me))
        )
    return sessions


FAULT_CONFIGS = [
    pytest.param(dict(seed=7, loss=0.25), id="loss"),
    pytest.param(dict(seed=8, duplicate=0.4), id="duplicate"),
    pytest.param(dict(seed=9, reorder=0.5), id="reorder"),
    pytest.param(dict(seed=10, latency_ticks=3), id="latency"),
    pytest.param(
        dict(seed=11, loss=0.15, duplicate=0.2, reorder=0.3, latency_ticks=2),
        id="combined",
    ),
]


@pytest.mark.parametrize("faults", FAULT_CONFIGS)
def test_p2p_converges_bit_exact_under_faults(faults):
    net = InMemoryNetwork(**faults)
    clock = FakeClock()
    sess_a, sess_b = make_pair(net, clock)
    stub_a, stub_b = GameStub(), GameStub()

    n = 150
    for i in range(n):
        clock.now += 16
        net.tick()  # advances latency delivery time
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()
        sess_a.add_local_input(0, i % 5)
        stub_a.handle_requests(sess_a.advance_frame())
        sess_b.add_local_input(1, (i * 3) % 7)
        stub_b.handle_requests(sess_b.advance_frame())

    # drain with constant inputs until both peers have fully confirmed and
    # settled — repeat-last predictions become correct, rollbacks stop
    for i in range(40):
        clock.now += 16
        net.tick()
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()
        sess_a.add_local_input(0, 0)
        stub_a.handle_requests(sess_a.advance_frame())
        sess_b.add_local_input(1, 0)
        stub_b.handle_requests(sess_b.advance_frame())

    assert not sess_a.local_connect_status[1].disconnected
    assert not sess_b.local_connect_status[0].disconnected
    assert stub_a.gs.frame == stub_b.gs.frame
    assert stub_a.gs.state == stub_b.gs.state


def test_faults_with_input_delay_converge():
    net = InMemoryNetwork(seed=13, loss=0.2, reorder=0.3)
    clock = FakeClock()
    sess_a, sess_b = make_pair(net, clock, input_delay=2)
    stub_a, stub_b = GameStub(), GameStub()

    for i in range(120):
        clock.now += 16
        net.tick()
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()
        sess_a.add_local_input(0, i % 4)
        stub_a.handle_requests(sess_a.advance_frame())
        sess_b.add_local_input(1, (i * 5) % 9)
        stub_b.handle_requests(sess_b.advance_frame())
    for i in range(40):
        clock.now += 16
        net.tick()
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()
        sess_a.add_local_input(0, 1)
        stub_a.handle_requests(sess_a.advance_frame())
        sess_b.add_local_input(1, 1)
        stub_b.handle_requests(sess_b.advance_frame())

    assert stub_a.gs.frame == stub_b.gs.frame
    assert stub_a.gs.state == stub_b.gs.state


def test_spectator_overflow_force_disconnects():
    """A spectator that never acks accumulates >128 unacked inputs on the
    host's endpoint; the host must force-disconnect it
    (/root/reference/src/network/protocol.rs:441-445)."""
    net = InMemoryNetwork()
    clock = FakeClock()

    sessions = []
    for me, other, local_handle in (("A", "B", 0), ("B", "A", 1)):
        b = (
            SessionBuilder(stub_config())
            .with_clock(clock)
            .with_rng(random.Random(51 + local_handle))
        )
        if me == "A":
            b = b.add_player(Spectator("S"), 2)  # never pumped: dead weight
        sessions.append(
            b.add_player(Local(), local_handle)
            .add_player(Remote(other), 1 - local_handle)
            .start_p2p_session(net.socket(me))
        )
    sess_a, sess_b = sessions
    net.socket("S")  # the address exists; nobody ever reads or acks

    stub_a, stub_b = GameStub(), GameStub()
    disconnected_addrs = []
    for i in range(170):
        clock.now += 16
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()
        for e in sess_a.events():
            if isinstance(e, Disconnected):
                disconnected_addrs.append(e.addr)
        sess_a.add_local_input(0, i % 3)
        stub_a.handle_requests(sess_a.advance_frame())
        sess_b.add_local_input(1, i % 3)
        stub_b.handle_requests(sess_b.advance_frame())
        if disconnected_addrs:
            break

    assert disconnected_addrs == ["S"]
    # the overflow trips right at the 128-unacked-input cap (the game frame
    # trails the forwarded confirmed frames by the prediction window)
    assert stub_a.gs.frame > 120
    # the game itself is unaffected by losing a spectator
    frame_at_disconnect = stub_a.gs.frame
    for i in range(5):
        clock.now += 16
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()
        sess_a.add_local_input(0, 0)
        stub_a.handle_requests(sess_a.advance_frame())
        sess_b.add_local_input(1, 0)
        stub_b.handle_requests(sess_b.advance_frame())
    assert stub_a.gs.frame > frame_at_disconnect
