"""Transient OS send errors must not crash a session tick
(`UdpNonBlockingSocket.send_to`): on Linux UDP, a previous datagram's ICMP
error can surface as ENETUNREACH/ECONNREFUSED on the NEXT sendto.  The
socket counts them in ``NetworkStats.send_errors`` and treats the datagram
as lost — the endpoint protocol's redundant sends already cover loss —
mirroring the receive path's existing ConnectionResetError handling.  Real
programming errors (EBADF after close) still raise.
"""

from __future__ import annotations

import errno

import pytest

from ggrs_tpu.net.messages import KeepAlive, Message
from ggrs_tpu.net.sockets import UdpNonBlockingSocket


def make_socket():
    sock = UdpNonBlockingSocket(0)  # OS-assigned port
    return sock


def msg():
    return Message(magic=7, body=KeepAlive())


class _Raising:
    """Stand-in for the OS socket: raises a chosen errno on sendto."""

    def __init__(self, eno):
        self.eno = eno
        self.calls = 0

    def sendto(self, buf, addr):
        self.calls += 1
        raise OSError(self.eno, errno.errorcode.get(self.eno, "?"))

    def close(self):
        pass


@pytest.mark.parametrize(
    "eno",
    [errno.ENETUNREACH, errno.EHOSTUNREACH, errno.ECONNREFUSED,
     errno.ENOBUFS, errno.EAGAIN],
)
def test_transient_send_errors_are_counted_not_raised(eno):
    sock = make_socket()
    try:
        sock._sock.close()
        sock._sock = _Raising(eno)
        for _ in range(3):
            sock.send_to(msg(), ("192.0.2.1", 9))  # TEST-NET: never routable
        assert sock.stats.send_errors == 3
        assert sock._sock.calls == 3
    finally:
        sock.close()


def test_non_transient_send_errors_still_raise():
    sock = make_socket()
    try:
        sock._sock.close()
        sock._sock = _Raising(errno.EBADF)
        with pytest.raises(OSError):
            sock.send_to(msg(), ("192.0.2.1", 9))
        assert sock.stats.send_errors == 0
    finally:
        sock.close()


def test_real_udp_send_still_works():
    """A loopback round trip keeps working with the error handling in
    place (the happy path is untouched)."""
    a = make_socket()
    b = make_socket()
    try:
        port_b = b._sock.getsockname()[1]
        a.send_to(msg(), ("127.0.0.1", port_b))
        # non-blocking receive: poll briefly for delivery
        import time

        got = []
        for _ in range(100):
            got = b.receive_all_messages()
            if got:
                break
            time.sleep(0.005)
        assert got and isinstance(got[0][1].body, KeepAlive)
        assert a.stats.send_errors == 0
    finally:
        a.close()
        b.close()
