"""SyncLayer unit tests, parity oracle from the reference
(/root/reference/src/sync_layer.rs:381-436) plus save/load ring behavior."""

import pytest

from ggrs_tpu.core import Config, NULL_FRAME, PlayerInput, SyncLayer
from ggrs_tpu.net.messages import ConnectionStatus


def test_different_delays():
    sl = SyncLayer(Config.for_uint(8), num_players=2, max_prediction=8)
    p1_delay, p2_delay = 2, 0
    sl.set_frame_delay(0, p1_delay)
    sl.set_frame_delay(1, p2_delay)

    status = [ConnectionStatus(), ConnectionStatus()]

    for i in range(20):
        gi = PlayerInput(i, i)
        # add as remote to avoid prediction threshold checks
        sl.add_remote_input(0, gi)
        sl.add_remote_input(1, gi)
        status[0].last_frame = i
        status[1].last_frame = i

        if i >= 3:
            sync_inputs = sl.synchronized_inputs(status)
            assert sync_inputs[0][0] == i - p1_delay
            assert sync_inputs[1][0] == i - p2_delay

        sl.advance_frame()


def test_save_load_round_trip():
    sl = SyncLayer(Config.for_uint(8), num_players=1, max_prediction=4)
    req = sl.save_current_state()
    assert req.frame == 0
    req.cell.save(0, {"hp": 100}, checksum=42)
    assert sl.last_saved_frame == 0

    for _ in range(3):
        sl.advance_frame()
        sl.save_current_state().cell.save(sl.current_frame, {"hp": 90}, None)

    load = sl.load_frame(0)
    assert load.frame == 0
    assert load.cell.load() == {"hp": 100}
    assert sl.current_frame == 0


def test_load_frame_window_asserts():
    sl = SyncLayer(Config.for_uint(8), num_players=1, max_prediction=2)
    for _ in range(5):
        req = sl.save_current_state()
        req.cell.save(req.frame, None, None)
        sl.advance_frame()
    with pytest.raises(AssertionError):
        sl.load_frame(1)  # outside prediction window (current=5, max_pred=2)
    with pytest.raises(AssertionError):
        sl.load_frame(5)  # not in the past
    with pytest.raises(AssertionError):
        sl.load_frame(NULL_FRAME)


def test_set_last_confirmed_discards_inputs():
    sl = SyncLayer(Config.for_uint(8), num_players=1, max_prediction=8)
    status = [ConnectionStatus()]
    for i in range(10):
        sl.add_remote_input(0, PlayerInput(i, i))
        status[0].last_frame = i
        sl.synchronized_inputs(status)
        sl.advance_frame()
    sl.set_last_confirmed_frame(8, sparse_saving=False)
    assert sl.last_confirmed_frame == 8
    # frame 7 (= 8-1) and beyond must still be fetchable
    assert sl.confirmed_input(0, 8).input == 8


def test_disconnected_player_gets_default_input():
    sl = SyncLayer(Config.for_uint(8), num_players=2, max_prediction=8)
    status = [ConnectionStatus(), ConnectionStatus(disconnected=True, last_frame=NULL_FRAME)]
    sl.add_remote_input(0, PlayerInput(0, 5))
    status[0].last_frame = 0
    inputs = sl.synchronized_inputs(status)
    assert inputs[0][0] == 5
    assert inputs[1][0] == 0  # default
    from ggrs_tpu.core import InputStatus

    assert inputs[1][1] == InputStatus.DISCONNECTED
