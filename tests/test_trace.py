"""Tests for tick tracing & desync forensics (DESIGN.md §14).

Pin layers:

1. the Tracer primitive (ring bounds, nesting, disabled no-op, Chrome
   trace-event export) and the forensics primitives (bisection, checksum
   history) — no native code needed;
2. tracing is observational only: a fault-injected chaos run's wire
   bytes / requests / events are bit-identical with the tracer on vs off,
   and tracing adds ZERO tick crossings (the native timing tail rides the
   existing tick output);
3. the native phase spans: they nest inside the measured crossing span
   and sum to no more than its duration, the Perfetto export is valid
   JSON with the required keys, and the cumulative totals ride the stats
   crossing;
4. the HTTP endpoints (/healthz, /trace) and DesyncReport artifacts.
"""

from __future__ import annotations

import json

import pytest

from ggrs_tpu.chaos import drive_chaos, drive_desync_forensics
from ggrs_tpu.net import _native
from ggrs_tpu.obs import (
    ChecksumHistory,
    Registry,
    Tracer,
    first_divergent_frame,
    start_http_server,
)

needs_native = pytest.mark.skipif(
    _native.bank_lib() is None, reason="native session bank unavailable"
)


# ---------------------------------------------------------------------------
# 1. tracer + forensics primitives
# ---------------------------------------------------------------------------


class TestTracer:
    def test_ring_bounds_and_drop_count(self):
        t = Tracer(capacity=4)
        for i in range(10):
            with t.span(f"s{i}"):
                pass
        assert len(t) == 4
        assert t.recorded == 10
        assert t.dropped == 6
        assert [e[1] for e in t.events()] == ["s6", "s7", "s8", "s9"]

    def test_nesting_containment(self):
        """Chrome infers the span tree from time containment: a child's
        [start, start+dur) must sit inside its parent's."""
        t = Tracer()
        with t.span("outer"):
            with t.span("inner"):
                pass
        events = {e[1]: e for e in t.events()}
        _, _, _, o_start, o_dur, _, _ = events["outer"]
        _, _, _, i_start, i_dur, _, _ = events["inner"]
        assert o_start <= i_start
        assert i_start + i_dur <= o_start + o_dur

    def test_disabled_is_noop(self):
        t = Tracer(enabled=False)
        cm = t.span("x")
        assert cm is t.span("y")  # shared singleton: zero allocation
        with cm:
            pass
        t.add_instant("i")
        t.add_complete("c", 0, 5)
        assert len(t) == 0 and t.recorded == 0
        assert t.chrome_trace()["traceEvents"] == []

    def test_chrome_export_shape(self):
        t = Tracer()
        with t.span("a", cat="py", slot=3):
            pass
        t.add_instant("fault", cat="py", code=-71)
        doc = t.chrome_trace()
        json.dumps(doc)  # serializable end to end
        events = doc["traceEvents"]
        assert len(events) == 2
        complete = next(e for e in events if e["ph"] == "X")
        assert {"name", "cat", "ph", "ts", "dur", "pid", "tid"} <= set(
            complete
        )
        assert complete["args"] == {"slot": 3}
        instant = next(e for e in events if e["ph"] == "i")
        assert instant["args"] == {"code": -71}
        # time base is shifted: the oldest event sits at ts 0
        assert min(e["ts"] for e in events) == 0

    def test_summary_totals(self):
        t = Tracer()
        for _ in range(3):
            with t.span("tick"):
                pass
        s = t.summary()
        assert s["tick"]["count"] == 3
        assert s["tick"]["total_us"] >= s["tick"]["max_us"] > 0


class TestForensicsPrimitives:
    def test_bisection_finds_first_divergence(self):
        local = {f: f * 7 for f in range(1, 200)}
        for div in (1, 2, 57, 199):
            remote = {
                f: (f * 7 if f < div else f * 7 + 1) for f in range(1, 200)
            }
            assert first_divergent_frame(local, remote) == div

    def test_bisection_sparse_and_disjoint_windows(self):
        local = {f: f for f in range(0, 100, 3)}
        remote = {f: (f if f < 50 else f + 1) for f in range(0, 100, 5)}
        # shared frames are multiples of 15; first divergent shared is 60
        assert first_divergent_frame(local, remote) == 60
        assert first_divergent_frame(local, {}) == -1
        assert first_divergent_frame({}, {}) == -1

    def test_bisection_no_divergence(self):
        h = {f: f for f in range(50)}
        assert first_divergent_frame(h, dict(h)) == -1

    def test_checksum_history_bounds(self):
        h = ChecksumHistory(capacity=8)
        for f in range(20):
            h.record(f, f * 3)
        assert len(h) == 8
        assert h.frames() == list(range(12, 20))
        assert h.get(19) == 57 and h.get(3) is None
        h.record(19, 1)  # update in place, no eviction
        assert len(h) == 8 and h.get(19) == 1


# ---------------------------------------------------------------------------
# 2. + 3. observational-only pins and native phase spans
# ---------------------------------------------------------------------------


def _inject_at_60(i, ctx):
    if i == 60:
        ctx["pool"].inject_slot_error(ctx["target"])


@needs_native
class TestTracingObservational:
    def test_wire_bit_identical_and_zero_extra_crossings(self):
        """The whole tracing layer — Python spans, the armed native phase
        timers, the timing tail — must not move a wire byte or add a tick
        crossing: identical fault-injected runs with the tracer on vs
        off."""
        on = drive_chaos(160, n_matches=2, seed=11, metrics=Registry(),
                         tracer=Tracer(), inject=_inject_at_60)
        off = drive_chaos(160, n_matches=2, seed=11, metrics=Registry(),
                          tracer=None, inject=_inject_at_60)
        assert on["pool"]._trace_native  # the timers really were armed
        assert on["states"] == off["states"]
        assert on["frames"] == off["frames"]
        for idx in range(len(on["states"])):
            assert on["wire"][idx] == off["wire"][idx], (
                f"slot {idx}: wire bytes diverged with tracing enabled"
            )
            assert on["reqs"][idx] == off["reqs"][idx]
            assert on["events"][idx] == off["events"][idx]
        # zero extra crossings: one tick crossing per pool tick, and the
        # scrape budget untouched (one stats crossing from the final
        # scrape, one harvest for the eviction — same as the off leg)
        assert on["pool"].crossings == off["pool"].crossings == 160
        assert on["pool"].harvests == off["pool"].harvests
        assert on["pool"].stat_crossings == off["pool"].stat_crossings

    def test_native_phase_spans_nest_and_sum(self):
        """Per-phase native spans: laid end-to-end inside the measured
        crossing span, summing to the in-crossing time (<= the ctypes
        window; the remainder is crossing overhead)."""
        tracer = Tracer(capacity=1 << 14)
        run = drive_chaos(60, n_matches=2, seed=12, metrics=Registry(),
                          tracer=tracer)
        pool = run["pool"]
        events = tracer.events()
        crossings = [e for e in events if e[1] == "bank.crossing"]
        assert crossings, "no crossing spans recorded"
        phase_names = {f"bank.{n}" for n in _native.BANK_PHASES}
        seen = {e[1] for e in events}
        assert "pool.tick" in seen and "pool.slot" in seen
        assert seen & phase_names, "no native phase spans recorded"
        # last tick: phases nest inside the last crossing and sum <= dur
        _, _, _, c_start, c_dur, _, _ = crossings[-1]
        tail = [e for e in events if e[1] in phase_names
                and e[3] >= c_start]
        assert tail, "no phase spans for the last crossing"
        for _, name, _, start, dur, _, _ in tail:
            assert start >= c_start
            assert start + dur <= c_start + c_dur
        phases = pool.last_tick_phases()
        assert phases is not None and set(phases) == set(
            _native.BANK_PHASES
        )
        assert 0 < sum(phases.values()) <= c_dur
        # the Perfetto export round-trips
        doc = json.loads(json.dumps(tracer.chrome_trace()))
        assert doc["traceEvents"]

    def test_64_slot_pool_perfetto_export(self):
        """The acceptance-shaped pin: a 64+-slot pool run exports a valid
        Perfetto document whose per-phase native spans sum to within 10%
        of the measured tick crossing time (the `other` phase closes the
        books natively; the residual gap is ctypes call overhead, which
        amortizes to noise at this scale)."""
        tracer = Tracer(capacity=1 << 15)
        run = drive_chaos(30, n_matches=32, seed=17, metrics=Registry(),
                          tracer=tracer)  # 2*32+1 = 65 bank slots
        assert len(run["states"]) == 65
        events = tracer.events()
        phase_names = {f"bank.{n}" for n in _native.BANK_PHASES}
        crossings = [e for e in events if e[1] == "bank.crossing"]
        assert crossings
        ratios = []
        for _, _, _, c_start, c_dur, _, _ in crossings:
            span_sum = sum(
                e[4] for e in events
                if e[1] in phase_names and c_start <= e[3] < c_start + c_dur
            )
            if span_sum:
                ratios.append(span_sum / c_dur)
        assert ratios
        ratios.sort()
        median = ratios[len(ratios) // 2]
        assert 0.9 <= median <= 1.0, (
            f"native phase spans cover {median:.1%} of the median "
            f"crossing; expected within 10%"
        )
        # the export loads: valid JSON, complete events carry ts+dur
        doc = json.loads(json.dumps(tracer.chrome_trace()))
        assert len(doc["traceEvents"]) == len(events)
        for ev in doc["traceEvents"]:
            assert {"name", "cat", "ph", "ts", "pid", "tid"} <= set(ev)
            if ev["ph"] == "X":
                assert ev["dur"] >= 0

    def test_phase_totals_ride_the_stats_crossing(self):
        tracer = Tracer()
        run = drive_chaos(50, n_matches=1, seed=13, metrics=Registry(),
                          tracer=tracer)
        pool = run["pool"]  # drive_chaos ends with a scrape
        totals = pool.native_phase_totals()
        assert totals is not None
        timed_ticks, by_phase = totals
        assert timed_ticks == 50
        assert set(by_phase) == set(_native.BANK_PHASES)
        assert sum(by_phase.values()) > 0
        # the scrape that refreshed them was the run's single stats
        # crossing: the cumulative view costs nothing extra
        assert pool.stat_crossings == 1


# ---------------------------------------------------------------------------
# 4. HTTP endpoints + DesyncReport artifacts
# ---------------------------------------------------------------------------


class TestHttpEndpoints:
    def test_healthz_and_trace(self):
        import time as _time
        import urllib.error
        import urllib.request

        reg = Registry()
        reg.counter("x_total").inc()
        tracer = Tracer()
        with tracer.span("tick"):
            pass
        stamp = [_time.monotonic()]
        try:
            server = start_http_server(
                reg, port=0, tracer=tracer, health=lambda: stamp[0],
                stale_after=60.0,
            )
        except OSError:
            pytest.skip("cannot bind a loopback socket in this sandbox")
        try:
            base = f"http://127.0.0.1:{server.port}"
            body = json.loads(
                urllib.request.urlopen(base + "/healthz", timeout=5).read()
            )
            assert body["ok"] is True
            assert body["last_tick_age_s"] >= 0
            doc = json.loads(
                urllib.request.urlopen(base + "/trace", timeout=5).read()
            )
            assert doc["traceEvents"][0]["name"] == "tick"
            # stale loop: 503 with ok false
            stamp[0] = _time.monotonic() - 3600
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(base + "/healthz", timeout=5)
            assert exc.value.code == 503
            assert json.loads(exc.value.read())["ok"] is False
        finally:
            server.close()

    def test_trace_404_without_tracer(self):
        import urllib.error
        import urllib.request

        try:
            server = start_http_server(Registry(), port=0)
        except OSError:
            pytest.skip("cannot bind a loopback socket in this sandbox")
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/trace", timeout=5
                )
            assert exc.value.code == 404
        finally:
            server.close()


class TestDesyncReports:
    def test_checksum_compare_report_round_trips(self, tmp_path):
        """The reference-path report: first-divergent-frame bisection
        lands on the seeded fault frame and the artifact round-trips
        through JSON with every forensic section present."""
        run = drive_desync_forensics(160, fault_frame=30, seed=14,
                                     interval=1, tracer=Tracer())
        assert run["reports_a"] and run["reports_b"]
        report = run["reports_b"][0]
        assert report.kind == "checksum-compare"
        assert report.first_divergent_frame == 30
        assert report.detected_frame == 30
        assert report.local_checksum != report.remote_checksum
        # the checksum window straddles the divergence on both sides
        assert "29" in report.to_dict()["checksum_window"]["local"]
        assert report.recorder_dump
        assert report.trace_events
        path = report.write(tmp_path / "report.json")
        loaded = json.load(open(path))
        assert loaded["first_divergent_frame"] == 30
        assert loaded["kind"] == "checksum-compare"
        assert loaded["trace_events"]

    def test_report_list_is_bounded(self):
        """A persistent desync re-fires every interval; the report list
        must not grow without bound."""
        from ggrs_tpu.obs.forensics import MAX_REPORTS

        run = drive_desync_forensics(400, fault_frame=30, seed=15,
                                     interval=1)
        assert len(run["desyncs"][0]) > MAX_REPORTS
        assert len(run["reports_a"]) == MAX_REPORTS

    @needs_native
    def test_native_fault_report_on_quarantine(self):
        """A desync-class bank fault (BANK_ERR_SYNC) leaves a forensic
        artifact on the pool, with the recorder dump and trace window
        attached."""
        tracer = Tracer()
        run = drive_chaos(
            120, n_matches=2, seed=16, metrics=Registry(), tracer=tracer,
            inject=lambda i, ctx: (
                ctx["pool"].inject_slot_error(
                    ctx["target"], _native.BANK_ERR_SYNC
                )
                if i == 60 else None
            ),
        )
        pool, target = run["pool"], run["target"]
        report = pool.desync_report(target)
        assert report is not None
        assert report.kind == "native-fault"
        assert report.recorder_dump
        assert report.trace_events
        json.dumps(report.to_dict())
        # non-desync slots carry no report
        assert pool.desync_report(0) is None
        # the injected non-desync fault class leaves no report either
        other = drive_chaos(80, n_matches=1, seed=16, metrics=Registry(),
                            inject=_inject_at_60)
        assert other["pool"].desync_report(other["target"]) is None
