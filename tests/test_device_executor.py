"""DeviceRequestExecutor: host sessions fulfilled with device-resident state.

The host SyncTestSession emits the reference's exact request sequences; the
executor must fulfill them on device such that the simulation matches the
independent NumPy mirror and rollback bursts reproduce plain forward play."""

import numpy as np
import pytest

import jax.numpy as jnp

from ggrs_tpu.games import BoxGame, boxgame_config
from ggrs_tpu.ops import DeviceRequestExecutor
from ggrs_tpu.sessions import SessionBuilder

_box_config = boxgame_config


def _inputs_to_array(pairs):
    return jnp.asarray(np.asarray([p[0] for p in pairs], np.uint8))


def _run_session(check_distance, n_frames, seed):
    game = BoxGame(2)
    rng = np.random.default_rng(seed)
    all_inputs = rng.integers(0, 16, size=(n_frames, 2)).astype(np.uint8)
    sess = (
        SessionBuilder(_box_config())
        .with_check_distance(check_distance)
        .start_synctest_session()
    )
    ex = DeviceRequestExecutor(game.advance, game.init_state(), _inputs_to_array)
    for i in range(n_frames):
        sess.add_local_input(0, int(all_inputs[i, 0]))
        sess.add_local_input(1, int(all_inputs[i, 1]))
        ex.run(sess.advance_frame())
    return game, all_inputs, ex


class TestDeviceExecutor:
    @pytest.mark.parametrize("check_distance", [0, 1, 2, 4])
    def test_matches_numpy_mirror(self, check_distance):
        n = 30
        game, inputs, ex = _run_session(check_distance, n, seed=13)
        s_np = game.init_state_np()
        for i in range(n):
            s_np = game.advance_np(s_np, inputs[i])
        live = {k: np.asarray(v) for k, v in ex.state.items()}
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(live[k], s_np[k], err_msg=k)

    def test_synctest_checksums_stable(self):
        # a full synctest run with rollbacks raises on any nondeterminism;
        # passing means save/load/advance on device is self-consistent
        _run_session(2, 60, seed=17)

    def test_checksums_are_u128(self):
        game = BoxGame(2)
        sess = (
            SessionBuilder(_box_config())
            .with_check_distance(1)
            .start_synctest_session()
        )
        ex = DeviceRequestExecutor(game.advance, game.init_state(), _inputs_to_array)
        sess.add_local_input(0, 1)
        sess.add_local_input(1, 2)
        reqs = sess.advance_frame()
        ex.run(reqs)
        saves = [r for r in reqs if hasattr(r, "cell") and r.cell.frame == 0]
        assert saves and 0 <= saves[0].cell.checksum < (1 << 128)
