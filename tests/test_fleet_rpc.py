"""Fleet transport + tuning + journal-hardening tests (DESIGN.md §17).

Adversarial coverage of the supervisor↔runner frame protocol: truncated
frames, bad crc, wrong version tag, max-size violations, and interleaved
partial reads each yield a TYPED error (never a wedged parser), and a
poisoned stream refuses further traffic instead of resyncing into
garbage.  Plus the ``FleetTuning`` consolidation satellite (env
overrides, artifact round trip) and the journal write-failure hardening
satellite (ENOSPC/EIO degrade the shard loudly; the
torn-final-record-then-reopen path recovers the intact prefix).
"""

from __future__ import annotations

import errno
import json
import socket
import struct
import threading
import time
import zlib

import pytest

from ggrs_tpu.broadcast.journal import (
    MatchJournal,
    read_journal,
    resume_from_file,
)
from ggrs_tpu.chaos import CrcGame, InMemoryNetwork, two_peer_builder
from ggrs_tpu.core.errors import NotSynchronized, PredictionThreshold
from ggrs_tpu.fleet import FleetTuning, PoolShard, ShardSupervisor
from ggrs_tpu.fleet.rpc import (
    DEFAULT_MAX_FRAME,
    FrameError,
    HEADER_SIZE,
    KIND_CALL,
    KIND_ERR,
    KIND_HEARTBEAT,
    KIND_REPLY,
    MAGIC,
    RpcClosed,
    RpcConn,
    RpcRemoteError,
    RpcTimeout,
    VERSION,
    encode_frame,
)
from ggrs_tpu.obs import Registry


def _pair(**kw):
    a, b = socket.socketpair()
    return RpcConn(a, **kw), RpcConn(b, **kw)


# ----------------------------------------------------------------------
# frame protocol: the happy path
# ----------------------------------------------------------------------


class TestFrameRoundTrip:
    def test_objects_round_trip(self):
        a, b = _pair()
        try:
            for kind, obj in (
                (KIND_CALL, dict(op="tick", inputs=[("m0", 0, 7)])),
                (KIND_REPLY, dict(frames={"m0": 31}, blob=b"\x00" * 4096)),
                (KIND_HEARTBEAT, dict(ticks=12)),
            ):
                a.send(kind, obj)
                got_kind, got = b.recv(timeout=5)
                assert got_kind == kind and got == obj
        finally:
            a.close(), b.close()

    def test_call_skips_interleaved_heartbeats(self):
        a, b = _pair()
        try:
            def runner():
                kind, msg = b.recv(timeout=5)
                assert kind == KIND_CALL and msg["op"] == "ping"
                b.send(KIND_HEARTBEAT, dict(ticks=1))
                b.send(KIND_HEARTBEAT, dict(ticks=2))
                b.send(KIND_REPLY, dict(pong=True))

            t = threading.Thread(target=runner)
            t.start()
            before = a.last_frame_at
            assert a.call("ping", timeout=5) == dict(pong=True)
            t.join()
            assert a.last_frame_at >= before  # heartbeats refreshed it
        finally:
            a.close(), b.close()

    def test_remote_error_frame(self):
        a, b = _pair()
        try:
            def runner():
                b.recv(timeout=5)
                b.send(KIND_ERR, dict(type="InvalidRequest",
                                      msg="nope", traceback="tb"))

            t = threading.Thread(target=runner)
            t.start()
            with pytest.raises(RpcRemoteError) as exc:
                a.call("admit", timeout=5)
            t.join()
            assert exc.value.type_name == "InvalidRequest"
        finally:
            a.close(), b.close()

    def test_interleaved_partial_reads_on_slow_socket(self):
        """Frames dribbled a few bytes at a time (slow peer, fragmented
        stream) parse intact — the buffer survives arbitrary chunking."""
        a, b = _pair()
        try:
            payload = dict(blob=bytes(range(256)) * 64, n=7)
            frame = encode_frame(
                KIND_REPLY,
                __import__("pickle").dumps(payload),
            )
            raw = a._sock  # write raw bytes, bypassing send()

            def dribble():
                for i in range(0, len(frame), 7):
                    raw.sendall(frame[i : i + 7])
                    time.sleep(0.001)

            t = threading.Thread(target=dribble)
            t.start()
            kind, got = b.recv(timeout=10)
            t.join()
            assert kind == KIND_REPLY and got == payload
        finally:
            a.close(), b.close()

    def test_recv_timeout_is_typed(self):
        a, b = _pair()
        try:
            with pytest.raises(RpcTimeout):
                b.recv(timeout=0.05)
            # ... and the connection is still usable afterwards
            a.send(KIND_HEARTBEAT, dict(ok=1))
            assert b.recv(timeout=5)[1] == dict(ok=1)
        finally:
            a.close(), b.close()


# ----------------------------------------------------------------------
# frame protocol: adversarial
# ----------------------------------------------------------------------


def _raw_frame(payload: bytes, *, magic=MAGIC, version=VERSION,
               kind=KIND_REPLY, plen=None, crc=None) -> bytes:
    head = struct.pack("<2sBBI", magic, version, kind,
                       len(payload) if plen is None else plen)
    if crc is None:
        crc = zlib.crc32(payload, zlib.crc32(head)) & 0xFFFFFFFF
    return head + struct.pack("<I", crc) + payload


class TestFrameAdversarial:
    def _recv_raw(self, raw: bytes):
        a, b = _pair()
        try:
            a._sock.sendall(raw)
            return b.recv(timeout=5)
        finally:
            a.close(), b.close()

    def test_truncated_frame_then_eof_is_closed(self):
        """A peer dying mid-frame yields RpcClosed naming the torn tail,
        never a hang or a bare parse exception."""
        import pickle

        frame = encode_frame(KIND_REPLY, pickle.dumps({"x": 1}))
        a, b = _pair()
        try:
            a._sock.sendall(frame[: HEADER_SIZE + 3])
            a._sock.close()
            with pytest.raises(RpcClosed, match="mid-frame"):
                b.recv(timeout=5)
        finally:
            a.close(), b.close()

    def test_bad_crc(self):
        import pickle

        payload = pickle.dumps({"x": 1})
        frame = bytearray(_raw_frame(payload))
        frame[-1] ^= 0x40  # flip a payload byte under an intact crc
        with pytest.raises(FrameError, match="crc"):
            self._recv_raw(bytes(frame))

    def test_wrong_version_tag(self):
        with pytest.raises(FrameError, match="version"):
            self._recv_raw(_raw_frame(b"x", version=VERSION + 1))

    def test_bad_magic(self):
        with pytest.raises(FrameError, match="magic"):
            self._recv_raw(_raw_frame(b"x", magic=b"ZZ"))

    def test_unknown_kind(self):
        with pytest.raises(FrameError, match="kind"):
            self._recv_raw(_raw_frame(b"x", kind=99))

    def test_undecodable_payload(self):
        with pytest.raises(FrameError, match="undecodable"):
            self._recv_raw(_raw_frame(b"\xff not a pickle \x00"))

    def test_max_size_violation_on_receive(self):
        """An adversarial length field must be rejected from the HEADER,
        before any buffering toward OOM."""
        a, b = _pair(max_frame=1024)
        try:
            a._sock.sendall(_raw_frame(b"x", plen=1 << 30))
            with pytest.raises(FrameError, match="clamp"):
                b.recv(timeout=5)
        finally:
            a.close(), b.close()

    def test_max_size_violation_on_send(self):
        a, b = _pair(max_frame=1024)
        try:
            with pytest.raises(FrameError, match="clamp"):
                a.send(KIND_REPLY, dict(blob=b"\x00" * 4096))
        finally:
            a.close(), b.close()

    def test_poisoned_stream_refuses_further_use(self):
        """There is no resync for a corrupted length-prefixed stream:
        after one FrameError every later recv/send refuses — the caller
        must tear down and reconnect (contained, never wedged)."""
        a, b = _pair()
        try:
            a._sock.sendall(_raw_frame(b"x", magic=b"ZZ"))
            with pytest.raises(FrameError):
                b.recv(timeout=5)
            with pytest.raises(FrameError, match="poisoned"):
                b.recv(timeout=5)
            with pytest.raises(FrameError, match="poisoned"):
                b.send(KIND_HEARTBEAT, {})
            assert b.poll_frames() == []
        finally:
            a.close(), b.close()

    def test_default_clamp_matches_tuning_default(self):
        assert DEFAULT_MAX_FRAME == FleetTuning().max_frame_bytes


# ----------------------------------------------------------------------
# FleetTuning: one dataclass for every knob
# ----------------------------------------------------------------------


class TestFleetTuning:
    def test_defaults_mirror_module_constants(self):
        from ggrs_tpu.fleet.supervisor import (
            READMIT_BACKOFF_TICKS,
            READMIT_MAX_ATTEMPTS,
        )
        from ggrs_tpu.parallel.host_bank import EVICT_MAX_PER_TICK

        t = FleetTuning()
        assert t.readmit_backoff_ticks == READMIT_BACKOFF_TICKS
        assert t.readmit_max_attempts == READMIT_MAX_ATTEMPTS
        assert t.evict_max_per_tick == EVICT_MAX_PER_TICK

    def test_env_overrides(self):
        t = FleetTuning.from_env({
            "GGRS_FLEET_HEARTBEAT_DEADLINE_S": "7.5",
            "GGRS_FLEET_RESTART_MAX": "9",
            "GGRS_FLEET_MAX_FRAME_BYTES": "1048576",
            "UNRELATED": "ignored",
        })
        assert t.heartbeat_deadline_s == 7.5
        assert t.restart_max == 9
        assert t.max_frame_bytes == 1 << 20
        # kwargs beat env
        t2 = FleetTuning.from_env(
            {"GGRS_FLEET_RESTART_MAX": "9"}, restart_max=2
        )
        assert t2.restart_max == 2

    def test_malformed_env_value_is_loud(self):
        with pytest.raises(ValueError, match="GGRS_FLEET_RESTART_MAX"):
            FleetTuning.from_env({"GGRS_FLEET_RESTART_MAX": "many"})

    def test_negative_values_rejected(self):
        with pytest.raises(ValueError, match="rpc_timeout_s"):
            FleetTuning(rpc_timeout_s=-1)

    def test_artifact_json_round_trip(self):
        """Chaos artifacts record the knobs a run ran with; the dict must
        survive JSON and rebuild an equal FleetTuning."""
        t = FleetTuning(heartbeat_interval_s=0.125, restart_max=5)
        assert FleetTuning.from_dict(json.loads(json.dumps(t.as_dict()))) == t

    def test_link_env_overrides(self):
        """The §25 link knobs ride the same GGRS_FLEET_* env plumbing —
        including the first STRING-typed knob (the auth token, passed
        through verbatim, never float-cast)."""
        t = FleetTuning.from_env({
            "GGRS_FLEET_LINK_AUTH_TOKEN": "sekrit",
            "GGRS_FLEET_LINK_RECONNECT_WINDOW_S": "1.25",
            "GGRS_FLEET_LINK_BACKOFF_S": "0.02",
            "GGRS_FLEET_LINK_KEEPALIVE_S": "11",
            "GGRS_FLEET_LINK_RETAIN_FRAMES": "512",
        })
        assert t.link_auth_token == "sekrit"
        assert t.link_reconnect_window_s == 1.25
        assert t.link_backoff_s == 0.02
        assert t.link_keepalive_s == 11.0
        assert t.link_retain_frames == 512

    def test_link_malformed_env_is_loud(self):
        with pytest.raises(ValueError,
                           match="GGRS_FLEET_LINK_RECONNECT_WINDOW_S"):
            FleetTuning.from_env(
                {"GGRS_FLEET_LINK_RECONNECT_WINDOW_S": "soon"}
            )

    def test_link_token_must_be_string(self):
        with pytest.raises(ValueError, match="link_auth_token"):
            FleetTuning(link_auth_token=123)

    def test_link_knobs_round_trip(self):
        t = FleetTuning(link_auth_token="tok", link_reconnect_window_s=0.5,
                        link_retain_frames=64, failover_retry_s=1.0)
        assert FleetTuning.from_dict(json.loads(json.dumps(t.as_dict()))) == t

    def test_supervisor_uses_its_tuning(self, tmp_path):
        """The readmission backoff now flows from the instance's tuning,
        not the module constants."""
        t = FleetTuning(readmit_backoff_ticks=2, readmit_max_attempts=1)
        sup = ShardSupervisor(("a",), capacity=0, seed=5, tuning=t,
                              metrics=Registry())
        clock = [0]
        bf, sf, _, _ = _mk_match(clock, 41, "m0")
        assert sup.admit("m0", bf, sf) is None
        for _ in range(16):
            sup.advance_all()
            if sup.lost_matches():
                break
        assert "m0" in sup.lost_matches()

    def test_evict_clamp_flows_into_the_pool(self):
        t = FleetTuning(evict_max_per_tick=1)
        shard = PoolShard("x", capacity=2, metrics=Registry(), tuning=t)
        assert shard.pool._evict_max_per_tick == 1


# ----------------------------------------------------------------------
# journal write-failure hardening
# ----------------------------------------------------------------------


def _write_frames(journal, start, count, isize=2, players=2):
    recs = []
    for f in range(start, start + count):
        blob = b"".join(
            (f * 10 + p).to_bytes(isize, "little") for p in range(players)
        )
        recs.append((bytes(players), blob))
    journal.append_frames(start, recs)


def _mk_match(clock, seed, name):
    """One fleet-admittable 2-peer match against an external peer."""
    from ggrs_tpu.chaos import RecordingSocket

    net = InMemoryNetwork(latency_ticks=1, seed=seed)
    host_sock = RecordingSocket(net.socket(f"H-{name}"))
    bf = lambda: two_peer_builder(clock, seed, 0, f"P-{name}")  # noqa: E731
    peer = two_peer_builder(
        clock, seed + 1, 1, f"H-{name}", other_handle=0
    ).start_p2p_session(net.socket(f"P-{name}"))
    return bf, (lambda: host_sock), peer, net


class TestJournalWriteFailure:
    def test_enospc_on_append_degrades_loudly_and_stops_writing(
        self, tmp_path
    ):
        reg = Registry()
        j = MatchJournal(tmp_path / "j.ggjl", 2, 2, metrics=reg)
        _write_frames(j, 0, 8)
        j.flush(fsync=True)
        size_before = (tmp_path / "j.ggjl").stat().st_size

        def fault(stage):
            if stage == "write":
                raise OSError(errno.ENOSPC, "no space left on device")

        j._inject_fault = fault
        _write_frames(j, 8, 4)
        assert j.failed is not None and "append" in j.failed
        assert reg.value("ggrs_journal_write_failures_total") == 1
        # degraded, not dead: further appends drop silently, exactly once
        # counted, and the file keeps its intact prefix
        j._inject_fault = None
        _write_frames(j, 12, 4)
        assert reg.value("ggrs_journal_write_failures_total") == 1
        assert (tmp_path / "j.ggjl").stat().st_size == size_before
        j.close()  # must not raise
        parsed = read_journal(tmp_path / "j.ggjl")
        assert [f for f, _, _ in parsed["frames"]] == list(range(8))

    def test_eio_on_fsync_degrades(self, tmp_path):
        j = MatchJournal(tmp_path / "f.ggjl", 2, 2, metrics=Registry())
        _write_frames(j, 0, 4)

        def fault(stage):
            if stage == "fsync":
                raise OSError(errno.EIO, "I/O error")

        j._inject_fault = fault
        j.flush(fsync=True)
        assert j.failed is not None and "fsync" in j.failed
        j.close()

    def test_torn_final_record_then_reopen(self, tmp_path):
        """The acceptance path: a write failure tears the final record
        mid-bytes; readers recover exactly the intact prefix, resume
        works from it, and a NEW incarnation reopens at a fresh path."""
        path = tmp_path / "torn.ggjl"
        j = MatchJournal(path, 2, 2, tail_window=64)
        _write_frames(j, 0, 8)
        j.append_checkpoint(4, {"s": 4})
        j.flush(fsync=True)
        real_write = j._f.write

        def torn_write(data):
            real_write(data[:3])  # a few bytes land, then the disk dies
            raise OSError(errno.ENOSPC, "no space left on device")

        j._f.write = torn_write
        _write_frames(j, 8, 1)
        assert j.failed is not None
        j._f.write = real_write
        j.close()
        parsed = read_journal(path)
        assert parsed["truncated"]
        assert [f for f, _, _ in parsed["frames"]] == list(range(8))
        res = resume_from_file(path, local_handles=[0],
                               endpoints=[([1], True)])
        assert res["durable_tip"] == 7
        assert res["checkpoint"][0] == 4
        # the reopen: a fresh incarnation at a fresh path serves on
        j2 = MatchJournal(tmp_path / "torn.001.ggjl", 2, 2, tail_window=64)
        _write_frames(j2, 0, 4)
        j2.close()
        assert not read_journal(tmp_path / "torn.001.ggjl")["truncated"]

    def test_in_memory_tail_keeps_tracking_after_disk_failure(
        self, tmp_path
    ):
        """Live eviction recovery reads the in-memory tail, which needs
        no disk: a degraded journal keeps the tail current even though
        the file froze."""
        j = MatchJournal(tmp_path / "t.ggjl", 2, 2, tail_window=8)
        _write_frames(j, 0, 4)
        j._inject_fault = lambda stage: (_ for _ in ()).throw(
            OSError(errno.ENOSPC, "full")
        )
        _write_frames(j, 4, 4)
        assert j.failed is not None
        assert [f for f, _, _ in j.tail] == list(range(8))
        assert j.next_frame == 8

    def test_shard_degrades_loudly_and_keeps_serving(self, tmp_path):
        """A shard whose match journal fails keeps the match ALIVE
        (degraded) — fault counter + health flag, never a dropped tick."""
        clock = [0]
        reg = Registry()
        shard = PoolShard("x", capacity=4, metrics=reg, checkpoint_every=4)
        bf, sf, peer, net = _mk_match(clock, 71, "m0")
        journal = MatchJournal(tmp_path / "m0.ggjl", 2, 2, metrics=reg)
        shard.admit("m0", bf(), sf(), journal=journal)
        game, peer_game = CrcGame(), CrcGame()

        def drive(n):
            for i in range(n):
                clock[0] += 16
                try:
                    peer.add_local_input(1, i % 7)
                    peer_game.fulfill(peer.advance_frame())
                except (NotSynchronized, PredictionThreshold):
                    pass
                shard.add_local_input("m0", 0, i % 5)
                game.fulfill(shard.advance_all().get("m0", []))
                net.tick()

        drive(16)
        assert shard.journal_failed_matches() == []
        journal._inject_fault = lambda stage: (_ for _ in ()).throw(
            OSError(errno.ENOSPC, "full")
        )
        before = shard.current_frame("m0")
        drive(16)
        assert shard.journal_failed_matches() == ["m0"]
        assert shard.healthz()["journal_failed"] == 1
        assert shard.healthz()["ok"] is True  # degraded, not dead
        assert reg.value(
            "ggrs_shard_journal_failures_total", shard="x"
        ) == 1
        assert shard.current_frame("m0") > before  # still serving

    def test_supervisor_marks_match_journal_less_for_failover(
        self, tmp_path
    ):
        """The fleet contract: after a journal write failure the match
        serves on, but failover treats it as journal-less — resuming
        from the stale durable tip would silently desync the peers, so
        a later crash loses it LOUDLY instead."""
        clock = [0]
        reg = Registry()
        sup = ShardSupervisor(("a", "b"), capacity=4, seed=2, metrics=reg,
                              journal_dir=tmp_path, checkpoint_every=4)
        bf, sf, peer, net = _mk_match(clock, 81, "m0")
        sup.admit("m0", bf, sf, state_template=0, shard="a")
        game, peer_game = CrcGame(), CrcGame()

        def drive(n):
            for i in range(n):
                clock[0] += 16
                try:
                    peer.add_local_input(1, i % 7)
                    peer_game.fulfill(peer.advance_frame())
                except (NotSynchronized, PredictionThreshold):
                    pass
                sup.add_local_input("m0", 0, i % 5)
                out = sup.advance_all()
                if "m0" in out:
                    game.fulfill(out["m0"])
                net.tick()

        drive(16)
        journal = sup.shards["a"]._journals["m0"]
        journal._inject_fault = lambda stage: (_ for _ in ()).throw(
            OSError(errno.EIO, "I/O error")
        )
        drive(8)
        record = sup._records["m0"]
        assert record.journal_failed is True
        assert reg.value("ggrs_fleet_journal_failures_total") == 1
        # crash the shard: the journal-less match is lost loudly, with
        # the write failure named — never a silent desync
        sup.kill("a")
        drive(2)
        assert "m0" in sup.lost_matches()
        assert "journal" in sup.lost_matches()["m0"]
        # a migration would have re-incarnated the journal and cleared
        # the flag — pinned by the _adopt_on reset
        assert record.location is None


# ----------------------------------------------------------------------
# §25 TCP fleet link: adversarial handshakes + fd hygiene + resume seam
# ----------------------------------------------------------------------

import os as _os

from ggrs_tpu.fleet.transport import (
    AUTH,
    CHALLENGE,
    HS_MAGIC_AUTH,
    HS_OK_FRESH,
    HS_REFUSED_AUTH,
    HS_REFUSED_FENCE,
    HS_REFUSED_VERSION,
    HS_VERSION,
    HandshakeError,
    ShardLink,
    VERDICT,
    client_handshake,
    pack_auth,
)

LINK_TUNING = FleetTuning(
    link_auth_token="test-token",
    link_reconnect_window_s=1.0,
    link_handshake_timeout_s=0.4,
    link_backoff_s=0.01,
)


def _count_fds() -> int:
    return len(_os.listdir("/proc/self/fd"))


def _mk_link(**kw):
    return ShardLink("s0", LINK_TUNING, metrics=Registry(), **kw)


def _dial_raw(link):
    s = socket.create_connection(link.address, timeout=2.0)
    s.settimeout(2.0)
    return s


def _pump_until(link, pred, timeout=3.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ev = link.pump()
        if pred(ev, link):
            return ev
        time.sleep(0.005)
    raise AssertionError("link pump never reached the condition")


def _read_challenge(sock):
    raw = b""
    while len(raw) < CHALLENGE.size:
        raw += sock.recv(CHALLENGE.size - len(raw))
    return CHALLENGE.unpack(raw)


def _read_verdict(sock):
    raw = b""
    while len(raw) < VERDICT.size:
        chunk = sock.recv(VERDICT.size - len(raw))
        if not chunk:
            raise AssertionError("no verdict before close")
        raw += chunk
    return VERDICT.unpack(raw)


class TestTcpHandshakeAdversarial:
    def test_wrong_token_refused(self):
        link = _mk_link()
        try:
            s = _dial_raw(link)
            _pump_until(link, lambda ev, lk: lk.info()["pending"] == 1)
            _, _, _, nonce = _read_challenge(s)
            s.sendall(pack_auth("WRONG-token", nonce, epoch=0, cursor=0,
                                shard_id="s0", resume=False))
            _pump_until(link, lambda ev, lk: lk.refusals.get("auth"))
            _, _, code, _, _ = _read_verdict(s)
            assert code == HS_REFUSED_AUTH
            assert link.link_state == "connecting"  # nothing granted
            s.close()
        finally:
            link.close()

    def test_replayed_handshake_refused(self):
        """A captured auth record is worthless on a new connection: the
        MAC is bound to the server's per-connection nonce."""
        link = _mk_link()
        try:
            s1 = _dial_raw(link)
            _pump_until(link, lambda ev, lk: lk.info()["pending"] == 1)
            _, _, _, nonce1 = _read_challenge(s1)
            record = pack_auth("test-token", nonce1, epoch=0, cursor=0,
                               shard_id="s0", resume=False)
            s1.close()  # attacker captured `record`; session never used
            _pump_until(link, lambda ev, lk: lk.info()["pending"] == 0)
            s2 = _dial_raw(link)
            _pump_until(link, lambda ev, lk: lk.info()["pending"] == 1)
            _read_challenge(s2)  # fresh nonce we ignore, like a replayer
            s2.sendall(record)
            _pump_until(link, lambda ev, lk: lk.refusals.get("auth"))
            _, _, code, _, _ = _read_verdict(s2)
            assert code == HS_REFUSED_AUTH
            s2.close()
        finally:
            link.close()

    def test_stale_epoch_fenced(self):
        """A resume presenting an old epoch is refused with FENCE before
        any link state moves — the split-brain rule at the wire."""
        link = _mk_link()
        try:
            link.mint_epoch()  # epoch 1: granted to a past incarnation
            link.mint_epoch()  # epoch 2: current
            s = _dial_raw(link)
            _pump_until(link, lambda ev, lk: lk.info()["pending"] == 1)
            _, _, _, nonce = _read_challenge(s)
            s.sendall(pack_auth("test-token", nonce, epoch=1, cursor=0,
                                shard_id="s0", resume=True))
            _pump_until(link, lambda ev, lk: lk.refusals.get("fence"))
            _, _, code, current, _ = _read_verdict(s)
            assert code == HS_REFUSED_FENCE
            assert current == 2  # the verdict names the current mint
            s.close()
        finally:
            link.close()

    def test_wrong_version_refused(self):
        link = _mk_link()
        try:
            s = _dial_raw(link)
            _pump_until(link, lambda ev, lk: lk.info()["pending"] == 1)
            _, _, _, nonce = _read_challenge(s)
            rec = bytearray(pack_auth("test-token", nonce, epoch=0,
                                      cursor=0, shard_id="s0",
                                      resume=False))
            rec[2] = 99  # version byte (MAC now stale too, but version
            s.sendall(bytes(rec))  # is judged first)
            _pump_until(link, lambda ev, lk: lk.refusals.get("version"))
            _, _, code, _, _ = _read_verdict(s)
            assert code == HS_REFUSED_VERSION
            s.close()
        finally:
            link.close()

    def test_truncated_auth_counted_not_wedged(self):
        link = _mk_link()
        try:
            s = _dial_raw(link)
            _pump_until(link, lambda ev, lk: lk.info()["pending"] == 1)
            _read_challenge(s)
            s.sendall(HS_MAGIC_AUTH + b"\x01")  # 3 of 68 bytes, then EOF
            s.close()
            _pump_until(link, lambda ev, lk: lk.refusals.get("eof"))
            assert link.info()["pending"] == 0
        finally:
            link.close()

    def test_slowloris_dribble_times_out(self):
        link = _mk_link()
        try:
            s = _dial_raw(link)
            _pump_until(link, lambda ev, lk: lk.info()["pending"] == 1)
            _read_challenge(s)
            s.sendall(HS_MAGIC_AUTH)  # valid magic, then... nothing
            # the per-connection deadline (0.4s) reaps it; the pump
            # (the supervisor tick loop) never blocks on the dribbler
            t0 = time.monotonic()
            _pump_until(link, lambda ev, lk: lk.refusals.get("timeout"))
            assert time.monotonic() - t0 < 3.0
            assert link.info()["pending"] == 0
            s.close()
        finally:
            link.close()

    def test_garbage_before_magic_dropped_early(self):
        link = _mk_link()
        try:
            s = _dial_raw(link)
            _pump_until(link, lambda ev, lk: lk.info()["pending"] == 1)
            _read_challenge(s)
            s.sendall(b"GET / HTTP/1.1\r\n\r\n")  # a scanner, basically
            # dropped on the FIRST two bytes — not held to the deadline
            _pump_until(link, lambda ev, lk: lk.refusals.get("garbage"),
                        timeout=0.3)
            assert link.info()["pending"] == 0
            s.close()
        finally:
            link.close()

    def test_fresh_handshake_grants_epoch(self):
        link = _mk_link()
        result = {}
        try:
            link.mint_epoch()

            def dial():
                s = socket.create_connection(link.address, timeout=2.0)
                try:
                    result["verdict"] = client_handshake(
                        s, token="test-token", shard_id="s0", epoch=0,
                        cursor=0, resume=False, timeout=2.0)
                finally:
                    s.close()

            t = threading.Thread(target=dial)
            t.start()
            ev = _pump_until(link, lambda ev, lk: ev is not None)
            t.join(timeout=2.0)
            assert ev[0] == "fresh" and ev[1] is not None
            ev[1].close()
            code, granted, cursor = result["verdict"]
            assert code == HS_OK_FRESH and granted == link.epoch
            assert cursor == 0
        finally:
            link.close()


class TestHandshakeFdHygiene:
    """PR 8 rule, extended to the TCP link: every handshake error path
    releases its fd — pinned by exact /proc/self/fd counts."""

    def test_refused_and_garbage_paths_leak_nothing(self):
        base = _count_fds()
        link = _mk_link()
        try:
            for payload in (b"junkjunkjunk", HS_MAGIC_AUTH + b"\x00"):
                s = _dial_raw(link)
                _pump_until(link, lambda ev, lk: lk.info()["pending"] == 1)
                _read_challenge(s)
                s.sendall(payload)
                if payload.startswith(HS_MAGIC_AUTH):
                    s.close()  # truncated-then-EOF variant
                    _pump_until(link,
                                lambda ev, lk: lk.refusals.get("eof"))
                else:
                    _pump_until(link,
                                lambda ev, lk: lk.refusals.get("garbage"))
                    s.close()
            # wrong token (a verdict IS owed on this path)
            s = _dial_raw(link)
            _pump_until(link, lambda ev, lk: lk.info()["pending"] == 1)
            _, _, _, nonce = _read_challenge(s)
            s.sendall(pack_auth("bad", nonce, epoch=0, cursor=0,
                                shard_id="s0", resume=False))
            _pump_until(link, lambda ev, lk: lk.refusals.get("auth"))
            s.close()
            assert link.info()["pending"] == 0
        finally:
            link.close()
        assert _count_fds() == base, "handshake error path leaked an fd"

    def test_timeout_mid_handshake_leaks_nothing(self):
        base = _count_fds()
        link = _mk_link()
        try:
            s = _dial_raw(link)
            _pump_until(link, lambda ev, lk: lk.info()["pending"] == 1)
            _read_challenge(s)  # then stall: never send the auth record
            _pump_until(link, lambda ev, lk: lk.refusals.get("timeout"))
            s.close()
        finally:
            link.close()
        assert _count_fds() == base

    def test_client_refused_version_leaks_nothing(self):
        base = _count_fds()
        with socket.socket() as srv:
            srv.bind(("127.0.0.1", 0))
            srv.listen(1)
            addr = srv.getsockname()[:2]

            def server():
                c, _ = srv.accept()
                with c:
                    # advertise a version this client does not speak
                    c.sendall(CHALLENGE.pack(b"GC", 99, 0, b"\x00" * 16))
                    c.recv(256)

            t = threading.Thread(target=server)
            t.start()
            from ggrs_tpu.fleet.transport import RunnerLink

            rl = RunnerLink(addr[0], addr[1], token="x", shard_id="s0")
            with pytest.raises(HandshakeError):
                rl.dial_fresh(timeout=1.0)
            t.join(timeout=2.0)
        assert _count_fds() == base


class TestRpcResumeSeam:
    """The rpc.py seam the link rides: sequence numbers, the retain
    ring, reattach+replay, and call-id correlation."""

    def test_seq_numbers_and_resume_replay(self):
        a, b = _pair()
        a.enable_retain(64)
        b.enable_retain(64)
        try:
            for i in range(3):
                a.send(KIND_CALL, dict(op="tick", i=i))
            assert a.tx_seq == 3
            kind, obj = b.recv(timeout=2)
            assert obj["i"] == 0 and b.rx_seq == 1
            # sever: both sides move to a fresh pair; b's unread frames
            # (i=1, i=2) are lost in flight and must be replayed
            na, nb = socket.socketpair()
            a.reattach(na)
            b.reattach(nb)
            assert a.can_resume(b.rx_seq)
            replayed = a.replay_from(b.rx_seq)
            assert replayed == 2
            for want in (1, 2):
                kind, obj = b.recv(timeout=2)
                assert obj["i"] == want
            assert b.rx_seq == 3
        finally:
            a.close(), b.close()

    def test_can_resume_respects_ring_floor(self):
        a, b = _pair()
        a.enable_retain(2)
        try:
            for i in range(5):
                a.send(KIND_HEARTBEAT, dict(i=i))
            assert a.can_resume(5)          # nothing to replay
            assert a.can_resume(4)          # frame 5 still retained
            assert a.can_resume(3)          # frames 4,5 retained
            assert not a.can_resume(2)      # frame 3 fell off the ring
            assert not a.can_resume(0)
            assert not a.can_resume(9)      # peer claims frames we
        finally:                            # never sent: nonsense
            a.close(), b.close()

    def test_replay_past_ring_raises(self):
        a, b = _pair()
        a.enable_retain(2)
        try:
            for i in range(5):
                a.send(KIND_HEARTBEAT, dict(i=i))
            with pytest.raises(RpcClosed):
                a.replay_from(1)
        finally:
            a.close(), b.close()

    def test_reattach_refuses_poisoned_stream(self):
        a, b = _pair()
        try:
            b._sock.sendall(b"\x00" * HEADER_SIZE)
            with pytest.raises(FrameError):
                a.recv(timeout=2)
            na, _nb = socket.socketpair()
            with pytest.raises(FrameError):
                a.reattach(na)
            na.close(), _nb.close()
        finally:
            a.close(), b.close()

    def test_call_drops_stale_replies(self):
        """A reply replayed from before a reconnect must not be taken
        as the answer to the CURRENT call: call ids correlate."""
        a, b = _pair()
        try:
            def runner():
                kind, msg = b.recv(timeout=5)
                cid = msg["_cid"]
                # a stale reply (old cid), then the real one
                b.send(KIND_REPLY, {"_cid": cid - 1 or 999, "_r": "old"})
                b.send(KIND_REPLY, {"_cid": cid, "_r": "fresh"})

            t = threading.Thread(target=runner)
            t.start()
            assert a.call("op", timeout=5) == "fresh"
            t.join(timeout=2)
            assert a.stale_replies == 1
        finally:
            a.close(), b.close()

    def test_plain_replies_still_work(self):
        """Back-compat: a reply without the _cid envelope (pre-link
        servers, tests with bare fakes) is returned as-is."""
        a, b = _pair()
        try:
            def runner():
                b.recv(timeout=5)
                b.send(KIND_REPLY, dict(plain=True))

            t = threading.Thread(target=runner)
            t.start()
            assert a.call("op", timeout=5) == dict(plain=True)
            t.join(timeout=2)
        finally:
            a.close(), b.close()
