"""Fleet transport + tuning + journal-hardening tests (DESIGN.md §17).

Adversarial coverage of the supervisor↔runner frame protocol: truncated
frames, bad crc, wrong version tag, max-size violations, and interleaved
partial reads each yield a TYPED error (never a wedged parser), and a
poisoned stream refuses further traffic instead of resyncing into
garbage.  Plus the ``FleetTuning`` consolidation satellite (env
overrides, artifact round trip) and the journal write-failure hardening
satellite (ENOSPC/EIO degrade the shard loudly; the
torn-final-record-then-reopen path recovers the intact prefix).
"""

from __future__ import annotations

import errno
import json
import socket
import struct
import threading
import time
import zlib

import pytest

from ggrs_tpu.broadcast.journal import (
    MatchJournal,
    read_journal,
    resume_from_file,
)
from ggrs_tpu.chaos import CrcGame, InMemoryNetwork, two_peer_builder
from ggrs_tpu.core.errors import NotSynchronized, PredictionThreshold
from ggrs_tpu.fleet import FleetTuning, PoolShard, ShardSupervisor
from ggrs_tpu.fleet.rpc import (
    DEFAULT_MAX_FRAME,
    FrameError,
    HEADER_SIZE,
    KIND_CALL,
    KIND_ERR,
    KIND_HEARTBEAT,
    KIND_REPLY,
    MAGIC,
    RpcClosed,
    RpcConn,
    RpcRemoteError,
    RpcTimeout,
    VERSION,
    encode_frame,
)
from ggrs_tpu.obs import Registry


def _pair(**kw):
    a, b = socket.socketpair()
    return RpcConn(a, **kw), RpcConn(b, **kw)


# ----------------------------------------------------------------------
# frame protocol: the happy path
# ----------------------------------------------------------------------


class TestFrameRoundTrip:
    def test_objects_round_trip(self):
        a, b = _pair()
        try:
            for kind, obj in (
                (KIND_CALL, dict(op="tick", inputs=[("m0", 0, 7)])),
                (KIND_REPLY, dict(frames={"m0": 31}, blob=b"\x00" * 4096)),
                (KIND_HEARTBEAT, dict(ticks=12)),
            ):
                a.send(kind, obj)
                got_kind, got = b.recv(timeout=5)
                assert got_kind == kind and got == obj
        finally:
            a.close(), b.close()

    def test_call_skips_interleaved_heartbeats(self):
        a, b = _pair()
        try:
            def runner():
                kind, msg = b.recv(timeout=5)
                assert kind == KIND_CALL and msg["op"] == "ping"
                b.send(KIND_HEARTBEAT, dict(ticks=1))
                b.send(KIND_HEARTBEAT, dict(ticks=2))
                b.send(KIND_REPLY, dict(pong=True))

            t = threading.Thread(target=runner)
            t.start()
            before = a.last_frame_at
            assert a.call("ping", timeout=5) == dict(pong=True)
            t.join()
            assert a.last_frame_at >= before  # heartbeats refreshed it
        finally:
            a.close(), b.close()

    def test_remote_error_frame(self):
        a, b = _pair()
        try:
            def runner():
                b.recv(timeout=5)
                b.send(KIND_ERR, dict(type="InvalidRequest",
                                      msg="nope", traceback="tb"))

            t = threading.Thread(target=runner)
            t.start()
            with pytest.raises(RpcRemoteError) as exc:
                a.call("admit", timeout=5)
            t.join()
            assert exc.value.type_name == "InvalidRequest"
        finally:
            a.close(), b.close()

    def test_interleaved_partial_reads_on_slow_socket(self):
        """Frames dribbled a few bytes at a time (slow peer, fragmented
        stream) parse intact — the buffer survives arbitrary chunking."""
        a, b = _pair()
        try:
            payload = dict(blob=bytes(range(256)) * 64, n=7)
            frame = encode_frame(
                KIND_REPLY,
                __import__("pickle").dumps(payload),
            )
            raw = a._sock  # write raw bytes, bypassing send()

            def dribble():
                for i in range(0, len(frame), 7):
                    raw.sendall(frame[i : i + 7])
                    time.sleep(0.001)

            t = threading.Thread(target=dribble)
            t.start()
            kind, got = b.recv(timeout=10)
            t.join()
            assert kind == KIND_REPLY and got == payload
        finally:
            a.close(), b.close()

    def test_recv_timeout_is_typed(self):
        a, b = _pair()
        try:
            with pytest.raises(RpcTimeout):
                b.recv(timeout=0.05)
            # ... and the connection is still usable afterwards
            a.send(KIND_HEARTBEAT, dict(ok=1))
            assert b.recv(timeout=5)[1] == dict(ok=1)
        finally:
            a.close(), b.close()


# ----------------------------------------------------------------------
# frame protocol: adversarial
# ----------------------------------------------------------------------


def _raw_frame(payload: bytes, *, magic=MAGIC, version=VERSION,
               kind=KIND_REPLY, plen=None, crc=None) -> bytes:
    head = struct.pack("<2sBBI", magic, version, kind,
                       len(payload) if plen is None else plen)
    if crc is None:
        crc = zlib.crc32(payload, zlib.crc32(head)) & 0xFFFFFFFF
    return head + struct.pack("<I", crc) + payload


class TestFrameAdversarial:
    def _recv_raw(self, raw: bytes):
        a, b = _pair()
        try:
            a._sock.sendall(raw)
            return b.recv(timeout=5)
        finally:
            a.close(), b.close()

    def test_truncated_frame_then_eof_is_closed(self):
        """A peer dying mid-frame yields RpcClosed naming the torn tail,
        never a hang or a bare parse exception."""
        import pickle

        frame = encode_frame(KIND_REPLY, pickle.dumps({"x": 1}))
        a, b = _pair()
        try:
            a._sock.sendall(frame[: HEADER_SIZE + 3])
            a._sock.close()
            with pytest.raises(RpcClosed, match="mid-frame"):
                b.recv(timeout=5)
        finally:
            a.close(), b.close()

    def test_bad_crc(self):
        import pickle

        payload = pickle.dumps({"x": 1})
        frame = bytearray(_raw_frame(payload))
        frame[-1] ^= 0x40  # flip a payload byte under an intact crc
        with pytest.raises(FrameError, match="crc"):
            self._recv_raw(bytes(frame))

    def test_wrong_version_tag(self):
        with pytest.raises(FrameError, match="version"):
            self._recv_raw(_raw_frame(b"x", version=VERSION + 1))

    def test_bad_magic(self):
        with pytest.raises(FrameError, match="magic"):
            self._recv_raw(_raw_frame(b"x", magic=b"ZZ"))

    def test_unknown_kind(self):
        with pytest.raises(FrameError, match="kind"):
            self._recv_raw(_raw_frame(b"x", kind=99))

    def test_undecodable_payload(self):
        with pytest.raises(FrameError, match="undecodable"):
            self._recv_raw(_raw_frame(b"\xff not a pickle \x00"))

    def test_max_size_violation_on_receive(self):
        """An adversarial length field must be rejected from the HEADER,
        before any buffering toward OOM."""
        a, b = _pair(max_frame=1024)
        try:
            a._sock.sendall(_raw_frame(b"x", plen=1 << 30))
            with pytest.raises(FrameError, match="clamp"):
                b.recv(timeout=5)
        finally:
            a.close(), b.close()

    def test_max_size_violation_on_send(self):
        a, b = _pair(max_frame=1024)
        try:
            with pytest.raises(FrameError, match="clamp"):
                a.send(KIND_REPLY, dict(blob=b"\x00" * 4096))
        finally:
            a.close(), b.close()

    def test_poisoned_stream_refuses_further_use(self):
        """There is no resync for a corrupted length-prefixed stream:
        after one FrameError every later recv/send refuses — the caller
        must tear down and reconnect (contained, never wedged)."""
        a, b = _pair()
        try:
            a._sock.sendall(_raw_frame(b"x", magic=b"ZZ"))
            with pytest.raises(FrameError):
                b.recv(timeout=5)
            with pytest.raises(FrameError, match="poisoned"):
                b.recv(timeout=5)
            with pytest.raises(FrameError, match="poisoned"):
                b.send(KIND_HEARTBEAT, {})
            assert b.poll_frames() == []
        finally:
            a.close(), b.close()

    def test_default_clamp_matches_tuning_default(self):
        assert DEFAULT_MAX_FRAME == FleetTuning().max_frame_bytes


# ----------------------------------------------------------------------
# FleetTuning: one dataclass for every knob
# ----------------------------------------------------------------------


class TestFleetTuning:
    def test_defaults_mirror_module_constants(self):
        from ggrs_tpu.fleet.supervisor import (
            READMIT_BACKOFF_TICKS,
            READMIT_MAX_ATTEMPTS,
        )
        from ggrs_tpu.parallel.host_bank import EVICT_MAX_PER_TICK

        t = FleetTuning()
        assert t.readmit_backoff_ticks == READMIT_BACKOFF_TICKS
        assert t.readmit_max_attempts == READMIT_MAX_ATTEMPTS
        assert t.evict_max_per_tick == EVICT_MAX_PER_TICK

    def test_env_overrides(self):
        t = FleetTuning.from_env({
            "GGRS_FLEET_HEARTBEAT_DEADLINE_S": "7.5",
            "GGRS_FLEET_RESTART_MAX": "9",
            "GGRS_FLEET_MAX_FRAME_BYTES": "1048576",
            "UNRELATED": "ignored",
        })
        assert t.heartbeat_deadline_s == 7.5
        assert t.restart_max == 9
        assert t.max_frame_bytes == 1 << 20
        # kwargs beat env
        t2 = FleetTuning.from_env(
            {"GGRS_FLEET_RESTART_MAX": "9"}, restart_max=2
        )
        assert t2.restart_max == 2

    def test_malformed_env_value_is_loud(self):
        with pytest.raises(ValueError, match="GGRS_FLEET_RESTART_MAX"):
            FleetTuning.from_env({"GGRS_FLEET_RESTART_MAX": "many"})

    def test_negative_values_rejected(self):
        with pytest.raises(ValueError, match="rpc_timeout_s"):
            FleetTuning(rpc_timeout_s=-1)

    def test_artifact_json_round_trip(self):
        """Chaos artifacts record the knobs a run ran with; the dict must
        survive JSON and rebuild an equal FleetTuning."""
        t = FleetTuning(heartbeat_interval_s=0.125, restart_max=5)
        assert FleetTuning.from_dict(json.loads(json.dumps(t.as_dict()))) == t

    def test_supervisor_uses_its_tuning(self, tmp_path):
        """The readmission backoff now flows from the instance's tuning,
        not the module constants."""
        t = FleetTuning(readmit_backoff_ticks=2, readmit_max_attempts=1)
        sup = ShardSupervisor(("a",), capacity=0, seed=5, tuning=t,
                              metrics=Registry())
        clock = [0]
        bf, sf, _, _ = _mk_match(clock, 41, "m0")
        assert sup.admit("m0", bf, sf) is None
        for _ in range(16):
            sup.advance_all()
            if sup.lost_matches():
                break
        assert "m0" in sup.lost_matches()

    def test_evict_clamp_flows_into_the_pool(self):
        t = FleetTuning(evict_max_per_tick=1)
        shard = PoolShard("x", capacity=2, metrics=Registry(), tuning=t)
        assert shard.pool._evict_max_per_tick == 1


# ----------------------------------------------------------------------
# journal write-failure hardening
# ----------------------------------------------------------------------


def _write_frames(journal, start, count, isize=2, players=2):
    recs = []
    for f in range(start, start + count):
        blob = b"".join(
            (f * 10 + p).to_bytes(isize, "little") for p in range(players)
        )
        recs.append((bytes(players), blob))
    journal.append_frames(start, recs)


def _mk_match(clock, seed, name):
    """One fleet-admittable 2-peer match against an external peer."""
    from ggrs_tpu.chaos import RecordingSocket

    net = InMemoryNetwork(latency_ticks=1, seed=seed)
    host_sock = RecordingSocket(net.socket(f"H-{name}"))
    bf = lambda: two_peer_builder(clock, seed, 0, f"P-{name}")  # noqa: E731
    peer = two_peer_builder(
        clock, seed + 1, 1, f"H-{name}", other_handle=0
    ).start_p2p_session(net.socket(f"P-{name}"))
    return bf, (lambda: host_sock), peer, net


class TestJournalWriteFailure:
    def test_enospc_on_append_degrades_loudly_and_stops_writing(
        self, tmp_path
    ):
        reg = Registry()
        j = MatchJournal(tmp_path / "j.ggjl", 2, 2, metrics=reg)
        _write_frames(j, 0, 8)
        j.flush(fsync=True)
        size_before = (tmp_path / "j.ggjl").stat().st_size

        def fault(stage):
            if stage == "write":
                raise OSError(errno.ENOSPC, "no space left on device")

        j._inject_fault = fault
        _write_frames(j, 8, 4)
        assert j.failed is not None and "append" in j.failed
        assert reg.value("ggrs_journal_write_failures_total") == 1
        # degraded, not dead: further appends drop silently, exactly once
        # counted, and the file keeps its intact prefix
        j._inject_fault = None
        _write_frames(j, 12, 4)
        assert reg.value("ggrs_journal_write_failures_total") == 1
        assert (tmp_path / "j.ggjl").stat().st_size == size_before
        j.close()  # must not raise
        parsed = read_journal(tmp_path / "j.ggjl")
        assert [f for f, _, _ in parsed["frames"]] == list(range(8))

    def test_eio_on_fsync_degrades(self, tmp_path):
        j = MatchJournal(tmp_path / "f.ggjl", 2, 2, metrics=Registry())
        _write_frames(j, 0, 4)

        def fault(stage):
            if stage == "fsync":
                raise OSError(errno.EIO, "I/O error")

        j._inject_fault = fault
        j.flush(fsync=True)
        assert j.failed is not None and "fsync" in j.failed
        j.close()

    def test_torn_final_record_then_reopen(self, tmp_path):
        """The acceptance path: a write failure tears the final record
        mid-bytes; readers recover exactly the intact prefix, resume
        works from it, and a NEW incarnation reopens at a fresh path."""
        path = tmp_path / "torn.ggjl"
        j = MatchJournal(path, 2, 2, tail_window=64)
        _write_frames(j, 0, 8)
        j.append_checkpoint(4, {"s": 4})
        j.flush(fsync=True)
        real_write = j._f.write

        def torn_write(data):
            real_write(data[:3])  # a few bytes land, then the disk dies
            raise OSError(errno.ENOSPC, "no space left on device")

        j._f.write = torn_write
        _write_frames(j, 8, 1)
        assert j.failed is not None
        j._f.write = real_write
        j.close()
        parsed = read_journal(path)
        assert parsed["truncated"]
        assert [f for f, _, _ in parsed["frames"]] == list(range(8))
        res = resume_from_file(path, local_handles=[0],
                               endpoints=[([1], True)])
        assert res["durable_tip"] == 7
        assert res["checkpoint"][0] == 4
        # the reopen: a fresh incarnation at a fresh path serves on
        j2 = MatchJournal(tmp_path / "torn.001.ggjl", 2, 2, tail_window=64)
        _write_frames(j2, 0, 4)
        j2.close()
        assert not read_journal(tmp_path / "torn.001.ggjl")["truncated"]

    def test_in_memory_tail_keeps_tracking_after_disk_failure(
        self, tmp_path
    ):
        """Live eviction recovery reads the in-memory tail, which needs
        no disk: a degraded journal keeps the tail current even though
        the file froze."""
        j = MatchJournal(tmp_path / "t.ggjl", 2, 2, tail_window=8)
        _write_frames(j, 0, 4)
        j._inject_fault = lambda stage: (_ for _ in ()).throw(
            OSError(errno.ENOSPC, "full")
        )
        _write_frames(j, 4, 4)
        assert j.failed is not None
        assert [f for f, _, _ in j.tail] == list(range(8))
        assert j.next_frame == 8

    def test_shard_degrades_loudly_and_keeps_serving(self, tmp_path):
        """A shard whose match journal fails keeps the match ALIVE
        (degraded) — fault counter + health flag, never a dropped tick."""
        clock = [0]
        reg = Registry()
        shard = PoolShard("x", capacity=4, metrics=reg, checkpoint_every=4)
        bf, sf, peer, net = _mk_match(clock, 71, "m0")
        journal = MatchJournal(tmp_path / "m0.ggjl", 2, 2, metrics=reg)
        shard.admit("m0", bf(), sf(), journal=journal)
        game, peer_game = CrcGame(), CrcGame()

        def drive(n):
            for i in range(n):
                clock[0] += 16
                try:
                    peer.add_local_input(1, i % 7)
                    peer_game.fulfill(peer.advance_frame())
                except (NotSynchronized, PredictionThreshold):
                    pass
                shard.add_local_input("m0", 0, i % 5)
                game.fulfill(shard.advance_all().get("m0", []))
                net.tick()

        drive(16)
        assert shard.journal_failed_matches() == []
        journal._inject_fault = lambda stage: (_ for _ in ()).throw(
            OSError(errno.ENOSPC, "full")
        )
        before = shard.current_frame("m0")
        drive(16)
        assert shard.journal_failed_matches() == ["m0"]
        assert shard.healthz()["journal_failed"] == 1
        assert shard.healthz()["ok"] is True  # degraded, not dead
        assert reg.value(
            "ggrs_shard_journal_failures_total", shard="x"
        ) == 1
        assert shard.current_frame("m0") > before  # still serving

    def test_supervisor_marks_match_journal_less_for_failover(
        self, tmp_path
    ):
        """The fleet contract: after a journal write failure the match
        serves on, but failover treats it as journal-less — resuming
        from the stale durable tip would silently desync the peers, so
        a later crash loses it LOUDLY instead."""
        clock = [0]
        reg = Registry()
        sup = ShardSupervisor(("a", "b"), capacity=4, seed=2, metrics=reg,
                              journal_dir=tmp_path, checkpoint_every=4)
        bf, sf, peer, net = _mk_match(clock, 81, "m0")
        sup.admit("m0", bf, sf, state_template=0, shard="a")
        game, peer_game = CrcGame(), CrcGame()

        def drive(n):
            for i in range(n):
                clock[0] += 16
                try:
                    peer.add_local_input(1, i % 7)
                    peer_game.fulfill(peer.advance_frame())
                except (NotSynchronized, PredictionThreshold):
                    pass
                sup.add_local_input("m0", 0, i % 5)
                out = sup.advance_all()
                if "m0" in out:
                    game.fulfill(out["m0"])
                net.tick()

        drive(16)
        journal = sup.shards["a"]._journals["m0"]
        journal._inject_fault = lambda stage: (_ for _ in ()).throw(
            OSError(errno.EIO, "I/O error")
        )
        drive(8)
        record = sup._records["m0"]
        assert record.journal_failed is True
        assert reg.value("ggrs_fleet_journal_failures_total") == 1
        # crash the shard: the journal-less match is lost loudly, with
        # the write failure named — never a silent desync
        sup.kill("a")
        drive(2)
        assert "m0" in sup.lost_matches()
        assert "journal" in sup.lost_matches()["m0"]
        # a migration would have re-incarnated the journal and cleared
        # the flag — pinned by the _adopt_on reset
        assert record.location is None
