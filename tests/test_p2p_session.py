"""P2P session integration tests over both the in-memory network and real
loopback UDP (parity with /root/reference/tests/test_p2p_session.rs)."""

import random

import pytest

from ggrs_tpu.core import (
    DesyncDetected,
    DesyncDetection,
    InvalidRequest,
    Local,
    Remote,
    Spectator,
)
from ggrs_tpu.net import InMemoryNetwork, UdpNonBlockingSocket
from ggrs_tpu.sessions import SessionBuilder

from stubs import GameStub, stub_config


def make_pair(net, desync=None, input_delay=0, clock=None):
    """Two P2P sessions connected through an in-memory network."""
    clock = clock if clock is not None else (lambda: 0)
    builders = []
    for me, other, local_handle in (("A", "B", 0), ("B", "A", 1)):
        b = (
            SessionBuilder(stub_config())
            .with_clock(clock)
            .with_rng(random.Random(hash(me) & 0xFFFF | 1))
        )
        if input_delay:
            b = b.with_input_delay(input_delay)
        if desync is not None:
            b = b.with_desync_detection_mode(desync)
        b = b.add_player(Local(), local_handle).add_player(Remote(other), 1 - local_handle)
        builders.append(b.start_p2p_session(net.socket(me)))
    return builders


def test_add_more_players():
    net = InMemoryNetwork()
    sess = (
        SessionBuilder(stub_config())
        .with_num_players(4)
        .add_player(Local(), 0)
        .add_player(Remote("R1"), 1)
        .add_player(Remote("R2"), 2)
        .add_player(Remote("R3"), 3)
        .add_player(Spectator("SPEC"), 4)
        .start_p2p_session(net.socket("me"))
    )
    assert sess.num_players == 4
    assert sess.num_spectators == 1


def test_builder_validation():
    with pytest.raises(InvalidRequest):
        SessionBuilder(stub_config()).add_player(Local(), 5)  # local handle too big
    with pytest.raises(InvalidRequest):
        SessionBuilder(stub_config()).add_player(Spectator("S"), 0)  # spec too small
    with pytest.raises(InvalidRequest):
        b = SessionBuilder(stub_config()).add_player(Local(), 0)
        b.add_player(Local(), 0)  # duplicate
    with pytest.raises(InvalidRequest):
        net = InMemoryNetwork()
        SessionBuilder(stub_config()).add_player(Local(), 0).start_p2p_session(
            net.socket("me")
        )  # not enough players


def test_disconnect_player():
    net = InMemoryNetwork()
    sess = (
        SessionBuilder(stub_config())
        .add_player(Local(), 0)
        .add_player(Remote("R"), 1)
        .add_player(Spectator("S"), 2)
        .start_p2p_session(net.socket("me"))
    )
    with pytest.raises(InvalidRequest):
        sess.disconnect_player(5)  # invalid handle
    with pytest.raises(InvalidRequest):
        sess.disconnect_player(0)  # local players cannot be disconnected
    sess.disconnect_player(1)
    with pytest.raises(InvalidRequest):
        sess.disconnect_player(1)  # already disconnected
    sess.disconnect_player(2)  # spectators are fine


def test_advance_frame_p2p_sessions():
    net = InMemoryNetwork()
    sess1, sess2 = make_pair(net)

    for _ in range(50):
        sess1.poll_remote_clients()
        sess2.poll_remote_clients()

    stub1, stub2 = GameStub(), GameStub()
    for i in range(10):
        sess1.poll_remote_clients()
        sess2.poll_remote_clients()

        sess1.add_local_input(0, i)
        stub1.handle_requests(sess1.advance_frame())
        sess2.add_local_input(1, i)
        stub2.handle_requests(sess2.advance_frame())

        assert stub1.gs.frame == i + 1
        assert stub2.gs.frame == i + 1


def test_p2p_sessions_state_converges():
    """Both peers end at identical state after mixed inputs."""
    net = InMemoryNetwork(seed=3, loss=0.1)
    sess1, sess2 = make_pair(net)
    stub1, stub2 = GameStub(), GameStub()

    for i in range(120):
        sess1.poll_remote_clients()
        sess2.poll_remote_clients()
        sess1.add_local_input(0, i % 3)
        stub1.handle_requests(sess1.advance_frame())
        sess2.add_local_input(1, (i * 7) % 5)
        stub2.handle_requests(sess2.advance_frame())

    # drain: let both finish pending rollbacks with all inputs confirmed
    for i in range(120, 130):
        sess1.poll_remote_clients()
        sess2.poll_remote_clients()
        sess1.add_local_input(0, 0)
        stub1.handle_requests(sess1.advance_frame())
        sess2.add_local_input(1, 0)
        stub2.handle_requests(sess2.advance_frame())

    assert stub1.gs.frame == stub2.gs.frame
    assert stub1.gs.state == stub2.gs.state


def test_desyncs_detected():
    """Deliberately corrupt one peer's state; both sides must report symmetric
    DesyncDetected at frame 200 with crossed checksums (reference:
    test_p2p_session.rs:114-213)."""
    net = InMemoryNetwork()
    desync_mode = DesyncDetection.on(100)
    sess1, sess2 = make_pair(net, desync=desync_mode)

    assert sess1.events() == []
    assert sess2.events() == []

    stub1, stub2 = GameStub(), GameStub()

    for i in range(110):
        sess1.poll_remote_clients()
        sess2.poll_remote_clients()
        sess1.add_local_input(0, i)
        sess2.add_local_input(1, i)
        stub1.handle_requests(sess1.advance_frame())
        stub2.handle_requests(sess2.advance_frame())

    assert sess1.events() == []
    assert sess2.events() == []

    for _ in range(100):
        sess1.poll_remote_clients()
        sess2.poll_remote_clients()

        # mess up state for peer 1
        stub1.gs.state = 1234

        # keep inputs steady to avoid rollbacks restoring valid state
        sess1.add_local_input(0, 0)
        sess2.add_local_input(1, 1)
        stub1.handle_requests(sess1.advance_frame())
        stub2.handle_requests(sess2.advance_frame())

    ev1 = [e for e in sess1.events() if isinstance(e, DesyncDetected)]
    ev2 = [e for e in sess2.events() if isinstance(e, DesyncDetected)]
    assert len(ev1) == 1
    assert len(ev2) == 1

    assert ev1[0].frame == 200
    assert ev1[0].addr == "B"
    assert ev1[0].local_checksum != ev1[0].remote_checksum
    assert ev2[0].frame == 200
    assert ev2[0].addr == "A"
    assert ev2[0].local_checksum != ev2[0].remote_checksum
    # crossed checksums match
    assert ev1[0].remote_checksum == ev2[0].local_checksum
    assert ev2[0].remote_checksum == ev1[0].local_checksum


def test_desyncs_and_input_delay_no_panic():
    net = InMemoryNetwork()
    sess1, sess2 = make_pair(net, desync=DesyncDetection.on(100), input_delay=5)
    stub1, stub2 = GameStub(), GameStub()

    for i in range(150):
        sess1.poll_remote_clients()
        sess2.poll_remote_clients()
        sess1.add_local_input(0, i)
        sess2.add_local_input(1, i)
        stub1.handle_requests(sess1.advance_frame())
        stub2.handle_requests(sess2.advance_frame())


def test_lockstep_mode_never_saves_or_loads():
    """max_prediction=0: only AdvanceFrame requests, only on confirmed frames
    (fork delta #3, reference: p2p_session.rs:301-307,393-397)."""
    from ggrs_tpu.core import AdvanceFrame

    net = InMemoryNetwork()
    clock = lambda: 0
    sessions = []
    for me, other, local_handle in (("A", "B", 0), ("B", "A", 1)):
        sessions.append(
            SessionBuilder(stub_config())
            .with_clock(clock)
            .with_max_prediction_window(0)
            .add_player(Local(), local_handle)
            .add_player(Remote(other), 1 - local_handle)
            .start_p2p_session(net.socket(me))
        )
    sess1, sess2 = sessions
    stub1, stub2 = GameStub(), GameStub()

    advanced1 = advanced2 = 0
    for i in range(30):
        sess1.poll_remote_clients()
        sess2.poll_remote_clients()
        sess1.add_local_input(0, i)
        r1 = sess1.advance_frame()
        sess2.add_local_input(1, i)
        r2 = sess2.advance_frame()
        assert all(isinstance(r, AdvanceFrame) for r in r1)
        assert all(isinstance(r, AdvanceFrame) for r in r2)
        advanced1 += len(r1)
        advanced2 += len(r2)
        stub1.handle_requests(r1)
        stub2.handle_requests(r2)

    # lockstep advances at most one frame behind the slowest confirmation
    assert advanced1 > 0 and advanced2 > 0
    assert abs(stub1.gs.frame - stub2.gs.frame) <= 1

    # after a drain both peers must pin the SAME frame and state exactly
    for i in range(5):
        sess1.poll_remote_clients()
        sess2.poll_remote_clients()
        sess1.add_local_input(0, 0)
        stub1.handle_requests(sess1.advance_frame())
        sess2.add_local_input(1, 0)
        stub2.handle_requests(sess2.advance_frame())
    assert stub1.gs.frame == stub2.gs.frame
    assert stub1.gs.state == stub2.gs.state
    # lockstep needs a full confirmation round-trip per frame (~2 ticks each)
    assert stub1.gs.frame >= 15


def test_confirmed_frame_asserts_when_all_players_disconnected():
    """Parity with the reference's panic: confirmed_frame() over zero
    connected players is a programming error, surfaced as an assertion
    (reference: p2p_session.rs:542-553)."""
    net = InMemoryNetwork()
    sess = (
        SessionBuilder(stub_config())
        .add_player(Local(), 0)
        .add_player(Remote("R"), 1)
        .start_p2p_session(net.socket("me"))
    )
    for status in sess.local_connect_status:
        status.disconnected = True
    with pytest.raises(AssertionError):
        sess.confirmed_frame()


def test_disconnect_before_any_frame_is_not_a_rollback():
    """A peer that vanishes before sending a single input schedules a
    'rollback to frame 0' while the session is still AT frame 0 — there is
    nothing simulated to rewind, and advance_frame must treat it as a no-op
    instead of tripping the load-frame window assert (found by the example
    trio smoke test; the reference panics on this edge,
    /root/reference/src/sync_layer.rs:229-249)."""
    net = InMemoryNetwork()
    sess = (
        SessionBuilder(stub_config())
        .add_player(Local(), 0)
        .add_player(Remote("R"), 1)
        .start_p2p_session(net.socket("me"))
    )
    sess.disconnect_player(1)  # last received frame is NULL_FRAME
    sess.add_local_input(0, 1)
    stub = GameStub()
    stub.handle_requests(sess.advance_frame())  # must not raise
    # the session keeps working with disconnect-dummy inputs for the peer
    for i in range(2, 6):
        sess.add_local_input(0, i)
        stub.handle_requests(sess.advance_frame())
    assert sess.current_frame >= 4


def test_advance_frame_p2p_sessions_real_udp():
    """Same as the in-memory test but over real loopback UDP sockets
    (reference: test_p2p_session.rs:69-110)."""
    addr1, addr2 = ("127.0.0.1", 7777), ("127.0.0.1", 8888)
    socket1 = UdpNonBlockingSocket.bind_to_port(7777)
    socket2 = UdpNonBlockingSocket.bind_to_port(8888)
    try:
        sess1 = (
            SessionBuilder(stub_config())
            .add_player(Local(), 0)
            .add_player(Remote(addr2), 1)
            .start_p2p_session(socket1)
        )
        sess2 = (
            SessionBuilder(stub_config())
            .add_player(Remote(addr1), 0)
            .add_player(Local(), 1)
            .start_p2p_session(socket2)
        )

        for _ in range(50):
            sess1.poll_remote_clients()
            sess2.poll_remote_clients()

        stub1, stub2 = GameStub(), GameStub()
        for i in range(10):
            sess1.poll_remote_clients()
            sess2.poll_remote_clients()
            sess1.add_local_input(0, i)
            stub1.handle_requests(sess1.advance_frame())
            sess2.add_local_input(1, i)
            stub2.handle_requests(sess2.advance_frame())
            assert stub1.gs.frame == i + 1
            assert stub2.gs.frame == i + 1
    finally:
        socket1.close()
        socket2.close()


def test_sparse_saving_reduces_saves_and_converges():
    """With sparse saving the session only saves at the rollback-window edge
    (the confirmed frame), trading save frequency for longer replays
    (reference: builder.rs:161-169, p2p_session.rs:666-672,819-843)."""
    net = InMemoryNetwork(seed=11)
    clock = lambda: 0
    import random as _random

    sessions = []
    for me, other, local_handle in (("A", "B", 0), ("B", "A", 1)):
        sessions.append(
            SessionBuilder(stub_config())
            .with_clock(clock)
            .with_rng(_random.Random(7 + local_handle))
            .with_sparse_saving_mode(True)
            .add_player(Local(), local_handle)
            .add_player(Remote(other), 1 - local_handle)
            .start_p2p_session(net.socket(me))
        )
    sess1, sess2 = sessions

    from ggrs_tpu.core import SaveGameState

    stub1, stub2 = GameStub(), GameStub()
    saves = [0, 0]
    n = 60
    for i in range(n):
        for idx, (sess, stub, handle) in enumerate(
            ((sess1, stub1, 0), (sess2, stub2, 1))
        ):
            sess.poll_remote_clients()
            # constant inputs: repeat-last predictions hold, so no rollbacks —
            # sparse saving then only saves at the prediction-window edge
            # (changing inputs would legitimately save once per rollback to
            # pin the confirmed frame)
            sess.add_local_input(handle, 42)
            reqs = sess.advance_frame()
            saves[idx] += sum(1 for r in reqs if isinstance(r, SaveGameState))
            stub.handle_requests(reqs)

    # far fewer saves than frames (the non-sparse session saves every frame)
    assert saves[0] < n // 2 and saves[1] < n // 2, saves
    assert stub1.gs.frame == n and stub2.gs.frame == n
    # simulations agree wherever both have confirmed
    assert stub1.gs.state == stub2.gs.state
