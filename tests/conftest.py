"""Test configuration: force JAX onto a virtual 8-device CPU platform so
multi-chip sharding paths can be exercised without TPU hardware.  Must run
before any test imports jax.

The container's sitecustomize registers the tunneled TPU platform and
overrides JAX_PLATFORMS at interpreter start, so an env-var default is not
enough — we override the jax config directly (safe: backends initialize
lazily, and no jax computation has run yet at conftest import time).
Set GGRS_TPU_TEST_PLATFORM to opt out (e.g. =axon to run the suite on TPU).
"""

import os

platform = os.environ.get("GGRS_TPU_TEST_PLATFORM", "cpu")
if platform == "cpu":
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax  # noqa: E402  (import after XLA_FLAGS is set)

jax.config.update("jax_platforms", platform)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "soak: long-horizon (1e5-frame) endurance tests; deselect with "
        '-m "not soak" when iterating',
    )
    config.addinivalue_line(
        "markers",
        "slow: multi-thousand-tick stress runs (e.g. the bank fault soak); "
        "excluded from the tier-1 gate, run explicitly with -m slow",
    )
