"""Fleet layer tests (DESIGN.md §16): sharded pool serving, graceful
drain, live match migration, and kill-a-shard crash failover.

The acceptance pins, mirrored by ``scripts/chaos.py --fault shard``:

* killing one of two shards recovers EVERY affected match on the
  survivor within a bounded number of ticks, from the durable journals
  alone, with the surviving shard's matches bit-identical to a
  fault-free control leg (wire bytes, request lists, events);
* a live migration under seeded loss/dup/reorder keeps the migrated
  match's peer connected and desync-free — a retransmission hiccup,
  never a reset — and spectators resume from their ack window;
* graceful drain moves every match off and retires the shard, with the
  same survivor bit-identity.

Satellites pinned here: the export bundle's process-portability
(serialize→deserialize round trip, no live objects), native I/O detach
on release (the ``_detach_io`` leak check), eviction/readmission backoff
jitter, and journal recovery under concurrent/torn writes (crc32-chain
prefix).
"""

from __future__ import annotations

import pickle
import random
import threading

import pytest

from ggrs_tpu.broadcast.journal import (
    MatchJournal,
    read_journal,
    resume_from_file,
)
from ggrs_tpu.chaos import (
    CrcGame,
    InMemoryNetwork,
    RecordingSocket,
    drive_fleet_chaos,
    fleet_recovery_violations,
    fleet_survivor_violations,
    two_peer_builder,
)
from ggrs_tpu.core.errors import (
    GgrsError,
    NotSynchronized,
    PredictionThreshold,
)
from ggrs_tpu.fleet import (
    FleetError,
    HashRing,
    PoolShard,
    SHARD_DEAD,
    SHARD_DRAINING,
    SHARD_RETIRED,
    ShardSupervisor,
)
from ggrs_tpu.fleet.supervisor import (
    READMIT_BACKOFF_TICKS,
    READMIT_MAX_ATTEMPTS,
)
from ggrs_tpu.net import _native
from ggrs_tpu.obs import Registry
from ggrs_tpu.parallel.host_bank import (
    EVICT_BACKOFF_TICKS,
    SLOT_MIGRATED,
    _evict_jitter,
)

needs_native = pytest.mark.skipif(
    _native.bank_lib() is None, reason="native session bank unavailable"
)


# ----------------------------------------------------------------------
# placement: the consistent-hash ring
# ----------------------------------------------------------------------


class TestHashRing:
    def test_owner_stable_across_instances(self):
        """md5 points, not hash(): placement is identical across processes
        and hash-randomization seeds."""
        a = HashRing(["s0", "s1", "s2"])
        b = HashRing(["s2", "s0", "s1"])  # insertion order must not matter
        for k in range(64):
            assert a.owner(f"m{k}") == b.owner(f"m{k}")

    def test_preference_walk_covers_every_shard_owner_first(self):
        ring = HashRing(["s0", "s1", "s2", "s3"])
        for k in range(16):
            order = list(ring.preference(f"m{k}"))
            assert order[0] == ring.owner(f"m{k}")
            assert sorted(order) == ["s0", "s1", "s2", "s3"]

    def test_remove_moves_only_the_removed_shards_matches(self):
        """The consistent-hash contract: losing one shard re-homes only
        the matches it owned; every other match keeps its owner."""
        ring = HashRing(["s0", "s1", "s2"])
        before = {f"m{k}": ring.owner(f"m{k}") for k in range(200)}
        ring.remove("s1")
        for mid, owner in before.items():
            if owner == "s1":
                assert ring.owner(mid) != "s1"
            else:
                assert ring.owner(mid) == owner

    def test_spread(self):
        """Virtual points keep the split usable (no shard starves)."""
        ring = HashRing(["s0", "s1", "s2"])
        counts = {"s0": 0, "s1": 0, "s2": 0}
        for k in range(600):
            counts[ring.owner(f"match-{k}")] += 1
        assert min(counts.values()) > 600 // 3 // 3


# ----------------------------------------------------------------------
# admission: capacity-aware placement + backoff with jitter
# ----------------------------------------------------------------------


def _mk_match(clock, seed, name):
    """One fleet-admittable 2-peer match against an external peer."""
    net = InMemoryNetwork(latency_ticks=1, seed=seed)
    host_sock = RecordingSocket(net.socket(f"H-{name}"))
    bf = lambda: two_peer_builder(clock, seed, 0, f"P-{name}")  # noqa: E731
    peer = two_peer_builder(
        clock, seed + 1, 1, f"H-{name}", other_handle=0
    ).start_p2p_session(net.socket(f"P-{name}"))
    return bf, (lambda: host_sock), peer, net


class TestAdmission:
    def test_capacity_refusal_parks_then_places(self):
        clock = [0]
        sup = ShardSupervisor(("a",), capacity=1, seed=3)
        bf0, sf0, _, _ = _mk_match(clock, 11, "m0")
        bf1, sf1, _, _ = _mk_match(clock, 13, "m1")
        assert sup.admit("m0", bf0, sf0) == "a"
        # full: parks in the retry queue instead of failing
        assert sup.admit("m1", bf1, sf1) is None
        assert sup.pending_admissions() == 1
        assert sup.match_location("m1") is None
        # free capacity, then tick past the backoff window: it places.
        # (ticking an empty-ish supervisor only drives the control plane)
        sup.shards["a"].capacity = 4
        for _ in range(2 * READMIT_BACKOFF_TICKS):
            clock[0] += 16
            sup.add_local_input("m0", 0, 1)
            sup.advance_all()
            if sup.match_location("m1") == "a":
                break
        assert sup.match_location("m1") == "a"
        assert sup.pending_admissions() == 0

    def test_backoff_has_jitter(self):
        """A shard-wide refusal parks N matches with DIFFERENT retry
        ticks — the re-admission herd must not land on one tick."""
        clock = [0]
        sup = ShardSupervisor(("a",), capacity=0, seed=9)
        for k in range(6):
            bf, sf, _, _ = _mk_match(clock, 31 + 2 * k, f"m{k}")
            assert sup.admit(f"m{k}", bf, sf) is None
        due = [p.next_try for p in sup._pending]
        assert len(set(due)) > 1, f"no jitter: all retries due at {due[0]}"

    def test_refused_to_exhaustion_is_lost_loudly(self):
        clock = [0]
        sup = ShardSupervisor(("a",), capacity=0, seed=5)
        bf, sf, _, _ = _mk_match(clock, 41, "m0")
        assert sup.admit("m0", bf, sf) is None
        # worst-case total wait: sum of max backoff+jitter per attempt
        budget = sum(
            READMIT_BACKOFF_TICKS * (2 ** a) + READMIT_BACKOFF_TICKS
            for a in range(READMIT_MAX_ATTEMPTS + 1)
        )
        for _ in range(budget):
            clock[0] += 16
            sup.advance_all()
            if sup.lost_matches():
                break
        assert "m0" in sup.lost_matches()
        reg = sup.metrics
        assert reg.value("ggrs_fleet_matches_lost_total") == 1

    def test_draining_and_dead_shards_refuse(self):
        sup = ShardSupervisor(("a", "b"), capacity=8, seed=1)
        sup.drain("a")
        assert sup.shards["a"].admission_refusal() == "draining"
        sup.kill("b")
        assert sup.shards["b"].admission_refusal() == "dead"


class TestEvictJitter:
    """Satellite: the bank's eviction retry backoff decorrelates
    co-quarantined slots (a shard-wide failure must not retry N slots on
    the same tick cadence)."""

    def test_deterministic_and_in_range(self):
        for index in range(16):
            for attempt in range(4):
                j = _evict_jitter(index, attempt)
                assert 0 <= j < EVICT_BACKOFF_TICKS
                assert j == _evict_jitter(index, attempt)

    def test_co_quarantined_slots_draw_different_delays(self):
        draws = [_evict_jitter(i, 1) for i in range(8)]
        assert len(set(draws)) > 1, f"retry storm: all slots drew {draws[0]}"
        # and across attempts for one slot the delay moves too
        attempts = [_evict_jitter(3, a) for a in range(6)]
        assert len(set(attempts)) > 1

    @needs_native
    def test_shard_wide_storm_is_clamped_per_tick(self):
        """Six slots faulting on ONE tick must not all evict on that
        tick: EVICT_MAX_PER_TICK bounds the supervision pass's work, the
        rest stay quarantined and drain over the following ticks."""
        from ggrs_tpu.chaos import drive_chaos
        from ggrs_tpu.parallel.host_bank import (
            EVICT_MAX_PER_TICK,
            SLOT_EVICTED,
            SLOT_QUARANTINED,
        )

        def storm(i, ctx):
            if i == 60:
                for s in range(6):
                    ctx["pool"].inject_slot_error(s)

        one_tick = drive_chaos(61, n_matches=4, seed=13, inject=storm)
        states = one_tick["states"][:6]
        assert states.count(SLOT_EVICTED) <= EVICT_MAX_PER_TICK
        assert states.count(SLOT_QUARANTINED) >= 6 - EVICT_MAX_PER_TICK
        # ... and the storm drains fully within a few more ticks
        later = drive_chaos(66, n_matches=4, seed=13, inject=storm)
        assert later["states"][:6] == [SLOT_EVICTED] * 6


# ----------------------------------------------------------------------
# satellite: the export bundle is process-portable
# ----------------------------------------------------------------------


def _assert_plain_data(obj, path="bundle"):
    """No live objects / ctypes buffers in the migration bundle: it must
    survive leaving the process."""
    import ctypes

    if isinstance(obj, dict):
        for k, v in obj.items():
            _assert_plain_data(v, f"{path}[{k!r}]")
        return
    if isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            _assert_plain_data(v, f"{path}[{i}]")
        return
    assert not isinstance(obj, (ctypes._SimpleCData, ctypes.Array,
                                ctypes.Structure, memoryview, bytearray)), (
        f"{path}: live buffer {type(obj).__name__} in the export bundle"
    )
    assert isinstance(obj, (bytes, str, int, float, bool, type(None))), (
        f"{path}: non-plain {type(obj).__name__} in the export bundle"
    )


@needs_native
class TestExportPortability:
    def test_bundle_survives_pickle_and_is_plain_data(self):
        clock = [0]
        sup = ShardSupervisor(("a",), capacity=4, seed=2)
        bf, sf, peer, net = _mk_match(clock, 51, "m0")
        assert sup.admit("m0", bf, sf) == "a"
        game, peer_game = CrcGame(), CrcGame()
        for i in range(24):
            clock[0] += 16
            try:
                peer.add_local_input(1, i % 7)
                peer_game.fulfill(peer.advance_frame())
            except (NotSynchronized, PredictionThreshold):
                pass
            sup.add_local_input("m0", 0, i % 5)
            out = sup.advance_all()
            if "m0" in out:
                game.fulfill(out["m0"])
            net.tick()
        shard = sup.shards["a"]
        assert shard.pool._native_active, "bank did not go native"
        slot = shard._matches["m0"]
        bundle = shard.pool.export_resume_state(slot)
        # the portability contract, structurally
        bundle = pickle.loads(pickle.dumps(bundle))
        checked = dict(bundle)
        checked.pop("pending_events")  # GgrsEvent dataclasses: picklable
        _assert_plain_data(checked)
        for ev in bundle["pending_events"]:
            assert ev == pickle.loads(pickle.dumps(ev))
        assert bundle["resume_frame"] >= 0
        assert bundle["num_players"] == 2

    def test_release_slot_detaches_and_goes_migrated(self):
        """The ``_detach_io`` leak check: a released slot drops its
        NetBatch handle, io delta keys, and addr routing — and the slot
        state records the match lives on elsewhere."""
        clock = [0]
        sup = ShardSupervisor(("a",), capacity=4, seed=4)
        bf, sf, peer, net = _mk_match(clock, 61, "m0")
        sup.admit("m0", bf, sf)
        game, peer_game = CrcGame(), CrcGame()
        for i in range(10):
            clock[0] += 16
            try:
                peer.add_local_input(1, i)
                peer_game.fulfill(peer.advance_frame())
            except (NotSynchronized, PredictionThreshold):
                pass
            sup.add_local_input("m0", 0, i)
            out = sup.advance_all()
            if "m0" in out:
                game.fulfill(out["m0"])
            net.tick()
        pool = sup.shards["a"].pool
        slot = sup.shards["a"]._matches["m0"]
        pool.export_resume_state(slot)
        pool.release_slot(slot, detail="test migration")
        assert pool.slot_state(slot) == SLOT_MIGRATED
        # the leak checks: no NetBatch handle, no attach flag, no stale
        # delta-tracking keys for the slot (io_state reports python)
        assert pool._net_handles[slot] is None
        assert not pool._io_attached[slot]
        assert not any(k[0] == slot for k in pool._io_prev)
        # released slots drop inputs and tick empty, like dead — but the
        # state is distinct (the match is alive elsewhere)
        pool.add_local_input(slot, 0, 1)
        clock[0] += 16
        assert sup.shards["a"].pool.advance_all()[slot] == []


# ----------------------------------------------------------------------
# live migration
# ----------------------------------------------------------------------


@needs_native
class TestLiveMigrationNative:
    """The harvest-seam migration path: bank-eligible matches move
    between shards through ``export_resume_state`` → pickle round trip →
    ``adopt_resume_bundle``."""

    def _run(self, migrate_at=None, dst="b", ticks=56):
        clock = [0]
        sup = ShardSupervisor(
            ("a", "b"), capacity=4, seed=6, metrics=Registry()
        )
        bf, sf, peer, net = _mk_match(clock, 71, "m0")
        sup.admit("m0", bf, sf, shard="a")
        game, peer_game = CrcGame(), CrcGame()
        peer_events = []
        for i in range(ticks):
            clock[0] += 16
            if migrate_at is not None and i == migrate_at:
                assert sup.migrate("m0", dst) == dst
            try:
                peer.add_local_input(1, (i * 5) % 16)
                peer_game.fulfill(peer.advance_frame())
            except (NotSynchronized, PredictionThreshold):
                pass
            peer_events.extend(peer.events())
            sup.add_local_input("m0", 0, (i * 3) % 16)
            out = sup.advance_all()
            if "m0" in out:
                game.fulfill(out["m0"])
            net.tick()
        return dict(sup=sup, peer=peer, peer_events=peer_events,
                    game=game, peer_game=peer_game)

    def test_peer_sees_hiccup_never_reset(self):
        run = self._run(migrate_at=30)
        sup, peer = run["sup"], run["peer"]
        assert sup.match_location("m0") == "b"
        assert not sup.lost_matches()
        # the peer never noticed a new endpoint: no disconnect, no desync,
        # and the match caught back up behind it
        names = [type(e).__name__ for e in run["peer_events"]]
        assert "Disconnected" not in names
        assert "DesyncDetected" not in names
        assert peer.current_frame - sup.current_frame("m0") <= 8
        reg = sup.metrics
        assert reg.value(
            "ggrs_fleet_migrations_total", reason="manual"
        ) == 1

    def _drive(self, sup, peer, net, ticks, clock):
        game, peer_game = CrcGame(), CrcGame()
        for i in range(ticks):
            clock[0] += 16
            try:
                peer.add_local_input(1, i % 7)
                peer_game.fulfill(peer.advance_frame())
            except (NotSynchronized, PredictionThreshold):
                pass
            sup.add_local_input("m0", 0, i % 5)
            out = sup.advance_all()
            if "m0" in out:
                game.fulfill(out["m0"])
            net.tick()

    def test_destination_failure_falls_back_to_journal(self, tmp_path):
        """A migration that fails AFTER the source released the match
        must not strand it half-tracked: a journaled match re-adopts
        from its journal instead."""
        clock = [0]
        sup = ShardSupervisor(
            ("a", "b"), capacity=4, seed=8, metrics=Registry(),
            journal_dir=tmp_path, checkpoint_every=4,
        )
        bf, sf, peer, net = _mk_match(clock, 81, "m0")
        sup.admit("m0", bf, sf, state_template=0, shard="a")
        self._drive(sup, peer, net, 24, clock)
        dst = sup.shards["b"]
        orig, tripped = dst.adopt_match, {"n": 0}

        def flaky(*a, **k):
            if tripped["n"] == 0:
                tripped["n"] += 1
                raise RuntimeError("simulated destination failure")
            return orig(*a, **k)

        dst.adopt_match = flaky
        assert sup.migrate("m0", "b") == "b"
        assert tripped["n"] == 1
        assert sup.match_location("m0") == "b"
        assert not sup.lost_matches()
        reg = sup.metrics
        assert reg.value("ggrs_fleet_migration_failures_total") == 1

    def test_destination_failure_without_journal_is_lost_loudly(self):
        """Same failure on an UNjournaled match: nothing to fall back to
        — the match is lost, the bookkeeping says so, and the fleet tick
        survives (FleetError, not a bare exception)."""
        clock = [0]
        sup = ShardSupervisor(("a", "b"), capacity=4, seed=8,
                              metrics=Registry())
        bf, sf, peer, net = _mk_match(clock, 83, "m0")
        sup.admit("m0", bf, sf, shard="a")
        self._drive(sup, peer, net, 24, clock)

        def broken(*a, **k):
            raise RuntimeError("simulated destination failure")

        sup.shards["b"].adopt_match = broken
        with pytest.raises(FleetError):
            sup.migrate("m0", "b")
        assert "m0" in sup.lost_matches()
        assert sup.match_location("m0") is None
        # the serving loop keeps ticking afterwards
        clock[0] += 16
        sup.advance_all()

    def test_migrate_rejects_bad_destinations(self):
        run = self._run()  # no migration during the run
        sup = run["sup"]
        with pytest.raises(FleetError):
            sup.migrate("m0", "a")  # destination is the source
        sup.shards["b"].capacity = 0
        with pytest.raises(FleetError):
            sup.migrate("m0", "b")  # refused: full
        with pytest.raises(FleetError):
            sup.migrate("m0")  # no shard accepts


# ----------------------------------------------------------------------
# the fleet chaos world: kill-a-shard, drain-under-load,
# migrate-under-loss (same driver scripts/chaos.py fronts)
# ----------------------------------------------------------------------

TICKS = 48
PER_SHARD = 2
AFFECTED = [f"m{k}" for k in range(PER_SHARD, 2 * PER_SHARD)]  # on s1
SURVIVORS = [f"m{k}" for k in range(PER_SHARD)]  # on s0
LOSSY = dict(latency_ticks=1, loss=0.05, duplicate=0.02, reorder=0.05)


@pytest.fixture(scope="module")
def control():
    return drive_fleet_chaos(TICKS, matches_per_shard=PER_SHARD, seed=7)


@pytest.fixture(scope="module")
def lossy_control():
    return drive_fleet_chaos(
        TICKS, matches_per_shard=PER_SHARD, seed=7, fault_cfg=dict(LOSSY),
        n_spectators=1,
    )


class TestKillAShard:
    def test_every_match_fails_over_survivors_bit_identical(self, control):
        def inject(i, ctx):
            if i == TICKS // 2:
                ctx["sup"].kill("s1")

        chaos = drive_fleet_chaos(
            TICKS, matches_per_shard=PER_SHARD, seed=7, inject=inject
        )
        assert not fleet_survivor_violations(chaos, control, SURVIVORS)
        assert not fleet_recovery_violations(
            chaos, AFFECTED, dead_shards=["s1"]
        )
        # every affected match landed on the survivor, within bounded lag
        for mid in AFFECTED:
            assert chaos["locations"][mid] == "s0"
        sup = chaos["sup"]
        assert sup.shards["s1"].healthz()["state"] == SHARD_DEAD
        reg = chaos["registry"]
        assert reg.value("ggrs_fleet_failovers_total") == 1
        assert reg.value(
            "ggrs_fleet_migrations_total", reason="failover"
        ) == len(AFFECTED)

    def test_fleet_healthz_aggregates(self, control):
        """The fleet-wide ``/healthz`` record: per-shard reports plus one
        top-level verdict, served verbatim by ``MetricsServer``."""
        h = control["healthz"]
        assert h["ok"] and h["matches"] == 2 * PER_SHARD
        assert set(h["shards"]) == {"s0", "s1"}

        def inject(i, ctx):
            if i == 10:
                ctx["sup"].kill("s0")
            if i == 12:
                ctx["sup"].kill("s1")

        dead = drive_fleet_chaos(
            24, matches_per_shard=1, seed=9, inject=inject
        )
        assert not dead["healthz"]["ok"]  # no serving shard left

    def test_healthz_endpoint_serves_fleet_dict(self, control):
        import json
        import urllib.request

        from ggrs_tpu.obs import start_http_server

        report = dict(control["healthz"])
        server = start_http_server(
            Registry(), port=0, health=lambda: dict(report),
            stale_after=5.0,
        )
        try:
            url = f"http://127.0.0.1:{server.port}/healthz"
            body = json.loads(urllib.request.urlopen(url, timeout=5).read())
            assert body["ok"] is True
            assert body["matches"] == 2 * PER_SHARD
            # a wedged serving loop (advance_all stopped, age growing)
            # must go 503 even though the frozen aggregate still says ok
            # — the server's stale_after applies to the dict path too
            report["last_tick_age_s"] = 999.0
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(url, timeout=5)
            assert exc.value.code == 503
        finally:
            server.close()


class TestGracefulDrain:
    def test_drain_moves_everything_and_retires(self, control):
        def inject(i, ctx):
            if i == TICKS // 3:
                ctx["sup"].drain("s1")

        chaos = drive_fleet_chaos(
            TICKS, matches_per_shard=PER_SHARD, seed=7, inject=inject
        )
        assert not fleet_survivor_violations(chaos, control, SURVIVORS)
        assert not fleet_recovery_violations(chaos, AFFECTED)
        for mid in AFFECTED:
            assert chaos["locations"][mid] == "s0"
        assert chaos["sup"].shards["s1"].state == SHARD_RETIRED

    def test_drain_only_active_shards(self):
        sup = ShardSupervisor(("a", "b"), seed=1)
        sup.drain("a")
        assert sup.shards["a"].state == SHARD_DRAINING
        with pytest.raises(GgrsError):
            sup.drain("a")  # already draining


class TestMigrateUnderLoss:
    def test_wire_stream_consistent_spectators_resume(self, lossy_control):
        """Live migration with seeded loss/dup/reorder on every match's
        network: the migrated match's peer stays connected and
        desync-free, untouched matches stay bit-identical to control, and
        the spectator resumes from its ack window (its decoded stream
        agrees with control wherever both observed a frame)."""

        def inject(i, ctx):
            if i == TICKS // 3:
                ctx["sup"].migrate("m0")

        chaos = drive_fleet_chaos(
            TICKS, matches_per_shard=PER_SHARD, seed=7, inject=inject,
            fault_cfg=dict(LOSSY), n_spectators=1,
        )
        # m0 moved; everything else stayed put and identical
        assert chaos["locations"]["m0"] != lossy_control["locations"]["m0"]
        untouched = [m for m in chaos["match_ids"] if m != "m0"]
        assert not fleet_survivor_violations(
            chaos, lossy_control, untouched
        )
        assert not fleet_recovery_violations(chaos, ["m0"])
        # the viewer kept decoding across the migration from its ack
        # window: the frame sequence never resets or regresses (a fresh
        # endpoint would restart at 0), and it advances well past the
        # move.  NOTE the confirmed stream itself legitimately differs
        # from control — the migration stall shifts which tick's local
        # input lands on which frame — so only continuity is pinned, not
        # control equality (that pin lives on the untouched matches).
        frames = [f for f, _ in chaos["viewer_streams"][0]]
        assert frames == sorted(set(frames)), "viewer stream reset/regressed"
        assert len(frames) >= TICKS // 2
        assert max(frames) >= TICKS // 3 + 8  # advanced past the move


# ----------------------------------------------------------------------
# satellite: journal recovery under concurrent / torn writes
# ----------------------------------------------------------------------


def _write_frames(journal, start, count, isize=2, players=2):
    recs = []
    for f in range(start, start + count):
        blob = b"".join(
            (f * 10 + p).to_bytes(isize, "little") for p in range(players)
        )
        recs.append((bytes(players), blob))
    journal.append_frames(start, recs)


class TestJournalConcurrentRecovery:
    def test_torn_tail_write_resumes_to_last_durable_frame(self, tmp_path):
        """A journal whose writer died mid-append: the crc32 chain
        truncates the parse at the last intact record and recovery resumes
        exactly there."""
        path = tmp_path / "torn.ggjl"
        j = MatchJournal(path, 2, 2, tail_window=64)
        _write_frames(j, 0, 12)
        j.append_checkpoint(8, {"s": 8})
        j.flush(fsync=True)
        size_at_12 = path.stat().st_size
        _write_frames(j, 12, 1)
        j.flush()
        j._f.close()
        # tear the last append mid-record (a crash between write() calls)
        full = path.read_bytes()
        assert len(full) > size_at_12
        path.write_bytes(full[: size_at_12 + 7])
        parsed = read_journal(path)
        assert parsed["truncated"]
        assert [f for f, _, _ in parsed["frames"]] == list(range(12))
        res = resume_from_file(
            path, local_handles=[0], endpoints=[([1], True)]
        )
        assert res["durable_tip"] == 11
        assert res["checkpoint"][0] == 8
        assert res["harvest"]["last_confirmed"] == 11

    def test_corrupt_middle_byte_recovers_intact_prefix(self, tmp_path):
        path = tmp_path / "flip.ggjl"
        j = MatchJournal(path, 2, 2, tail_window=64)
        _write_frames(j, 0, 20)
        j.close()
        data = bytearray(path.read_bytes())
        data[len(data) // 2] ^= 0x40
        path.write_bytes(bytes(data))
        parsed = read_journal(path)
        assert parsed["truncated"]
        frames = [f for f, _, _ in parsed["frames"]]
        assert frames == list(range(len(frames)))  # an intact prefix
        assert 0 < len(frames) < 20
        res = resume_from_file(
            path, local_handles=[0], endpoints=[([1], True)]
        )
        assert res["durable_tip"] == frames[-1]

    def test_recovery_while_writer_appends(self, tmp_path):
        """``resume_from_file`` raced against a live writer: every read
        sees a valid prefix (never an exception, never a gap), and the
        durable tip only moves forward."""
        path = tmp_path / "live.ggjl"
        j = MatchJournal(path, 2, 2, fsync_every=1, tail_window=64)
        _write_frames(j, 0, 4)
        j.append_checkpoint(2, {"s": 2})
        j.flush(fsync=True)
        stop = threading.Event()
        errors = []

        def writer():
            f = 4
            while not stop.is_set() and f < 600:
                _write_frames(j, f, 1)
                if f % 16 == 0:
                    j.append_checkpoint(f, {"s": f})
                f += 1

        t = threading.Thread(target=writer)
        t.start()
        try:
            tips = []
            for _ in range(40):
                res = resume_from_file(
                    path, local_handles=[0], endpoints=[([1], True)]
                )
                tip = res["durable_tip"]
                tips.append(tip)
                w = [f for f, _, _ in res["window"]]
                if w != list(range(w[0], tip + 1)):
                    errors.append(f"non-contiguous window at tip {tip}")
                if res["harvest"]["last_confirmed"] != tip:
                    errors.append(f"harvest tip mismatch at {tip}")
        finally:
            stop.set()
            t.join()
            j.close()
        assert not errors, errors[:3]
        assert tips == sorted(tips), "durable tip regressed"
        # the close()d journal reads back complete
        final = resume_from_file(
            path, local_handles=[0], endpoints=[([1], True)]
        )
        assert final["durable_tip"] >= tips[-1]

    def test_post_tip_checkpoint_is_not_resumable(self, tmp_path):
        """A checkpoint at durable_tip+1 already INCLUDES the tip frame
        (its ``frame`` is the next frame to simulate): resuming from it
        would re-apply the tip and silently desync.  Recovery must fall
        back to an older in-window checkpoint, or report none."""
        path = tmp_path / "post_tip.ggjl"
        j = MatchJournal(path, 2, 2, tail_window=64)
        _write_frames(j, 0, 10)
        j.append_checkpoint(6, {"s": 6})
        j.append_checkpoint(10, {"s": 10})  # tip+1: durable but not usable
        j.close()
        res = resume_from_file(
            path, local_handles=[0], endpoints=[([1], True)]
        )
        assert res["durable_tip"] == 9
        assert res["checkpoint"][0] == 6
        # with ONLY the post-tip checkpoint, the match is unrecoverable
        path2 = tmp_path / "post_tip_only.ggjl"
        j2 = MatchJournal(path2, 2, 2, tail_window=64)
        _write_frames(j2, 0, 10)
        j2.append_checkpoint(10, {"s": 10})
        j2.close()
        res2 = resume_from_file(
            path2, local_handles=[0], endpoints=[([1], True)]
        )
        assert res2["checkpoint"] is None

    def test_local_tail_round_trips(self, tmp_path):
        """LOCAL records (the staged-input failover seam): the tail at or
        after the durable tip comes back per frame per handle."""
        path = tmp_path / "local.ggjl"
        j = MatchJournal(path, 2, 2, tail_window=64)
        _write_frames(j, 0, 6)
        j.append_checkpoint(4, {"s": 4})
        for f, v in ((5, 500), (6, 600), (7, 700)):
            j.append_local_input(f, 0, v.to_bytes(2, "little"))
        j.flush_local()
        j.close()
        res = resume_from_file(
            path, local_handles=[0], endpoints=[([1], True)]
        )
        assert res["durable_tip"] == 5
        assert sorted(res["local_tail"]) == [5, 6, 7]
        assert res["local_tail"][6][0] == (600).to_bytes(2, "little")


# ----------------------------------------------------------------------
# shard bookkeeping edges
# ----------------------------------------------------------------------


class TestPoolShard:
    def test_killed_shard_stops_ticking_and_refuses(self):
        clock = [0]
        shard = PoolShard("x", capacity=2, metrics=Registry())
        bf, sf, _, _ = _mk_match(clock, 91, "m0")
        shard.admit("m0", bf(), sf())
        shard.kill()
        assert shard.advance_all() == {}
        assert shard.admission_refusal() == "dead"
        assert shard.healthz()["ok"] is False

    def test_late_admission_lands_on_adopted_tier(self):
        clock = [0]
        shard = PoolShard("x", capacity=4, metrics=Registry())
        bf, sf, peer, net = _mk_match(clock, 95, "m0")
        assert shard.admit("m0", bf(), sf()) == "bank"
        game, peer_game = CrcGame(), CrcGame()
        for i in range(3):
            clock[0] += 16
            try:
                peer.add_local_input(1, i)
                peer_game.fulfill(peer.advance_frame())
            except (NotSynchronized, PredictionThreshold):
                pass
            shard.add_local_input("m0", 0, i)
            game.fulfill(shard.advance_all().get("m0", []))
            net.tick()
        # the pool sealed on the first tick: a later admit is per-session
        bf2, sf2, _, _ = _mk_match(clock, 97, "m1")
        assert shard.admit("m1", bf2(), sf2()) == "standalone"
        assert shard.live_matches() == 2


# ----------------------------------------------------------------------
# checkpoint timing: a rollback pending in the just-returned request
# list must never leak into a journal checkpoint (the chaos
# shard_migrate desync, ROADMAP item 5's named precondition)
# ----------------------------------------------------------------------


def _journal_chain_violations(jpath) -> list:
    """Recompute the CrcGame chain from a first-incarnation journal's
    own confirmed-input records and check every embedded checkpoint
    state lies ON that chain.  A checkpoint written from a save cell
    whose corrective rollback re-save had not been fulfilled yet holds a
    MISPREDICTED chain value — off-chain, and a permanent desync for any
    incarnation that resumes from it."""
    import zlib

    from ggrs_tpu.utils.checkpoint import loads_pytree

    parsed = read_journal(jpath)
    frames = parsed["frames"]
    if not frames or frames[0][0] != 0:
        return []  # later incarnation: chain base not in this file
    chain, chain_at = 0, {}
    for f, statuses, blob in frames:
        isize = len(blob) // len(statuses)
        vals = tuple(
            int.from_bytes(blob[p * isize:(p + 1) * isize], "little")
            for p in range(len(statuses))
        )
        chain = zlib.crc32(repr(vals).encode(), chain)
        chain_at[f] = chain
    out = []
    for cf, blob in parsed["checkpoints"]:
        state = int(loads_pytree(blob, 0)[0])
        if state not in (chain_at.get(cf), chain_at.get(cf - 1), 0):
            out.append(f"checkpoint@{cf}: state {state} is off-chain")
    return out


class TestCheckpointNotPoisonedByPendingRollback:
    def test_lossy_migration_stays_desync_free(self, tmp_path):
        """Seed 6 reproduces the pre-fix failure shape: under seeded
        loss, a rollback corrects a frame at a checkpoint boundary in
        the same tick the checkpoint fires, the stale cell is embedded,
        and the tick-50 journal-path migration resumes the session-
        backed match (spectated, hubless => not bank-resident) from the
        poisoned state — every post-migration checksum compare then
        desyncs.  With checkpointing moved ahead of the tick (previous
        tick fully fulfilled), both observables below must stay clean
        for every seed; this one is pinned because it fails loudest."""

        def migrate(i, ctx):
            if i == 50:
                ctx["sup"].migrate("m0")

        ctx = drive_fleet_chaos(
            150, matches_per_shard=1, seed=6, inject=migrate,
            fault_cfg=dict(LOSSY), n_spectators=1,
            journal_dir=str(tmp_path),
        )
        desyncs = [
            e
            for e in ctx["host_events"]["m0"] + ctx["peer_events"]["m0"]
            if type(e).__name__ == "DesyncDetected"
        ]
        assert desyncs == [], desyncs[:4]
        assert ctx["locations"]["m0"] == "s1"  # the migration happened
        violations = _journal_chain_violations(tmp_path / "m0.000.ggjl")
        assert violations == [], violations

    def test_checkpoint_states_on_chain_across_seeds(self, tmp_path):
        """The chain invariant alone, three more seeds: poisoning is
        seed-dependent (it needs a rollback to straddle a checkpoint
        boundary), so pin a spread — pre-fix, seeds 2 and 5 poison
        without ever desyncing in-run, the silent variant that bites
        only on a LATER failover."""
        for seed in (2, 5, 7):
            jdir = tmp_path / f"s{seed}"
            jdir.mkdir()

            def migrate(i, ctx):
                if i == 50:
                    ctx["sup"].migrate("m0")

            drive_fleet_chaos(
                150, matches_per_shard=1, seed=seed, inject=migrate,
                fault_cfg=dict(LOSSY), n_spectators=1,
                journal_dir=str(jdir),
            )
            violations = _journal_chain_violations(jdir / "m0.000.ggjl")
            assert violations == [], (seed, violations)
