"""Structural performance pins for the hot device programs (VERDICT r3
item 7).

Wall-clock numbers on the shared tunnel drift run to run, so perf
regressions on the flagship replay and the batched-session tick are pinned
STRUCTURALLY instead, extending the pattern of
tests/test_spec_integration.py's dispatch pins:

- dispatch-count pins: a steady-state chunk is exactly ONE jitted call
  (catches per-tick dispatching, chunk splitting, accidental warmup
  re-entry);
- program-shape pins: the tick program is two nested scans (outer ticks,
  inner resim window) with a bounded equation count (catches fusion
  structure loss, runaway unrolling, and graph blowup).

Known limitation, measured while building these: the ~30x
shared-vs-per-session ring-index regression (ReplayPrograms docstring) is
invisible to primitive counts — both forms produce identical jaxprs up to
the VALUES feeding the scatter indices — so that property stays covered by
its behavioral test and the bench deltas, not by these pins.
"""

from __future__ import annotations

from collections import Counter

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ggrs_tpu.games.boxgame import BoxGame
from ggrs_tpu.ops.replay import build_replay_programs
from ggrs_tpu.parallel.batch import BatchedSessions, make_mesh
from ggrs_tpu.sessions.device_synctest import DeviceSyncTestSession


def _walk_primitives(closed_jaxpr) -> Counter:
    """Primitive-name counts over a jaxpr, recursing into sub-jaxprs."""
    counts: Counter = Counter()

    def walk(j):
        for eq in j.eqns:
            counts[eq.primitive.name] += 1
            for v in eq.params.values():
                for x in v if isinstance(v, (list, tuple)) else [v]:
                    if hasattr(x, "jaxpr"):
                        walk(x.jaxpr)
                    elif hasattr(x, "eqns"):
                        walk(x)

    walk(closed_jaxpr.jaxpr)
    return counts


class TestFlagshipReplayPins:
    def make_session(self):
        game = BoxGame(2)
        return game, DeviceSyncTestSession(
            game.advance, game.init_state(), jnp.zeros((2,), jnp.uint8),
            check_distance=8, max_prediction=8,
        )

    def test_steady_chunk_is_exactly_one_dispatch(self):
        """After warmup, each run_ticks(chunk) must invoke the steady
        program exactly once and the warmup program never."""
        _, sess = self.make_session()
        chunk = np.zeros((32, 2), np.uint8)
        sess.run_ticks(chunk, check=False)  # covers the warmup split
        calls = {"steady": 0, "warmup": 0}
        orig_steady = sess._programs.run_steady
        orig_warm = sess._programs.run_warmup

        def spy_steady(*a, **k):
            calls["steady"] += 1
            return orig_steady(*a, **k)

        def spy_warm(*a, **k):
            calls["warmup"] += 1
            return orig_warm(*a, **k)

        # ReplayPrograms is frozen; bypass for the spy
        object.__setattr__(sess._programs, "run_steady", spy_steady)
        object.__setattr__(sess._programs, "run_warmup", spy_warm)
        try:
            for i in range(3):
                sess.run_ticks(chunk, check=False)
        finally:
            object.__setattr__(sess._programs, "run_steady", orig_steady)
            object.__setattr__(sess._programs, "run_warmup", orig_warm)
        assert calls == {"steady": 3, "warmup": 0}, calls
        sess.verify()  # and the ticks were real (desync gate still green)

    def test_steady_program_shape(self):
        """Two nested scans (ticks outer, resim window inner), no
        while/cond, equation count bounded at ~2x today's 419."""
        game = BoxGame(2)
        progs = build_replay_programs(game.advance, 9, 8, donate=False)
        carry0 = progs.init_carry(game.init_state(), jnp.zeros((2,), jnp.uint8))
        j = jax.make_jaxpr(progs.scan_steady)(
            carry0, jnp.zeros((32, 2), jnp.uint8), np.int32(9)
        )
        counts = _walk_primitives(j)
        assert counts["scan"] == 2, counts["scan"]
        assert counts.get("while", 0) == 0
        assert counts.get("cond", 0) == 0
        total = sum(counts.values())
        assert total < 850, (
            f"steady tick program grew to {total} equations (was ~419); "
            f"check for lost fusion structure or runaway unrolling"
        )


class TestBatchedSessionsPins:
    @pytest.fixture()
    def batched(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual mesh")
        game = BoxGame(2)
        return game, BatchedSessions(
            game.advance, game.init_state(), jnp.zeros((2,), jnp.uint8),
            batch_size=16, mesh=make_mesh(8),
            check_distance=8, max_prediction=8,
        )

    def test_steady_chunk_is_exactly_one_dispatch(self, batched):
        _, bs = batched
        chunk = np.zeros((16, 32, 2), np.uint8)
        bs.run_ticks(chunk, check=False)  # warmup split
        calls = {"steady": 0, "warmup": 0}
        orig_steady, orig_warm = bs._run_steady, bs._run_warmup
        bs._run_steady = lambda *a: (
            calls.__setitem__("steady", calls["steady"] + 1) or orig_steady(*a)
        )
        bs._run_warmup = lambda *a: (
            calls.__setitem__("warmup", calls["warmup"] + 1) or orig_warm(*a)
        )
        try:
            for _ in range(3):
                bs.run_ticks(chunk, check=False)
        finally:
            bs._run_steady, bs._run_warmup = orig_steady, orig_warm
        assert calls == {"steady": 3, "warmup": 0}, calls
        stats = bs.verify()
        assert stats["mismatches"] == 0

    def test_sharded_steady_program_shape(self, batched):
        """The whole-pool tick lowers to ONE fused program: a single
        top-level while (the ticks scan), bounded size, and the two on-mesh
        stat reductions (psum/pmin) — no extra collectives."""
        _, bs = batched
        chunk = jnp.zeros((16, 32, 2), jnp.uint8)
        txt = bs._run_steady.lower(
            bs._carry, chunk, np.int32(9)
        ).as_text()
        lines = len(txt.splitlines())
        # exactly the two loops of the design: the outer ticks scan and the
        # (rolled, round-4 retune) inner resim scan — anything more means
        # the program split
        assert 1 <= txt.count("stablehlo.while") <= 2, "tick scan must stay fused"
        assert lines < 2000, (
            f"sharded tick program grew to {lines} stablehlo lines "
            f"(was ~950); check for structure loss"
        )
        # collectives: exactly the two stat reductions ride the mesh
        assert txt.count("all_reduce") <= 2, "unexpected extra collectives"
