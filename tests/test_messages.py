"""Wire message round-trip and hardening tests."""

import pytest

pytest.importorskip("hypothesis")  # fuzz-only dep: absent on lean CI images

from hypothesis import example, given, settings
from hypothesis import strategies as st

from ggrs_tpu.net.messages import (
    ChecksumReport,
    ConnectionStatus,
    InputAck,
    InputMessage,
    KeepAlive,
    Message,
    QualityReply,
    QualityReport,
    SyncReply,
    SyncRequest,
)
from ggrs_tpu.net.wire import WireError


def roundtrip(msg: Message) -> Message:
    return Message.decode(msg.encode())


def test_keep_alive_roundtrip():
    m = roundtrip(Message(magic=7, body=KeepAlive()))
    assert m.magic == 7
    assert isinstance(m.body, KeepAlive)


def test_input_roundtrip():
    body = InputMessage(
        peer_connect_status=[
            ConnectionStatus(False, 10),
            ConnectionStatus(True, -1),
        ],
        disconnect_requested=False,
        start_frame=5,
        ack_frame=-1,
        bytes=b"\x01\x02\x03",
    )
    m = roundtrip(Message(magic=0xABCD, body=body))
    assert m.body == body


def test_quality_roundtrip():
    m = roundtrip(Message(magic=1, body=QualityReport(frame_advantage=-3, ping=123456)))
    assert m.body == QualityReport(frame_advantage=-3, ping=123456)
    m = roundtrip(Message(magic=1, body=QualityReply(pong=42)))
    assert m.body == QualityReply(pong=42)


def test_input_ack_roundtrip():
    m = roundtrip(Message(magic=1, body=InputAck(ack_frame=99)))
    assert m.body == InputAck(ack_frame=99)


def test_sync_messages_roundtrip():
    m = roundtrip(Message(magic=1, body=SyncRequest(random=0xDEADBEEF)))
    assert m.body == SyncRequest(random=0xDEADBEEF)
    m = roundtrip(Message(magic=1, body=SyncReply(random=1)))
    assert m.body == SyncReply(random=1)


def test_checksum_report_roundtrip_u128():
    checksum = (1 << 127) | 12345
    m = roundtrip(Message(magic=1, body=ChecksumReport(checksum=checksum, frame=200)))
    assert m.body == ChecksumReport(checksum=checksum, frame=200)


# Committed regression seeds (analog of proptest-regressions/): replay on
# every checkout before hypothesis generates novel cases.
@settings(max_examples=300)
@given(data=st.binary(max_size=256))
@example(data=b"")
@example(data=b"\xaa\xbb\x63")  # unknown tag
@example(data=b"\xaa\xbb\x00\x41")  # input msg claiming 65 statuses
@example(data=b"\xaa\xbb\x00\x01\x02")  # invalid bool byte in status
@example(data=b"\xaa\xbb\x01" + b"\xff" * 9 + b"\x01")  # 10-byte varint ack
@example(data=b"\xaa\xbb\x05\x00")  # keepalive with trailing byte
@example(data=b"\xaa\xbb\x00\x00\x00\x00\x00\x05abc")  # payload len > data
def test_decode_arbitrary_bytes_never_crashes(data):
    try:
        Message.decode(data)
    except WireError:
        pass


def test_trailing_garbage_rejected():
    buf = Message(magic=1, body=KeepAlive()).encode() + b"\x00"
    with pytest.raises(WireError):
        Message.decode(buf)
