"""Lazy device checksums and the fused speculation dispatches.

Round-3 perf redesign contract: the executor's save path attaches
``DeviceChecksum`` handles (no device→host read until the value is actually
consumed), and speculation's steady-state tick / rollback fulfillment are
single fused dispatches whose results are bit-identical to the unfused
primitives they replaced."""

import numpy as np

import jax
import jax.numpy as jnp

from ggrs_tpu.core.sync_layer import GameStateCell
from ggrs_tpu.games import BoxGame
from ggrs_tpu.ops import pytree_checksum
from ggrs_tpu.ops.checksum import DeviceChecksum, checksum_device
from ggrs_tpu.parallel import SpeculativeRollback


class TestDeviceChecksum:
    def test_materializes_to_pytree_checksum(self):
        state = BoxGame(2).init_state()
        lazy = DeviceChecksum(checksum_device(state))
        assert lazy.materialize() == pytree_checksum(state)
        assert int(lazy) == pytree_checksum(state)  # cached second read

    def test_cell_accepts_lazy_and_property_materializes(self):
        state = BoxGame(2).init_state()
        cell = GameStateCell()
        cell.save(7, state, DeviceChecksum(checksum_device(state)))
        got = cell.checksum
        assert isinstance(got, int)
        assert got == pytree_checksum(state)
        assert 0 <= got < (1 << 128)

    def test_cell_still_validates_int_range(self):
        cell = GameStateCell()
        try:
            cell.save(1, None, 1 << 128)
        except ValueError:
            pass
        else:  # pragma: no cover
            raise AssertionError("expected ValueError for out-of-range int")

    def test_equality_against_plain_int(self):
        state = BoxGame(2).init_state()
        lazy = DeviceChecksum(checksum_device(state))
        assert lazy == pytree_checksum(state)


def _mk_spec(game, K=3):
    candidates = np.asarray([0, 4, 8], np.uint8)

    def branch_inputs(k, frame, local_inputs):
        out = np.array(np.asarray(local_inputs), np.uint8, copy=True)
        out[1] = candidates[k]
        return out

    return SpeculativeRollback(game.advance, K, branch_inputs, max_window=8)


class TestFusedSpeculation:
    def test_advance_and_extend_matches_separate_calls(self):
        game = BoxGame(2)
        state = game.init_state()
        spec_a, spec_b = _mk_spec(game), _mk_spec(game)
        spec_a.root(0, state)
        spec_b.root(0, state)

        live_a = state
        live_b = state
        for i in range(4):
            inp = np.asarray([i % 3, 4], np.uint8)
            fused = spec_a.advance_and_extend(live_a, inp)
            assert fused is not None
            live_a = fused
            live_b = game.advance(live_b, inp)
            spec_b.extend(inp)

        assert spec_a.window == spec_b.window == 4
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(
                np.asarray(live_a[k]), np.asarray(live_b[k]), err_msg=k
            )
        # both windows resolve identically (remote candidate 4 was correct)
        confirmed = [
            np.asarray([i % 3, 4], np.uint8) for i in range(4)
        ]
        ta = spec_a.resolve(0, confirmed)
        tb = spec_b.resolve(0, confirmed)
        assert ta is not None and tb is not None
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(
                np.asarray(ta[-1][k]), np.asarray(tb[-1][k]), err_msg=k
            )

    def test_advance_and_extend_none_when_unrooted_or_full(self):
        game = BoxGame(2)
        state = game.init_state()
        spec = _mk_spec(game)
        inp = np.asarray([1, 4], np.uint8)
        assert spec.advance_and_extend(state, inp) is None  # unrooted
        spec.root(0, state)
        for _ in range(8):
            assert spec.advance_and_extend(state, inp) is not None
        assert spec.window == 8
        assert spec.advance_and_extend(state, inp) is None  # window full

    def test_fulfill_hit_matches_replay_and_counts(self):
        game = BoxGame(2)
        state = game.init_state()
        spec = _mk_spec(game)
        spec.root(0, state)
        seq = [np.asarray([i, 4], np.uint8) for i in (1, 2, 3)]
        for s in seq:
            spec.extend(s)

        assert spec.window_valid(0, 3)
        steps, sums = spec.fulfill(0, seq, state, with_checksums=True)
        assert len(steps) == 3 and len(sums) == 3
        truth = state
        for t, s in enumerate(seq):
            truth = game.advance(truth, s)
            for k in ("pos", "vel", "rot"):
                np.testing.assert_array_equal(
                    np.asarray(steps[t][k]), np.asarray(truth[k]), err_msg=k
                )
            assert DeviceChecksum(sums[t]) == pytree_checksum(truth)
        assert spec.hits == 1

    def test_fulfill_miss_replays_from_load_state(self):
        game = BoxGame(2)
        state = game.init_state()
        spec = _mk_spec(game)
        spec.root(0, state)
        hyp = [np.asarray([1, 4], np.uint8)]
        spec.extend(hyp[0])
        # confirmed remote input 15 matches no candidate: the fused cond must
        # fall back to replaying load_state under the confirmed inputs
        confirmed = [np.asarray([1, 15], np.uint8)]
        steps, _ = spec.fulfill(0, confirmed, state, with_checksums=False)
        truth = game.advance(state, confirmed[0])
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(
                np.asarray(steps[0][k]), np.asarray(truth[k]), err_msg=k
            )
        assert spec.hits == 0

    def test_refill_reanchors_window(self):
        game = BoxGame(2)
        state = game.init_state()
        spec = _mk_spec(game)
        spec.root(0, state)
        seq = [np.asarray([i, 4], np.uint8) for i in (1, 2, 3)]
        for s in seq:
            spec.extend(s)
        steps, _ = spec.fulfill(0, seq, state, with_checksums=False)
        # re-anchor at frame 1 with the remaining tail hypothesized again
        spec.refill(1, steps[0], seq[1:])
        assert spec.root_frame == 1 and spec.window == 2
        # the refilled window must resolve the same tail
        traj = spec.resolve(1, seq[1:])
        assert traj is not None
        truth = state
        for s in seq:
            truth = game.advance(truth, s)
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(
                np.asarray(traj[-1][k]), np.asarray(truth[k]), err_msg=k
            )
