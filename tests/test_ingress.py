"""The ingress plane (DESIGN.md §26): stable virtual match endpoints.

Three layers, mirroring the module:

- the wire codec — ``FWD_HEADER`` / ``ROUTE_UPDATE`` pack/unpack and the
  ``WireError`` refusal matrix (one decoder judges the RPC op and the
  in-process path alike);
- the in-process :class:`IngressNode` dataplane — forwarding through a
  claimed virtual endpoint over real loopback UDP, the route fence
  (stale-epoch / stale-version refusals that survive DEL), and the
  dataplane fence (only the CURRENT route's leg may speak as the
  endpoint; unclaimed peers never hear replies);
- :class:`VirtualEndpointSocket` — the serving-host leg wraps/unwraps
  transparently, and an end-to-end adopted ``shard_runner.py --ingress
  --tcp`` serves the same control surface over the §25 link.

The cross-host failover/migration scenarios behind the ingress live in
tests/test_placement.py and scripts/chaos.py --fault net.
"""

from __future__ import annotations

import socket
import time

import pytest

from ggrs_tpu.core.errors import InvalidRequest
from ggrs_tpu.fleet import FleetTuning
from ggrs_tpu.fleet.ingress import (
    FWD_HEADER,
    FWD_VERSION,
    INGRESS_MAGIC,
    IngressHandle,
    IngressNode,
    ROUTE_OP_DEL,
    ROUTE_OP_PUT,
    ROUTE_UPDATE,
    ROUTE_WIRE_VERSION,
    VirtualEndpointSocket,
    decode_route_update,
    encode_route_update,
    pack_fwd,
    unpack_fwd,
)
from ggrs_tpu.net.wire import WireError
from ggrs_tpu.obs import Registry
from ggrs_tpu.obs.timeline import (
    ZERO_TRACE_CTX,
    match_trace_id,
    pack_trace_ctx,
    unpack_trace_ctx,
)


# ----------------------------------------------------------------------
# the wire codec
# ----------------------------------------------------------------------


class TestRouteUpdateCodec:
    def test_round_trip_put(self):
        data = encode_route_update(
            ROUTE_OP_PUT, 3, 17, 9, ("127.0.0.1", 40001))
        assert len(data) == ROUTE_UPDATE.size == 44
        op, epoch, version, vport, dst, ctx = decode_route_update(data)
        assert (op, epoch, version, vport) == (ROUTE_OP_PUT, 3, 17, 9)
        assert dst == ("127.0.0.1", 40001)
        assert ctx == ZERO_TRACE_CTX  # no causal stamp by default

    def test_round_trip_del(self):
        data = encode_route_update(
            ROUTE_OP_DEL, 1, 2, 5, ("10.0.0.7", 0))
        op, epoch, version, vport, dst, _ = decode_route_update(data)
        assert op == ROUTE_OP_DEL and dst == ("10.0.0.7", 0)

    def test_trace_ctx_rides_the_frame(self):
        # §28: the 16-byte trace context survives the wire round trip
        # and carries the match's stable trace hash + epoch + span
        ctx = pack_trace_ctx("m7", 3, 12)
        data = encode_route_update(
            ROUTE_OP_PUT, 3, 18, 9, ("127.0.0.1", 40001), ctx)
        *_, got = decode_route_update(data)
        assert got == ctx
        trace, epoch, span = unpack_trace_ctx(got)
        assert trace == match_trace_id("m7")
        assert (epoch, span) == (3, 12)

    def test_short_frame_refused(self):
        with pytest.raises(WireError, match="bytes"):
            decode_route_update(b"GI\x01\x01")

    def test_bad_magic_refused(self):
        data = bytearray(encode_route_update(
            ROUTE_OP_PUT, 1, 1, 1, ("127.0.0.1", 1)))
        data[:2] = b"XX"
        with pytest.raises(WireError, match="magic"):
            decode_route_update(bytes(data))

    def test_unknown_version_refused(self):
        data = bytearray(encode_route_update(
            ROUTE_OP_PUT, 1, 1, 1, ("127.0.0.1", 1)))
        data[2] = ROUTE_WIRE_VERSION + 1
        with pytest.raises(WireError, match="version"):
            decode_route_update(bytes(data))

    def test_unknown_op_refused(self):
        data = bytearray(encode_route_update(
            ROUTE_OP_PUT, 1, 1, 1, ("127.0.0.1", 1)))
        data[3] = 9
        with pytest.raises(WireError, match="op"):
            decode_route_update(bytes(data))


class TestFwdCodec:
    def test_round_trip(self):
        data = pack_fwd(7, ("192.168.1.20", 5555), b"payload!")
        assert data[:FWD_HEADER.size] == FWD_HEADER.pack(
            INGRESS_MAGIC, FWD_VERSION, 0, 7, 5555,
            socket.inet_aton("192.168.1.20"))
        vport, peer, payload = unpack_fwd(data)
        assert vport == 7
        assert peer == ("192.168.1.20", 5555)
        assert payload == b"payload!"

    def test_empty_payload(self):
        vport, peer, payload = unpack_fwd(pack_fwd(1, ("1.2.3.4", 9), b""))
        assert payload == b""

    def test_short_frame_refused(self):
        with pytest.raises(WireError, match="short"):
            unpack_fwd(b"GI\x01")

    def test_bad_magic_refused(self):
        data = b"XY" + pack_fwd(1, ("1.2.3.4", 9), b"x")[2:]
        with pytest.raises(WireError, match="magic"):
            unpack_fwd(data)

    def test_unknown_version_refused(self):
        data = bytearray(pack_fwd(1, ("1.2.3.4", 9), b"x"))
        data[2] = FWD_VERSION + 1
        with pytest.raises(WireError, match="version"):
            unpack_fwd(bytes(data))


# ----------------------------------------------------------------------
# the in-process dataplane
# ----------------------------------------------------------------------


def _udp(port: int = 0) -> socket.socket:
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", port))
    s.setblocking(False)
    return s


def _recv(sock: socket.socket, timeout: float = 2.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            return sock.recvfrom(65535)
        except BlockingIOError:
            time.sleep(0.002)
    raise AssertionError("no datagram arrived")


@pytest.fixture
def node():
    n = IngressNode(metrics=Registry())
    yield n
    n.close()


def _route(node, vport, leg_addr, epoch=1, version=None, op=ROUTE_OP_PUT):
    if version is None:
        _route.v += 1
        version = _route.v
    return node.apply_route_update(
        encode_route_update(op, epoch, version, vport, leg_addr))


_route.v = 0


class TestIngressNodeForwarding:
    def test_forwarded_round_trip(self, node):
        peer, leg = _udp(), _udp()
        try:
            vport = node.allocate_endpoint(peers=[peer.getsockname()])
            assert _route(node, vport, leg.getsockname()) == "ok"
            # inbound: peer -> public port -> FWD-framed to the leg
            peer.sendto(b"hello-in", node.public_addr())
            node.pump()
            data, src = _recv(leg)
            got_vport, got_peer, payload = unpack_fwd(data)
            assert got_vport == vport
            assert got_peer == peer.getsockname()
            assert payload == b"hello-in"
            # outbound: leg reply -> uplink -> peer, FROM the public
            # address (the stable-endpoint contract)
            leg.sendto(pack_fwd(vport, got_peer, b"hello-out"), src)
            node.pump()
            data, reply_src = _recv(peer)
            assert data == b"hello-out"
            assert reply_src == node.public_addr()
            assert node.forwarded == {"in": 1, "out": 1}
        finally:
            peer.close()
            leg.close()

    def test_unrouted_vport_drops(self, node):
        peer = _udp()
        try:
            node.allocate_endpoint(peers=[peer.getsockname()])
            peer.sendto(b"early", node.public_addr())
            node.pump()
            assert node.dropped.get("no-route") == 1
        finally:
            peer.close()

    def test_fenced_sender_cannot_speak(self, node):
        """After a route flip the OLD leg's replies are dropped: only
        the current route's registered address may speak as the
        endpoint — a fenced incarnation still breathing stays mute."""
        peer, old_leg, new_leg = _udp(), _udp(), _udp()
        try:
            vport = node.allocate_endpoint(peers=[peer.getsockname()])
            assert _route(node, vport, old_leg.getsockname()) == "ok"
            assert _route(node, vport, new_leg.getsockname()) == "ok"
            assert node.flips == 1
            old_leg.sendto(
                pack_fwd(vport, peer.getsockname(), b"stale!"),
                node.uplink_addr())
            node.pump()
            assert node.dropped.get("fenced-sender") == 1
            with pytest.raises(AssertionError):
                _recv(peer, timeout=0.05)
        finally:
            peer.close()
            old_leg.close()
            new_leg.close()

    def test_unclaimed_peer_never_hears(self, node):
        leg, stranger = _udp(), _udp()
        try:
            vport = node.allocate_endpoint()
            assert _route(node, vport, leg.getsockname()) == "ok"
            leg.sendto(
                pack_fwd(vport, stranger.getsockname(), b"psst"),
                node.uplink_addr())
            node.pump()
            assert node.dropped.get("unclaimed-peer") == 1
        finally:
            leg.close()
            stranger.close()

    def test_claim_unknown_vport_refused(self, node):
        with pytest.raises(InvalidRequest, match="no virtual endpoint"):
            node.claim_peers(42, [("127.0.0.1", 1)])


class TestRouteFence:
    def test_stale_epoch_refused(self, node):
        leg = ("127.0.0.1", 40000)
        vport = node.allocate_endpoint()
        assert _route(node, vport, leg, epoch=2) == "ok"
        assert _route(node, vport, ("127.0.0.1", 40001),
                      epoch=1) == "stale-epoch"
        assert node._routes[vport].dst == leg

    def test_stale_version_refused(self, node):
        vport = node.allocate_endpoint()
        assert _route(node, vport, ("127.0.0.1", 40000),
                      epoch=1, version=5) == "ok"
        assert _route(node, vport, ("127.0.0.1", 40001),
                      epoch=1, version=5) == "stale-version"
        assert _route(node, vport, ("127.0.0.1", 40001),
                      epoch=1, version=4) == "stale-version"
        # strictly newer wins (same epoch)
        assert _route(node, vport, ("127.0.0.1", 40001),
                      epoch=1, version=6) == "ok"

    def test_fence_survives_delete(self, node):
        """A late PUT from a dead epoch stays refused even after its
        route was deleted — the floor outlives the entry."""
        vport = node.allocate_endpoint()
        assert _route(node, vport, ("127.0.0.1", 40000),
                      epoch=2, version=10) == "ok"
        assert _route(node, vport, ("127.0.0.1", 40000),
                      epoch=2, version=11, op=ROUTE_OP_DEL) == "ok"
        assert vport not in node._routes
        assert _route(node, vport, ("127.0.0.1", 40666),
                      epoch=1, version=99) == "stale-epoch"
        assert vport not in node._routes

    def test_unknown_vport_and_garbage(self, node):
        assert _route(node, 777, ("127.0.0.1", 1)) == "unknown-vport"
        assert node.apply_route_update(b"junk") == "bad-frame"
        assert node.route_updates == {"unknown-vport": 1, "bad-frame": 1}

    def test_verdicts_counted(self, node):
        vport = node.allocate_endpoint()
        _route(node, vport, ("127.0.0.1", 40000), epoch=2)
        _route(node, vport, ("127.0.0.1", 40001), epoch=1)
        reg = node.metrics
        assert reg.value("ggrs_ingress_route_updates_total",
                         verdict="ok") == 1
        assert reg.value("ggrs_ingress_route_updates_total",
                         verdict="stale-epoch") == 1


# ----------------------------------------------------------------------
# the serving-host leg
# ----------------------------------------------------------------------


class TestVirtualEndpointSocket:
    def test_wraps_and_unwraps(self):
        uplink = _udp()
        try:
            up_host, up_port = uplink.getsockname()
            leg = VirtualEndpointSocket(up_host, up_port, vport=5)
            try:
                peer = ("203.0.113.9", 7777)
                leg.send_datagram(b"to-peer", peer)
                data, src = _recv(uplink)
                assert unpack_fwd(data) == (5, peer, b"to-peer")
                assert src[1] == leg.local_port()
                # inbound: only FWD frames from the uplink, our vport
                uplink.sendto(pack_fwd(5, peer, b"from-peer"),
                              ("127.0.0.1", leg.local_port()))
                uplink.sendto(pack_fwd(6, peer, b"wrong-vport"),
                              ("127.0.0.1", leg.local_port()))
                deadline = time.monotonic() + 2.0
                got = []
                while not got and time.monotonic() < deadline:
                    got = leg.receive_all_datagrams()
                assert got == [(peer, b"from-peer")]
            finally:
                leg.close()
        finally:
            uplink.close()

    def test_batch_send(self):
        uplink = _udp()
        try:
            up_host, up_port = uplink.getsockname()
            leg = VirtualEndpointSocket(up_host, up_port, vport=3)
            try:
                leg.send_datagram_batch([
                    (b"a", ("1.2.3.4", 10)), (b"b", ("1.2.3.4", 11)),
                ])
                seen = {unpack_fwd(_recv(uplink)[0]) for _ in range(2)}
                assert seen == {
                    (3, ("1.2.3.4", 10), b"a"), (3, ("1.2.3.4", 11), b"b"),
                }
            finally:
                leg.close()
        finally:
            uplink.close()

    def test_stranger_datagrams_ignored(self):
        uplink, stranger = _udp(), _udp()
        try:
            up_host, up_port = uplink.getsockname()
            leg = VirtualEndpointSocket(up_host, up_port, vport=1)
            try:
                stranger.sendto(pack_fwd(1, ("1.2.3.4", 9), b"forged"),
                                ("127.0.0.1", leg.local_port()))
                time.sleep(0.05)
                assert leg.receive_all_datagrams() == []
            finally:
                leg.close()
        finally:
            uplink.close()
            stranger.close()


# ----------------------------------------------------------------------
# end to end: an adopted ingress runner over the §25 TCP link
# ----------------------------------------------------------------------


class TestIngressRunnerE2E:
    def test_spawned_runner_serves_control_and_dataplane(self):
        tuning = FleetTuning(
            heartbeat_interval_s=0.05, heartbeat_deadline_s=1.0,
            rpc_timeout_s=5.0, spawn_timeout_s=120.0,
            drain_deadline_s=2.0,
            link_auth_token="ingress-e2e-token",
            link_reconnect_window_s=2.0, link_backoff_s=0.01,
            link_handshake_timeout_s=1.0,
        )
        handle = IngressHandle("ing0", tuning=tuning, metrics=Registry(),
                               spawn_child=True)
        peer = leg = None
        try:
            hello = handle.adopt()
            assert hello["role"] == "ingress"
            public = handle.public_addr()
            uplink = handle.uplink_addr()
            assert public is not None and uplink is not None
            peer, leg = _udp(), _udp()
            vport = handle.allocate_endpoint(peers=[peer.getsockname()])
            assert handle.apply_route_update(encode_route_update(
                ROUTE_OP_PUT, 1, 1, vport, leg.getsockname())) == "ok"
            # the dataplane lives in the CHILD's select loop: no local
            # pump — the forwarded frame just arrives
            peer.sendto(b"over-the-wall", tuple(public))
            data, src = _recv(leg, timeout=10.0)
            got_vport, got_peer, payload = unpack_fwd(data)
            assert (got_vport, payload) == (vport, b"over-the-wall")
            leg.sendto(pack_fwd(vport, got_peer, b"and-back"), src)
            data, reply_src = _recv(peer, timeout=10.0)
            assert data == b"and-back"
            assert reply_src == tuple(public)
            # the fence judges identically over RPC
            assert handle.apply_route_update(encode_route_update(
                ROUTE_OP_PUT, 0, 99, vport,
                leg.getsockname())) == "stale-epoch"
            info = handle.info()
            assert info["routes"] == 1
            assert info["forwarded"]["in"] >= 1
            assert info["forwarded"]["out"] >= 1
        finally:
            for s in (peer, leg):
                if s is not None:
                    s.close()
            handle.close()
