"""ggrs-model pillar 4, the machines half: the tree's real §9/§16/§17
models and the MODEL_CATALOG expectations.

The load-bearing pins:
- the pre-PR-11 checkpoint-ordering fixture must REDISCOVER the
  shard_migrate desync (DESIGN.md §20.4) as its shortest
  counterexample — that bug cost a full PR to diagnose by chaos
  testing, and is this plane's reason to exist;
- every HEAD model explores invariant-clean;
- fixture counterexamples replay (they are runs, not pretty-prints);
- the whole catalog fits the build_sanitized.sh 60s budget with
  orders of magnitude to spare.
"""

import time
from pathlib import Path

import pytest

from ggrs_tpu.analysis import check, replay
from ggrs_tpu.analysis.machines import (
    MODEL_CATALOG,
    check_models,
    checkpoint_order_model,
    durable_before_send_model,
    reconvergence_model,
    supervision_model,
    watchdog_model,
)

REPO = Path(__file__).resolve().parents[1]


def actions_of(result):
    return [s.action for s in result.trace[1:]]


class TestCatalog:
    def test_catalog_is_clean_and_fast(self):
        t0 = time.monotonic()
        findings, results = check_models(REPO)
        elapsed = time.monotonic() - t0
        assert findings == []
        assert len(results) == len(MODEL_CATALOG) == 18
        assert elapsed < 60.0  # the build_sanitized.sh budget
        by_name = {r["model"]: r for r in results}
        heads = [n for n in by_name if n.endswith(":head")
                 or n in ("supervision", "lifecycle")]
        assert all(by_name[n]["kind"] == "clean" for n in heads)

    def test_fixture_traces_are_embedded(self):
        _, results = check_models(REPO)
        by_name = {r["model"]: r for r in results}
        fix = by_name["checkpoint-order:pre-pr11"]
        assert fix["kind"] == "invariant"
        assert [s["action"] for s in fix["trace"][1:]] == [
            "advance_rollback", "checkpoint", "crash_failover",
        ]
        assert fix["trace"][-1]["state"]["desynced"] is True

    def test_budget_exhaustion_is_a_finding(self):
        findings, results = check_models(REPO, max_states=3)
        assert findings  # expectation broken: "budget" != clean
        assert all(f.rule == "model/expectation" for f in findings)
        assert any(r["kind"] == "budget" for r in results)


class TestCheckpointOrdering:
    def test_pre_pr11_rediscovers_the_shard_migrate_desync(self):
        r = check(checkpoint_order_model("pre-pr11"))
        assert not r.ok and r.kind == "invariant"
        assert r.violation == "resume-on-chain"
        # SHORTEST counterexample: rollback-advance, checkpoint inside
        # the mispredicted-cell window, failover from that checkpoint
        assert actions_of(r) == [
            "advance_rollback", "checkpoint", "crash_failover",
        ]
        final = replay(checkpoint_order_model("pre-pr11"), r.trace)
        assert final.desynced and final.ckpt == "poisoned"

    def test_head_ordering_is_clean(self):
        r = check(checkpoint_order_model("head"))
        assert r.ok, r.describe()


class TestDurableBeforeSend:
    def test_no_barrier_loses_the_wire(self):
        r = check(durable_before_send_model(False))
        assert not r.ok and r.violation == "journal-covers-the-wire"
        assert actions_of(r) == [
            "stage_local", "send_tick", "crash_resume",
        ]

    def test_barrier_is_clean(self):
        assert check(durable_before_send_model(True)).ok


class TestAckRebase:
    def test_threshold_three_survives_reordering(self):
        assert check(reconvergence_model()).ok

    def test_threshold_one_rebases_on_a_duplicate(self):
        r = check(reconvergence_model(1))
        assert not r.ok and r.violation == "no-rebase-on-reorder"
        assert actions_of(r) == ["reorder_dup", "rebase"]


class TestWatchdog:
    def test_head_watchdog_is_clean(self):
        r = check(watchdog_model(REPO))
        assert r.ok, r.describe()
        # the wedged-but-still-sending runner is actually in the state
        # space: depth must exceed the trivial kill path
        assert r.depth >= 8

    def test_premature_failover_is_caught(self):
        r = check(watchdog_model(REPO, premature_failover=True))
        assert not r.ok
        assert r.violation == "failover-only-after-confirmed-death"
        assert actions_of(r) == ["sigterm", "failover_premature"]


class TestSourceCoupling:
    def test_supervision_model_tracks_the_declared_table(self, tmp_path):
        # the model is BUILT from the parsed SLOT_TRANSITIONS table;
        # a tree without the table must fail loudly, not model a stale
        # hardcoded copy
        from ggrs_tpu.analysis import ModelError
        with pytest.raises(ModelError):
            supervision_model(tmp_path)

    def test_supervision_head_is_clean(self):
        r = check(supervision_model(REPO))
        assert r.ok, r.describe()
