"""Parity pin: the native sync core (native/sync_core.cpp) must be
indistinguishable from the Python InputQueue/SyncLayer mechanism across the
whole operation surface — landed frames, synchronized inputs + statuses,
confirmed inputs, first-incorrect tracking, watermark discard behavior,
delay grow/shrink, disconnects, and the error paths.

Method: drive a native-core SyncLayer and a Python-core SyncLayer through
identical randomized operation sequences and compare every observable after
every operation.  Mirrors the role tests/test_native_endpoint.py plays for
the endpoint datapath.
"""

from __future__ import annotations

import random

import pytest

from ggrs_tpu.core.config import Config, PredictDefault
from ggrs_tpu.core.frame_info import PlayerInput
from ggrs_tpu.core.sync_layer import SyncLayer, _native_sync_eligible
from ggrs_tpu.core.types import NULL_FRAME
from ggrs_tpu.net import _native
from ggrs_tpu.net.messages import ConnectionStatus

pytestmark = pytest.mark.skipif(
    _native.sync_lib() is None, reason="native sync core unavailable"
)


def make_pair(players=2, max_prediction=8, bits=16):
    cfg = Config.for_uint(bits)
    nat = SyncLayer(cfg, players, max_prediction, use_native=True)
    py = SyncLayer(cfg, players, max_prediction, use_native=False)
    assert nat._native is not None, "native core did not engage"
    assert py._native is None
    return nat, py


class TestEligibility:
    def test_for_uint_is_eligible(self):
        assert _native_sync_eligible(Config.for_uint(8))

    def test_custom_predictor_not_eligible(self):
        assert not _native_sync_eligible(
            Config.for_uint(8, predictor=PredictDefault())
        )

    def test_variable_size_not_eligible(self):
        assert not _native_sync_eligible(Config.for_bytes())

    def test_float_struct_not_eligible(self):
        assert not _native_sync_eligible(Config.for_struct("<fI"))

    def test_int_struct_eligible(self):
        assert _native_sync_eligible(Config.for_struct("<hI"))

    def test_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("GGRS_TPU_NO_NATIVE", "1")
        assert not _native_sync_eligible(Config.for_uint(8))


def assert_same_view(nat, py, status, frame_probe):
    """Compare every observable the session layer reads."""
    assert nat.check_simulation_consistency(NULL_FRAME) == \
        py.check_simulation_consistency(NULL_FRAME)
    for f in frame_probe:
        nat_exc = py_exc = None
        nat_val = py_val = None
        try:
            nat_val = nat.confirmed_input(0, f).input
        except AssertionError:
            nat_exc = True
        try:
            py_val = py.confirmed_input(0, f).input
        except AssertionError:
            py_exc = True
        assert nat_exc == py_exc, f"confirmed_input({f}) availability differs"
        assert nat_val == py_val


class TestRandomizedParity:
    @pytest.mark.parametrize("seed", [1, 7, 23, 99])
    def test_lockfree_stream(self, seed):
        """Remote inputs stream in while the local side runs ahead on
        predictions; occasional mispredictions trigger reset_prediction (as
        the session's rollback path would)."""
        rng = random.Random(seed)
        nat, py = make_pair(players=2, max_prediction=8)
        status = [ConnectionStatus(), ConnectionStatus()]
        remote_frame = -1
        for step in range(400):
            cur = nat.current_frame
            assert cur == py.current_frame
            # local input for current frame, always
            v = rng.randrange(0, 1 << 16)
            pi_n = PlayerInput(cur, v)
            pi_p = PlayerInput(cur, v)
            assert nat.add_local_input(0, pi_n) == py.add_local_input(0, pi_p)
            status[0].last_frame = cur
            # remote inputs arrive late and in bursts
            while remote_frame < cur - rng.randrange(0, 6) and remote_frame < cur:
                remote_frame += 1
                rv = rng.randrange(0, 1 << 16)
                nat.add_remote_input(1, PlayerInput(remote_frame, rv))
                py.add_remote_input(1, PlayerInput(remote_frame, rv))
                status[1].last_frame = remote_frame
            # the session resolves mispredictions (rollback) AFTER polling
            # remote inputs and BEFORE advancing — mirror that order
            fi_n = nat.check_simulation_consistency(NULL_FRAME)
            fi_p = py.check_simulation_consistency(NULL_FRAME)
            assert fi_n == fi_p
            if fi_n != NULL_FRAME:
                nat.reset_prediction()
                py.reset_prediction()
            ni = nat.synchronized_inputs(status)
            pi = py.synchronized_inputs(status)
            assert ni == pi, f"step {step}: {ni} != {pi}"
            nat.advance_frame()
            py.advance_frame()
            # raise the watermark like the session does
            confirmed = min(status[0].last_frame, status[1].last_frame)
            if confirmed > 0 and rng.random() < 0.5:
                nat.set_last_confirmed_frame(confirmed, sparse_saving=False)
                py.set_last_confirmed_frame(confirmed, sparse_saving=False)
                assert nat.last_confirmed_frame == py.last_confirmed_frame
            if step % 37 == 0:
                probe = [max(0, cur - 3), cur]
                assert_same_view(nat, py, status, probe)

    @pytest.mark.parametrize("seed", [5, 13])
    def test_delay_changes_and_disconnect(self, seed):
        rng = random.Random(seed)
        nat, py = make_pair(players=2, max_prediction=8)
        status = [ConnectionStatus(), ConnectionStatus()]
        for step in range(200):
            cur = nat.current_frame
            if step in (31, 90):  # grow, then shrink, player 0's delay
                d = 3 if step == 31 else 1
                nat.set_frame_delay(0, d)
                py.set_frame_delay(0, d)
            if step == 120:
                status[1].disconnected = True
            v = rng.randrange(0, 1 << 16)
            assert nat.add_local_input(0, PlayerInput(cur, v)) == \
                py.add_local_input(0, PlayerInput(cur, v))
            if not status[1].disconnected:
                rv = rng.randrange(0, 1 << 16)
                nat.add_remote_input(1, PlayerInput(cur, rv))
                py.add_remote_input(1, PlayerInput(cur, rv))
                status[1].last_frame = cur
            status[0].last_frame = cur
            ni = nat.synchronized_inputs(status)
            pi = py.synchronized_inputs(status)
            assert ni == pi, f"step {step}: {ni} != {pi}"
            nat.advance_frame()
            py.advance_frame()
            fi_n = nat.check_simulation_consistency(NULL_FRAME)
            assert fi_n == py.check_simulation_consistency(NULL_FRAME)
            if fi_n != NULL_FRAME:
                nat.reset_prediction()
                py.reset_prediction()

    def test_confirm_past_incorrect_raises_identically(self):
        nat, py = make_pair(players=1, max_prediction=8)
        status = [ConnectionStatus()]
        # go into prediction, then contradict it
        nat.add_local_input(0, PlayerInput(0, 1))
        py.add_local_input(0, PlayerInput(0, 1))
        status[0].last_frame = 0
        for layer in (nat, py):
            layer.synchronized_inputs(status)
            layer.advance_frame()
            layer.synchronized_inputs(status)  # predicted for frame 1
            layer.advance_frame()
        # reality disagrees with the repeat-last prediction at frame 1
        nat.add_remote_input(0, PlayerInput(1, 999))
        py.add_remote_input(0, PlayerInput(1, 999))
        assert nat.check_simulation_consistency(NULL_FRAME) == \
            py.check_simulation_consistency(NULL_FRAME) == 1
        with pytest.raises(AssertionError):
            nat.set_last_confirmed_frame(2, sparse_saving=False)
        with pytest.raises(AssertionError):
            py.set_last_confirmed_frame(2, sparse_saving=False)

    def test_input_during_pending_misprediction_raises_identically(self):
        nat, py = make_pair(players=1, max_prediction=8)
        status = [ConnectionStatus()]
        for layer in (nat, py):
            layer.synchronized_inputs(status)  # prediction from empty queue
            layer.advance_frame()
        nat.add_remote_input(0, PlayerInput(0, 7))
        py.add_remote_input(0, PlayerInput(0, 7))
        if nat.check_simulation_consistency(NULL_FRAME) != NULL_FRAME:
            with pytest.raises(AssertionError):
                nat.synchronized_inputs(status)
            with pytest.raises(AssertionError):
                py.synchronized_inputs(status)

    def test_queue_capacity_guard_raises_identically(self):
        """129 sequential inputs without a watermark raise in both cores
        rather than silently wrapping the 128-slot ring."""
        nat, py = make_pair(players=1, max_prediction=8)
        for layer in (nat, py):
            with pytest.raises(AssertionError):
                for i in range(200):
                    layer.add_remote_input(0, PlayerInput(i, i % 251))

    def test_force_native_on_ineligible_config_refuses(self):
        with pytest.raises(ValueError):
            SyncLayer(Config.for_bytes(), 1, 8, use_native=True)

    def test_string_struct_not_eligible(self):
        # '4s' packs b'ab' and b'ab\x00\x00' identically: not injective
        assert Config.for_struct("<4s").native_input_size is None
        assert Config.for_struct("<?").native_input_size is None
        assert Config.for_struct("<2hxx").native_input_size is not None

    @pytest.mark.parametrize("frame", [-5, -128, -129, -1000])
    def test_negative_frame_confirmed_input_raises_identically(self, frame):
        """C++ % on a negative frame used to index out of bounds (UB); both
        cores must refuse a negative frame the same loud way (ADVICE r5 /
        ISSUE 1 satellite)."""
        nat, py = make_pair(players=1, max_prediction=8)
        for layer in (nat, py):
            layer.add_remote_input(0, PlayerInput(0, 7))
            with pytest.raises(AssertionError):
                layer.confirmed_input(0, frame)

    def test_frame_minus_one_matches_blank_slot_identically(self):
        """The odd corner the naive guard would break: frame -1 lands (via
        Python's positive mod) on a still-blank slot whose tag IS -1, so
        both cores return the blank default instead of raising."""
        nat, py = make_pair(players=1, max_prediction=8)
        for layer in (nat, py):
            layer.add_remote_input(0, PlayerInput(0, 7))
        assert nat.confirmed_input(0, -1).input == \
            py.confirmed_input(0, -1).input == 0

    def test_negative_frame_confirmed_inputs_raises_identically(self):
        nat, py = make_pair(players=2, max_prediction=8)
        status = [ConnectionStatus(), ConnectionStatus()]
        for layer in (nat, py):
            layer.add_remote_input(0, PlayerInput(0, 1))
            layer.add_remote_input(1, PlayerInput(0, 2))
            with pytest.raises(AssertionError):
                layer.confirmed_inputs(-3, status)
