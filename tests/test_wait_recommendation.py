"""WaitRecommendation behavior through a live P2P pair: emission threshold,
60-frame cadence, skip_frames magnitude, and the throttling loop consuming
the recommendation (reference: /root/reference/src/sessions/p2p_session.rs:20-21,
804-817 and the example's slow-down loop, ex_game_p2p.rs:110-136).

The underlying frame-advantage averaging itself is covered by
tests/test_time_sync.py (parity with /root/reference/src/time_sync.rs:46-115).
"""

import random

from ggrs_tpu.core import Local, Remote, WaitRecommendation
from ggrs_tpu.net import InMemoryNetwork
from ggrs_tpu.sessions import SessionBuilder
from ggrs_tpu.sessions.p2p import MIN_RECOMMENDATION, RECOMMENDATION_INTERVAL

from stubs import GameStub, stub_config


class FakeClock:
    def __init__(self) -> None:
        self.now = 0

    def __call__(self) -> int:
        return self.now


def make_pair(clock):
    net = InMemoryNetwork()
    sessions = []
    for me, other, local_handle in (("A", "B", 0), ("B", "A", 1)):
        sessions.append(
            SessionBuilder(stub_config())
            .with_clock(clock)
            .with_rng(random.Random(71 + local_handle))
            .add_player(Local(), local_handle)
            .add_player(Remote(other), 1 - local_handle)
            .start_p2p_session(net.socket(me))
        )
    return sessions


def run_scenario(iterations, throttle):
    """A ticks every iteration; B starts 12 iterations late, then runs at the
    same rate — so A runs ahead until the prediction window caps it.  With
    ``throttle`` A honors each recommendation by skipping ``skip_frames``
    ticks, letting B catch up (the example's slow-down loop)."""
    clock = FakeClock()
    sess_a, sess_b = make_pair(clock)
    stub_a, stub_b = GameStub(), GameStub()

    rec_frames = []
    recs = []
    b_ticks = 0
    skip = 0
    for i in range(iterations):
        clock.now += 100  # generous: quality reports flow every other tick
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()

        for e in sess_a.events():
            if isinstance(e, WaitRecommendation):
                recs.append(e)
                rec_frames.append(sess_a.current_frame)
                if throttle:
                    skip = e.skip_frames
        if skip > 0:
            skip -= 1
        else:
            sess_a.add_local_input(0, i % 4)
            stub_a.handle_requests(sess_a.advance_frame())

        if i >= 12:
            sess_b.add_local_input(1, b_ticks % 4)
            stub_b.handle_requests(sess_b.advance_frame())
            b_ticks += 1
    return sess_a, recs, rec_frames


def test_recommendations_fire_with_threshold_and_cadence():
    sess_a, recs, rec_frames = run_scenario(300, throttle=False)
    assert len(recs) >= 3, "a peer running ahead must be told to wait"
    # magnitude: always at least the minimum advantage that triggers it
    assert all(r.skip_frames >= MIN_RECOMMENDATION for r in recs)
    # cadence: at most one recommendation per 60-frame interval
    gaps = [b - a for a, b in zip(rec_frames, rec_frames[1:])]
    assert all(g >= RECOMMENDATION_INTERVAL for g in gaps), gaps
    # the session's own ahead-ness metric agrees
    assert sess_a.frames_ahead() >= MIN_RECOMMENDATION


def test_throttling_consumes_recommendation():
    sess_a, recs, _ = run_scenario(300, throttle=True)
    # honoring the waits lets the late peer catch up: after the initial
    # transient, recommendations stop and the advantage falls below threshold
    assert 1 <= len(recs) <= 2, [r.skip_frames for r in recs]
    assert sess_a.frames_ahead() < MIN_RECOMMENDATION


def test_no_recommendation_when_in_sync():
    clock = FakeClock()
    sess_a, sess_b = make_pair(clock)
    stub_a, stub_b = GameStub(), GameStub()
    recs = []
    for i in range(150):
        clock.now += 100
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()
        recs += [e for e in sess_a.events() if isinstance(e, WaitRecommendation)]
        recs += [e for e in sess_b.events() if isinstance(e, WaitRecommendation)]
        sess_a.add_local_input(0, i % 4)
        stub_a.handle_requests(sess_a.advance_frame())
        sess_b.add_local_input(1, i % 3)
        stub_b.handle_requests(sess_b.advance_frame())
    assert recs == []
