"""Journal + replay pins (DESIGN.md §13): write → reopen →
``ReplaySession`` reproduces the original request stream bit-identically
(including seek-to-checkpoint), the crc chain catches corruption by
recovering exactly the intact prefix, and the fused device scrub
(``ops.replay.build_scrub_program``) advances a journal window in one
dispatch to the same state as per-frame playback.
"""

from __future__ import annotations

import numpy as np
import pytest

from ggrs_tpu.broadcast import (
    JournalError,
    JournalExhausted,
    MatchJournal,
    read_journal,
)
from ggrs_tpu.chaos import drive_broadcast
from ggrs_tpu.core.config import Config
from ggrs_tpu.core.types import InputStatus
from ggrs_tpu.net import _native
from ggrs_tpu.sessions import ReplaySession

needs_broadcast = pytest.mark.skipif(
    _native.broadcast_lib() is None,
    reason="native broadcast bank unavailable",
)

CFG = Config.for_uint(16)
ISIZE = CFG.native_input_size


def write_journal(path, frames, players=2, checkpoints=(), **kw):
    """Journal ``frames[i]`` = per-player int inputs for frame i."""
    j = MatchJournal(path, players, ISIZE, **kw)
    for f, row in enumerate(frames):
        for cf, state in checkpoints:
            if cf == f:
                j.append_checkpoint(cf, state)
        blob = b"".join(CFG.input_encode(v) for v in row)
        j.append_frames(f, [(bytes(players), blob)])
    j.close()
    return j


def drain(rs):
    out = []
    try:
        while True:
            for r in rs.advance_frame():
                out.append((rs.current_frame - 1, tuple(r.inputs)))
    except JournalExhausted:
        pass
    return out


class TestJournalRoundTrip:
    def test_synthetic_roundtrip_bit_identical(self, tmp_path):
        rng = np.random.default_rng(7)
        frames = rng.integers(0, 16, size=(200, 2)).tolist()
        path = tmp_path / "m.ggjl"
        write_journal(path, frames)
        rs = ReplaySession(path, CFG)
        assert rs.closed and not rs.truncated
        stream = drain(rs)
        assert len(stream) == 200
        for f, inputs in stream:
            assert inputs == tuple(
                (v, InputStatus.CONFIRMED) for v in frames[f]
            )

    @needs_broadcast
    def test_live_match_roundtrip_matches_spectator(self, tmp_path):
        """The satellite property test over a REAL match under seeded
        loss/dup/reorder: reopening the journal reproduces exactly the
        stream the live spectator observed."""
        ctx = drive_broadcast(
            220, use_hub=True, seed=13,
            fault_cfg=dict(seed=13, loss=0.05, duplicate=0.03,
                           reorder=0.03, latency_ticks=1),
            journal_path=tmp_path / "live.ggjl", journal_fsync=16,
        )
        ctx["journal"].close()
        rs = ReplaySession(tmp_path / "live.ggjl", CFG)
        replayed = dict(drain(rs))
        observed = dict(ctx["viewer_streams"][0])
        assert observed, "viewer observed nothing"
        for f, inputs in observed.items():
            assert replayed[f] == inputs, f"replay diverged at frame {f}"
        # the journal reaches at least as far as the viewer did
        assert rs.last_frame >= max(observed)

    def test_disconnected_blanks_replay_as_disconnected(self, tmp_path):
        j = MatchJournal(tmp_path / "d.ggjl", 2, ISIZE)
        blob = CFG.input_encode(5) + bytes(ISIZE)
        j.append_frames(0, [(bytes([0, 0]), CFG.input_encode(3) * 2),
                            (bytes([0, 1]), blob)])
        j.close()
        rs = ReplaySession(tmp_path / "d.ggjl", CFG)
        (first,) = rs.advance_frame()
        assert first.inputs[1][1] is InputStatus.CONFIRMED
        (second,) = rs.advance_frame()
        assert second.inputs[0] == (5, InputStatus.CONFIRMED)
        assert second.inputs[1] == (0, InputStatus.DISCONNECTED)


class TestCheckpointSeek:
    def test_seek_resumes_bit_identically(self, tmp_path):
        """Checkpoint-seek: simulate a toy game alongside journaling,
        embed its state every 50 frames, then seek and verify the
        continuation equals the full-replay suffix AND the restored state
        equals the live state at the checkpoint."""
        rng = np.random.default_rng(3)
        frames = rng.integers(0, 16, size=(180, 2)).tolist()
        state = {"acc": np.zeros(2, np.int64)}
        checkpoints = []
        path = tmp_path / "c.ggjl"
        j = MatchJournal(path, 2, ISIZE)
        for f, row in enumerate(frames):
            if f and f % 50 == 0:
                checkpoints.append((f, {"acc": state["acc"].copy()}))
                j.append_checkpoint(f, state)
            blob = b"".join(CFG.input_encode(v) for v in row)
            j.append_frames(f, [(bytes(2), blob)])
            state["acc"] = state["acc"] + np.asarray(row)
        j.close()

        rs = ReplaySession(path, CFG)
        full = drain(rs)
        for target in (60, 120, 179):
            cf, restored, meta = rs.seek(
                target, template={"acc": np.zeros(2, np.int64)}
            )
            assert cf == (target // 50) * 50
            assert meta["frame"] == cf
            live = next(s for f, s in checkpoints if f == cf)
            np.testing.assert_array_equal(restored["acc"], live["acc"])
            suffix = drain(rs)
            assert suffix == [e for e in full if e[0] >= cf]

    def test_seek_before_any_checkpoint_plays_from_start(self, tmp_path):
        path = tmp_path / "p.ggjl"
        write_journal(path, [[1, 2], [3, 4], [5, 6]])
        rs = ReplaySession(path, CFG)
        cf, state, meta = rs.seek(1)
        assert (cf, state, meta) == (0, None, None)
        assert len(drain(rs)) == 3


class TestCorruptionAndGaps:
    def test_crc_chain_recovers_intact_prefix(self, tmp_path):
        path = tmp_path / "x.ggjl"
        write_journal(path, [[i % 16, (i * 3) % 16] for i in range(100)])
        data = bytearray(path.read_bytes())
        data[len(data) // 2] ^= 0xFF  # one flipped bit-pattern mid-file
        path.write_bytes(bytes(data))
        parsed = read_journal(path)
        assert parsed["truncated"]
        assert 0 < len(parsed["frames"]) < 100
        # the prefix still replays
        rs = ReplaySession(path, CFG)
        assert not rs.closed
        stream = drain(rs)
        assert len(stream) == len(parsed["frames"])

    def test_bad_magic_raises(self, tmp_path):
        p = tmp_path / "junk"
        p.write_bytes(b"not a journal at all")
        with pytest.raises(JournalError):
            read_journal(p)

    def test_gap_is_explicit_and_stops_replay(self, tmp_path):
        j = MatchJournal(tmp_path / "g.ggjl", 2, ISIZE)
        blob = CFG.input_encode(1) * 2
        j.append_frames(0, [(bytes(2), blob), (bytes(2), blob)])
        j.append_frames(5, [(bytes(2), blob)])  # frames 2..4 lost
        j.close()
        parsed = read_journal(tmp_path / "g.ggjl")
        assert parsed["gaps"] == [5]
        rs = ReplaySession(tmp_path / "g.ggjl", CFG)
        rs.advance_frame()
        rs.advance_frame()
        with pytest.raises(JournalExhausted):
            rs.advance_frame()  # never silently jumps the hole

    def test_fast_forward_window_is_gap_aware(self, tmp_path):
        """frames_remaining/stacked_inputs count the CONTIGUOUS run, and
        an over-ask raises with the cursor unmoved — never a half-consumed
        window stranded at the hole."""
        j = MatchJournal(tmp_path / "gw.ggjl", 2, ISIZE)
        blob = CFG.input_encode(1) * 2
        j.append_frames(0, [(bytes(2), blob)] * 5)   # frames 0..4
        j.append_frames(7, [(bytes(2), blob)] * 3)   # 5..6 lost, 7..9
        j.close()
        rs = ReplaySession(tmp_path / "gw.ggjl", CFG)
        assert rs.frames_remaining() == 5
        with pytest.raises(JournalExhausted):
            rs.stacked_inputs(6)
        assert rs.current_frame == 0  # nothing was consumed
        inputs, _ = rs.stacked_inputs()  # default = the contiguous run
        assert len(inputs) == 5 and rs.current_frame == 5

    def test_journal_never_truncates_an_existing_file(self, tmp_path):
        path = tmp_path / "precious.ggjl"
        write_journal(path, [[1, 2], [3, 4]])
        with pytest.raises(FileExistsError):
            MatchJournal(path, 2, ISIZE)
        # the prior match's artifact is untouched
        assert len(read_journal(path)["frames"]) == 2

    def test_duplicate_delivery_is_idempotent(self, tmp_path):
        j = MatchJournal(tmp_path / "dup.ggjl", 2, ISIZE)
        blob = CFG.input_encode(9) * 2
        j.append_frames(0, [(bytes(2), blob), (bytes(2), blob)])
        j.append_frames(1, [(bytes(2), blob)])  # re-delivered frame 1
        j.close()
        parsed = read_journal(tmp_path / "dup.ggjl")
        assert [f for f, _, _ in parsed["frames"]] == [0, 1]


class TestFusedScrub:
    def test_scrub_matches_per_frame_playback(self, tmp_path):
        """Fast-forward mode: N frames through the fused device scan
        equal N per-frame advances over the same journal window."""
        import jax.numpy as jnp

        from ggrs_tpu.ops.replay import build_scrub_program

        rng = np.random.default_rng(11)
        frames = rng.integers(0, 16, size=(96, 2)).tolist()
        path = tmp_path / "s.ggjl"
        write_journal(path, frames)

        def advance(state, inp):
            return {
                "pos": state["pos"] + inp.astype(jnp.int32),
                "tick": state["tick"] + 1,
            }

        scrub = build_scrub_program(advance, donate=False)
        init = {"pos": jnp.zeros(2, jnp.int32), "tick": jnp.int32(0)}

        rs = ReplaySession(path, CFG)
        inputs, statuses = rs.stacked_inputs(64)
        assert rs.current_frame == 64  # consumed: playback continues there
        fused = scrub(init, jnp.asarray(inputs, jnp.int32))

        ref = {"pos": np.zeros(2, np.int64), "tick": 0}
        rs2 = ReplaySession(path, CFG)
        for _ in range(64):
            (req,) = rs2.advance_frame()
            row = np.asarray([v for v, _ in req.inputs])
            ref = {"pos": ref["pos"] + row, "tick": ref["tick"] + 1}
        np.testing.assert_array_equal(np.asarray(fused["pos"]), ref["pos"])
        assert int(fused["tick"]) == 64
        assert all(
            s is InputStatus.CONFIRMED for row in statuses for s in row
        )


class TestCheckpointBytes:
    def test_dumps_loads_roundtrip_and_validation(self):
        from ggrs_tpu.utils.checkpoint import dumps_pytree, loads_pytree

        tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
                "b": np.int64(7)}
        blob = dumps_pytree(tree, {"frame": 42})
        out, meta = loads_pytree(blob, {
            "a": np.zeros((2, 3), np.float32), "b": np.int64(0),
        })
        assert meta["frame"] == 42
        np.testing.assert_array_equal(out["a"], tree["a"])
        assert out["b"] == 7
        with pytest.raises(ValueError):
            loads_pytree(blob, {"a": np.zeros((3, 2), np.float32),
                                "b": np.int64(0)})
        with pytest.raises(ValueError):
            loads_pytree(blob, {"a": np.zeros((2, 3), np.float32)})
