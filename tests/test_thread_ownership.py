"""The sessions' thread-ownership contract (README "Threading"): the
runtime analog of the reference's Send-but-not-Sync bounds
(/root/reference/src/lib.rs:204-240)."""

from __future__ import annotations

import random
import threading

import pytest

from ggrs_tpu.core.errors import CrossThreadAccess
from ggrs_tpu.core.types import Local, Remote
from ggrs_tpu.games.boxgame import boxgame_config
from ggrs_tpu.net.sockets import InMemoryNetwork
from ggrs_tpu.sessions.builder import SessionBuilder


def make_pair():
    net = InMemoryNetwork()
    sessions = []
    for me, other, h in (("A", "B", 0), ("B", "A", 1)):
        sessions.append(
            SessionBuilder(boxgame_config())
            .with_clock(lambda: 0)
            .with_rng(random.Random(61 + h))
            .add_player(Local(), h)
            .add_player(Remote(other), 1 - h)
            .start_p2p_session(net.socket(me))
        )
    return sessions


def drive_tick(sessions, i, state):
    for s in sessions:
        s.poll_remote_clients()
    for h, s in enumerate(sessions):
        s.add_local_input(h, i % 16)
        for r in s.advance_frame():
            k = type(r).__name__
            if k == "SaveGameState":
                r.cell.save(r.frame, state[h], None)
            elif k == "LoadGameState":
                state[h] = r.cell.data()


def run_in_thread(fn):
    box = {}

    def wrapper():
        try:
            box["result"] = fn()
        except BaseException as e:  # pragma: no cover - assertion transport
            box["error"] = e

    t = threading.Thread(target=wrapper)
    t.start()
    t.join()
    return box


class TestThreadOwnership:
    def test_second_thread_driving_raises(self):
        sessions = make_pair()
        state = [0, 0]
        drive_tick(sessions, 0, state)  # pins the owner (this thread)

        box = run_in_thread(lambda: sessions[0].advance_frame())
        assert isinstance(box.get("error"), CrossThreadAccess)
        # ... and the owning thread may keep driving
        drive_tick(sessions, 1, state)
        assert all(s.current_frame == 2 for s in sessions)

    def test_transfer_ownership_is_the_send_analog(self):
        sessions = make_pair()
        state = [0, 0]
        drive_tick(sessions, 0, state)

        def handed_off():
            for s in sessions:
                s.transfer_ownership()
            for i in range(1, 4):
                drive_tick(sessions, i, state)
            return [s.current_frame for s in sessions]

        box = run_in_thread(handed_off)
        assert box.get("result") == [4, 4], box
        # after the hand-off the ORIGINAL thread is now the foreign one
        with pytest.raises(CrossThreadAccess):
            sessions[0].advance_frame()

    def test_reading_returned_data_needs_no_ownership(self):
        sessions = make_pair()
        state = [0, 0]
        drive_tick(sessions, 0, state)
        events = sessions[0].events()  # plain data once returned
        box = run_in_thread(lambda: len(events))
        assert box.get("result") == len(events)
