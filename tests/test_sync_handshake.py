"""Opt-in sync handshake (builder ``with_sync_handshake``).

The reference fork removed the upstream handshake and ships vestigial
Synchronizing/Synchronized events plus a NotSynchronized error that nothing
produces (SURVEY fork delta #4).  With the handshake enabled those become
real: endpoints complete nonce-echo round trips before carrying inputs,
sessions report SYNCHRONIZING / raise NotSynchronized until every remote is
up, and the disconnect timers don't run while waiting — so a slow-starting
peer is not misdiagnosed as dead (the failure mode that motivated this)."""

import random

import pytest

from ggrs_tpu.core import (
    Local,
    Remote,
    SessionState,
    Spectator,
    Synchronized,
    Synchronizing,
)
from ggrs_tpu.core.errors import NotSynchronized
from ggrs_tpu.net import InMemoryNetwork
from ggrs_tpu.sessions import SessionBuilder

from stubs import GameStub, stub_config


def _make_pair(net, clock, handshake=True):
    sessions = []
    for me, other, local_handle in (("A", "B", 0), ("B", "A", 1)):
        sessions.append(
            SessionBuilder(stub_config())
            .with_clock(clock)
            .with_rng(random.Random(7 + local_handle))
            .with_sync_handshake(handshake)
            .add_player(Local(), local_handle)
            .add_player(Remote(other), 1 - local_handle)
            .start_p2p_session(net.socket(me))
        )
    return sessions


class TestSyncHandshake:
    def test_default_off_is_fork_parity(self):
        net = InMemoryNetwork()
        sess1, sess2 = _make_pair(net, lambda: 0, handshake=False)
        assert sess1.current_state() is SessionState.RUNNING
        sess1.add_local_input(0, 1)
        sess1.advance_frame()  # no NotSynchronized without the handshake

    def test_not_synchronized_until_handshake_completes(self):
        net = InMemoryNetwork()
        sess1, sess2 = _make_pair(net, lambda: 0)
        assert sess1.current_state() is SessionState.SYNCHRONIZING
        sess1.add_local_input(0, 1)
        with pytest.raises(NotSynchronized):
            sess1.advance_frame()

    def test_handshake_completes_and_emits_events(self):
        net = InMemoryNetwork()
        sess1, sess2 = _make_pair(net, lambda: 0)
        for _ in range(12):  # a few pump rounds: 5 round trips each way
            sess1.poll_remote_clients()
            sess2.poll_remote_clients()

        assert sess1.current_state() is SessionState.RUNNING
        assert sess2.current_state() is SessionState.RUNNING

        ev1 = sess1.events()
        progress = [e for e in ev1 if isinstance(e, Synchronizing)]
        done = [e for e in ev1 if isinstance(e, Synchronized)]
        assert [e.count for e in progress] == [1, 2, 3, 4, 5]
        assert all(e.total == 5 for e in progress)
        assert len(done) == 1 and done[0].addr == "B"

    def test_sessions_play_normally_after_handshake(self):
        net = InMemoryNetwork()
        sess1, sess2 = _make_pair(net, lambda: 0)
        for _ in range(12):
            sess1.poll_remote_clients()
            sess2.poll_remote_clients()
        stub1, stub2 = GameStub(), GameStub()
        for i in range(20):
            sess1.poll_remote_clients()
            sess2.poll_remote_clients()
            sess1.add_local_input(0, i)
            stub1.handle_requests(sess1.advance_frame())
            sess2.add_local_input(1, i)
            stub2.handle_requests(sess2.advance_frame())
        # drain so predictions resolve, then both states must pin exactly
        for i in range(8):
            sess1.poll_remote_clients()
            sess2.poll_remote_clients()
            sess1.add_local_input(0, 0)
            stub1.handle_requests(sess1.advance_frame())
            sess2.add_local_input(1, 0)
            stub2.handle_requests(sess2.advance_frame())
        assert stub1.gs.frame > 20
        assert abs(stub1.gs.frame - stub2.gs.frame) <= 1

    def test_no_disconnect_timer_while_waiting_for_peer(self):
        """A peer that hasn't started yet must not be declared interrupted or
        dead, no matter how long it takes (the handshake-free stream cannot
        make this distinction — the whole point of opting in)."""
        clock_now = [0]
        net = InMemoryNetwork()
        sess1 = (
            SessionBuilder(stub_config())
            .with_clock(lambda: clock_now[0])
            .with_rng(random.Random(3))
            .with_sync_handshake(True)
            .add_player(Local(), 0)
            .add_player(Remote("B"), 1)
            .start_p2p_session(net.socket("A"))
        )
        for step in range(40):
            clock_now[0] += 1000  # way past the 2000ms disconnect timeout
            sess1.poll_remote_clients()
        names = {type(e).__name__ for e in sess1.events()}
        assert "NetworkInterrupted" not in names
        assert "Disconnected" not in names
        assert sess1.current_state() is SessionState.SYNCHRONIZING

    def test_handshake_survives_packet_loss(self):
        clock_now = [0]
        net = InMemoryNetwork(loss=0.3, seed=11)
        sess1, sess2 = _make_pair(net, lambda: clock_now[0])
        for _ in range(200):
            clock_now[0] += 100  # let the 200ms sync retry fire
            sess1.poll_remote_clients()
            sess2.poll_remote_clients()
            if (
                sess1.current_state() is SessionState.RUNNING
                and sess2.current_state() is SessionState.RUNNING
            ):
                break
        assert sess1.current_state() is SessionState.RUNNING
        assert sess2.current_state() is SessionState.RUNNING

    def test_sync_timeout_surfaces_disconnected_for_dead_peer(self):
        """Probing is bounded: a peer that never appears (dead address)
        eventually surfaces Disconnected instead of hanging the session in
        SYNCHRONIZING forever (review finding, round 3).  The default is a
        generous 60s; here we shorten it via with_sync_timeout."""
        clock_now = [0]
        net = InMemoryNetwork()
        sess = (
            SessionBuilder(stub_config())
            .with_clock(lambda: clock_now[0])
            .with_rng(random.Random(4))
            .with_sync_handshake(True)
            .with_sync_timeout(3_000)
            .add_player(Local(), 0)
            .add_player(Remote("NOBODY"), 1)
            .start_p2p_session(net.socket("A"))
        )
        events = []
        for _ in range(40):
            clock_now[0] += 100
            sess.poll_remote_clients()
            events.extend(sess.events())
        names = [type(e).__name__ for e in events]
        assert "Disconnected" in names
        # before the deadline there must be no disconnect noise
        assert "NetworkInterrupted" not in names

    def test_handshake_completes_when_rtt_exceeds_retry_interval(self):
        """The probe nonce is per round trip, not per send: with RTT above
        the 200ms retry interval every reply arrives after a retry has gone
        out, and regenerating the nonce on retry would make every reply look
        stale — a silent livelock (review finding, round 3)."""
        clock_now = [0]
        # 3 network ticks of latency; each loop iteration = 100ms and one
        # tick, so RTT = 600ms >> the 200ms sync retry interval
        net = InMemoryNetwork(latency_ticks=3)
        sess1, sess2 = _make_pair(net, lambda: clock_now[0])
        for _ in range(300):
            clock_now[0] += 100
            net.tick()
            sess1.poll_remote_clients()
            sess2.poll_remote_clients()
            if (
                sess1.current_state() is SessionState.RUNNING
                and sess2.current_state() is SessionState.RUNNING
            ):
                break
        assert sess1.current_state() is SessionState.RUNNING
        assert sess2.current_state() is SessionState.RUNNING

    def test_sync_timeout_bounds_silence_not_total_duration(self):
        """Five round trips on a high-RTT link can exceed one sync timeout;
        a peer making progress must not be disconnected mid-handshake — the
        deadline extends on every completed round (review finding, round 3)."""
        clock_now = [0]
        net = InMemoryNetwork(latency_ticks=3)  # RTT 600ms at 100ms/loop
        sessions = []
        for me, other, local_handle in (("A", "B", 0), ("B", "A", 1)):
            sessions.append(
                SessionBuilder(stub_config())
                .with_clock(lambda: clock_now[0])
                .with_rng(random.Random(13 + local_handle))
                .with_sync_handshake(True)
                .with_sync_timeout(1_500)  # < 5 round trips x 600ms RTT
                .add_player(Local(), local_handle)
                .add_player(Remote(other), 1 - local_handle)
                .start_p2p_session(net.socket(me))
            )
        sess1, sess2 = sessions
        for _ in range(100):
            clock_now[0] += 100
            net.tick()
            sess1.poll_remote_clients()
            sess2.poll_remote_clients()
            if (
                sess1.current_state() is SessionState.RUNNING
                and sess2.current_state() is SessionState.RUNNING
            ):
                break
        assert sess1.current_state() is SessionState.RUNNING
        assert sess2.current_state() is SessionState.RUNNING
        names = {type(e).__name__ for e in sess1.events()}
        assert "Disconnected" not in names

    def test_spectator_handshake(self):
        net = InMemoryNetwork()
        host = (
            SessionBuilder(stub_config())
            .with_clock(lambda: 0)
            .with_rng(random.Random(5))
            .with_sync_handshake(True)
            .add_player(Local(), 0)
            .add_player(Local(), 1)
            .add_player(Spectator("S"), 2)
            .start_p2p_session(net.socket("H"))
        )
        spec = (
            SessionBuilder(stub_config())
            .with_clock(lambda: 0)
            .with_rng(random.Random(6))
            .with_sync_handshake(True)
            .start_spectator_session("H", net.socket("S"))
        )
        assert spec.current_state() is SessionState.SYNCHRONIZING
        with pytest.raises(NotSynchronized):
            spec.advance_frame()
        for _ in range(12):
            host.poll_remote_clients()
            spec.poll_remote_clients()
        assert spec.current_state() is SessionState.RUNNING
        assert any(isinstance(e, Synchronized) for e in spec.events())
