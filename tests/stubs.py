"""Deterministic fake game fixtures (parity with /root/reference/tests/stubs.rs):
GameStub advances a tiny arithmetic state; RandomChecksumGameStub deliberately
breaks checksums to exercise desync machinery."""

from __future__ import annotations

import random
import struct
from dataclasses import dataclass
from typing import List, Tuple

from ggrs_tpu.core import (
    AdvanceFrame,
    Config,
    InputStatus,
    LoadGameState,
    SaveGameState,
)


def stub_config() -> Config:
    return Config.for_uint(32)


@dataclass
class StateStub:
    frame: int = 0
    state: int = 0

    def advance(self, inputs: List[Tuple[int, InputStatus]]) -> None:
        p0 = inputs[0][0]
        p1 = inputs[1][0] if len(inputs) > 1 else 0
        if (p0 + p1) % 2 == 0:
            self.state += 2
        else:
            self.state -= 1
        self.frame += 1


def stub_checksum(gs: StateStub) -> int:
    # deterministic across processes (unlike Python's salted hash())
    data = struct.pack("<qq", gs.frame, gs.state)
    acc = 0xCBF29CE484222325
    for b in data:
        acc = ((acc ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return acc


class GameStub:
    def __init__(self) -> None:
        self.gs = StateStub()

    def handle_requests(self, requests) -> None:
        for request in requests:
            if isinstance(request, LoadGameState):
                self.gs = StateStub(**vars(request.cell.load()))
            elif isinstance(request, SaveGameState):
                assert self.gs.frame == request.frame
                snapshot = StateStub(**vars(self.gs))
                request.cell.save(request.frame, snapshot, stub_checksum(snapshot))
            elif isinstance(request, AdvanceFrame):
                self.gs.advance(request.inputs)


class RandomChecksumGameStub:
    """Saves random checksums: the SyncTest session must flag the mismatch."""

    def __init__(self) -> None:
        self.gs = StateStub()
        self._rng = random.Random()

    def handle_requests(self, requests) -> None:
        for request in requests:
            if isinstance(request, LoadGameState):
                self.gs = StateStub(**vars(request.cell.load()))
            elif isinstance(request, SaveGameState):
                assert self.gs.frame == request.frame
                snapshot = StateStub(**vars(self.gs))
                request.cell.save(request.frame, snapshot, self._rng.getrandbits(128))
            elif isinstance(request, AdvanceFrame):
                self.gs.advance(request.inputs)
