"""BoxGame + DeviceSyncTestSession: the desync gate.

The fixed-point BoxGame must be bitwise identical between the JAX program and
the independent NumPy mirror — that equivalence is the framework's analog of
the reference's cross-peer determinism requirement, and the checksum-level
comparison is exactly what desync detection/synctest rely on."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ggrs_tpu.core.errors import InvalidRequest, MismatchedChecksum
from ggrs_tpu.games import BoxGame
from ggrs_tpu.ops import pytree_checksum
from ggrs_tpu.sessions import DeviceSyncTestSession


def _random_inputs(n, players, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 16, size=(n, players)).astype(np.uint8)


class TestBoxGameDeterminism:
    @pytest.mark.parametrize("players", [2, 4])
    def test_jax_matches_numpy_mirror_bitwise(self, players):
        game = BoxGame(players)
        n = 120
        inputs = _random_inputs(n, players, seed=7)
        s_jax = game.init_state()
        s_np = game.init_state_np()
        adv = jax.jit(game.advance)
        for i in range(n):
            s_jax = adv(s_jax, jnp.asarray(inputs[i]))
            s_np = game.advance_np(s_np, inputs[i])
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(np.asarray(s_jax[k]), s_np[k], err_msg=k)

    def test_checksums_match_across_paths(self):
        game = BoxGame(2)
        inputs = _random_inputs(50, 2, seed=3)
        s_jax, s_np = game.init_state(), game.init_state_np()
        for i in range(50):
            s_jax = game.advance(s_jax, jnp.asarray(inputs[i]))
            s_np = game.advance_np(s_np, inputs[i])
        assert pytree_checksum(s_jax) == pytree_checksum(
            jax.tree_util.tree_map(jnp.asarray, s_np)
        )

    def test_ships_actually_move(self):
        game = BoxGame(2)
        state = game.init_state()
        thrust = jnp.full((2,), 1, jnp.uint8)  # both hold "up"
        for _ in range(30):
            state = game.advance(state, thrust)
        assert not np.array_equal(
            np.asarray(state["pos"]), np.asarray(game.init_state()["pos"])
        )
        assert np.any(np.asarray(state["vel"]) != 0)

    def test_float_variant_runs(self):
        game = BoxGame(2, variant="float")
        state = game.init_state()
        state = jax.jit(game.advance)(state, jnp.asarray([1, 8], jnp.uint8))
        assert state["pos"].dtype == jnp.float32


class TestDeviceSyncTest:
    def test_deterministic_game_passes(self):
        game = BoxGame(2)
        sess = DeviceSyncTestSession(
            game.advance,
            game.init_state(),
            jnp.zeros((2,), jnp.uint8),
            check_distance=2,
        )
        sess.run_ticks(_random_inputs(200, 2, seed=11))
        assert sess.current_frame == 200

    def test_matches_plain_forward_simulation(self):
        game = BoxGame(2)
        inputs = _random_inputs(64, 2, seed=5)
        sess = DeviceSyncTestSession(
            game.advance, game.init_state(), jnp.zeros((2,), jnp.uint8), check_distance=8
        )
        sess.run_ticks(inputs)
        live = sess.live_state()
        s_np = game.init_state_np()
        for i in range(64):
            s_np = game.advance_np(s_np, inputs[i])
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(np.asarray(live[k]), s_np[k], err_msg=k)

    def test_split_batches_equivalent(self):
        game = BoxGame(2)
        inputs = _random_inputs(40, 2, seed=9)
        a = DeviceSyncTestSession(
            game.advance, game.init_state(), jnp.zeros((2,), jnp.uint8), check_distance=3
        )
        a.run_ticks(inputs)
        b = DeviceSyncTestSession(
            game.advance, game.init_state(), jnp.zeros((2,), jnp.uint8), check_distance=3
        )
        for chunk in np.split(inputs, [7, 13, 29]):
            if len(chunk):
                b.run_ticks(chunk)
        for k in ("pos", "vel", "rot"):
            np.testing.assert_array_equal(
                np.asarray(a.live_state()[k]), np.asarray(b.live_state()[k])
            )

    def test_nondeterministic_game_caught(self):
        # Emulate a nondeterministic simulation (the reference's
        # RandomChecksumGameStub, /root/reference/tests/stubs.rs:68-107) by
        # corrupting the saved state the next rollback will reload: after 10
        # ticks the session is at frame 10 with check_distance=2, so the next
        # steady tick loads frame 8 and its resimulation of frame 9 must
        # diverge from frame 9's first-seen checksum.
        game = BoxGame(2)
        sess = DeviceSyncTestSession(
            game.advance, game.init_state(), jnp.zeros((2,), jnp.uint8), check_distance=2
        )
        sess.run_ticks(_random_inputs(10, 2, seed=1))
        ring_len = sess._programs.ring.length
        slot = 8 % ring_len
        sess._carry["ring"]["states"]["pos"] = (
            sess._carry["ring"]["states"]["pos"].at[slot, 0, 0].add(1)
        )
        with pytest.raises(MismatchedChecksum) as ei:
            sess.run_ticks(_random_inputs(10, 2, seed=2))
        assert ei.value.mismatched_frames == [9]

    def test_all_window_mismatches_reported(self):
        # Corrupting the first-seen history of TWO window frames makes the
        # next tick's resimulations of both diverge; the error must list every
        # divergent frame, matching the reference's full mismatched-frames
        # report (/root/reference/src/sessions/sync_test_session.rs:93-102).
        game = BoxGame(2)
        sess = DeviceSyncTestSession(
            game.advance, game.init_state(), jnp.zeros((2,), jnp.uint8), check_distance=2
        )
        sess.run_ticks(_random_inputs(10, 2, seed=1))
        ring_len = sess._programs.ring.length
        for frame in (9, 10):
            sess._carry["hist"] = (
                sess._carry["hist"].at[frame % ring_len].set(jnp.uint32(0xBAD))
            )
        with pytest.raises(MismatchedChecksum) as ei:
            sess.run_ticks(_random_inputs(1, 2, seed=2))
        assert ei.value.mismatched_frames == [9, 10]

    def test_check_distance_zero_rejected(self):
        game = BoxGame(2)
        with pytest.raises(InvalidRequest):
            DeviceSyncTestSession(
                game.advance, game.init_state(), jnp.zeros((2,), jnp.uint8),
                check_distance=0,
            )
