"""Pins for the vectorized policy plane (DESIGN.md §19).

The host bank's tick output now leads with a packed per-slot header and
``HostSessionPool`` classifies all B slots from it, fast-pathing quiet
slots through pooled requests without a positional body parse.  Everything
here pins that path bit-identical to the legacy per-slot parser (the
reference decoder, forced via ``GGRS_TPU_NO_FASTPATH=1``): request values,
events, wire bytes, journal streams, frames — under seeded
loss/dup/reorder, on the event-heavy blackout path, and across the
eviction/export seams.  Plus: the crossing budget is untouched (still one
tick crossing + one stats crossing per pool tick), the fast path actually
engages, the B=256 scrape stays allocation-free (tracemalloc), and the
supervision transition feed drains incrementally.
"""

from __future__ import annotations

import os
import random
import tracemalloc

import pytest

from ggrs_tpu.core import Local, Remote
from ggrs_tpu.core.config import Config
from ggrs_tpu.net import InMemoryNetwork, _native
from ggrs_tpu.obs.registry import Registry
from ggrs_tpu.parallel.host_bank import HostSessionPool
from ggrs_tpu.sessions import SessionBuilder

from test_session_bank import (  # noqa: E402  (pytest rootdir path)
    RecordingSocket,
    assert_requests_equal,
    fulfill_saves,
    needs_native,
    two_peer_builders,
)


def _make_pool(builders, fastpath: bool, metrics=None):
    """Build + finalize one pool with the vectorized path on or off (the
    env flag is read at finalization)."""
    prev = os.environ.pop("GGRS_TPU_NO_FASTPATH", None)
    if not fastpath:
        os.environ["GGRS_TPU_NO_FASTPATH"] = "1"
    try:
        pool = HostSessionPool(metrics=metrics)
        for b, s in builders:
            pool.add_session(b, s)
        assert pool.native_active, "native bank did not engage"
    finally:
        os.environ.pop("GGRS_TPU_NO_FASTPATH", None)
        if prev is not None:
            os.environ["GGRS_TPU_NO_FASTPATH"] = prev
    assert pool._vectorized == fastpath
    return pool


def _drive_both(faults, ticks, n_matches=3, journals=None, blackout=None,
                scrape_every=0):
    """Drive a vectorized and a legacy pool with identical seeded traffic;
    compare requests, events, frames, and wire bytes every tick.  Returns
    (fast_pool, legacy_pool)."""
    clock = [0]
    net_a = InMemoryNetwork(**faults)
    net_b = InMemoryNetwork(**faults)
    builders_a = two_peer_builders(net_a, clock, n_matches)
    builders_b = two_peer_builders(net_b, clock, n_matches)
    pool_a = _make_pool(builders_a, fastpath=True)
    pool_b = _make_pool(builders_b, fastpath=False)
    if journals is not None:
        from ggrs_tpu.broadcast.hub import SpectatorHub

        hub_a = SpectatorHub(pool_a)
        hub_b = SpectatorHub(pool_b)
        (ja, jb) = journals
        hub_a.attach_journal(0, ja)
        hub_b.attach_journal(0, jb)
    n = len(builders_a)
    saw_events = 0
    for i in range(ticks):
        dark = blackout is not None and i in blackout
        if dark:
            # starve the liveness timers: big clock steps with NO packet
            # delivery below — interrupt (then resume) events, retries,
            # the event-heavy slow path
            clock[0] += 300
        clock[0] += 16
        for idx in range(n):
            v = (i + idx) % 16
            pool_a.add_local_input(idx, idx % 2, v)
            pool_b.add_local_input(idx, idx % 2, v)
        reqs_a = pool_a.advance_all()
        reqs_b = pool_b.advance_all()
        if scrape_every and i % scrape_every == 0:
            pool_a.scrape()
            pool_b.scrape()
        for idx in range(n):
            assert_requests_equal(
                reqs_b[idx], reqs_a[idx], f"tick {i} slot {idx}"
            )
            fulfill_saves(reqs_a[idx])
            fulfill_saves(reqs_b[idx])
        if not dark:
            net_a.tick()
            net_b.tick()
        for idx in range(n):
            ev_a = pool_a.events(idx)
            saw_events += len(ev_a)
            assert ev_a == pool_b.events(idx), (
                f"tick {i} slot {idx}: events diverged"
            )
            assert pool_a.current_frame(idx) == pool_b.current_frame(idx)
            assert (
                pool_a.last_confirmed_frame(idx)
                == pool_b.last_confirmed_frame(idx)
            )
            sa = builders_a[idx][1].sent
            sb = builders_b[idx][1].sent
            assert sa == sb, (
                f"tick {i} slot {idx}: wire bytes diverged "
                f"({len(sa)} vs {len(sb)} datagrams)"
            )
    return pool_a, pool_b, saw_events


@needs_native
class TestVectorizedParity:
    @pytest.mark.parametrize("seed", [11, 23])
    def test_fuzzed_traffic_bit_identical(self, seed):
        """Seeded loss/dup/reorder: the vectorized decode is bit-identical
        to the legacy per-slot parser — and the fast path actually served
        slots (the quiet majority)."""
        rng = random.Random(seed)
        faults = dict(
            loss=0.08, duplicate=0.04, reorder=0.15,
            seed=rng.randrange(1 << 30),
        )
        pool_a, pool_b, _ = _drive_both(faults, ticks=180)
        assert pool_a.fast_slot_ticks > 0, "fast path never engaged"
        assert pool_b.fast_slot_ticks == 0, "legacy leg took the fast path"

    def test_event_heavy_blackout_path(self):
        """Clock-jump blackouts force interrupt/resume events and retry
        storms: the event (slow) path of the vectorized decoder, pinned
        against the reference under the same schedule."""
        pool_a, _, saw_events = _drive_both(
            dict(), ticks=120, blackout={40, 41, 42, 80}
        )
        # the blackout actually produced protocol events (the slow path),
        # and the rest of the run stayed on the fast path
        assert saw_events > 0, "blackout produced no events"
        assert 0 < pool_a.fast_slot_ticks < 120 * len(pool_a._slot_state)

    def test_journal_streams_bit_identical(self, tmp_path):
        """The journal tap rides the fast path (kHdrConf): both pools'
        journal files must be byte-identical."""
        from ggrs_tpu.broadcast.journal import MatchJournal

        cfg_players, isize = 2, Config.for_uint(16).native_input_size
        ja = MatchJournal(tmp_path / "a.journal", cfg_players, isize)
        jb = MatchJournal(tmp_path / "b.journal", cfg_players, isize)
        pool_a, _, _ = _drive_both(dict(loss=0.05, seed=7), ticks=100,
                                   journals=(ja, jb))
        assert pool_a.fast_slot_ticks > 0
        ja.close()
        jb.close()
        a = (tmp_path / "a.journal").read_bytes()
        b = (tmp_path / "b.journal").read_bytes()
        assert a == b and len(a) > 0, "journal streams diverged"

    def test_export_bundle_identical_after_quiet_run(self):
        """Migration continuity: after a long quiet run (stale Python
        mirrors on the fast leg), the export bundle — which now reads the
        harvest's peer mirrors — matches the legacy pool's exactly."""
        pool_a, pool_b, _ = _drive_both(dict(), ticks=90, n_matches=2)
        for slot in range(2):
            ba = pool_a.export_resume_state(slot)
            bb = pool_b.export_resume_state(slot)
            assert ba == bb, f"slot {slot}: export bundles diverged"
            assert ba["endpoints"][0]["peer_last"] == (
                bb["endpoints"][0]["peer_last"]
            )

    def test_export_bundle_materializes_pending_events(self):
        """A bundle exported while lazily-staged events sit undrained must
        carry real GgrsEvent objects — the destination session's queue is
        extended verbatim and its consumer does isinstance checks."""
        clock = [0]
        net = InMemoryNetwork()
        builders = two_peer_builders(net, clock, 1)
        pool = _make_pool(builders, fastpath=True)
        n = len(builders)
        for i in range(40):
            dark = 20 <= i < 24
            if dark:
                clock[0] += 300  # starved liveness: interrupt events
            clock[0] += 16
            for idx in range(n):
                pool.add_local_input(idx, idx % 2, (i + idx) % 16)
            for reqs in pool.advance_all():
                fulfill_saves(reqs)
            if not dark:
                net.tick()
        # deliberately NOT drained via events(): export with a live queue
        assert any(pool._mirrors[i].event_queue for i in range(n)), (
            "blackout produced no staged events — test setup broken"
        )
        for i in range(n):
            for ev in pool.export_resume_state(i)["pending_events"]:
                assert not isinstance(ev, tuple), (
                    f"raw lazy tuple leaked into the export bundle: {ev!r}"
                )

    def test_crossing_budget_unchanged(self):
        """Still exactly one tick crossing per pool tick and one stats
        crossing per scraped tick on the vectorized path."""
        pool_a, _, _ = _drive_both(dict(), ticks=60, scrape_every=1)
        assert pool_a.crossings == 60
        assert pool_a.stat_crossings == 60
        assert pool_a.harvests == 0


@needs_native
class TestIncrementalSupervision:
    def _pool(self, n_matches=2, **kw):
        clock = [0]
        net = InMemoryNetwork()
        builders = two_peer_builders(net, clock, n_matches)
        pool = HostSessionPool(metrics=Registry(), **kw)
        for b, s in builders:
            pool.add_session(b, s)
        assert pool.native_active
        return pool, builders, net, clock

    def _tick(self, pool, net, clock, i, n):
        clock[0] += 16
        for idx in range(n):
            pool.add_local_input(idx, idx % 2, (i + idx) % 16)
        for reqs in pool.advance_all():
            fulfill_saves(reqs)
        net.tick()

    def test_transition_feed_drains_incrementally(self):
        pool, builders, net, clock = self._pool()
        n = len(builders)
        for i in range(10):
            self._tick(pool, net, clock, i, n)
        assert pool.drain_state_transitions() == []
        pool.inject_slot_error(1)
        for i in range(10, 30):
            self._tick(pool, net, clock, i, n)
        feed = pool.drain_state_transitions()
        assert feed and feed[0][0] == 1
        assert [t[2] for t in feed][:2] == ["quarantined", "evicted"]
        assert pool.drain_state_transitions() == []
        # and the attention set holds exactly the evicted slot
        assert pool._attention == {1}

    def test_evicted_session_is_pooled_and_ticks(self):
        pool, builders, net, clock = self._pool()
        n = len(builders)
        for i in range(8):
            self._tick(pool, net, clock, i, n)
        pool.inject_slot_error(0)
        for i in range(8, 40):
            self._tick(pool, net, clock, i, n)
        assert pool.slot_state(0) == "evicted"
        session = pool._evicted[0]
        assert session._pooled_list is not None, (
            "evicted session did not take the pooled-request path"
        )
        assert pool.current_frame(0) > 8  # it resumed and advances


@needs_native
class TestPooledSessionParity:
    def test_pooled_requests_value_identical(self):
        """P2PSession.enable_request_pooling changes object lifetimes, not
        values: two identically-seeded matches, one pooled, compare every
        tick's requests/events/frames."""
        def build(pool_requests):
            clock = [0]
            net = InMemoryNetwork(loss=0.05, reorder=0.1, seed=99)
            sessions = []
            for me in (0, 1):
                names = ("A", "B")
                b = (
                    SessionBuilder(Config.for_uint(16))
                    .with_clock(lambda: clock[0])
                    .with_rng(random.Random(5 + me))
                    .add_player(Local(), me)
                    .add_player(Remote(names[1 - me]), 1 - me)
                )
                s = b.start_p2p_session(
                    RecordingSocket(net.socket(names[me]))
                )
                if pool_requests:
                    s.enable_request_pooling()
                sessions.append(s)
            return net, clock, sessions

        net_a, clock_a, plain = build(False)
        net_b, clock_b, pooled = build(True)
        for i in range(150):
            clock_a[0] += 16
            clock_b[0] += 16
            for me in (0, 1):
                plain[me].add_local_input(me, (i + me) % 16)
                pooled[me].add_local_input(me, (i + me) % 16)
            for me in (0, 1):
                ra = plain[me].advance_frame()
                rb = pooled[me].advance_frame()
                assert_requests_equal(ra, rb, f"tick {i} session {me}")
                fulfill_saves(ra)
                fulfill_saves(rb)
            net_a.tick()
            net_b.tick()
            for me in (0, 1):
                assert plain[me].events() == pooled[me].events()
                assert plain[me].current_frame == pooled[me].current_frame
                assert (
                    plain[me]._socket.sent == pooled[me]._socket.sent
                )


@needs_native
class TestScrapeAllocationB256:
    def test_b256_steady_state_is_allocation_free(self):
        """ISSUE 10 satellite: at B=256 the tick+scrape steady state must
        not grow the heap — the record dicts refill in place, the gauge
        setters are prebound, and the fast path reuses its pooled
        requests.  Measured with tracemalloc, filtered to this package."""
        clock = [0]
        net = InMemoryNetwork()
        # plain (non-recording) sockets: a RecordingSocket's unbounded
        # .sent list would dominate the measurement
        builders = []
        for m in range(128):  # 256 sessions
            names = (f"A{m}", f"B{m}")
            for me in (0, 1):
                b = (
                    SessionBuilder(Config.for_uint(16))
                    .with_clock(lambda: clock[0])
                    .with_rng(random.Random(3 + 5 * m + me))
                    .add_player(Local(), me)
                    .add_player(Remote(names[1 - me]), 1 - me)
                )
                builders.append((b, net.socket(names[me])))
        # small flight-recorder rings so they FILL during warmup — the
        # measurement targets the scrape/decode steady state, not the
        # bounded one-time fill of 256 rings
        pool = HostSessionPool(metrics=Registry(), flight_recorder_size=8)
        for b, s in builders:
            pool.add_session(b, s)
        assert pool.native_active
        n = len(builders)

        def tick(i):
            clock[0] += 16
            for idx in range(n):
                pool.add_local_input(idx, idx % 2, (i + idx) % 16)
            for reqs in pool.advance_all():
                fulfill_saves(reqs)
            pool.scrape()
            net.tick()

        for i in range(12):  # warm: caches, prebinds, recorder rings
            tick(i)
        assert pool.fast_slot_ticks > 0
        tracemalloc.start()
        try:
            for i in range(12, 24):  # churn the bounded rings with
                tick(i)             # TRACKED objects before baselining
            snap1 = tracemalloc.take_snapshot()
            for i in range(24, 44):
                tick(i)
            snap2 = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        # the flight recorder's ring is BOUNDED but churns (newest N
        # events replace oldest): tracemalloc attributes the live tail to
        # whichever window allocated it, which reads as spurious growth —
        # out of scope for this pin (the scrape/decode steady state)
        flt = [
            tracemalloc.Filter(True, "*ggrs_tpu*"),
            tracemalloc.Filter(False, "*obs/recorder.py"),
        ]
        growth = sum(
            s.size_diff
            for s in snap2.filter_traces(flt).compare_to(
                snap1.filter_traces(flt), "filename"
            )
        )
        # 20 ticks × 256 slots with per-tick scrapes: the steady state
        # must retain (almost) nothing — the bound is deliberately tight
        # relative to the ~500 dicts/tick the naive version allocated.
        # (The descriptor plane retains ONE RequestPlan — bounded, O(B),
        # replaced each tick — whose resim-row list varies with the
        # tick's rollback count; the slack above 64 KiB covers that
        # variance, nothing per-tick.)
        assert growth < 96 * 1024, (
            f"steady-state heap grew {growth} bytes over 20 scraped ticks"
        )
