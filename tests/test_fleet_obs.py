"""Fleet-wide observability plane tests (DESIGN.md §18): delta-snapshot
harvest over the RPC piggyback, cross-process trace correlation,
forensics ferry, exposition conformance, and cardinality bounds.

The acceptance pins, mirrored by ``scripts/chaos.py --fault proc/shard``
artifacts:

* One supervisor scrape (``supervisor.merged_registry()`` through one
  ``MetricsServer``) returns a subprocess runner's counters — e.g. the
  journal fsync histogram — labeled ``shard=<id>,backend=proc``,
  value-equal to querying the runner's registry directly under the same
  seeded traffic.
* The harvest adds ZERO RPC round trips: only the ops the serving path
  already makes appear in the RPC latency histogram.
* One Perfetto export shows a ``fleet.tick`` span with a subprocess
  runner's ``bank.crossing`` phases nested inside it, and the export
  passes schema validation.
* Registry merge is idempotent under re-delivered heartbeat snapshots,
  and a B=128 pool plus a 4-shard fleet emits a bounded series count
  with no per-match/per-viewer label explosion.
"""

from __future__ import annotations

import os
import signal
import time

import pytest

from ggrs_tpu.chaos import drive_chaos, drive_fleet_chaos, drive_proc_fleet
from ggrs_tpu.fleet import FleetTuning, ProcShard, ShardSupervisor
from ggrs_tpu.net import _native
from ggrs_tpu.obs import (
    FleetObs,
    MultiRegistry,
    Registry,
    RegistryCollector,
    Tracer,
    fleet_metrics_digest,
    histogram_quantile,
    json_snapshot,
    prometheus_text,
    validate_chrome_trace,
    validate_exposition,
)

needs_native = pytest.mark.skipif(
    _native.bank_lib() is None, reason="native session bank unavailable"
)

TICKS = 48
PER_SHARD = 2

# fast deadlines, harvest on, tracing via the supervisor tracer;
# desync detection OFF in the e2e fixture so matches are bank-eligible
# (the native in-crossing phase spans are what the trace pin needs)
TUNING = FleetTuning(
    heartbeat_interval_s=0.05,
    heartbeat_deadline_s=1.0,
    rpc_timeout_s=5.0,
    spawn_timeout_s=120.0,
    drain_deadline_s=0.5,
    restart_max=0,
)


# ----------------------------------------------------------------------
# the snapshot/merge seam, no processes involved
# ----------------------------------------------------------------------


class TestRegistryCollector:
    def _populated(self):
        reg = Registry()
        reg.counter("c_total", "a counter").inc(5)
        reg.counter("lc_total", "labeled", labels=("kind",)).labels(
            kind="x").inc(2)
        reg.gauge("g", "a gauge").set(7)
        h = reg.histogram("h_seconds", "a histogram", buckets=(1, 2, 4))
        h.observe(0.5)
        h.observe(3)
        h.observe(100)
        return reg

    def test_deltas_then_merge_reproduce_values(self):
        reg = self._populated()
        coll = RegistryCollector(reg, gen=1)
        obs = FleetObs(metrics=Registry())
        snap = coll.collect()
        assert snap is not None and snap["seq"] == 1
        assert obs.merge_snapshot("s1", snap)
        # second interval: only the moved samples ship
        reg.value  # (no-op)
        reg.counter("c_total").inc(3)
        snap2 = coll.collect()
        names = {f["name"] for f in snap2["families"]}
        assert names == {"c_total"}
        assert obs.merge_snapshot("s1", snap2)
        har = obs.harvest
        assert har.value("c_total", shard="s1", backend="proc") == 8
        assert har.value("lc_total", kind="x", shard="s1",
                         backend="proc") == 2
        assert har.value("g", shard="s1", backend="proc") == 7
        # histogram: bucket-for-bucket equality with the source
        fam = {f.name: f for f in har.families()}["h_seconds"]
        child = fam.labels(shard="s1", backend="proc")
        src = {f.name: f for f in reg.families()}["h_seconds"]
        assert child.cumulative() == src.cumulative()
        assert child.sum == src.sum and child.count == src.count

    def test_idle_collect_returns_none(self):
        reg = self._populated()
        coll = RegistryCollector(reg, gen=1)
        assert coll.collect() is not None
        assert coll.collect() is None  # nothing moved

    def test_merge_is_idempotent_under_redelivery(self):
        reg = self._populated()
        coll = RegistryCollector(reg, gen=9)
        obs = FleetObs(metrics=Registry())
        snap = coll.collect()
        assert obs.merge_snapshot("s1", snap) is True
        before = obs.harvest.value("c_total", shard="s1", backend="proc")
        # the same snapshot re-delivered (duplicated heartbeat): dropped
        assert obs.merge_snapshot("s1", snap) is False
        assert obs.harvest.value(
            "c_total", shard="s1", backend="proc") == before
        # and an OLDER seq after a newer one: dropped too
        reg.counter("c_total").inc(1)
        snap2 = coll.collect()
        assert obs.merge_snapshot("s1", snap2) is True
        assert obs.merge_snapshot("s1", snap) is False

    def test_new_incarnation_gen_applies_fresh(self):
        reg = self._populated()
        obs = FleetObs(metrics=Registry())
        snap = RegistryCollector(reg, gen=1).collect()
        assert obs.merge_snapshot("s1", snap)
        v1 = obs.harvest.value("c_total", shard="s1", backend="proc")
        # runner restarted: fresh registry, fresh gen, seq starts over —
        # merged counters keep growing monotonically (no reset dip)
        reg2 = Registry()
        reg2.counter("c_total", "a counter").inc(4)
        snap2 = RegistryCollector(reg2, gen=2).collect()
        assert snap2["seq"] == 1
        assert obs.merge_snapshot("s1", snap2) is True
        assert obs.harvest.value(
            "c_total", shard="s1", backend="proc") == v1 + 4

    def test_two_shards_share_one_family(self):
        obs = FleetObs(metrics=Registry())
        for sid, gen in (("s1", 1), ("s2", 2)):
            reg = Registry()
            reg.counter("c_total", "a counter").inc(3)
            obs.merge_snapshot(sid, RegistryCollector(reg,
                                                      gen=gen).collect())
        assert obs.harvest.value("c_total", shard="s1",
                                 backend="proc") == 3
        assert obs.harvest.value("c_total", shard="s2",
                                 backend="proc") == 3

    def test_first_seen_snapshot_with_seq_gt_one_counts_a_gap(self):
        # a lost FIRST snapshot (discarded tick reply at startup or
        # right after a respawn) must still be visible as a gap
        m = Registry()
        obs = FleetObs(metrics=m)
        reg = Registry()
        reg.counter("c_total", "c").inc(1)
        coll = RegistryCollector(reg, gen=5)
        coll.collect()  # seq=1, "lost in transit"
        reg.counter("c_total").inc(1)
        snap2 = coll.collect()  # seq=2, first to arrive
        assert obs.merge_snapshot("s1", snap2) is True
        assert m.value("ggrs_fleet_obs_snapshot_gaps_total",
                       shard="s1") == 1

    def test_malformed_span_does_not_discard_sibling_forensics(self):
        # one torn span tuple in a payload must not throw away the
        # forensics ferried beside it (per-section ingest isolation)
        obs = FleetObs(metrics=Registry())
        obs.ingest("s1", {
            "spans": [("X", "n", "c", 0, "not-a-duration", 1, None)],
            "forensics": [{"kind": "slot", "match": "m0"}],
        })
        assert len(obs.forensics) == 1

    def test_shard_label_is_overridden_not_duplicated(self):
        # a runner family that ALREADY carries a shard label (e.g.
        # ggrs_shard_matches) keeps one shard label, set to the
        # supervisor's id
        reg = Registry()
        reg.gauge("sm", "shard matches", labels=("shard", "tier")).labels(
            shard="whatever", tier="bank").set(4)
        obs = FleetObs(metrics=Registry())
        obs.merge_snapshot("s1", RegistryCollector(reg, gen=1).collect())
        assert obs.harvest.value("sm", shard="s1", tier="bank",
                                 backend="proc") == 4


# ----------------------------------------------------------------------
# exposition conformance (satellite: promtool-style validation)
# ----------------------------------------------------------------------


class TestExpositionConformance:
    def test_nasty_label_and_help_values_escape_cleanly(self):
        reg = Registry()
        reg.counter('evil_total', 'help with\nnewline and \\backslash',
                    labels=("why",)).labels(
            why='a "quoted"\nmulti\\line value').inc(1)
        h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0),
                          labels=("op",))
        h.labels(op='weird"op').observe(0.5)
        text = prometheus_text(reg)
        assert validate_exposition(text) == []
        assert "\\n" in text and '\\"' in text

    def test_merged_view_single_type_header_per_family(self):
        local = Registry()
        local.counter("dup_total", "local flavor").inc(1)
        harvest = Registry()
        harvest.counter("dup_total", "harvested flavor",
                        labels=("shard", "backend")).labels(
            shard="s1", backend="proc").inc(2)
        text = prometheus_text(MultiRegistry(local, harvest))
        assert text.count("# TYPE dup_total counter") == 1
        assert validate_exposition(text) == []
        snap = json_snapshot(MultiRegistry(local, harvest))
        assert len(snap["dup_total"]["samples"]) == 2

    def test_validator_catches_histogram_violations(self):
        bad_order = (
            "# TYPE h histogram\n"
            'h_bucket{le="2"} 1\n'
            'h_bucket{le="1"} 1\n'
            'h_bucket{le="+Inf"} 2\n'
            "h_sum 3\nh_count 2\n"
        )
        assert any("ascending" in e
                   for e in validate_exposition(bad_order))
        no_inf = (
            "# TYPE h histogram\n"
            'h_bucket{le="1"} 1\n'
            "h_sum 1\nh_count 1\n"
        )
        assert any("+Inf" in e for e in validate_exposition(no_inf))
        decreasing = (
            "# TYPE h histogram\n"
            'h_bucket{le="1"} 5\n'
            'h_bucket{le="+Inf"} 3\n'
            "h_sum 1\nh_count 3\n"
        )
        assert any("decrease" in e for e in validate_exposition(decreasing))

    def test_validator_catches_syntax_violations(self):
        assert any("duplicate sample" in e for e in validate_exposition(
            "a_total 1\na_total 2\n"))
        assert any("escape" in e for e in validate_exposition(
            'a{x="bad\\q"} 1\n'))
        assert any("bad sample value" in e for e in validate_exposition(
            "a_total one\n"))
        # histograms need le strictly ascending even with equal uppers
        dup_le = (
            "# TYPE h histogram\n"
            'h_bucket{le="1"} 1\n'
            'h_bucket{le="1"} 1\n'
            'h_bucket{le="+Inf"} 1\n'
            "h_sum 1\nh_count 1\n"
        )
        assert validate_exposition(dup_le)

    def test_fleet_registry_exposition_is_conformant(self):
        # a real (in-process) fleet's merged view passes the validator
        ctx = drive_fleet_chaos(24, matches_per_shard=2, seed=5)
        try:
            text = prometheus_text(ctx["sup"].merged_registry())
            assert validate_exposition(text) == []
        finally:
            ctx["sup"].close()

    def test_placement_fleet_families_are_conformant(self):
        """§28 satellite: the §26 planes' families ride ONE merged
        scrape of the cross-host world and conform — ingress, placement,
        lockstep demotions, and the new slo family all present."""
        from ggrs_tpu.chaos import drive_placement_fleet

        ctx = drive_placement_fleet(16, matches_per_host=1, seed=11)
        try:
            text = prometheus_text(ctx["registry"])
        finally:
            ctx["close"]()
        assert validate_exposition(text) == []
        lines = text.splitlines()
        for prefix in ("ggrs_ingress_", "ggrs_placement_",
                       "ggrs_pool_lockstep_", "ggrs_slo_"):
            assert any(ln.startswith(prefix) for ln in lines), prefix


# ----------------------------------------------------------------------
# Perfetto export schema validation (satellite: CI-checked traces)
# ----------------------------------------------------------------------


class TestPerfettoValidation:
    def test_nested_spans_validate(self):
        tracer = Tracer()
        with tracer.span("outer"):
            with tracer.span("inner"):
                pass
        tracer.add_instant("mark")
        assert validate_chrome_trace(tracer.chrome_trace()) == []

    def test_violations_detected(self):
        assert validate_chrome_trace({"nope": 1})
        bad_ph = {"traceEvents": [
            {"name": "x", "ph": "Z", "ts": 0, "pid": 1, "tid": 1}]}
        assert any("unknown ph" in p for p in validate_chrome_trace(bad_ph))
        neg_ts = {"traceEvents": [
            {"name": "x", "ph": "X", "ts": -5, "dur": 1, "pid": 1,
             "tid": 1}]}
        assert any("bad ts" in p for p in validate_chrome_trace(neg_ts))
        overlap = {"traceEvents": [
            {"name": "a", "ph": "X", "ts": 0, "dur": 10, "pid": 1,
             "tid": 1},
            {"name": "b", "ph": "X", "ts": 5, "dur": 10, "pid": 1,
             "tid": 1},
        ]}
        assert any("partially overlaps" in p
                   for p in validate_chrome_trace(overlap))

    def test_span_ship_cap_defers_instead_of_dropping(self):
        # a burst beyond the per-reply cap ships oldest-first across
        # SEVERAL replies; nothing retained by the ring is lost
        from ggrs_tpu.fleet.proc import ShardRunner
        from ggrs_tpu.fleet.tuning import FleetTuning

        runner = ShardRunner.__new__(ShardRunner)
        runner.tracer = Tracer(capacity=64)
        runner.tuning = FleetTuning(obs_max_spans_per_reply=4)
        runner._spans_shipped = 0
        for i in range(10):
            runner.tracer.add_complete(f"s{i}", i * 100, 10)
        shipped = []
        for _ in range(4):
            shipped.extend(runner._new_spans())
        assert [e[1] for e in shipped] == [f"s{i}" for i in range(10)]
        assert runner._new_spans() == []

    def test_import_spans_shift_and_tag(self):
        tracer = Tracer()
        events = [("X", "remote.span", "native", 1_000_000, 500_000,
                   42, {"k": 1})]
        n = tracer.import_spans(events, offset_ns=1_000_000,
                                extra_args={"shard": "s9"})
        assert n == 1
        (ph, name, _cat, start, dur, _tid, args) = tracer.events()[0]
        assert (ph, name, start, dur) == ("X", "remote.span", 0, 500_000)
        assert args["shard"] == "s9" and args["src_tid"] == 42
        # malformed entries are skipped, not raised
        assert tracer.import_spans([("X", "torn")]) == 0


# ----------------------------------------------------------------------
# cardinality bounds (satellite: no per-match label explosion)
# ----------------------------------------------------------------------


def _series_stats(registry):
    series = 0
    per_slotish = 0
    label_values = set()
    for fam in registry.families():
        n = len(fam.children)
        series += n
        if any(ln in ("slot", "endpoint") for ln in fam.labelnames):
            per_slotish += n
        for values in fam.children:
            label_values.update(values)
    return series, per_slotish, label_values


@needs_native
class TestCardinalityBounds:
    def test_b128_pool_series_bounded(self):
        # B = 2*63 + 1 = 127 slots plus the ext target's peer = a
        # 128-session world; scrape materializes the per-slot gauges
        n_matches = 63
        ctx = drive_chaos(4, n_matches=n_matches, seed=2)
        B = 2 * n_matches + 1
        series, per_slot, values = _series_stats(ctx["registry"])
        # per-slot families scale with B (bounded by design); everything
        # else must stay O(1): pin total <= per_slot + a fixed budget
        assert per_slot <= 16 * B
        assert series - per_slot < 128, (
            f"{series - per_slot} non-slot series for a B={B} pool"
        )

    def test_fleet_plus_pool_no_match_or_viewer_labels(self):
        ctx = drive_fleet_chaos(24, matches_per_shard=2, seed=3,
                                n_spectators=2)
        sup = ctx["sup"]
        try:
            # grow to 4 shards' worth of harvest: merge two synthetic
            # runner snapshots beside the two real shards
            for sid in ("s2", "s3"):
                reg = Registry()
                reg.counter("ggrs_pool_ticks_total", "ticks").inc(10)
                sup.fleet_obs.merge_snapshot(
                    sid, RegistryCollector(reg, gen=99).collect())
            merged = sup.merged_registry()
            series, _per_slot, values = _series_stats(merged)
            match_ids = set(ctx["match_ids"])
            viewer_ids = {"V0", "V1"}
            leaked = (match_ids | viewer_ids) & values
            assert not leaked, f"per-match/per-viewer labels: {leaked}"
            assert series < 400, f"series count {series} unbounded?"
        finally:
            sup.close()


# ----------------------------------------------------------------------
# the tentpole, end to end: harvest + traces over a real subprocess
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def traced_proc_fleet():
    tracer = Tracer(capacity=16384)
    ctx = drive_proc_fleet(
        TICKS, matches_per_shard=PER_SHARD, seed=7, backend="proc",
        tuning=TUNING, tracer=tracer, desync_interval=0,
    )
    ctx["tracer"] = tracer
    # the direct-query control: the runner's registries, fetched over an
    # explicit debug RPC AFTER the run (all prior frames drain first, so
    # the harvest and the query observe the same final state)
    sup = ctx["sup"]
    rpc_ops_before_query = {
        labels["op"]
        for fam in sup.metrics.families()
        if fam.name == "ggrs_fleet_proc_rpc_seconds"
        for labels, _child in fam.samples()
    }
    ctx["rpc_ops"] = rpc_ops_before_query
    ctx["direct"] = sup.shards["s1"]._call("metrics")
    yield ctx
    ctx["sup"].close()


@needs_native
class TestFleetHarvestE2E:
    def test_one_scrape_serves_runner_counters_by_shard(
            self, traced_proc_fleet):
        """The acceptance pin: the merged view carries the subprocess
        runner's counters (journal family, fsync histogram, pool ticks)
        labeled shard=s1,backend=proc — and they are VALUE-EQUAL to
        querying the runner's registry directly."""
        ctx = traced_proc_fleet
        har = ctx["sup"].fleet_obs.harvest
        direct = ctx["direct"]["shard"]

        def direct_value(name, **labels):
            for s in direct[name]["samples"]:
                if all(s["labels"].get(k) == v for k, v in labels.items()):
                    return s.get("value", s.get("count"))
            return None

        for name in ("ggrs_journal_frames_total",
                     "ggrs_journal_bytes_total",
                     "ggrs_pool_ticks_total"):
            merged = har.value(name, shard="s1", backend="proc")
            assert merged is not None, f"{name} not harvested"
            assert merged == direct_value(name), name
        # the histogram acceptance example: journal fsync, bucket-equal
        fam = {f.name: f for f in har.families()}[
            "ggrs_journal_fsync_seconds"]
        child = fam.labels(shard="s1", backend="proc")
        dsamp = direct["ggrs_journal_fsync_seconds"]["samples"][0]
        assert child.count == dsamp["count"]
        assert child.sum == pytest.approx(dsamp["sum"])
        assert [c for _u, c in child.cumulative()] == [
            b["count"] for b in dsamp["buckets"]
        ]

    def test_harvest_adds_zero_rpc_round_trips(self, traced_proc_fleet):
        """Only the serving path's ops appear in the RPC histogram — the
        harvest rides their replies, it never adds a call."""
        assert traced_proc_fleet["rpc_ops"] <= {
            "hello", "tick", "admit", "adopt", "evict", "drop",
            "identity", "healthz", "retire", "shutdown",
        }

    def test_metrics_server_serves_the_fleet(self, traced_proc_fleet):
        """One HTTP scrape of the supervisor returns the runner's
        families, shard-labeled, as conformant exposition."""
        import urllib.request

        from ggrs_tpu.obs import start_http_server

        sup = traced_proc_fleet["sup"]
        server = start_http_server(sup.merged_registry(), port=0,
                                   health=sup.healthz)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics"
            ) as r:
                text = r.read().decode()
        finally:
            server.close()
        assert validate_exposition(text) == []
        assert 'ggrs_journal_fsync_seconds_bucket{' in text
        line = next(l for l in text.splitlines()
                    if l.startswith("ggrs_pool_ticks_total{"))
        assert 'shard="s1"' in line and 'backend="proc"' in line

    def test_fleet_link_families_are_conformant(self):
        """§28 satellite: the TCP fleet-link transport's families are
        present and conformant in the merged scrape (the link only
        instruments when a shard actually serves over TCP)."""
        ctx = drive_proc_fleet(16, matches_per_shard=1, seed=9,
                               backend="tcp", tuning=TUNING,
                               desync_interval=0)
        try:
            text = prometheus_text(ctx["sup"].merged_registry())
        finally:
            ctx["sup"].close()
        assert validate_exposition(text) == []
        assert any(ln.startswith("ggrs_fleet_link_")
                   for ln in text.splitlines())

    def test_perfetto_export_nests_runner_crossing_in_fleet_tick(
            self, traced_proc_fleet):
        """The cross-process trace pin: fleet.tick spans contain the
        subprocess runner's bank.crossing (offset-adjusted), and the
        export passes schema validation."""
        tracer = traced_proc_fleet["tracer"]
        trace = tracer.chrome_trace()
        assert validate_chrome_trace(trace, eps_us=50.0) == []
        evs = trace["traceEvents"]
        fleet_ticks = [e for e in evs if e["name"] == "fleet.tick"]
        crossings = [
            e for e in evs if e["name"] == "bank.crossing"
            and e.get("args", {}).get("shard") == "s1"
        ]
        assert len(fleet_ticks) == TICKS
        assert crossings, "no runner bank.crossing spans shipped"
        nested = sum(
            1 for c in crossings for f in fleet_ticks
            if f["ts"] <= c["ts"]
            and c["ts"] + c["dur"] <= f["ts"] + f["dur"]
        )
        assert nested == len(crossings)
        # the runner's tick span carries the fleet tick id (correlation)
        rt = [e for e in evs if e["name"] == "runner.tick"]
        assert rt and all(
            isinstance(e["args"].get("tick"), int) for e in rt
        )

    def test_healthz_aggregates_runner_liveness(self, traced_proc_fleet):
        h = traced_proc_fleet["sup"].healthz()
        assert h["proc"]["s1"]["watchdog"] == "ok"
        assert h["proc"]["s1"]["heartbeat_age_s"] is not None
        assert h["max_proc_heartbeat_age_s"] is not None
        assert h["shards"]["s1"]["watchdog"] == "ok"

    def test_digest_is_json_safe(self, traced_proc_fleet):
        import json as _json

        d = fleet_metrics_digest(traced_proc_fleet["sup"])
        _json.dumps(d)
        assert d["snapshots_merged"] > 0
        assert d["snapshot_dups"] == 0 and d["samples_dropped"] == 0


# ----------------------------------------------------------------------
# the forensics ferry
# ----------------------------------------------------------------------


@needs_native
class TestForensicsFerry:
    def test_runner_fault_forensics_reach_the_supervisor(self):
        """A native slot fault injected IN the runner quarantines the
        slot there; the flight-recorder dump and fault log ferry back on
        the next tick reply instead of dying with the child."""

        def inject(i, ctx):
            if i == 24:
                ctx["sup"].shards["s1"].inject_match_error("m1")

        ctx = drive_proc_fleet(
            TICKS, matches_per_shard=1, seed=13, backend="proc",
            tuning=TUNING, inject=inject, desync_interval=0,
        )
        sup = ctx["sup"]
        try:
            items = [f for f in sup.fleet_obs.forensics
                     if f["shard"] == "s1"]
            assert items, "no forensics ferried from the runner"
            item = items[0]
            assert item["kind"] == "slot" and item["match"] == "m1"
            assert "fault" in item["dump"]  # the recorder saw the fault
            assert item["faults"]
            assert sup.metrics.value(
                "ggrs_fleet_obs_forensics_total", shard="s1", kind="slot"
            ) >= 1
        finally:
            sup.close()

    def test_inproc_shard_feeds_the_same_ring(self):
        def inject(i, ctx):
            if i == 24:
                ctx["sup"].shards["s0"].inject_match_error("m0")

        ctx = drive_fleet_chaos(TICKS, matches_per_shard=1, seed=13,
                                inject=inject, desync_interval=0)
        sup = ctx["sup"]
        try:
            items = [f for f in sup.fleet_obs.forensics
                     if f["shard"] == "s0"]
            assert items and items[0]["match"] == "m0"
        finally:
            sup.close()


# ----------------------------------------------------------------------
# healthz satellite: a STALE runner pages before it is dead
# ----------------------------------------------------------------------


class TestStaleRunnerPages:
    def test_sigstopped_runner_flips_fleet_healthz(self):
        """SIGSTOP a runner (alive but silent): within the heartbeat
        deadline the fleet /healthz aggregate must go not-ok and surface
        the watchdog stage — paging on staleness, not only on death."""
        t = FleetTuning(
            heartbeat_interval_s=0.05, heartbeat_deadline_s=0.3,
            rpc_timeout_s=0.3, drain_deadline_s=30.0,
            spawn_timeout_s=120.0, restart_max=0,
        )
        sup = ShardSupervisor(("s1",), proc_shards=("s1",), tuning=t,
                              metrics=Registry())
        try:
            s1 = sup.shards["s1"]
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                sup.advance_all()
                if sup.healthz()["ok"]:
                    break
                time.sleep(0.02)
            assert sup.healthz()["ok"]
            os.kill(s1.pid, signal.SIGSTOP)
            paged = None
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                sup.advance_all()
                h = sup.healthz()
                if not h["ok"] and s1._child_alive():
                    paged = h
                    break
                time.sleep(0.02)
            assert paged is not None, "stale runner never paged"
            assert paged["proc"]["s1"]["watchdog"] in (
                "suspect", "terminating"
            )
            assert s1._child_alive()  # paged while merely wedged
        finally:
            sup.close()


# ----------------------------------------------------------------------
# fleet_top rendering
# ----------------------------------------------------------------------


class TestFleetTop:
    def test_histogram_quantile(self):
        uppers = [0.001, 0.01, 0.1]
        # 10 obs <=1ms, 10 in (1,10]ms, none beyond
        assert histogram_quantile(0.5, uppers, [10, 20, 20, 20]) == \
            pytest.approx(0.001)
        q99 = histogram_quantile(0.99, uppers, [10, 20, 20, 20])
        assert 0.001 < q99 <= 0.01
        assert histogram_quantile(0.99, uppers, []) is None
        assert histogram_quantile(0.99, uppers, [0, 0, 0, 0]) is None

    def test_render_from_fleet_snapshots(self):
        import importlib.util
        from pathlib import Path

        spec = importlib.util.spec_from_file_location(
            "fleet_top",
            Path(__file__).resolve().parents[1] / "scripts"
            / "fleet_top.py",
        )
        fleet_top = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(fleet_top)

        ctx = drive_fleet_chaos(24, matches_per_shard=2, seed=5)
        sup = ctx["sup"]
        try:
            healthz = sup.healthz()
            metrics = json_snapshot(sup.merged_registry())
        finally:
            sup.close()
        frame = fleet_top.render(healthz, metrics)
        assert "s0" in frame and "s1" in frame
        assert "SHARD" in frame and "WATCHDOG" in frame
        assert "admissions=" in frame and "harvest:" in frame
