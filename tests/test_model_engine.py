"""ggrs-model pillar 4, the engine half: toy machines with known
diameters and known shortest counterexamples pin the explorer's
semantics — BFS determinism, shortest-counterexample, deadlock policy,
progress-as-reachability, budget verdicts, and replayable traces.

The tree's real machines are exercised by tests/test_model_machines.py;
here every model is small enough to verify by hand.
"""

from typing import NamedTuple

import pytest

from ggrs_tpu.analysis import (
    Action,
    Invariant,
    Model,
    ModelError,
    Progress,
    check,
    replay,
)


class S(NamedTuple):
    n: int


def counter(limit: int, **kwargs) -> Model:
    """0 -> 1 -> ... -> limit, absorbing at limit."""
    return Model(
        "counter",
        S(0),
        [Action("inc", lambda s: s.n < limit, lambda s: S(s.n + 1))],
        terminal=lambda s: s.n == limit,
        **kwargs,
    )


class TestExploration:
    def test_clean_chain_counts_states_and_depth(self):
        r = check(counter(5))
        assert r.ok and r.kind == "clean"
        assert r.states == 6
        assert r.transitions == 5
        assert r.depth == 5
        assert r.trace == ()

    def test_invariant_violation_is_shortest(self):
        # two ways to reach n=3: the long inc chain and a 1-step jump.
        # BFS must report the 1-step trace, never the 3-step one.
        m = Model(
            "shortcut",
            S(0),
            [
                Action("inc", lambda s: s.n < 3, lambda s: S(s.n + 1)),
                Action("jump", lambda s: s.n == 0, lambda s: S(3)),
            ],
            invariants=[Invariant("below-three", lambda s: s.n < 3)],
            terminal=lambda s: True,
        )
        r = check(m)
        assert not r.ok and r.kind == "invariant"
        assert r.violation == "below-three"
        assert [t.action for t in r.trace] == ["<init>", "jump"]

    def test_exploration_is_deterministic(self):
        m = Model(
            "nondet",
            S(0),
            [Action("fan", lambda s: s.n < 4,
                    lambda s: [S(s.n + 1), S(s.n + 2)])],
            terminal=lambda s: True,
        )
        results = [check(m) for _ in range(3)]
        assert len({(r.kind, r.states, r.transitions, r.depth)
                    for r in results}) == 1

    def test_nondet_branch_recorded_in_trace(self):
        m = Model(
            "branchy",
            S(0),
            [Action("fan", lambda s: s.n == 0, lambda s: [S(1), S(7)])],
            invariants=[Invariant("small", lambda s: s.n < 7)],
            terminal=lambda s: True,
        )
        r = check(m)
        assert not r.ok
        assert r.trace[-1].action == "fan" and r.trace[-1].branch == 1
        assert replay(m, r.trace) == S(7)

    def test_multiple_inits_are_a_list(self):
        m = Model(
            "two-roots",
            [S(0), S(10)],
            [Action("inc", lambda s: s.n in (0, 10),
                    lambda s: S(s.n + 1))],
            invariants=[Invariant("not-eleven", lambda s: s.n != 11)],
            terminal=lambda s: True,
        )
        r = check(m)
        assert not r.ok
        # counterexample roots at the SECOND init state
        assert r.trace[0].state == {"n": 10}

    def test_duplicate_action_names_rejected(self):
        with pytest.raises(ModelError, match="duplicate action"):
            Model("dup", S(0), [
                Action("a", lambda s: True, lambda s: s),
                Action("a", lambda s: True, lambda s: s),
            ])

    def test_unhashable_state_is_a_model_error(self):
        m = Model(
            "unhashable", S(0),
            [Action("bad", lambda s: True, lambda s: [[1]])],
        )
        with pytest.raises(ModelError, match="unhashable"):
            check(m)


class TestDeadlockAndProgress:
    def test_undeclared_sink_is_a_deadlock(self):
        r = check(Model(
            "stuck", S(0),
            [Action("inc", lambda s: s.n < 2, lambda s: S(s.n + 1))],
        ))
        assert not r.ok and r.kind == "deadlock"
        assert [t.action for t in r.trace] == ["<init>", "inc", "inc"]

    def test_terminal_blesses_the_sink(self):
        assert check(counter(2)).ok

    def test_progress_catches_the_wedge(self):
        # n=2 branches to a wedged n=9 loop from which the goal n=3 is
        # unreachable — safety never fires, progress must
        m = Model(
            "wedge",
            S(0),
            [
                Action("inc", lambda s: s.n < 3, lambda s: S(s.n + 1)),
                Action("wedge", lambda s: s.n == 2, lambda s: S(9)),
                Action("spin", lambda s: s.n == 9, lambda s: S(9)),
            ],
            progress=[Progress("reaches-three", lambda s: s.n == 3)],
            terminal=lambda s: s.n == 3,
        )
        r = check(m)
        assert not r.ok and r.kind == "progress"
        assert r.violation == "reaches-three"
        assert r.trace[-1].state == {"n": 9}
        assert replay(m, r.trace) == S(9)

    def test_progress_clean_when_goal_always_reachable(self):
        m = counter(3, progress=[Progress("done", lambda s: s.n == 3)])
        assert check(m).ok


class TestBudgets:
    def test_state_budget_yields_budget_verdict(self):
        r = check(counter(10_000), max_states=50)
        assert not r.ok and r.kind == "budget"
        assert "50 states" in r.violation

    def test_time_budget_uses_injected_clock(self):
        ticks = iter(range(1000))
        r = check(counter(10_000), max_seconds=5.0,
                  clock=lambda: float(next(ticks)))
        assert not r.ok and r.kind == "budget"


class TestReplay:
    def test_replay_rejects_tampered_trace(self):
        m = Model(
            "tamper", S(0),
            [Action("inc", lambda s: s.n < 3, lambda s: S(s.n + 1))],
            invariants=[Invariant("below", lambda s: s.n < 3)],
            terminal=lambda s: True,
        )
        r = check(m)
        assert not r.ok
        forged = list(r.trace)
        forged[-1] = forged[-1]._replace(state={"n": 99})
        with pytest.raises(ModelError, match="diverged"):
            replay(m, forged)

    def test_replay_rejects_disabled_action(self):
        m = counter(2)
        r = check(m)
        trace = list(check(Model(
            "donor", S(0),
            [Action("inc", lambda s: s.n < 3, lambda s: S(s.n + 1))],
            invariants=[Invariant("below", lambda s: s.n < 3)],
            terminal=lambda s: True,
        )).trace)
        assert r.ok
        with pytest.raises(ModelError, match="not enabled"):
            replay(m, trace)  # third inc is disabled at limit=2

    def test_describe_and_trace_json_round(self):
        r = check(Model(
            "desc", S(0),
            [Action("inc", lambda s: s.n < 1, lambda s: S(s.n + 1))],
            invariants=[Invariant("zero", lambda s: s.n == 0)],
        ))
        assert "invariant (zero)" in r.describe()
        assert "counterexample (1 steps): inc" in r.describe()
        assert r.trace_json() == [
            {"action": "<init>", "branch": 0, "state": {"n": 0}},
            {"action": "inc", "branch": 0, "state": {"n": 1}},
        ]
