"""Codec tests: round-trip property and never-crash-on-garbage hardening
(parity with /root/reference/src/network/compression.rs:188-231)."""

import pytest

pytest.importorskip("hypothesis")  # fuzz-only dep: absent on lean CI images

from hypothesis import example, given, settings
from hypothesis import strategies as st

from ggrs_tpu.net.compression import CodecError, decode, encode


def test_encode_decode_fixed_case():
    ref = bytes([0, 0, 0, 1])
    inputs = [
        bytes([0, 0, 1, 0]),
        bytes([0, 0, 1, 1]),
        bytes([0, 1, 0, 0]),
        bytes([0, 1, 0, 1]),
        bytes([0, 1, 1, 0]),
    ]
    assert decode(ref, encode(ref, inputs)) == inputs


def test_highly_redundant_inputs_compress_well():
    ref = bytes(16)
    inputs = [bytes(16)] * 100  # all identical to reference: pure zero delta
    encoded = encode(ref, inputs)
    assert len(encoded) < 32  # 1600 raw bytes collapse under XOR+RLE


# Committed regression seeds (the analog of the reference's
# proptest-regressions/network/compression.txt): @example cases replay on
# every checkout before hypothesis generates novel ones.
@settings(max_examples=200)
@given(
    reference=st.binary(max_size=32),
    inputs=st.lists(st.binary(max_size=32), max_size=32),
)
@example(reference=b"", inputs=[b"", b""])  # the reference's own shrunk case
@example(reference=b"", inputs=[])
@example(reference=b"\x00", inputs=[b"", b"\x00", b"\x00\x00"])
@example(reference=b"\x07" * 32, inputs=[b"\x07" * 32] * 32)  # max redundancy
def test_encode_decode_round_trip(reference, inputs):
    encoded = encode(reference, inputs)
    # empty reference with no explicit sizes cannot be decoded; the encoder
    # only omits sizes when the reference is non-empty, so decode must succeed
    assert decode(reference, encoded) == inputs


@settings(max_examples=300)
@given(reference=st.binary(max_size=2048), data=st.binary(max_size=2048))
@example(reference=b"", data=b"\x01")  # size mode, then truncated
@example(reference=b"", data=b"\x02")  # invalid size-mode byte
@example(reference=b"", data=b"\x00\x01" + b"\xff" * 8 + b"\x01")
@example(  # huge claimed zero run inside the RLE stream
    reference=b"\x00",
    data=b"\x00\x0a" + b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\x01",
)
@example(  # negative input size via zigzag delta
    reference=b"", data=b"\x01\x01\x03\x00"
)
def test_decode_arbitrary_input_never_crashes(reference, data):
    # bytes come from potentially malicious peers: CodecError is the only
    # acceptable failure mode
    try:
        decode(reference, data)
    except CodecError:
        pass


def test_zero_run_bomb_rejected():
    # craft a packet claiming a gigantic zero run; decode must refuse to
    # allocate it
    from ggrs_tpu.net.wire import Writer

    w = Writer()
    w.u8(1)
    w.uvarint(1)
    w.svarint((1 << 40))  # one input of absurd size
    inner = Writer()
    inner.uvarint(((1 << 40) << 1) | 1)  # zero run of 2^40
    w.bytes(inner.finish())
    with pytest.raises(CodecError):
        decode(b"", w.finish())
