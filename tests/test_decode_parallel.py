"""Pins for the parallel slow-slot decode plane + GRO inbound (§24).

The host bank's slow slots now decode on a worker pool —
``decode_slot_record`` (the pure half of ``_parse_slot``) runs against
read-only views of the shared tick buffer and the owning thread replays
the side effects in slot order.  Everything here pins that plane
bit-identical to the serial reference under every backend this box can
run: request values, events, wire bytes, journal streams, and frame
mirrors, under seeded loss/dup/reorder, on the event-heavy blackout
path, and across fault/eviction ticks.  Plus: the crossing budget is
untouched (the plane adds ZERO ctypes crossings), the §20 ownership
guard holds, the kill switches force bit-identical degradation, and the
GRO receive path is pinned both natively (a forced GSO train splits
back into per-datagram records) and at the pool level (arming GRO never
changes the peer-observed wire stream over real loopback UDP).
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import random
import socket as pysocket
import struct
import threading

import pytest

from ggrs_tpu.core.config import Config
from ggrs_tpu.net import InMemoryNetwork, _native
from ggrs_tpu.parallel.decode_pool import DecodePool
from ggrs_tpu.parallel.host_bank import HostSessionPool
from ggrs_tpu.utils.ownership import CrossThreadAccess

from test_session_bank import (  # noqa: E402  (pytest rootdir path)
    assert_requests_equal,
    fulfill_saves,
    needs_native,
    two_peer_builders,
)
from test_net_gen2 import needs_gen2, run_inbound_leg  # noqa: E402

# Backends worth exercising on THIS box: serial always; thread always
# (on a GIL build it wins no wall time but must stay bit-identical —
# the whole point of the pin); interp only where the stdlib has it.
_BACKENDS = ["thread"]
if DecodePool._interp_available():
    _BACKENDS.append("interp")

_PLANE_ENV = (
    "GGRS_TPU_DECODE_BACKEND",
    "GGRS_TPU_NO_PARALLEL_DECODE",
    "GGRS_TPU_DECODE_WORKERS",
    "GGRS_TPU_NO_GRO",
    "GGRS_TPU_NO_FASTPATH",
)


@contextlib.contextmanager
def _env(d):
    """Hold exactly ``d`` of the decode-plane env switches, restoring the
    previous posture after.  The backend is resolved at pool finalization
    (the first ``advance_all``), so drives wrap EVERY advance call — cheap,
    and robust to re-plans."""
    saved = {k: os.environ.pop(k, None) for k in _PLANE_ENV}
    os.environ.update(d)
    try:
        yield
    finally:
        for k in _PLANE_ENV:
            os.environ.pop(k, None)
        for k, v in saved.items():
            if v is not None:
                os.environ[k] = v


def _make_pool(builders, env):
    with _env(env):
        pool = HostSessionPool()
        for b, s in builders:
            pool.add_session(b, s)
        assert pool.native_active, "native bank did not engage"
    return pool


def _drive_pair(env_a, env_b, faults, ticks, n_matches=3, journals=None,
                blackout=None, scrape_every=0, inject_error_at=None):
    """Drive two identically-seeded pools — leg A under ``env_a``, leg B
    under ``env_b`` — comparing requests, events, frames, and wire bytes
    every tick.  Returns (pool_a, pool_b, saw_events)."""
    clock = [0]
    net_a = InMemoryNetwork(**faults)
    net_b = InMemoryNetwork(**faults)
    builders_a = two_peer_builders(net_a, clock, n_matches)
    builders_b = two_peer_builders(net_b, clock, n_matches)
    pool_a = _make_pool(builders_a, env_a)
    pool_b = _make_pool(builders_b, env_b)
    if journals is not None:
        from ggrs_tpu.broadcast.hub import SpectatorHub

        hub_a = SpectatorHub(pool_a)
        hub_b = SpectatorHub(pool_b)
        (ja, jb) = journals
        hub_a.attach_journal(0, ja)
        hub_b.attach_journal(0, jb)
    n = len(builders_a)
    saw_events = 0
    for i in range(ticks):
        if inject_error_at is not None and i == inject_error_at:
            pool_a.inject_slot_error(1)
            pool_b.inject_slot_error(1)
        dark = blackout is not None and i in blackout
        if dark:
            clock[0] += 300  # starved liveness: the event-heavy path
        clock[0] += 16
        for idx in range(n):
            v = (i + idx) % 16
            pool_a.add_local_input(idx, idx % 2, v)
            pool_b.add_local_input(idx, idx % 2, v)
        with _env(env_a):
            reqs_a = pool_a.advance_all()
        with _env(env_b):
            reqs_b = pool_b.advance_all()
        if scrape_every and i % scrape_every == 0:
            pool_a.scrape()
            pool_b.scrape()
        for idx in range(n):
            assert_requests_equal(
                reqs_b[idx], reqs_a[idx], f"tick {i} slot {idx}"
            )
            fulfill_saves(reqs_a[idx])
            fulfill_saves(reqs_b[idx])
        if not dark:
            net_a.tick()
            net_b.tick()
        for idx in range(n):
            ev_a = pool_a.events(idx)
            saw_events += len(ev_a)
            assert ev_a == pool_b.events(idx), (
                f"tick {i} slot {idx}: events diverged"
            )
            assert pool_a.current_frame(idx) == pool_b.current_frame(idx)
            assert (
                pool_a.last_confirmed_frame(idx)
                == pool_b.last_confirmed_frame(idx)
            )
            sa = builders_a[idx][1].sent
            sb = builders_b[idx][1].sent
            assert sa == sb, (
                f"tick {i} slot {idx}: wire bytes diverged "
                f"({len(sa)} vs {len(sb)} datagrams)"
            )
    return pool_a, pool_b, saw_events


# ----------------------------------------------------------------------
# the headline parity fuzz: each available backend vs the serial
# reference, bit for bit
# ----------------------------------------------------------------------


@needs_native
class TestParallelDecodeParity:
    @pytest.mark.parametrize("backend", _BACKENDS)
    @pytest.mark.parametrize("seed", [7, 19])
    def test_fuzzed_traffic_bit_identical(self, backend, seed):
        """Seeded loss/dup/reorder with the fast path OFF (every slot
        slow, every tick fans out): the parallel plane is bit-identical
        to the serial reference — and it actually engaged."""
        rng = random.Random(seed)
        faults = dict(
            loss=0.08, duplicate=0.04, reorder=0.15,
            seed=rng.randrange(1 << 30),
        )
        pool_a, pool_b, _ = _drive_pair(
            {"GGRS_TPU_DECODE_BACKEND": backend,
             "GGRS_TPU_NO_FASTPATH": "1"},
            {"GGRS_TPU_NO_PARALLEL_DECODE": "1",
             "GGRS_TPU_NO_FASTPATH": "1"},
            faults, ticks=120,
        )
        dec = pool_a.io_stats()["decode"]
        assert dec["backend"] == backend
        assert dec["parallel_ticks"] > 0, "parallel plane never engaged"
        assert dec["jobs"] >= 2 * dec["parallel_ticks"]
        assert len(dec["worker_jobs"]) >= 2, (
            f"one worker decoded everything: {dec['worker_jobs']}"
        )
        assert pool_b.io_stats()["decode"]["backend"] == "serial"
        assert pool_b.io_stats()["decode"]["parallel_ticks"] == 0

    def test_fastpath_regime_parity(self):
        """With the §19 fast path ON, only the tick's genuinely slow
        slots reach the pool — parity must hold through the mixed
        fast/slow plan decode too."""
        faults = dict(loss=0.1, duplicate=0.05, reorder=0.2, seed=1234)
        pool_a, _, _ = _drive_pair(
            {"GGRS_TPU_DECODE_BACKEND": "thread"},
            {"GGRS_TPU_NO_PARALLEL_DECODE": "1"},
            faults, ticks=150,
        )
        assert pool_a.fast_slot_ticks > 0, "fast path never engaged"

    def test_event_heavy_blackout_parity(self):
        """Clock-jump blackouts force interrupt/resume events and retry
        storms — the densest records the decoder sees — through the
        parallel plane, pinned against the reference."""
        pool_a, _, saw_events = _drive_pair(
            {"GGRS_TPU_DECODE_BACKEND": "thread",
             "GGRS_TPU_NO_FASTPATH": "1"},
            {"GGRS_TPU_NO_PARALLEL_DECODE": "1",
             "GGRS_TPU_NO_FASTPATH": "1"},
            dict(), ticks=100, blackout={40, 41, 42, 80},
        )
        assert saw_events > 0, "blackout produced no events"
        assert pool_a.decode_parallel_ticks > 0

    def test_fault_and_eviction_ticks_parity(self):
        """A slot faulting mid-run (quarantine -> eviction, §9) must
        transit identically whether its neighbours decode in parallel or
        serial — including the supervision feed and the evicted slot's
        resumed progress."""
        pool_a, pool_b, _ = _drive_pair(
            {"GGRS_TPU_DECODE_BACKEND": "thread",
             "GGRS_TPU_NO_FASTPATH": "1"},
            {"GGRS_TPU_NO_PARALLEL_DECODE": "1",
             "GGRS_TPU_NO_FASTPATH": "1"},
            dict(), ticks=60, inject_error_at=12,
        )
        feed_a = pool_a.drain_state_transitions()
        feed_b = pool_b.drain_state_transitions()
        assert feed_a == feed_b, "supervision transitions diverged"
        assert [t[2] for t in feed_a][:2] == ["quarantined", "evicted"]
        for idx in range(len(pool_a._mirrors)):
            assert pool_a.slot_state(idx) == pool_b.slot_state(idx)
        assert pool_a.current_frame(1) > 12  # evicted slot resumed
        assert pool_a.decode_parallel_ticks > 0

    def test_journal_streams_bit_identical(self, tmp_path):
        """The journal tap's confirmed-frame records ride the decoded
        broadcast tail: both legs' journal files must be byte-identical."""
        from ggrs_tpu.broadcast.journal import MatchJournal

        cfg_players, isize = 2, Config.for_uint(16).native_input_size
        ja = MatchJournal(tmp_path / "a.journal", cfg_players, isize)
        jb = MatchJournal(tmp_path / "b.journal", cfg_players, isize)
        pool_a, _, _ = _drive_pair(
            {"GGRS_TPU_DECODE_BACKEND": "thread",
             "GGRS_TPU_NO_FASTPATH": "1"},
            {"GGRS_TPU_NO_PARALLEL_DECODE": "1",
             "GGRS_TPU_NO_FASTPATH": "1"},
            dict(loss=0.05, seed=7), ticks=90, journals=(ja, jb),
        )
        assert pool_a.decode_parallel_ticks > 0
        ja.close()
        jb.close()
        a = (tmp_path / "a.journal").read_bytes()
        b = (tmp_path / "b.journal").read_bytes()
        assert a == b and len(a) > 0, "journal streams diverged"

    def test_crossing_budget_plane_adds_zero(self):
        """The decode plane lives entirely on the Python side of the tick
        buffer: still exactly one tick crossing per pool tick and one
        stats crossing per scraped tick — zero new ctypes crossings."""
        pool_a, _, _ = _drive_pair(
            {"GGRS_TPU_DECODE_BACKEND": "thread",
             "GGRS_TPU_NO_FASTPATH": "1"},
            {"GGRS_TPU_NO_PARALLEL_DECODE": "1",
             "GGRS_TPU_NO_FASTPATH": "1"},
            dict(), ticks=60, scrape_every=1,
        )
        assert pool_a.crossings == 60
        assert pool_a.stat_crossings == 60
        assert pool_a.harvests == 0
        assert pool_a.decode_parallel_ticks > 0


# ----------------------------------------------------------------------
# kill switches, capability matrix, ownership
# ----------------------------------------------------------------------


@needs_native
class TestDecodePlaneDegradation:
    def test_kill_switch_forces_serial(self):
        """GGRS_TPU_NO_PARALLEL_DECODE beats a forced backend: the pool
        resolves serial, starts no workers, and the capability matrix
        says so."""
        with _env({"GGRS_TPU_NO_PARALLEL_DECODE": "1",
                   "GGRS_TPU_DECODE_BACKEND": "thread"}):
            dp = DecodePool()
        assert dp.backend == "serial" and dp._executor is None

        clock = [0]
        net = InMemoryNetwork()
        builders = two_peer_builders(net, clock, 1)
        env = {"GGRS_TPU_NO_PARALLEL_DECODE": "1"}
        pool = _make_pool(builders, env)
        for i in range(4):
            clock[0] += 16
            for idx in range(len(builders)):
                pool.add_local_input(idx, idx % 2, i)
            with _env(env):
                for reqs in pool.advance_all():
                    fulfill_saves(reqs)
            net.tick()
        caps = pool.io_capabilities()
        assert not caps["parallel_decode"]
        assert caps["decode_backend"] == "serial"
        assert pool.decode_parallel_ticks == 0

    def test_unknown_forced_backend_degrades_to_serial(self):
        with _env({"GGRS_TPU_DECODE_BACKEND": "quantum"}):
            dp = DecodePool()
        assert dp.backend == "serial"

    def test_capability_matrix_reports_backend(self):
        clock = [0]
        net = InMemoryNetwork()
        builders = two_peer_builders(net, clock, 1)
        env = {"GGRS_TPU_DECODE_BACKEND": "thread"}
        pool = _make_pool(builders, env)
        for i in range(4):
            clock[0] += 16
            for idx in range(len(builders)):
                pool.add_local_input(idx, idx % 2, i)
            with _env(env):
                for reqs in pool.advance_all():
                    fulfill_saves(reqs)
            net.tick()
        caps = pool.io_capabilities()
        assert caps["parallel_decode"]
        assert caps["decode_backend"] == "thread"
        dec = pool.io_stats()["decode"]
        assert set(dec) >= {"backend", "workers", "jobs", "batches",
                            "decode_ns", "worker_jobs", "parallel_ticks"}

    def test_ownership_guard_holds(self):
        """decode_slots is a §20 driving method: a foreign thread calling
        it trips CrossThreadAccess — the worker boundary is the
        module-level pure function, never the pool object."""
        dp = DecodePool(backend="thread", workers=2)
        try:
            assert dp.decode_slots(b"", []) == []  # pins ownership here
            caught = []

            def foreign():
                try:
                    dp.decode_slots(b"", [])
                except CrossThreadAccess as e:
                    caught.append(e)

            t = threading.Thread(target=foreign)
            t.start()
            t.join()
            assert caught, "cross-thread decode_slots did not raise"
        finally:
            dp.close()


# ----------------------------------------------------------------------
# GRO inbound: native split units + pool-level wire parity
# ----------------------------------------------------------------------


def _gro_supported():
    lib = _native.net_lib()
    return bool(
        lib is not None
        and hasattr(lib, "ggrs_net_gro_supported")
        and lib.ggrs_net_gro_supported()
    )


needs_gro = pytest.mark.skipif(
    not _gro_supported(), reason="kernel lacks UDP_GRO"
)


def _drain_gro(lib, fd_rows, route_rows, max_recs=256, slab_cap=1 << 20):
    """One-shot recv_table drain returning per-record ``seg`` too."""
    recs = ctypes.create_string_buffer(max_recs * _native.NET_RECV_STRIDE)
    slab = ctypes.create_string_buffer(slab_cap)
    stats = (ctypes.c_uint64 * _native.NET_RECV_TABLE_STATS)()
    fatal = (ctypes.c_int32 * 64)()
    n_fatal = ctypes.c_int32(0)
    fd_tab = b"".join(struct.pack("<ii", fd, s) for fd, s in fd_rows)
    route_rows = sorted(route_rows, key=lambda r: (r[0] << 16) | r[1])
    route_tab = b"".join(
        struct.pack("<IHHi", ip, port, 0, s) for ip, port, s in route_rows
    )
    n = lib.ggrs_net_recv_table(
        fd_tab, len(fd_rows), route_tab, len(route_rows),
        recs, max_recs, slab, slab_cap,
        stats, fatal, 32, ctypes.byref(n_fatal),
    )
    assert n >= 0, f"recv_table failed: {n}"
    out = []
    for k in range(n):
        slot, fd_idx, ip, port, seg, off, ln = struct.unpack_from(
            "<iiIHHII", recs, k * _native.NET_RECV_STRIDE
        )
        out.append((slot, seg, slab[off:off + ln]))
    return out, list(stats)


@needs_gen2
class TestGroInbound:
    @needs_gro
    def test_gso_train_splits_into_per_datagram_records(self):
        """A UDP_SEGMENT-coalesced train arriving on a UDP_GRO socket
        must come out of ``ggrs_net_recv_table`` as per-datagram records
        — seg-numbered, byte-exact, with the gro stat tail counting the
        train and ``datagrams`` counting post-split wire datagrams."""
        lib = _native.net_lib()
        sol_udp = getattr(pysocket, "IPPROTO_UDP", 17)
        tx = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_DGRAM)
        rx = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_DGRAM)
        try:
            rx.bind(("127.0.0.1", 0))
            rx.setblocking(False)
            rx.setsockopt(sol_udp, getattr(pysocket, "UDP_GRO", 104), 1)
            port = rx.getsockname()[1]
            seg_size, n_segs = 320, 4
            payload = b"".join(
                bytes([0x41 + i]) * seg_size for i in range(n_segs)
            )
            tx.setsockopt(
                sol_udp, getattr(pysocket, "UDP_SEGMENT", 103), seg_size
            )
            tx.sendto(payload, ("127.0.0.1", port))
            tx_ip = int.from_bytes(
                pysocket.inet_aton("127.0.0.1"), "little"
            )
            tx_port = tx.getsockname()[1]
            lib.ggrs_net_set_gro(1)
            try:
                recs, stats = _drain_gro(
                    lib, [(rx.fileno(), -1)], [(tx_ip, tx_port, 5)]
                )
            finally:
                lib.ggrs_net_set_gro(0)  # global posture: restore default
            assert [r[1] for r in recs] == list(range(n_segs))
            assert all(r[0] == 5 for r in recs)  # demux held through GRO
            assert b"".join(r[2] for r in recs) == payload
            assert stats[1] == n_segs  # datagrams: post-split count
            assert stats[12] == 1      # gro_datagrams: one train
            assert stats[13] == n_segs  # gro_segments
        finally:
            tx.close()
            rx.close()

    @needs_gro
    def test_ordinary_datagram_truncation_parity(self):
        """The GRO ring's 64 KB buffers must not change what a plain
        oversized datagram delivers: clamped to the non-GRO ring's 4096,
        both modes, byte-identical."""
        lib = _native.net_lib()
        legs = {}
        for mode in (0, 1):
            tx = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_DGRAM)
            rx = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_DGRAM)
            try:
                rx.bind(("127.0.0.1", 0))
                rx.setblocking(False)
                tx.sendto(bytes(range(256)) * 32,  # 8192 bytes
                          ("127.0.0.1", rx.getsockname()[1]))
                tx_ip = int.from_bytes(
                    pysocket.inet_aton("127.0.0.1"), "little"
                )
                lib.ggrs_net_set_gro(mode)
                try:
                    recs, stats = _drain_gro(
                        lib, [(rx.fileno(), -1)],
                        [(tx_ip, tx.getsockname()[1], 0)],
                    )
                finally:
                    lib.ggrs_net_set_gro(0)
                assert len(recs) == 1 and recs[0][1] == 0
                legs[mode] = recs[0][2]
            finally:
                tx.close()
                rx.close()
        assert len(legs[0]) == 4096
        assert legs[0] == legs[1], "GRO ring changed truncation bytes"

    @pytest.mark.parametrize("seed", [3])
    def test_gro_on_off_peer_wire_parity(self, seed):
        """Arming GRO on the dispatch hub must not change one byte of
        what peers observe over real loopback UDP under seeded
        loss/dup/reorder — any inbound divergence would change the
        host's outbound stream."""
        faults = dict(loss=0.05, duplicate=0.03, reorder=0.03)
        ticks, n_matches = 120, 2
        lib = _native.net_lib()
        try:
            with _env({"GGRS_TPU_NO_GRO": "1"}):
                ref = run_inbound_leg("dispatch", seed, ticks, n_matches,
                                      faults)
            assert not ref["stats"]["capabilities"]["gro"]  # killed
            assert not ref["stats"]["capabilities"]["gro_active"]
            with _env({}):
                leg = run_inbound_leg("dispatch", seed, ticks, n_matches,
                                      faults)
        finally:
            if hasattr(lib, "ggrs_net_set_gro"):
                lib.ggrs_net_set_gro(0)  # global posture: restore default
        for m in range(n_matches):
            assert leg["tapes"][m] == ref["tapes"][m], (
                f"match {m}: wire bytes diverged with GRO armed "
                f"(ref {len(ref['tapes'][m])} datagrams, "
                f"gro {len(leg['tapes'][m])})"
            )
        assert leg["frames"] == ref["frames"]
        if _gro_supported():
            assert leg["stats"]["capabilities"]["gro"]
            assert leg["stats"]["capabilities"]["gro_active"], (
                "kernel supports GRO but the hub never armed it"
            )
            drain = leg["stats"]["drain"]
            assert drain["gro_segments"] >= drain["gro_datagrams"]

    def test_no_gro_env_reports_killed_capability(self):
        """The kill switch shows up in the matrix even on a kernel with
        GRO — per-feature degradation, never silent."""
        with _env({"GGRS_TPU_NO_GRO": "1"}):
            pool = HostSessionPool()
            caps = pool.io_capabilities()
        assert not caps["gro"]
        assert not caps["gro_active"]
