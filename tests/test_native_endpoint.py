"""Parity pins for the endpoint datapath cores (net/endpoint.py).

The C++ ``NativeEndpointCore`` and pure-Python ``PyEndpointCore`` must be
indistinguishable above the ``make_endpoint_core`` seam: identical wire
bytes, identical events, identical session outcomes — including under
loss/duplication/reordering and under malformed or oversized input.  These
tests run full two-peer protocol pumps twice, once per core, and compare
the complete observable record.
"""

from __future__ import annotations

import random

import pytest

from ggrs_tpu.core.config import Config
from ggrs_tpu.core.frame_info import PlayerInput
from ggrs_tpu.core.types import DesyncDetection, NULL_FRAME
from ggrs_tpu.net import _native
from ggrs_tpu.net import protocol as protocol_mod
from ggrs_tpu.net.endpoint import NativeEndpointCore, PyEndpointCore
from ggrs_tpu.net.messages import ConnectionStatus
from ggrs_tpu.net.protocol import EvInput, PeerProtocol
from ggrs_tpu.net.sockets import InMemoryNetwork

pytestmark = pytest.mark.skipif(
    not _native.available(), reason="native library unavailable"
)


def u8_config() -> Config:
    return Config.for_uint(bits=8)


def make_pair(core: str, seed: int, net: InMemoryNetwork):
    """Two connected PeerProtocols using the requested core, plus their
    sockets."""
    protos = {}
    socks = {}
    orig = protocol_mod.make_endpoint_core

    def py_core(send_base, recv_base, max_prediction):
        return PyEndpointCore(send_base, recv_base, max_prediction)

    factory = py_core if core == "py" else orig
    protocol_mod.make_endpoint_core, saved = factory, orig
    try:
        for me, other, h in (("A", "B", 0), ("B", "A", 1)):
            protos[me] = PeerProtocol(
                config=u8_config(),
                handles=[1 - h],
                peer_addr=other,
                num_players=2,
                local_players=1,
                max_prediction=8,
                disconnect_timeout_ms=2000,
                disconnect_notify_start_ms=500,
                fps=60,
                desync_detection=DesyncDetection.off(),
                clock=lambda: 0,
                rng=random.Random(seed + h),
            )
            socks[me] = net.socket(me)
    finally:
        protocol_mod.make_endpoint_core = saved
    # sanity: the factory actually took effect
    want = PyEndpointCore if core == "py" else NativeEndpointCore
    assert isinstance(protos["A"]._core, want), type(protos["A"]._core)
    return protos, socks


def pump(core: str, seed: int, ticks: int, **faults):
    """Drive two peers for ``ticks`` frames; record every delivered datagram
    and every protocol event, in order."""
    net = InMemoryNetwork(seed=seed, **faults)
    protos, socks = make_pair(core, seed, net)
    record = []
    status = {
        "A": [ConnectionStatus(), ConnectionStatus()],
        "B": [ConnectionStatus(), ConnectionStatus()],
    }
    for i in range(ticks):
        net.tick()
        for me, other, h in (("A", "B", 0), ("B", "A", 1)):
            p = protos[me]
            for from_addr, data in socks[me].receive_all_datagrams():
                record.append(("recv", me, bytes(data)))
                p.handle_datagram(data)
            for ev in p.poll(status[me]):
                if isinstance(ev, EvInput):
                    record.append(
                        ("input", me, ev.player, ev.input.frame, ev.input.input)
                    )
                    status[me][ev.player].last_frame = ev.input.frame
                else:
                    record.append(("event", me, type(ev).__name__))
            status[me][h].last_frame = i
            p.send_input({h: PlayerInput(i, (i * 7 + h * 3) % 251)}, status[me])
            p.send_all_messages(socks[me])
    for me in ("A", "B"):
        record.append(
            ("final", me, protos[me].last_recv_frame(),
             protos[me]._core.pending_len())
        )
    return record


class TestCoreParity:
    def test_clean_link_record_identical(self):
        assert pump("native", seed=3, ticks=60) == pump("py", seed=3, ticks=60)

    def test_lossy_link_record_identical(self):
        for seed in (1, 7, 42):
            a = pump("native", seed=seed, ticks=80, loss=0.2, duplicate=0.1,
                     reorder=0.2)
            b = pump("py", seed=seed, ticks=80, loss=0.2, duplicate=0.1,
                     reorder=0.2)
            assert a == b, f"seed {seed}: native and python cores diverge"

    def test_latency_link_record_identical(self):
        a = pump("native", seed=9, ticks=80, latency_ticks=3)
        b = pump("py", seed=9, ticks=80, latency_ticks=3)
        assert a == b


class TestMalformedDatagrams:
    """handle_datagram must drop garbage silently with no state change,
    whichever core is active (the socket layer used to own this drop)."""

    GARBAGE = [
        b"",
        b"\x00",
        b"\xff\xff",
        b"\xaa\xbb\x00",  # input tag, truncated body
        b"\xaa\xbb\x00\x01\x02",  # bad bool in status
        b"\xaa\xbb\x00\x00\x00\x00\x00\x05abc",  # payload len > data
        b"\xaa\xbb\x63",  # unknown tag
        bytes(range(256)),
    ]

    @pytest.mark.parametrize("core", ["native", "py"])
    def test_garbage_dropped_silently(self, core):
        net = InMemoryNetwork()
        protos, socks = make_pair(core, seed=5, net=net)
        p = protos["A"]
        before = (p.last_recv_frame(), p._core.pending_len())
        for g in self.GARBAGE:
            p.handle_datagram(g)
        assert p.poll([ConnectionStatus(), ConnectionStatus()]) == []
        assert (p.last_recv_frame(), p._core.pending_len()) == before
        p.send_all_messages(socks["A"])
        # no acks or other responses were queued for garbage
        assert socks["B"].receive_all_datagrams() == []


class TestFrameSanityBound:
    @pytest.mark.parametrize("core", ["native", "py"])
    @pytest.mark.parametrize(
        "start", [2**62 + 5, 2**63 - 1, -(2**62) - 7, -(2**63)]
    )
    def test_beyond_i64_contract_start_frames_dropped_on_every_path(
        self, core, start
    ):
        """Frames beyond the i64 wire contract are malformed; the fused
        native path, the object path, and the Python core must all drop
        them with no state change (regression: the fused path once
        committed them, diverging the cores and risking signed-overflow UB
        in C++)."""
        from ggrs_tpu.net import compression
        from ggrs_tpu.net.messages import InputMessage, Message

        net = InMemoryNetwork()
        protos, _ = make_pair(core, seed=17, net=net)
        p = protos["A"]
        comp = compression.encode(b"", [b"\x01\x07"])
        evil = Message(7, InputMessage(
            peer_connect_status=[ConnectionStatus(), ConnectionStatus()],
            disconnect_requested=False, start_frame=start, ack_frame=-1,
            bytes=comp,
        )).encode()
        p.handle_datagram(evil)
        assert p.last_recv_frame() == NULL_FRAME
        assert not [
            e for e in p.poll([ConnectionStatus(), ConnectionStatus()])
            if isinstance(e, EvInput)
        ]


class TestOversizedFallback:
    def test_huge_window_takes_python_codec_path_and_stays_consistent(self):
        """More staged frames than the native caps (512) must fall back to
        the Python codec via fetch_base/store_one and still deliver every
        input in order."""
        net = InMemoryNetwork()
        protos, socks = make_pair("native", seed=11, net=net)
        a, b = protos["A"], protos["B"]
        status = [ConnectionStatus(), ConnectionStatus()]
        # A sends 600 frames without ever hearing an ack
        for i in range(600):
            status[0].last_frame = i
            a.send_input({0: PlayerInput(i, i % 251)}, status)
        a.send_all_messages(socks["A"])
        delivered = socks["B"].receive_all_datagrams()
        assert delivered  # one giant datagram per send; take the last
        b.handle_datagram(delivered[-1][1])
        events = [e for e in b.poll(status) if isinstance(e, EvInput)]
        assert len(events) == 600
        assert [e.input.frame for e in events] == list(range(600))
        assert [e.input.input for e in events] == [i % 251 for i in range(600)]
        assert b.last_recv_frame() == 599


class TestStatusCapParity:
    @pytest.mark.parametrize("core_name", ["py", "native"])
    def test_both_cores_reject_more_than_64_statuses(self, core_name):
        """The 64-entry connect-status wire cap must hold identically in both
        cores, not only via the SessionBuilder player-count guard — a caller
        constructing PeerProtocol directly must observe the same behavior."""
        if core_name == "py":
            core = PyEndpointCore(b"\x00", b"\x00", 8)
        else:
            lib = _native.endpoint_lib()
            if lib is None:  # prebuilt codec-only .so, no toolchain
                pytest.skip("endpoint symbols unavailable")
            core = NativeEndpointCore(lib, b"\x00", b"\x00", 8)
        core.push_input(0, b"\x05")
        statuses = [ConnectionStatus() for _ in range(65)]
        with pytest.raises(RuntimeError, match="65 connect statuses exceed"):
            core.emit_input(0xABCD, statuses, False)
        # at the cap itself both cores still emit
        ok = core.emit_input(0xABCD, statuses[:64], False)
        assert ok is not None
