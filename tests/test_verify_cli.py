"""scripts/ggrs_verify.py end to end: the self-clean gate the CI flow
(build_sanitized.sh) runs, plus the JSON artifact and baseline-update
round trip in a scratch location."""

import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
CLI = REPO / "scripts/ggrs_verify.py"


def run_cli(*args):
    return subprocess.run(
        [sys.executable, str(CLI), *args],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )


class TestVerifyCli:
    def test_tree_passes_baseline_aware(self):
        proc = run_cli()
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "ggrs-verify: PASS" in proc.stdout
        # the committed legacy findings are reported, not fatal
        assert "FAIL " not in proc.stdout

    def test_json_artifact(self, tmp_path):
        out = tmp_path / "verify.json"
        proc = run_cli("--json", str(out))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        verdict = json.loads(out.read_text())
        assert verdict["verdict"] == "PASS"
        assert verdict["new"] == []
        assert set(verdict["counts"]) == {
            "layout", "determinism", "ownership", "transitions",
            "hygiene",
        }
        assert "models" not in verdict  # only --model embeds the leg

    def test_quick_mode_passes(self):
        proc = run_cli("--quick")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "ggrs-verify: PASS" in proc.stdout
        assert "model leg:" not in proc.stdout

    def test_model_leg_and_trace_artifact(self, tmp_path):
        out = tmp_path / "verify.json"
        proc = run_cli("--model", "--no-runtime", "--json", str(out))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "model leg: 18 models," in proc.stdout
        assert "invariant(expected)" in proc.stdout
        verdict = json.loads(out.read_text())
        assert verdict["counts"]["model"] == 0
        models = {m["model"]: m for m in verdict["models"]}
        assert len(models) == 18
        # the pinned §20.4 counterexample rides in the artifact,
        # replayable from the trace alone
        fix = models["checkpoint-order:pre-pr11"]
        assert [s["action"] for s in fix["trace"][1:]] == [
            "advance_rollback", "checkpoint", "crash_failover",
        ]
        assert models["watchdog:head"]["kind"] == "clean"

    def test_bad_model_budget_is_a_tool_error(self):
        proc = run_cli("--model", "--model-budget", "lots")
        assert proc.returncode == 2
        assert "bad --model-budget" in proc.stderr

    def test_empty_baseline_fails_on_legacy_findings(self, tmp_path):
        """With a blank baseline the legacy findings become new: the
        exit must flip non-zero — the 'new violations fail' contract."""
        blank = tmp_path / "blank.json"
        blank.write_text('{"version": 2, "files": {}}\n')
        proc = run_cli("--baseline", str(blank))
        # the tree currently carries legacy determinism findings; if it
        # ever becomes fully clean this leg degenerates to PASS, which
        # is fine — assert consistency either way
        if json.loads(
            (REPO / "ggrs_tpu/analysis/determinism_baseline.json")
            .read_text()
        )["files"]:
            assert proc.returncode == 1, proc.stdout
            assert "FAIL" in proc.stdout
        else:
            assert proc.returncode == 0

    def test_baseline_update_roundtrip(self, tmp_path):
        scratch = tmp_path / "scratch.json"
        proc = run_cli("--baseline", str(scratch), "--baseline-update")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert scratch.exists()
        proc = run_cli("--baseline", str(scratch))
        assert proc.returncode == 0, proc.stdout + proc.stderr
