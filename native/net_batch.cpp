// Kernel-batched UDP datapath: recvmmsg/sendmmsg ring buffers around one
// bound non-blocking UDP fd (DESIGN.md §15).
//
// The Python shuttle pays one syscall PLUS one Python→C round trip per
// datagram on both sides of the tick crossing; at B matches × peers plus
// the spectator fan-out that is hundreds-to-thousands of sendto/recvfrom
// calls per pool tick.  A NetBatch replaces them with (typically) one
// recvmmsg and one sendmmsg per slot per tick: preallocated iovec +
// sockaddr slabs, datagrams copied once into a per-tick accumulation slab
// so the session bank can route them by source address without holding the
// kernel rings.
//
// SEMANTICS mirror ggrs_tpu.net.sockets.UdpNonBlockingSocket exactly:
//  - receive drains until EAGAIN/EWOULDBLOCK; ECONNRESET/ECONNREFUSED
//    between datagrams is skipped (the post-sendto ICMP echo some OSes
//    surface), anything else is fatal;
//  - transient send errnos (the _TRANSIENT_SEND_ERRNOS set: ENETUNREACH,
//    EHOSTUNREACH, ECONNREFUSED, ENETDOWN, EHOSTDOWN, ENOBUFS, EAGAIN,
//    EWOULDBLOCK) count the datagram as lost — the endpoint protocol's
//    redundant sends already cover loss — and the flush continues;
//  - EMSGSIZE / EPERM and friends are deterministic local faults: the
//    flush aborts fatally (the bank turns that into a per-slot fault, the
//    same blast radius a raising socket.sendto has on the Python path);
//  - datagrams above the 4096-byte receive buffer truncate, datagrams
//    above the 508-byte ideal UDP size are counted (never blocked).
//
// The NetBatch is owned by the Python pool (ggrs_net_attach/free); the
// session bank only borrows the pointer (ggrs_bank_attach_socket).  One
// NetBatch serves one fd and is single-threaded, like everything else in
// the host loop.
//
// TEST SEAMS (observational; zero cost when unused):
//  - capture tee: every staged datagram is mirrored into a drainable
//    buffer so parity fuzzes can pin the batched path's full wire byte
//    sequence — content AND send order — against the Python shuttle;
//  - errno injection: the next N staged datagrams fail with a chosen
//    errno before reaching sendmmsg (scripts/chaos.py --fault socket).
//
// Non-Linux builds compile the same extern-C surface as stubs
// (ggrs_net_supported() == 0); the pool then keeps the Python shuttle —
// the fallback matrix in DESIGN.md §15.

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <new>
#include <vector>

namespace {

// return codes (mirrored in ggrs_tpu/net/_native.py)
constexpr int kNetOk = 0;
constexpr int kNetErrUnsupported = -80;
constexpr int kNetErrFatal = -81;
constexpr int kNetErrBadArgs = -82;
constexpr int kNetErrBufferTooSmall = -11;  // wire_common kErrBufferTooSmall

// sockets.py RECV_BUFFER_SIZE / IDEAL_MAX_UDP_PACKET_SIZE
constexpr size_t kRecvBufSize = 4096;
constexpr size_t kIdealMaxUdp = 508;

// ---- one-shot batched send table (descriptor plane, DESIGN.md §21) ------
// ggrs_net_send_table record stride: non-attached sockets (native_io off,
// or sockets that could not attach) route their whole tick's outbound
// through ONE crossing — per datagram: i32 fd, u32 ip (sin_addr.s_addr as
// stored), u16 port (host order), u16 pad, u32 off, u32 len (off/len jump
// into the shared payload, usually the tick output buffer itself).
// Records for one fd must be contiguous (the pool emits per-slot runs);
// stride and field order mirrored by _native.NET_SEND_FIELDS.
constexpr size_t kSendStride = 20;

// ---- datapath gen 2 (DESIGN.md §23) -------------------------------------
// ggrs_net_recv_table: ONE crossing drains every non-attached fd-backed
// socket of the pool.  Inputs: an fd descriptor table (kFdStride bytes per
// entry: i32 fd, i32 slot; slot == -1 marks a shared DISPATCH fd whose
// datagrams are demuxed by source address through the route table) and a
// route table sorted by (ip, port) (kRouteStride bytes per entry: u32 ip,
// u16 port, u16 pad, i32 slot).  Output: a packed record table
// (kRecvStride bytes per datagram: i32 slot, i32 fd_idx, u32 ip, u16 port,
// u16 seg, u32 off, u32 len) whose off/len index the caller's slab.  `seg`
// is the segment index when a GRO-coalesced datagram was split back into
// its wire datagrams (§23d) — 0 for ordinary datagrams, 0..n-1 across one
// coalesced train (same stride, the u16 that used to be padding).
constexpr size_t kRecvStride = 24;
constexpr size_t kRouteStride = 12;
constexpr size_t kFdStride = 8;

// send-table record flags (the u16 at record offset 10, formerly pad):
// bit0 marks a DISPATCH record — the fd is shared by many slots, so a
// fatal errno faults only THIS record (reported, skipped, run continues)
// instead of abandoning the rest of the fd's run.
constexpr uint16_t kSendFlagDispatch = 1;

// ggrs_net_send_table stats words: {sent, transient_errors, oversized,
// gso_sends, gso_segments} — mirrored as _native.NET_SEND_STATS.
constexpr int kSendTableStats = 5;

// ggrs_net_recv_table stats words: {recv_calls, datagrams, unroutable,
// backpressure_stops} + the 8-bucket batch-size histogram, then the GRO
// tail appended AFTER the histogram so existing indices never move:
// [12] gro_datagrams (coalesced trains split), [13] gro_segments (wire
// datagrams recovered from them) — mirrored as _native.NET_RECV_TABLE_STATS.
constexpr int kRecvTableStats = 14;
constexpr int kStGroDgrams = 12;
constexpr int kStGroSegs = 13;

// stat slots (mirrored as _native.IO_STAT_FIELDS + two 8-bucket
// histograms; 22 u64 total, the per-slot io tail of ggrs_bank_stats)
enum NetStat : int {
  kStRecvCalls = 0,   // recvmmsg invocations (incl. the EAGAIN probe)
  kStRecvDgrams = 1,  // datagrams received
  kStSendCalls = 2,   // sendmmsg invocations
  kStSendDgrams = 3,  // datagrams handed to the kernel
  kStSendErrors = 4,  // transient send failures counted as loss
  kStOversized = 5,   // staged datagrams above kIdealMaxUdp
  kStRecvHist0 = 6,   // recv batch-size buckets: 1,2,4,8,16,32,64,+inf
  kStSendHist0 = 14,  // send batch-size buckets, same bounds
  kNumNetStats = 22,
};

inline int batch_bucket(int n) {
  int b = 0, upper = 1;
  while (b < 7 && n > upper) {
    upper <<= 1;
    ++b;
  }
  return b;
}

}  // namespace

extern "C" {

// runtime stride probes (ggrs-verify pins these against the static
// layout contract, like ggrs_bank_hdr_stride on the bank side)
int ggrs_net_recv_stride(void) { return static_cast<int>(kRecvStride); }
int ggrs_net_route_stride(void) { return static_cast<int>(kRouteStride); }
int ggrs_net_fd_stride(void) { return static_cast<int>(kFdStride); }
int ggrs_net_send_stats_len(void) { return kSendTableStats; }
int ggrs_net_recv_stats_len(void) { return kRecvTableStats; }

}  // extern "C"

#if defined(__linux__)

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/udp.h>
#include <sys/socket.h>
#include <unistd.h>

// UDP_SEGMENT landed in linux 4.18; build against older headers still
// produces a working probe (the setsockopt simply fails on old kernels).
#ifndef UDP_SEGMENT
#define UDP_SEGMENT 103
#endif
// UDP_GRO (receive-side coalescing) landed in linux 5.0; same old-header
// story as UDP_SEGMENT — the probe simply fails on kernels without it.
#ifndef UDP_GRO
#define UDP_GRO 104
#endif
#ifndef SOL_UDP
#define SOL_UDP 17
#endif

namespace {

bool transient_send_errno(int e) {
  // _TRANSIENT_SEND_ERRNOS in sockets.py, member for member.  EMSGSIZE and
  // EPERM are deliberately NOT here: deterministic local faults that every
  // retransmission would hit identically must fail loudly, not stall.
  switch (e) {
    case ENETUNREACH:
    case EHOSTUNREACH:
    case ECONNREFUSED:
    case ENETDOWN:
#ifdef EHOSTDOWN
    case EHOSTDOWN:
#endif
    case ENOBUFS:
    case EAGAIN:
#if EWOULDBLOCK != EAGAIN
    case EWOULDBLOCK:
#endif
      return true;
    default:
      return false;
  }
}

struct Dgram {
  uint32_t ip;    // sin_addr.s_addr, network byte order as stored
  uint16_t port;  // host byte order
  uint32_t off, len;  // slice into the owning slab
};

// ---- GSO capability (gen 2) ---------------------------------------------
// One-time per-process probe: can a UDP socket take the UDP_SEGMENT
// option on THIS kernel?  g_gso_mode is the caller-facing override
// (ggrs_net_set_gso): -1 auto (probe decides), 0 forced off, 1 forced on
// (still requires the probe — a kernel that refuses the option cannot be
// forced).
int g_gso_mode = -1;

int gso_probe() {
  static int cached = -1;
  if (cached >= 0) return cached;
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) {
    cached = 0;
    return cached;
  }
  int seg = 1400;
  cached = setsockopt(fd, SOL_UDP, UDP_SEGMENT, &seg, sizeof(seg)) == 0;
  close(fd);
  return cached;
}

bool gso_active() { return g_gso_mode != 0 && gso_probe() != 0; }

// ---- GRO capability (gen 2, §23d) ---------------------------------------
// The receive-side mirror of the GSO probe: can a UDP socket take the
// UDP_GRO option on THIS kernel?  Unlike GSO (auto by default — the send
// path only ever gains from coalescing), the recv drain defaults OFF:
// gro_active() switches the drain onto the wide 16-message GRO ring,
// which trades per-syscall message count for train capacity, so it must
// be armed explicitly — the pool calls ggrs_net_set_gro(1) exactly when
// it flipped UDP_GRO on the hub fds the recv table covers.  Contract:
// 0 off (default), 1 on, -1 auto (probe-gated).  Sockets that never had
// UDP_GRO set produce no cmsg and decode exactly as before (minus the
// ordinary-datagram clamp, which preserves truncation parity).
int g_gro_mode = 0;

int gro_probe() {
  static int cached = -1;
  if (cached >= 0) return cached;
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) {
    cached = 0;
    return cached;
  }
  int on = 1;
  cached = setsockopt(fd, SOL_UDP, UDP_GRO, &on, sizeof(on)) == 0;
  close(fd);
  return cached;
}

bool gro_active() {
  if (g_gro_mode == 0) return false;
  if (g_gro_mode == 1) return true;
  return gro_probe() != 0;
}

// route table binary search: entries sorted by (ip, port) as the packed
// u64 key below (the pool sorts the same way)
inline uint64_t route_key(uint32_t ip, uint16_t port) {
  return (static_cast<uint64_t>(ip) << 16) | port;
}

int32_t route_lookup(const uint8_t* routes, int n_routes, uint32_t ip,
                     uint16_t port) {
  uint64_t want = route_key(ip, port);
  int lo = 0, hi = n_routes - 1;
  while (lo <= hi) {
    int mid = lo + (hi - lo) / 2;
    const uint8_t* p = routes + static_cast<size_t>(mid) * kRouteStride;
    uint32_t rip = 0;
    for (int b = 0; b < 4; ++b) rip |= static_cast<uint32_t>(p[b]) << (8 * b);
    uint16_t rport = static_cast<uint16_t>(p[4] | (p[5] << 8));
    uint64_t key = route_key(rip, rport);
    if (key == want) {
      uint32_t slot = 0;
      for (int b = 0; b < 4; ++b) {
        slot |= static_cast<uint32_t>(p[8 + b]) << (8 * b);
      }
      return static_cast<int32_t>(slot);
    }
    if (key < want) {
      lo = mid + 1;
    } else {
      hi = mid - 1;
    }
  }
  return -1;
}

// send-table errno injection (scripts/chaos.py --fault socket, dispatch
// leg): record indices [at, at+count) of subsequent ggrs_net_send_table
// calls fail with `err` before any syscall, until count is exhausted.
int g_table_inject_errno = 0;
int64_t g_table_inject_at = 0;
int g_table_inject_count = 0;

struct NetBatch {
  int fd = -1;
  int vlen = 64;
  // receive rings (kernel-facing, reused every recvmmsg)
  std::vector<mmsghdr> rmsgs;
  std::vector<iovec> riov;
  std::vector<sockaddr_in> raddr;
  std::vector<uint8_t> rbuf;  // vlen * kRecvBufSize
  // per-tick accumulation (bank-facing: stable until the next recv_all)
  std::vector<uint8_t> rslab;
  std::vector<Dgram> rlist;
  // staged sends (flushed in stage order)
  std::vector<uint8_t> sslab;
  std::vector<Dgram> slist;
  std::vector<mmsghdr> smsgs;
  std::vector<iovec> siov;
  std::vector<sockaddr_in> saddr;
  uint64_t st[kNumNetStats] = {0};
  // test seams
  bool capture = false;
  std::vector<uint8_t> capture_buf;  // [u32 ip][u16 port][u32 len][bytes]*
  int inject_errno = 0;
  int inject_count = 0;
};

void put_u16le(std::vector<uint8_t>* b, uint16_t v) {
  b->push_back(v & 0xFF);
  b->push_back(v >> 8);
}

void put_u32le(std::vector<uint8_t>* b, uint32_t v) {
  for (int i = 0; i < 4; ++i) b->push_back((v >> (8 * i)) & 0xFF);
}

}  // namespace

extern "C" {

int ggrs_net_supported(void) { return 1; }

// Wrap a bound, non-blocking UDP fd.  The fd stays owned by the caller
// (the Python socket object); max_batch bounds each recvmmsg/sendmmsg
// window.  Returns NULL on bad args / allocation failure.
void* ggrs_net_attach(int fd, int max_batch) {
  if (fd < 0) return nullptr;
  if (max_batch < 1) max_batch = 64;
  if (max_batch > 1024) max_batch = 1024;
  NetBatch* nb = new (std::nothrow) NetBatch();
  if (!nb) return nullptr;
  nb->fd = fd;
  nb->vlen = max_batch;
  size_t v = static_cast<size_t>(max_batch);
  nb->rmsgs.resize(v);
  nb->riov.resize(v);
  nb->raddr.resize(v);
  nb->rbuf.resize(v * kRecvBufSize);
  nb->smsgs.resize(v);
  nb->siov.resize(v);
  nb->saddr.resize(v);
  for (size_t i = 0; i < v; ++i) {
    nb->riov[i].iov_base = nb->rbuf.data() + i * kRecvBufSize;
    nb->riov[i].iov_len = kRecvBufSize;
    std::memset(&nb->rmsgs[i], 0, sizeof(mmsghdr));
    nb->rmsgs[i].msg_hdr.msg_iov = &nb->riov[i];
    nb->rmsgs[i].msg_hdr.msg_iovlen = 1;
    nb->rmsgs[i].msg_hdr.msg_name = &nb->raddr[i];
    nb->rmsgs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
  }
  return nb;
}

void ggrs_net_free(void* p) { delete static_cast<NetBatch*>(p); }

// Drain everything available on the fd into the accumulation slab (the
// receive_all_datagrams analog: loop until EAGAIN, but a partial batch
// already proves the queue ran dry at call time, saving the probe call).
// Returns the datagram count, or kNetErrFatal on an unexpected errno.
int ggrs_net_recv_all(void* p) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  nb->rslab.clear();
  nb->rlist.clear();
  while (true) {
    for (int i = 0; i < nb->vlen; ++i) {
      // the kernel shrinks msg_namelen / sets msg_len; reset per call
      nb->rmsgs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
      nb->rmsgs[i].msg_len = 0;
    }
    int r = recvmmsg(nb->fd, nb->rmsgs.data(),
                     static_cast<unsigned>(nb->vlen), 0, nullptr);
    nb->st[kStRecvCalls] += 1;
    if (r < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (errno == EINTR || errno == ECONNRESET || errno == ECONNREFUSED) {
        continue;  // the ConnectionResetError-continue of the Python path
      }
      return kNetErrFatal;
    }
    if (r == 0) break;
    nb->st[kStRecvDgrams] += static_cast<uint64_t>(r);
    nb->st[kStRecvHist0 + batch_bucket(r)] += 1;
    for (int i = 0; i < r; ++i) {
      size_t len = nb->rmsgs[i].msg_len;  // > 4096 already truncated
      Dgram d;
      d.ip = nb->raddr[i].sin_addr.s_addr;
      d.port = ntohs(nb->raddr[i].sin_port);
      d.off = static_cast<uint32_t>(nb->rslab.size());
      d.len = static_cast<uint32_t>(len);
      nb->rslab.insert(nb->rslab.end(), nb->rbuf.data() + i * kRecvBufSize,
                       nb->rbuf.data() + i * kRecvBufSize + len);
      nb->rlist.push_back(d);
    }
    if (r < nb->vlen) break;  // queue ran dry mid-batch: no probe needed
  }
  return static_cast<int>(nb->rlist.size());
}

// Datagram count of the last recv_all (the accumulation list survives
// until the next recv_all, so a caller may drain early and route later).
int ggrs_net_recv_count(void* p) {
  return static_cast<int>(static_cast<NetBatch*>(p)->rlist.size());
}

// Accessor for datagram `i` of the last recv_all.  Pointers stay valid
// until the next recv_all on this NetBatch.
int ggrs_net_datagram(void* p, int i, uint32_t* ip, uint16_t* port,
                      const uint8_t** data, uint32_t* len) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  if (i < 0 || static_cast<size_t>(i) >= nb->rlist.size()) {
    return kNetErrBadArgs;
  }
  const Dgram& d = nb->rlist[static_cast<size_t>(i)];
  *ip = d.ip;
  *port = d.port;
  *data = nb->rslab.data() + d.off;
  *len = d.len;
  return kNetOk;
}

// Stage one datagram for the next flush (bytes are copied into the send
// slab; the caller's buffer may be reused immediately).
int ggrs_net_stage(void* p, uint32_t ip, uint16_t port, const uint8_t* data,
                   size_t len) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  if (len > kIdealMaxUdp) nb->st[kStOversized] += 1;
  if (nb->capture) {
    put_u32le(&nb->capture_buf, ip);
    put_u16le(&nb->capture_buf, port);
    put_u32le(&nb->capture_buf, static_cast<uint32_t>(len));
    nb->capture_buf.insert(nb->capture_buf.end(), data, data + len);
  }
  Dgram d;
  d.ip = ip;
  d.port = port;
  d.off = static_cast<uint32_t>(nb->sslab.size());
  d.len = static_cast<uint32_t>(len);
  nb->sslab.insert(nb->sslab.end(), data, data + len);
  nb->slist.push_back(d);
  return kNetOk;
}

// Flush everything staged, in stage order, via sendmmsg windows.  Transient
// errnos drop the failing datagram (counted; the protocol's redundancy
// covers loss) and keep going; a fatal errno abandons the remaining
// datagrams and returns kNetErrFatal — the caller faults the slot, exactly
// like a raising socket.sendto on the Python path.
int ggrs_net_flush(void* p) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  size_t i = 0;
  const size_t n = nb->slist.size();
  int rc_out = kNetOk;
  while (i < n) {
    if (nb->inject_count > 0) {
      // chaos seam: the head datagram "fails" with the injected errno
      // before any syscall (an ENOBUFS/EAGAIN storm, or a fatal EPERM)
      nb->inject_count -= 1;
      if (transient_send_errno(nb->inject_errno)) {
        nb->st[kStSendErrors] += 1;
        i += 1;
        continue;
      }
      rc_out = kNetErrFatal;
      break;
    }
    size_t win = n - i;
    if (win > static_cast<size_t>(nb->vlen)) win = nb->vlen;
    for (size_t k = 0; k < win; ++k) {
      const Dgram& d = nb->slist[i + k];
      nb->siov[k].iov_base = nb->sslab.data() + d.off;
      nb->siov[k].iov_len = d.len;
      std::memset(&nb->saddr[k], 0, sizeof(sockaddr_in));
      nb->saddr[k].sin_family = AF_INET;
      nb->saddr[k].sin_addr.s_addr = d.ip;
      nb->saddr[k].sin_port = htons(d.port);
      std::memset(&nb->smsgs[k], 0, sizeof(mmsghdr));
      nb->smsgs[k].msg_hdr.msg_iov = &nb->siov[k];
      nb->smsgs[k].msg_hdr.msg_iovlen = 1;
      nb->smsgs[k].msg_hdr.msg_name = &nb->saddr[k];
      nb->smsgs[k].msg_hdr.msg_namelen = sizeof(sockaddr_in);
    }
    int r = sendmmsg(nb->fd, nb->smsgs.data(), static_cast<unsigned>(win), 0);
    nb->st[kStSendCalls] += 1;
    if (r < 0) {
      if (errno == EINTR) continue;  // retry the same window: PEP 475
      // semantics — a signal mid-send is invisible on the Python path
      // the errno belongs to the FIRST datagram of the window
      if (transient_send_errno(errno)) {
        nb->st[kStSendErrors] += 1;
        i += 1;
        continue;
      }
      rc_out = kNetErrFatal;
      break;
    }
    nb->st[kStSendDgrams] += static_cast<uint64_t>(r);
    nb->st[kStSendHist0 + batch_bucket(r)] += 1;
    i += static_cast<size_t>(r);
    // r < win without an errno: the next loop iteration retries from the
    // stall point and surfaces the real errno if one is pending
  }
  nb->slist.clear();
  nb->sslab.clear();
  return rc_out;
}

int64_t ggrs_net_staged_len(void* p) {
  return static_cast<int64_t>(static_cast<NetBatch*>(p)->slist.size());
}

void ggrs_net_stats(void* p, uint64_t* out) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  std::memcpy(out, nb->st, sizeof(nb->st));
}

// ---- test seams ---------------------------------------------------------

void ggrs_net_set_capture(void* p, int on) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  nb->capture = on != 0;
  if (!nb->capture) nb->capture_buf.clear();
}

// Drain the capture tee: [u32 ip][u16 port][u32 len][bytes] per datagram,
// in stage (= send) order.  kNetErrBufferTooSmall reports the needed size
// without consuming.
int ggrs_net_drain_capture(void* p, uint8_t* out, size_t cap,
                           size_t* out_len) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  *out_len = nb->capture_buf.size();
  if (nb->capture_buf.size() > cap) return kNetErrBufferTooSmall;
  std::memcpy(out, nb->capture_buf.data(), nb->capture_buf.size());
  nb->capture_buf.clear();
  return kNetOk;
}

// The next `count` staged datagrams fail with `err` before any syscall.
void ggrs_net_inject_send_errno(void* p, int err, int count) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  nb->inject_errno = err;
  nb->inject_count = count;
}

// GSO capability + override (gen 2).  ggrs_net_gso_supported() is the
// cached per-kernel probe; ggrs_net_set_gso(-1/0/1) is the caller
// override (auto / forced off / forced on — forcing on still requires
// the probe, a kernel that refuses UDP_SEGMENT cannot be forced).
int ggrs_net_gso_supported(void) { return gso_probe(); }
void ggrs_net_set_gso(int mode) {
  g_gso_mode = mode < 0 ? -1 : (mode ? 1 : 0);
}

// GRO capability + override (gen 2, §23d) — the receive-side siblings.
// A kernel that refuses UDP_GRO cannot be forced on; forcing off pins the
// drain to the pre-GRO record walk bit-identically (the 4096-byte ring,
// no cmsg parse).
int ggrs_net_gro_supported(void) { return gro_probe(); }
void ggrs_net_set_gro(int mode) {
  g_gro_mode = mode < 0 ? -1 : (mode ? 1 : 0);
}

// Chaos seam for the table path (the NetBatch inject covers only
// attached sockets): record indices >= `at` of subsequent
// ggrs_net_send_table calls fail with `err` before any syscall, one
// record per count, until `count` is exhausted.
void ggrs_net_inject_table_errno(int err, int64_t at, int count) {
  g_table_inject_errno = err;
  g_table_inject_at = at < 0 ? 0 : at;
  g_table_inject_count = count;
}

// One-shot batched send over ARBITRARY fds (descriptor plane, §21; gen 2
// §23): no NetBatch attach, no rings kept — the Python pool hands the
// whole tick's non-attached outbound as one packed table (`desc`: n
// records of kSendStride bytes; `payload`: the buffer the off/len fields
// index, usually the tick output buffer itself, zero copies).
// Consecutive same-fd records group into sendmmsg windows, so a pool
// tick pays one Python→C crossing total and ~one syscall per socket
// instead of one of each per datagram.  Gen 2: consecutive same-(ip,port)
// equal-size records inside a window coalesce into ONE UDP_SEGMENT
// (GSO) message when the kernel supports it — the spectator fan-out's
// per-viewer catch-up bursts become one segmented send — with automatic
// per-group fallback to plain sendmmsg on any GSO send failure.
//
// Errno semantics mirror UdpNonBlockingSocket.send_datagram exactly:
// transient errnos count the datagram as lost (stats[1]) and the flush
// continues; a fatal errno is reported as a (record index, errno) pair
// in `fatal` so the caller can fault exactly the owning slot.  A fatal
// on a plain per-slot record abandons the REST OF THAT FD's run (the
// same partial-send window a raising sendto leaves); a fatal on a
// record carrying kSendFlagDispatch (offset 10, bit0) skips ONLY that
// record — the fd is shared by many slots, and co-tenant records must
// still flush (§9: fault the owning slot, never the pool).  Oversized
// datagrams are counted (stats[2]), never blocked.  stats =
// {sent, transient_errors, oversized, gso_sends, gso_segments}
// (kSendTableStats words, accumulated; callers zero it).
//
// Returns the number of fatal pairs written (0 = clean), or
// kNetErrBadArgs.  The caller must sort records so each fd forms one
// contiguous run; a fatal fd seen again in a LATER run is retried (the
// pool never emits split runs).
int ggrs_net_send_table(const uint8_t* desc, int64_t n,
                        const uint8_t* payload, size_t payload_len,
                        uint64_t* stats, int32_t* fatal, int fatal_cap) {
  if (n < 0 || (n > 0 && (!desc || !payload || !stats))) {
    return kNetErrBadArgs;
  }
  constexpr int kWin = 64;
  constexpr int kGsoMaxSegs = 60;       // < UDP_MAX_SEGMENTS (64)
  constexpr size_t kGsoMaxBytes = 60000;  // < 16-bit UDP length budget
  static thread_local std::vector<mmsghdr> msgs(kWin);
  static thread_local std::vector<iovec> iov(kWin * kGsoMaxSegs);
  static thread_local std::vector<sockaddr_in> addr(kWin);
  static thread_local std::vector<uint8_t> cmsg(
      kWin * CMSG_SPACE(sizeof(uint16_t)));
  static thread_local std::vector<int64_t> msg_rec0(kWin);
  static thread_local std::vector<int64_t> msg_nrec(kWin);
  int n_fatal = 0;
  int64_t i = 0;
  // per-call GSO retreat: any send failure whose window head is a GSO
  // group falls the whole group back to plain records (covers both
  // transient parity — drop ONE datagram, not the group — and kernels
  // that accept the setsockopt probe but refuse segmented sends)
  int64_t plain_until = -1;
  auto rec = [&](int64_t k, int32_t* fd, uint32_t* ip, uint16_t* port,
                 uint16_t* flags, uint32_t* off, uint32_t* len) {
    const uint8_t* p = desc + static_cast<size_t>(k) * kSendStride;
    auto r32 = [&p](size_t at) {
      uint32_t v = 0;
      for (int b = 0; b < 4; ++b) {
        v |= static_cast<uint32_t>(p[at + b]) << (8 * b);
      }
      return v;
    };
    *fd = static_cast<int32_t>(r32(0));
    *ip = r32(4);
    *port = static_cast<uint16_t>(p[8] | (p[9] << 8));
    *flags = static_cast<uint16_t>(p[10] | (p[11] << 8));
    *off = r32(12);
    *len = r32(16);
  };
  auto inject_hits = [&](int64_t k) {
    return g_table_inject_count > 0 && k >= g_table_inject_at;
  };
  while (i < n) {
    int32_t fd;
    uint32_t ip, off, len;
    uint16_t port, flags;
    rec(i, &fd, &ip, &port, &flags, &off, &len);
    // the fd's contiguous run [i, run_end)
    int64_t run_end = i;
    while (run_end < n) {
      int32_t fd2;
      uint32_t ip2, off2, len2;
      uint16_t port2, flags2;
      rec(run_end, &fd2, &ip2, &port2, &flags2, &off2, &len2);
      if (fd2 != fd) break;
      if (static_cast<size_t>(off2) + len2 > payload_len) {
        return kNetErrBadArgs;  // corrupt table: refuse whole call
      }
      if (len2 > kIdealMaxUdp) stats[2] += 1;
      ++run_end;
    }
    int64_t j = i;
    while (j < run_end) {
      // chaos seam: the head record "fails" with the injected errno
      // before any syscall (window building below guarantees an
      // injected record always surfaces as a window head)
      if (inject_hits(j)) {
        g_table_inject_count -= 1;
        int32_t fdj;
        uint32_t ipj, offj, lenj;
        uint16_t portj, flagsj;
        rec(j, &fdj, &ipj, &portj, &flagsj, &offj, &lenj);
        if (transient_send_errno(g_table_inject_errno)) {
          stats[1] += 1;
          j += 1;
          continue;
        }
        if (n_fatal < fatal_cap && fatal) {
          fatal[2 * n_fatal] = static_cast<int32_t>(j);
          fatal[2 * n_fatal + 1] = static_cast<int32_t>(g_table_inject_errno);
        }
        ++n_fatal;
        if (flagsj & kSendFlagDispatch) {
          j += 1;  // shared fd: co-tenant records keep flushing
          continue;
        }
        break;  // per-slot fd: abandon the rest of the run
      }
      // build one sendmmsg window of up to kWin messages; each message
      // is either a single record or a GSO group of >= 2 consecutive
      // same-destination records (all full segments except a shorter
      // tail), expressed as one multi-iovec message + UDP_SEGMENT cmsg
      size_t nmsg = 0;
      size_t iov_used = 0;
      int64_t cursor = j;
      const bool gso = gso_active();
      while (nmsg < static_cast<size_t>(kWin) && cursor < run_end) {
        if (cursor > j && inject_hits(cursor)) break;  // keep at head
        int32_t fd0;
        uint32_t ip0, off0, len0;
        uint16_t port0, flags0;
        rec(cursor, &fd0, &ip0, &port0, &flags0, &off0, &len0);
        int64_t g = 1;
        if (gso && cursor >= plain_until && len0 > 0) {
          size_t total = len0;
          while (cursor + g < run_end && g < kGsoMaxSegs) {
            if (inject_hits(cursor + g)) break;
            int32_t fdg;
            uint32_t ipg, offg, leng;
            uint16_t portg, flagsg;
            rec(cursor + g - 1, &fdg, &ipg, &portg, &flagsg, &offg, &leng);
            if (leng != len0) break;  // previous must be a full segment
            rec(cursor + g, &fdg, &ipg, &portg, &flagsg, &offg, &leng);
            if (ipg != ip0 || portg != port0) break;
            if (leng > len0 || total + leng > kGsoMaxBytes) break;
            total += leng;
            ++g;
          }
        }
        if (g >= 2 && iov_used + static_cast<size_t>(g) > iov.size()) {
          break;  // iovec pool exhausted: flush what we have first
        }
        std::memset(&addr[nmsg], 0, sizeof(sockaddr_in));
        addr[nmsg].sin_family = AF_INET;
        addr[nmsg].sin_addr.s_addr = ip0;
        addr[nmsg].sin_port = htons(port0);
        std::memset(&msgs[nmsg], 0, sizeof(mmsghdr));
        msgs[nmsg].msg_hdr.msg_name = &addr[nmsg];
        msgs[nmsg].msg_hdr.msg_namelen = sizeof(sockaddr_in);
        msgs[nmsg].msg_hdr.msg_iov = &iov[iov_used];
        msgs[nmsg].msg_hdr.msg_iovlen = static_cast<size_t>(g);
        for (int64_t s = 0; s < g; ++s) {
          int32_t fds_;
          uint32_t ips, offs, lens;
          uint16_t ports, flagss;
          rec(cursor + s, &fds_, &ips, &ports, &flagss, &offs, &lens);
          iov[iov_used + static_cast<size_t>(s)].iov_base =
              const_cast<uint8_t*>(payload) + offs;
          iov[iov_used + static_cast<size_t>(s)].iov_len = lens;
        }
        if (g >= 2) {
          uint8_t* cb = cmsg.data() + nmsg * CMSG_SPACE(sizeof(uint16_t));
          msgs[nmsg].msg_hdr.msg_control = cb;
          msgs[nmsg].msg_hdr.msg_controllen = CMSG_SPACE(sizeof(uint16_t));
          cmsghdr* cm = CMSG_FIRSTHDR(&msgs[nmsg].msg_hdr);
          cm->cmsg_level = SOL_UDP;
          cm->cmsg_type = UDP_SEGMENT;
          cm->cmsg_len = CMSG_LEN(sizeof(uint16_t));
          uint16_t seg = static_cast<uint16_t>(len0);
          std::memcpy(CMSG_DATA(cm), &seg, sizeof(seg));
        }
        msg_rec0[nmsg] = cursor;
        msg_nrec[nmsg] = g;
        iov_used += static_cast<size_t>(g);
        cursor += g;
        ++nmsg;
      }
      if (nmsg == 0) break;  // defensive: cannot make progress
      int r = sendmmsg(fd, msgs.data(), static_cast<unsigned>(nmsg), 0);
      if (r < 0) {
        if (errno == EINTR) continue;  // PEP 475: retry the window
        if (msg_nrec[0] > 1) {
          // GSO head failed: retreat the whole group to plain records
          // and retry, so the errno attributes to exactly one datagram
          plain_until = msg_rec0[0] + msg_nrec[0];
          continue;
        }
        if (transient_send_errno(errno)) {
          stats[1] += 1;  // the head datagram is lost; keep going
          j += 1;
          continue;
        }
        int32_t fdj;
        uint32_t ipj, offj, lenj;
        uint16_t portj, flagsj;
        rec(j, &fdj, &ipj, &portj, &flagsj, &offj, &lenj);
        if (n_fatal < fatal_cap && fatal) {
          fatal[2 * n_fatal] = static_cast<int32_t>(j);
          fatal[2 * n_fatal + 1] = static_cast<int32_t>(errno);
        }
        ++n_fatal;
        if (flagsj & kSendFlagDispatch) {
          j += 1;  // shared fd: co-tenant records keep flushing
          continue;
        }
        break;  // per-slot fd: abandon the rest of the run
      }
      int64_t sent_recs = 0;
      for (int k = 0; k < r; ++k) {
        sent_recs += msg_nrec[static_cast<size_t>(k)];
        if (msg_nrec[static_cast<size_t>(k)] > 1) {
          stats[3] += 1;
          stats[4] += static_cast<uint64_t>(msg_nrec[static_cast<size_t>(k)]);
        }
      }
      stats[0] += static_cast<uint64_t>(sent_recs);
      j += sent_recs;
      // r < nmsg without errno: retry from the stall point next iteration
    }
    i = run_end;
  }
  return n_fatal;
}

// One-crossing inbound drain over ARBITRARY fds (gen 2, §23): the pool
// hands its whole non-attached fd set as one packed table (`fds`: n_fds
// entries of kFdStride bytes — i32 fd, i32 slot; slot == -1 marks a
// shared DISPATCH fd) plus a route table sorted by (ip, port)
// (`routes`: n_routes entries of kRouteStride bytes) for demuxing
// dispatch datagrams by source address.  Every fd is drained
// recvmmsg-until-dry with ggrs_net_recv_all's errno semantics; each
// datagram is copied once into `slab` and described by one kRecvStride
// record in `recs` (i32 slot, i32 fd_idx, u32 ip, u16 port, u16 pad,
// u32 off, u32 len), in arrival order per fd — the exact order the
// per-slot receive_all_datagrams reference observes.
//
// A fatal recv errno is reported as a (fd index, errno) pair in `fatal`
// (that fd stops; others keep draining) so the caller faults exactly
// the owning slot(s).  Unroutable dispatch datagrams are dropped and
// counted (stats[2]), like the Python demux dropping unknown sources.
// When the record table or slab cannot hold another full batch the
// drain STOPS — never mid-batch, so nothing read from the kernel is
// lost — and counts a backpressure stop (stats[3]); the kernel queue
// keeps the rest for the caller to regrow and re-drain.  stats =
// {recv_calls, datagrams, unroutable, backpressure_stops, hist[8],
// gro_datagrams, gro_segments} (kRecvTableStats words, accumulated;
// callers zero it).
//
// GRO (§23d): when the kernel takes UDP_GRO (and the caller enabled it
// on the fds — DispatchHub does), the drain runs on a wide ring (64 KiB
// buffers + cmsg space), reads the UDP_GRO cmsg per message, and splits
// each coalesced train back into one record per WIRE datagram: seg index
// at record offset 14, stats[1] counting segments so the datagram count
// matches the GRO-off drain exactly.  Ordinary datagrams on the wide
// ring clamp to the reference ring's 4096-byte truncation, so GRO-on is
// bit-identical to GRO-off on everything the records describe; the
// backpressure clamp reserves the kernel's 64-segments-per-train worst
// case before every syscall, same never-lose-what-was-read rule.
//
// Returns the record count (>= 0) or kNetErrBadArgs; the fatal-pair
// count lands in *n_fatal_out.
int ggrs_net_recv_table(const uint8_t* fds, int n_fds,
                        const uint8_t* routes, int n_routes,
                        uint8_t* recs, int max_recs,
                        uint8_t* slab, int64_t slab_cap,
                        uint64_t* stats, int32_t* fatal, int fatal_cap,
                        int32_t* n_fatal_out) {
  if (n_fds < 0 || n_routes < 0 || max_recs < 0 || slab_cap < 0 ||
      (n_fds > 0 && (!fds || !recs || !slab || !stats || !n_fatal_out)) ||
      (n_routes > 0 && !routes)) {
    return kNetErrBadArgs;
  }
  constexpr int kDrainWin = 64;
  struct Ring {
    std::vector<mmsghdr> msgs;
    std::vector<iovec> iov;
    std::vector<sockaddr_in> addr;
    std::vector<uint8_t> buf;
    Ring() : msgs(kDrainWin), iov(kDrainWin), addr(kDrainWin),
             buf(static_cast<size_t>(kDrainWin) * kRecvBufSize) {
      for (int k = 0; k < kDrainWin; ++k) {
        iov[k].iov_base = buf.data() + static_cast<size_t>(k) * kRecvBufSize;
        iov[k].iov_len = kRecvBufSize;
        std::memset(&msgs[k], 0, sizeof(mmsghdr));
        msgs[k].msg_hdr.msg_iov = &iov[k];
        msgs[k].msg_hdr.msg_iovlen = 1;
        msgs[k].msg_hdr.msg_name = &addr[k];
        msgs[k].msg_hdr.msg_namelen = sizeof(sockaddr_in);
      }
    }
  };
  // GRO ring (§23d): 64 KiB messages — one coalesced train can be a full
  // UDP payload — plus per-message cmsg space for the UDP_GRO
  // segment-size ancillary data.  The window matches the normal ring's
  // (kDrainWin): when the kernel coalesces nothing (small sparse flows)
  // an armed drain must not batch WORSE than the 4 KiB ring, and when it
  // does coalesce, 64 msgs × up to 64 segments pulls ~4k wire datagrams
  // per syscall.  Lazily constructed thread-local: a GRO-less box never
  // pays the ~4 MiB.
  constexpr int kGroWin = 64;
  constexpr size_t kGroBufSize = 65536;
  constexpr int kGroMaxSegs = 64;   // kernel cap on segments per train
  constexpr size_t kGroCtlSpace = 64;  // >= CMSG_SPACE(sizeof(int)) + slack
  struct GroRing {
    std::vector<mmsghdr> msgs;
    std::vector<iovec> iov;
    std::vector<sockaddr_in> addr;
    std::vector<uint8_t> buf;
    std::vector<uint8_t> ctl;
    GroRing() : msgs(kGroWin), iov(kGroWin), addr(kGroWin),
                buf(static_cast<size_t>(kGroWin) * kGroBufSize),
                ctl(static_cast<size_t>(kGroWin) * kGroCtlSpace) {
      for (int k = 0; k < kGroWin; ++k) {
        iov[k].iov_base = buf.data() + static_cast<size_t>(k) * kGroBufSize;
        iov[k].iov_len = kGroBufSize;
        std::memset(&msgs[k], 0, sizeof(mmsghdr));
        msgs[k].msg_hdr.msg_iov = &iov[k];
        msgs[k].msg_hdr.msg_iovlen = 1;
        msgs[k].msg_hdr.msg_name = &addr[k];
        msgs[k].msg_hdr.msg_namelen = sizeof(sockaddr_in);
        msgs[k].msg_hdr.msg_control =
            ctl.data() + static_cast<size_t>(k) * kGroCtlSpace;
        msgs[k].msg_hdr.msg_controllen = kGroCtlSpace;
      }
    }
  };
  const bool gro = gro_active();
  mmsghdr* msgs;
  sockaddr_in* addr_ring;
  uint8_t* bufp;
  int win;
  size_t bufsz;
  if (gro) {
    static thread_local GroRing gring;
    msgs = gring.msgs.data();
    addr_ring = gring.addr.data();
    bufp = gring.buf.data();
    win = kGroWin;
    bufsz = kGroBufSize;
  } else {
    static thread_local Ring ring;
    msgs = ring.msgs.data();
    addr_ring = ring.addr.data();
    bufp = ring.buf.data();
    win = kDrainWin;
    bufsz = kRecvBufSize;
  }
  int n_recs = 0;
  int64_t slab_used = 0;
  int n_fatal = 0;
  bool full = false;
  auto emit_rec = [&](int32_t dst, int fd_idx, uint32_t ip, uint16_t port,
                      uint16_t seg, const uint8_t* src, size_t len) {
    uint8_t* rp = recs + static_cast<size_t>(n_recs) * kRecvStride;
    auto w32 = [&rp](size_t at, uint32_t v) {
      for (int b = 0; b < 4; ++b) rp[at + b] = (v >> (8 * b)) & 0xFF;
    };
    w32(0, static_cast<uint32_t>(dst));
    w32(4, static_cast<uint32_t>(fd_idx));
    w32(8, ip);
    rp[12] = port & 0xFF;
    rp[13] = port >> 8;
    rp[14] = seg & 0xFF;
    rp[15] = seg >> 8;
    w32(16, static_cast<uint32_t>(slab_used));
    w32(20, static_cast<uint32_t>(len));
    std::memcpy(slab + slab_used, src, len);
    slab_used += static_cast<int64_t>(len);
    ++n_recs;
    stats[1] += 1;
  };
  for (int e = 0; e < n_fds && !full; ++e) {
    const uint8_t* fp = fds + static_cast<size_t>(e) * kFdStride;
    int32_t fd = 0, slot = 0;
    for (int b = 0; b < 4; ++b) {
      fd |= static_cast<int32_t>(fp[b]) << (8 * b);
      slot |= static_cast<int32_t>(fp[4 + b]) << (8 * b);
    }
    while (true) {
      // clamp the batch so every datagram the kernel hands over has a
      // guaranteed record + slab home — backpressure stops BEFORE the
      // syscall, never after, so no datagram is silently dropped.  Under
      // GRO each message can explode into up to kGroMaxSegs records and a
      // full 64 KiB of slab, so the reservation divides by that worst
      // case; the Python regrow loop absorbs the conservatism.
      int vlen = win;
      int rec_room = (max_recs - n_recs) / (gro ? kGroMaxSegs : 1);
      if (vlen > rec_room) vlen = rec_room;
      int64_t slab_room =
          (slab_cap - slab_used) / static_cast<int64_t>(bufsz);
      if (vlen > slab_room) vlen = static_cast<int>(slab_room);
      if (vlen <= 0) {
        stats[3] += 1;
        full = true;
        break;
      }
      for (int k = 0; k < vlen; ++k) {
        msgs[k].msg_hdr.msg_namelen = sizeof(sockaddr_in);
        msgs[k].msg_len = 0;
        if (gro) {
          msgs[k].msg_hdr.msg_controllen = kGroCtlSpace;
          msgs[k].msg_hdr.msg_flags = 0;
        }
      }
      int r = recvmmsg(fd, msgs, static_cast<unsigned>(vlen), 0, nullptr);
      stats[0] += 1;
      if (r < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        if (errno == EINTR || errno == ECONNRESET || errno == ECONNREFUSED) {
          continue;  // the ConnectionResetError-continue of the Python path
        }
        if (n_fatal < fatal_cap && fatal) {
          fatal[2 * n_fatal] = e;
          fatal[2 * n_fatal + 1] = static_cast<int32_t>(errno);
        }
        ++n_fatal;
        break;  // this fd stops; the others keep draining
      }
      if (r == 0) break;
      stats[4 + batch_bucket(r)] += 1;
      for (int k = 0; k < r; ++k) {
        uint32_t ip = addr_ring[k].sin_addr.s_addr;
        uint16_t port = ntohs(addr_ring[k].sin_port);
        int32_t dst = slot;
        if (dst < 0) {
          dst = route_lookup(routes, n_routes, ip, port);
          if (dst < 0) {
            stats[2] += 1;  // unroutable dispatch source: drop, like the
            continue;       // Python demux ignoring unknown senders
          }
        }
        size_t len = msgs[k].msg_len;
        const uint8_t* src = bufp + static_cast<size_t>(k) * bufsz;
        size_t gso_size = 0;
        if (gro) {
          for (cmsghdr* cm = CMSG_FIRSTHDR(&msgs[k].msg_hdr); cm;
               cm = CMSG_NXTHDR(&msgs[k].msg_hdr, cm)) {
            if (cm->cmsg_level == SOL_UDP && cm->cmsg_type == UDP_GRO) {
              int gs = 0;
              std::memcpy(&gs, CMSG_DATA(cm), sizeof(gs));
              if (gs > 0) gso_size = static_cast<size_t>(gs);
              break;
            }
          }
        }
        if (gso_size > 0 && len > gso_size) {
          // coalesced train: split back into wire datagrams so the
          // record walk sees exactly what GRO-off would have seen,
          // tagging each record with its segment index
          stats[kStGroDgrams] += 1;
          uint16_t seg = 0;
          size_t off = 0;
          while (off < len) {
            size_t part = len - off;
            if (part > gso_size) part = gso_size;
            // defensive fold: the pre-syscall reserve guarantees
            // kGroMaxSegs records per message, so running out here
            // means a >64-segment train — fold the remainder into the
            // final record rather than drop bytes
            if (n_recs + 1 >= max_recs || seg == kGroMaxSegs - 1) {
              part = len - off;
            }
            emit_rec(dst, e, ip, port, seg, src + off, part);
            stats[kStGroSegs] += 1;
            off += part;
            ++seg;
          }
        } else {
          // ordinary datagram: on the wide GRO ring, clamp to the
          // reference ring's buffer size so an oversized datagram
          // truncates exactly as it does with GRO off (parity)
          if (gro && len > kRecvBufSize) len = kRecvBufSize;
          emit_rec(dst, e, ip, port, 0, src, len);
        }
      }
      if (r < vlen) break;  // queue ran dry mid-batch: no probe needed
    }
  }
  if (n_fatal_out) *n_fatal_out = n_fatal;
  return n_recs;
}

}  // extern "C"

#else  // !__linux__ -------------------------------------------------------

// Stub surface: same symbols, no batched path.  ggrs_net_supported() == 0
// keeps the pool on the Python shuttle (the documented fallback), and the
// bank never sees an attached socket.

extern "C" {

int ggrs_net_supported(void) { return 0; }
void* ggrs_net_attach(int, int) { return nullptr; }
void ggrs_net_free(void*) {}
int ggrs_net_recv_all(void*) { return kNetErrUnsupported; }
int ggrs_net_recv_count(void*) { return 0; }
int ggrs_net_datagram(void*, int, uint32_t*, uint16_t*, const uint8_t**,
                      uint32_t*) {
  return kNetErrUnsupported;
}
int ggrs_net_stage(void*, uint32_t, uint16_t, const uint8_t*, size_t) {
  return kNetErrUnsupported;
}
int ggrs_net_flush(void*) { return kNetErrUnsupported; }
int64_t ggrs_net_staged_len(void*) { return 0; }
void ggrs_net_stats(void*, uint64_t* out) {
  std::memset(out, 0, sizeof(uint64_t) * kNumNetStats);
}
void ggrs_net_set_capture(void*, int) {}
int ggrs_net_drain_capture(void*, uint8_t*, size_t, size_t* out_len) {
  *out_len = 0;
  return kNetErrUnsupported;
}
void ggrs_net_inject_send_errno(void*, int, int) {}
int ggrs_net_send_table(const uint8_t*, int64_t, const uint8_t*, size_t,
                        uint64_t*, int32_t*, int) {
  return kNetErrUnsupported;
}
int ggrs_net_recv_table(const uint8_t*, int, const uint8_t*, int, uint8_t*,
                        int, uint8_t*, int64_t, uint64_t*, int32_t*, int,
                        int32_t* n_fatal_out) {
  if (n_fatal_out) *n_fatal_out = 0;
  return kNetErrUnsupported;
}
int ggrs_net_gso_supported(void) { return 0; }
void ggrs_net_set_gso(int) {}
int ggrs_net_gro_supported(void) { return 0; }
void ggrs_net_set_gro(int) {}
void ggrs_net_inject_table_errno(int, int64_t, int) {}

}  // extern "C"

#endif  // __linux__
