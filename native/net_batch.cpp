// Kernel-batched UDP datapath: recvmmsg/sendmmsg ring buffers around one
// bound non-blocking UDP fd (DESIGN.md §15).
//
// The Python shuttle pays one syscall PLUS one Python→C round trip per
// datagram on both sides of the tick crossing; at B matches × peers plus
// the spectator fan-out that is hundreds-to-thousands of sendto/recvfrom
// calls per pool tick.  A NetBatch replaces them with (typically) one
// recvmmsg and one sendmmsg per slot per tick: preallocated iovec +
// sockaddr slabs, datagrams copied once into a per-tick accumulation slab
// so the session bank can route them by source address without holding the
// kernel rings.
//
// SEMANTICS mirror ggrs_tpu.net.sockets.UdpNonBlockingSocket exactly:
//  - receive drains until EAGAIN/EWOULDBLOCK; ECONNRESET/ECONNREFUSED
//    between datagrams is skipped (the post-sendto ICMP echo some OSes
//    surface), anything else is fatal;
//  - transient send errnos (the _TRANSIENT_SEND_ERRNOS set: ENETUNREACH,
//    EHOSTUNREACH, ECONNREFUSED, ENETDOWN, EHOSTDOWN, ENOBUFS, EAGAIN,
//    EWOULDBLOCK) count the datagram as lost — the endpoint protocol's
//    redundant sends already cover loss — and the flush continues;
//  - EMSGSIZE / EPERM and friends are deterministic local faults: the
//    flush aborts fatally (the bank turns that into a per-slot fault, the
//    same blast radius a raising socket.sendto has on the Python path);
//  - datagrams above the 4096-byte receive buffer truncate, datagrams
//    above the 508-byte ideal UDP size are counted (never blocked).
//
// The NetBatch is owned by the Python pool (ggrs_net_attach/free); the
// session bank only borrows the pointer (ggrs_bank_attach_socket).  One
// NetBatch serves one fd and is single-threaded, like everything else in
// the host loop.
//
// TEST SEAMS (observational; zero cost when unused):
//  - capture tee: every staged datagram is mirrored into a drainable
//    buffer so parity fuzzes can pin the batched path's full wire byte
//    sequence — content AND send order — against the Python shuttle;
//  - errno injection: the next N staged datagrams fail with a chosen
//    errno before reaching sendmmsg (scripts/chaos.py --fault socket).
//
// Non-Linux builds compile the same extern-C surface as stubs
// (ggrs_net_supported() == 0); the pool then keeps the Python shuttle —
// the fallback matrix in DESIGN.md §15.

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <new>
#include <vector>

namespace {

// return codes (mirrored in ggrs_tpu/net/_native.py)
constexpr int kNetOk = 0;
constexpr int kNetErrUnsupported = -80;
constexpr int kNetErrFatal = -81;
constexpr int kNetErrBadArgs = -82;
constexpr int kNetErrBufferTooSmall = -11;  // wire_common kErrBufferTooSmall

// sockets.py RECV_BUFFER_SIZE / IDEAL_MAX_UDP_PACKET_SIZE
constexpr size_t kRecvBufSize = 4096;
constexpr size_t kIdealMaxUdp = 508;

// ---- one-shot batched send table (descriptor plane, DESIGN.md §21) ------
// ggrs_net_send_table record stride: non-attached sockets (native_io off,
// or sockets that could not attach) route their whole tick's outbound
// through ONE crossing — per datagram: i32 fd, u32 ip (sin_addr.s_addr as
// stored), u16 port (host order), u16 pad, u32 off, u32 len (off/len jump
// into the shared payload, usually the tick output buffer itself).
// Records for one fd must be contiguous (the pool emits per-slot runs);
// stride and field order mirrored by _native.NET_SEND_FIELDS.
constexpr size_t kSendStride = 20;

// stat slots (mirrored as _native.IO_STAT_FIELDS + two 8-bucket
// histograms; 22 u64 total, the per-slot io tail of ggrs_bank_stats)
enum NetStat : int {
  kStRecvCalls = 0,   // recvmmsg invocations (incl. the EAGAIN probe)
  kStRecvDgrams = 1,  // datagrams received
  kStSendCalls = 2,   // sendmmsg invocations
  kStSendDgrams = 3,  // datagrams handed to the kernel
  kStSendErrors = 4,  // transient send failures counted as loss
  kStOversized = 5,   // staged datagrams above kIdealMaxUdp
  kStRecvHist0 = 6,   // recv batch-size buckets: 1,2,4,8,16,32,64,+inf
  kStSendHist0 = 14,  // send batch-size buckets, same bounds
  kNumNetStats = 22,
};

inline int batch_bucket(int n) {
  int b = 0, upper = 1;
  while (b < 7 && n > upper) {
    upper <<= 1;
    ++b;
  }
  return b;
}

}  // namespace

#if defined(__linux__)

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <sys/socket.h>

namespace {

bool transient_send_errno(int e) {
  // _TRANSIENT_SEND_ERRNOS in sockets.py, member for member.  EMSGSIZE and
  // EPERM are deliberately NOT here: deterministic local faults that every
  // retransmission would hit identically must fail loudly, not stall.
  switch (e) {
    case ENETUNREACH:
    case EHOSTUNREACH:
    case ECONNREFUSED:
    case ENETDOWN:
#ifdef EHOSTDOWN
    case EHOSTDOWN:
#endif
    case ENOBUFS:
    case EAGAIN:
#if EWOULDBLOCK != EAGAIN
    case EWOULDBLOCK:
#endif
      return true;
    default:
      return false;
  }
}

struct Dgram {
  uint32_t ip;    // sin_addr.s_addr, network byte order as stored
  uint16_t port;  // host byte order
  uint32_t off, len;  // slice into the owning slab
};

struct NetBatch {
  int fd = -1;
  int vlen = 64;
  // receive rings (kernel-facing, reused every recvmmsg)
  std::vector<mmsghdr> rmsgs;
  std::vector<iovec> riov;
  std::vector<sockaddr_in> raddr;
  std::vector<uint8_t> rbuf;  // vlen * kRecvBufSize
  // per-tick accumulation (bank-facing: stable until the next recv_all)
  std::vector<uint8_t> rslab;
  std::vector<Dgram> rlist;
  // staged sends (flushed in stage order)
  std::vector<uint8_t> sslab;
  std::vector<Dgram> slist;
  std::vector<mmsghdr> smsgs;
  std::vector<iovec> siov;
  std::vector<sockaddr_in> saddr;
  uint64_t st[kNumNetStats] = {0};
  // test seams
  bool capture = false;
  std::vector<uint8_t> capture_buf;  // [u32 ip][u16 port][u32 len][bytes]*
  int inject_errno = 0;
  int inject_count = 0;
};

void put_u16le(std::vector<uint8_t>* b, uint16_t v) {
  b->push_back(v & 0xFF);
  b->push_back(v >> 8);
}

void put_u32le(std::vector<uint8_t>* b, uint32_t v) {
  for (int i = 0; i < 4; ++i) b->push_back((v >> (8 * i)) & 0xFF);
}

}  // namespace

extern "C" {

int ggrs_net_supported(void) { return 1; }

// Wrap a bound, non-blocking UDP fd.  The fd stays owned by the caller
// (the Python socket object); max_batch bounds each recvmmsg/sendmmsg
// window.  Returns NULL on bad args / allocation failure.
void* ggrs_net_attach(int fd, int max_batch) {
  if (fd < 0) return nullptr;
  if (max_batch < 1) max_batch = 64;
  if (max_batch > 1024) max_batch = 1024;
  NetBatch* nb = new (std::nothrow) NetBatch();
  if (!nb) return nullptr;
  nb->fd = fd;
  nb->vlen = max_batch;
  size_t v = static_cast<size_t>(max_batch);
  nb->rmsgs.resize(v);
  nb->riov.resize(v);
  nb->raddr.resize(v);
  nb->rbuf.resize(v * kRecvBufSize);
  nb->smsgs.resize(v);
  nb->siov.resize(v);
  nb->saddr.resize(v);
  for (size_t i = 0; i < v; ++i) {
    nb->riov[i].iov_base = nb->rbuf.data() + i * kRecvBufSize;
    nb->riov[i].iov_len = kRecvBufSize;
    std::memset(&nb->rmsgs[i], 0, sizeof(mmsghdr));
    nb->rmsgs[i].msg_hdr.msg_iov = &nb->riov[i];
    nb->rmsgs[i].msg_hdr.msg_iovlen = 1;
    nb->rmsgs[i].msg_hdr.msg_name = &nb->raddr[i];
    nb->rmsgs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
  }
  return nb;
}

void ggrs_net_free(void* p) { delete static_cast<NetBatch*>(p); }

// Drain everything available on the fd into the accumulation slab (the
// receive_all_datagrams analog: loop until EAGAIN, but a partial batch
// already proves the queue ran dry at call time, saving the probe call).
// Returns the datagram count, or kNetErrFatal on an unexpected errno.
int ggrs_net_recv_all(void* p) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  nb->rslab.clear();
  nb->rlist.clear();
  while (true) {
    for (int i = 0; i < nb->vlen; ++i) {
      // the kernel shrinks msg_namelen / sets msg_len; reset per call
      nb->rmsgs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
      nb->rmsgs[i].msg_len = 0;
    }
    int r = recvmmsg(nb->fd, nb->rmsgs.data(),
                     static_cast<unsigned>(nb->vlen), 0, nullptr);
    nb->st[kStRecvCalls] += 1;
    if (r < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (errno == EINTR || errno == ECONNRESET || errno == ECONNREFUSED) {
        continue;  // the ConnectionResetError-continue of the Python path
      }
      return kNetErrFatal;
    }
    if (r == 0) break;
    nb->st[kStRecvDgrams] += static_cast<uint64_t>(r);
    nb->st[kStRecvHist0 + batch_bucket(r)] += 1;
    for (int i = 0; i < r; ++i) {
      size_t len = nb->rmsgs[i].msg_len;  // > 4096 already truncated
      Dgram d;
      d.ip = nb->raddr[i].sin_addr.s_addr;
      d.port = ntohs(nb->raddr[i].sin_port);
      d.off = static_cast<uint32_t>(nb->rslab.size());
      d.len = static_cast<uint32_t>(len);
      nb->rslab.insert(nb->rslab.end(), nb->rbuf.data() + i * kRecvBufSize,
                       nb->rbuf.data() + i * kRecvBufSize + len);
      nb->rlist.push_back(d);
    }
    if (r < nb->vlen) break;  // queue ran dry mid-batch: no probe needed
  }
  return static_cast<int>(nb->rlist.size());
}

// Datagram count of the last recv_all (the accumulation list survives
// until the next recv_all, so a caller may drain early and route later).
int ggrs_net_recv_count(void* p) {
  return static_cast<int>(static_cast<NetBatch*>(p)->rlist.size());
}

// Accessor for datagram `i` of the last recv_all.  Pointers stay valid
// until the next recv_all on this NetBatch.
int ggrs_net_datagram(void* p, int i, uint32_t* ip, uint16_t* port,
                      const uint8_t** data, uint32_t* len) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  if (i < 0 || static_cast<size_t>(i) >= nb->rlist.size()) {
    return kNetErrBadArgs;
  }
  const Dgram& d = nb->rlist[static_cast<size_t>(i)];
  *ip = d.ip;
  *port = d.port;
  *data = nb->rslab.data() + d.off;
  *len = d.len;
  return kNetOk;
}

// Stage one datagram for the next flush (bytes are copied into the send
// slab; the caller's buffer may be reused immediately).
int ggrs_net_stage(void* p, uint32_t ip, uint16_t port, const uint8_t* data,
                   size_t len) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  if (len > kIdealMaxUdp) nb->st[kStOversized] += 1;
  if (nb->capture) {
    put_u32le(&nb->capture_buf, ip);
    put_u16le(&nb->capture_buf, port);
    put_u32le(&nb->capture_buf, static_cast<uint32_t>(len));
    nb->capture_buf.insert(nb->capture_buf.end(), data, data + len);
  }
  Dgram d;
  d.ip = ip;
  d.port = port;
  d.off = static_cast<uint32_t>(nb->sslab.size());
  d.len = static_cast<uint32_t>(len);
  nb->sslab.insert(nb->sslab.end(), data, data + len);
  nb->slist.push_back(d);
  return kNetOk;
}

// Flush everything staged, in stage order, via sendmmsg windows.  Transient
// errnos drop the failing datagram (counted; the protocol's redundancy
// covers loss) and keep going; a fatal errno abandons the remaining
// datagrams and returns kNetErrFatal — the caller faults the slot, exactly
// like a raising socket.sendto on the Python path.
int ggrs_net_flush(void* p) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  size_t i = 0;
  const size_t n = nb->slist.size();
  int rc_out = kNetOk;
  while (i < n) {
    if (nb->inject_count > 0) {
      // chaos seam: the head datagram "fails" with the injected errno
      // before any syscall (an ENOBUFS/EAGAIN storm, or a fatal EPERM)
      nb->inject_count -= 1;
      if (transient_send_errno(nb->inject_errno)) {
        nb->st[kStSendErrors] += 1;
        i += 1;
        continue;
      }
      rc_out = kNetErrFatal;
      break;
    }
    size_t win = n - i;
    if (win > static_cast<size_t>(nb->vlen)) win = nb->vlen;
    for (size_t k = 0; k < win; ++k) {
      const Dgram& d = nb->slist[i + k];
      nb->siov[k].iov_base = nb->sslab.data() + d.off;
      nb->siov[k].iov_len = d.len;
      std::memset(&nb->saddr[k], 0, sizeof(sockaddr_in));
      nb->saddr[k].sin_family = AF_INET;
      nb->saddr[k].sin_addr.s_addr = d.ip;
      nb->saddr[k].sin_port = htons(d.port);
      std::memset(&nb->smsgs[k], 0, sizeof(mmsghdr));
      nb->smsgs[k].msg_hdr.msg_iov = &nb->siov[k];
      nb->smsgs[k].msg_hdr.msg_iovlen = 1;
      nb->smsgs[k].msg_hdr.msg_name = &nb->saddr[k];
      nb->smsgs[k].msg_hdr.msg_namelen = sizeof(sockaddr_in);
    }
    int r = sendmmsg(nb->fd, nb->smsgs.data(), static_cast<unsigned>(win), 0);
    nb->st[kStSendCalls] += 1;
    if (r < 0) {
      if (errno == EINTR) continue;  // retry the same window: PEP 475
      // semantics — a signal mid-send is invisible on the Python path
      // the errno belongs to the FIRST datagram of the window
      if (transient_send_errno(errno)) {
        nb->st[kStSendErrors] += 1;
        i += 1;
        continue;
      }
      rc_out = kNetErrFatal;
      break;
    }
    nb->st[kStSendDgrams] += static_cast<uint64_t>(r);
    nb->st[kStSendHist0 + batch_bucket(r)] += 1;
    i += static_cast<size_t>(r);
    // r < win without an errno: the next loop iteration retries from the
    // stall point and surfaces the real errno if one is pending
  }
  nb->slist.clear();
  nb->sslab.clear();
  return rc_out;
}

int64_t ggrs_net_staged_len(void* p) {
  return static_cast<int64_t>(static_cast<NetBatch*>(p)->slist.size());
}

void ggrs_net_stats(void* p, uint64_t* out) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  std::memcpy(out, nb->st, sizeof(nb->st));
}

// ---- test seams ---------------------------------------------------------

void ggrs_net_set_capture(void* p, int on) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  nb->capture = on != 0;
  if (!nb->capture) nb->capture_buf.clear();
}

// Drain the capture tee: [u32 ip][u16 port][u32 len][bytes] per datagram,
// in stage (= send) order.  kNetErrBufferTooSmall reports the needed size
// without consuming.
int ggrs_net_drain_capture(void* p, uint8_t* out, size_t cap,
                           size_t* out_len) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  *out_len = nb->capture_buf.size();
  if (nb->capture_buf.size() > cap) return kNetErrBufferTooSmall;
  std::memcpy(out, nb->capture_buf.data(), nb->capture_buf.size());
  nb->capture_buf.clear();
  return kNetOk;
}

// The next `count` staged datagrams fail with `err` before any syscall.
void ggrs_net_inject_send_errno(void* p, int err, int count) {
  NetBatch* nb = static_cast<NetBatch*>(p);
  nb->inject_errno = err;
  nb->inject_count = count;
}

// One-shot batched send over ARBITRARY fds (descriptor plane, §21): no
// NetBatch attach, no rings kept — the Python pool hands the whole tick's
// non-attached outbound as one packed table (`desc`: n records of
// kSendStride bytes; `payload`: the buffer the off/len fields index,
// usually the tick output buffer itself, zero copies).  Consecutive
// same-fd records group into sendmmsg windows, so a pool tick pays one
// Python→C crossing total and ~one syscall per socket instead of one of
// each per datagram.
//
// Errno semantics mirror UdpNonBlockingSocket.send_datagram exactly:
// transient errnos count the datagram as lost (stats3[1]) and the flush
// continues; a fatal errno abandons the REST OF THAT FD's run (the same
// partial-send window a raising sendto leaves) and is reported as a
// (record index, errno) pair in `fatal` so the caller can fault exactly
// the owning slot; other fds keep flushing.  Oversized datagrams are
// counted (stats3[2]), never blocked.  stats3 = {sent, transient_errors,
// oversized}, accumulated (callers zero it).
//
// Returns the number of fatal pairs written (0 = clean), or
// kNetErrBadArgs.  The caller must sort records so each fd forms one
// contiguous run; a fatal fd seen again in a LATER run is retried (the
// pool never emits split runs).
int ggrs_net_send_table(const uint8_t* desc, int64_t n,
                        const uint8_t* payload, size_t payload_len,
                        uint64_t* stats3, int32_t* fatal, int fatal_cap) {
  if (n < 0 || (n > 0 && (!desc || !payload || !stats3))) {
    return kNetErrBadArgs;
  }
  constexpr int kWin = 64;
  static thread_local std::vector<mmsghdr> msgs(kWin);
  static thread_local std::vector<iovec> iov(kWin);
  static thread_local std::vector<sockaddr_in> addr(kWin);
  int n_fatal = 0;
  int64_t i = 0;
  auto rec = [&](int64_t k, int32_t* fd, uint32_t* ip, uint16_t* port,
                 uint32_t* off, uint32_t* len) {
    const uint8_t* p = desc + static_cast<size_t>(k) * kSendStride;
    auto r32 = [&p](size_t at) {
      uint32_t v = 0;
      for (int b = 0; b < 4; ++b) {
        v |= static_cast<uint32_t>(p[at + b]) << (8 * b);
      }
      return v;
    };
    *fd = static_cast<int32_t>(r32(0));
    *ip = r32(4);
    *port = static_cast<uint16_t>(p[8] | (p[9] << 8));
    *off = r32(12);
    *len = r32(16);
  };
  while (i < n) {
    int32_t fd;
    uint32_t ip, off, len;
    uint16_t port;
    rec(i, &fd, &ip, &port, &off, &len);
    // the fd's contiguous run [i, run_end)
    int64_t run_end = i;
    while (run_end < n) {
      int32_t fd2;
      uint32_t ip2, off2, len2;
      uint16_t port2;
      rec(run_end, &fd2, &ip2, &port2, &off2, &len2);
      if (fd2 != fd) break;
      if (static_cast<size_t>(off2) + len2 > payload_len) {
        return kNetErrBadArgs;  // corrupt table: refuse whole call
      }
      if (len2 > kIdealMaxUdp) stats3[2] += 1;
      ++run_end;
    }
    int64_t j = i;
    bool fd_fatal = false;
    while (j < run_end) {
      size_t win = static_cast<size_t>(run_end - j);
      if (win > kWin) win = kWin;
      for (size_t k = 0; k < win; ++k) {
        int32_t fdk;
        uint32_t ipk, offk, lenk;
        uint16_t portk;
        rec(j + static_cast<int64_t>(k), &fdk, &ipk, &portk, &offk, &lenk);
        iov[k].iov_base = const_cast<uint8_t*>(payload) + offk;
        iov[k].iov_len = lenk;
        std::memset(&addr[k], 0, sizeof(sockaddr_in));
        addr[k].sin_family = AF_INET;
        addr[k].sin_addr.s_addr = ipk;
        addr[k].sin_port = htons(portk);
        std::memset(&msgs[k], 0, sizeof(mmsghdr));
        msgs[k].msg_hdr.msg_iov = &iov[k];
        msgs[k].msg_hdr.msg_iovlen = 1;
        msgs[k].msg_hdr.msg_name = &addr[k];
        msgs[k].msg_hdr.msg_namelen = sizeof(sockaddr_in);
      }
      int r = sendmmsg(fd, msgs.data(), static_cast<unsigned>(win), 0);
      if (r < 0) {
        if (errno == EINTR) continue;  // PEP 475: retry the window
        if (transient_send_errno(errno)) {
          stats3[1] += 1;  // the head datagram is lost; keep going
          j += 1;
          continue;
        }
        if (n_fatal < fatal_cap && fatal) {
          fatal[2 * n_fatal] = static_cast<int32_t>(j);
          fatal[2 * n_fatal + 1] = static_cast<int32_t>(errno);
        }
        ++n_fatal;
        fd_fatal = true;
        break;
      }
      stats3[0] += static_cast<uint64_t>(r);
      j += r;
      // r < win without errno: retry from the stall point next iteration
    }
    (void)fd_fatal;  // the rest of this fd's run was abandoned above
    i = run_end;
  }
  return n_fatal;
}

}  // extern "C"

#else  // !__linux__ -------------------------------------------------------

// Stub surface: same symbols, no batched path.  ggrs_net_supported() == 0
// keeps the pool on the Python shuttle (the documented fallback), and the
// bank never sees an attached socket.

extern "C" {

int ggrs_net_supported(void) { return 0; }
void* ggrs_net_attach(int, int) { return nullptr; }
void ggrs_net_free(void*) {}
int ggrs_net_recv_all(void*) { return kNetErrUnsupported; }
int ggrs_net_recv_count(void*) { return 0; }
int ggrs_net_datagram(void*, int, uint32_t*, uint16_t*, const uint8_t**,
                      uint32_t*) {
  return kNetErrUnsupported;
}
int ggrs_net_stage(void*, uint32_t, uint16_t, const uint8_t*, size_t) {
  return kNetErrUnsupported;
}
int ggrs_net_flush(void*) { return kNetErrUnsupported; }
int64_t ggrs_net_staged_len(void*) { return 0; }
void ggrs_net_stats(void*, uint64_t* out) {
  std::memset(out, 0, sizeof(uint64_t) * kNumNetStats);
}
void ggrs_net_set_capture(void*, int) {}
int ggrs_net_drain_capture(void*, uint8_t*, size_t, size_t* out_len) {
  *out_len = 0;
  return kNetErrUnsupported;
}
void ggrs_net_inject_send_errno(void*, int, int) {}
int ggrs_net_send_table(const uint8_t*, int64_t, const uint8_t*, size_t,
                        uint64_t*, int32_t*, int) {
  return kNetErrUnsupported;
}

}  // extern "C"

#endif  // __linux__
