// Native sync-layer mechanism: the per-player input-queue bank and the
// confirmed-frame watermark, exactly mirroring the Python reference cores
// (ggrs_tpu/core/input_queue.py, ggrs_tpu/core/sync_layer.py; behavior spec:
// /root/reference/src/input_queue.rs:104-265 and
// /root/reference/src/sync_layer.rs:168-375).
//
// Policy stays in Python (what frame to confirm under sparse saving, when to
// roll back, session orchestration); this file owns only the MECHANISM: ring
// maintenance, frame-delay insertion, repeat-last prediction with
// first-incorrect tracking, synchronized/confirmed input assembly, and
// confirmed-frame discard — the ops the capacity bench measured at ~90% of a
// pooled hosting tick when run as ~200 Python calls per session-tick.
//
// Inputs are fixed-size encoded byte blobs (Config.native_input_size);
// repeat-last prediction and equality are byte-wise, which matches the
// Python semantics whenever the encoding is injective (the for_uint /
// for_struct constructors).  Anything else — pluggable predictors, custom
// equality, variable-size inputs — stays on the Python core, selected at
// SyncLayer construction.

#include "wire_common.h"

#include <cstdlib>
#include <cstring>
#include <new>

namespace {

using i64 = int64_t;

constexpr int kQueueLen = 128;          // input_queue.py INPUT_QUEUE_LENGTH
constexpr i64 kNullFrame = -1;

// error codes (mirrored in _native.py as SYNC_ERR_*)
enum SyncRc : int {
  kSyncOk = 0,
  kSyncErrPredictionPending = -40,  // input() while first_incorrect set
  kSyncErrBeforeTail = -41,         // input() for a frame older than tail
  kSyncErrNoConfirmed = -42,        // confirmed_input() miss
  kSyncErrNonSequential = -43,      // _add_input_by_frame precondition
  kSyncErrConfirmPastIncorrect = -44,  // watermark past first_incorrect
  kSyncErrBadArgs = -45,
  kSyncErrQueueFull = -46,             // 128-slot ring exhausted
};

// input status codes (mirror core/types.py InputStatus order)
enum : int {
  kStatusConfirmed = 0,
  kStatusPredicted = 1,
  kStatusDisconnected = 2,
};

struct Queue {
  int head = 0;
  int tail = 0;
  int length = 0;
  bool first_frame = true;
  i64 last_added = kNullFrame;
  i64 first_incorrect = kNullFrame;
  i64 last_requested = kNullFrame;
  int frame_delay = 0;
  i64 pred_frame = kNullFrame;
  std::vector<uint8_t> pred_input;
  std::vector<i64> frames;          // kQueueLen slot frames
  std::vector<uint8_t> arena;       // kQueueLen * input_size input bytes
};

struct SyncCore {
  int players = 0;
  int input_size = 0;
  i64 last_confirmed = kNullFrame;
  std::vector<Queue> queues;

  uint8_t* slot_bytes(Queue& q, int idx) {
    return q.arena.data() + static_cast<size_t>(idx) * input_size;
  }
};

// ---- queue mechanics: 1:1 with input_queue.py --------------------------

void add_input_by_frame(SyncCore* c, Queue& q, const uint8_t* bytes,
                        i64 frame_number, int* rc) {
  int prev_pos = (q.head - 1 + kQueueLen) % kQueueLen;
  if (!(q.last_added == kNullFrame || frame_number == q.last_added + 1) ||
      !(frame_number == 0 || q.frames[prev_pos] == frame_number - 1)) {
    *rc = kSyncErrNonSequential;
    return;
  }
  if (q.length >= kQueueLen) {
    // the Python core raises at the same point (input_queue.py:154);
    // silently wrapping would overwrite the tail and serve wrong inputs
    *rc = kSyncErrQueueFull;
    return;
  }
  // compare prediction vs reality BEFORE the input enters the ring
  bool prediction_matches =
      q.pred_frame != kNullFrame &&
      std::memcmp(q.pred_input.data(), bytes, c->input_size) == 0;

  q.frames[q.head] = frame_number;
  std::memcpy(c->slot_bytes(q, q.head), bytes, c->input_size);
  q.head = (q.head + 1) % kQueueLen;
  q.length += 1;
  q.first_frame = false;
  q.last_added = frame_number;

  if (q.pred_frame != kNullFrame) {
    if (frame_number != q.pred_frame) {
      *rc = kSyncErrNonSequential;
      return;
    }
    if (q.first_incorrect == kNullFrame && !prediction_matches) {
      q.first_incorrect = frame_number;
    }
    if (q.pred_frame == q.last_requested &&
        q.first_incorrect == kNullFrame) {
      q.pred_frame = kNullFrame;
    } else {
      q.pred_frame += 1;
    }
  }
}

i64 advance_queue_head(SyncCore* c, Queue& q, const uint8_t* bytes,
                       i64 input_frame, int* rc) {
  int prev_pos = (q.head - 1 + kQueueLen) % kQueueLen;
  i64 expected = q.first_frame ? 0 : q.frames[prev_pos] + 1;
  input_frame += q.frame_delay;
  if (expected > input_frame) return kNullFrame;  // delay shrank: drop
  while (expected < input_frame) {                // delay grew: replicate
    int rep = (q.head - 1 + kQueueLen) % kQueueLen;
    // Python replicates PlayerInput(replicate.frame, replicate.input) but
    // passes the EXPECTED frame to _add_input_by_frame — copy the bytes
    // before the head moves
    std::vector<uint8_t> rep_bytes(c->slot_bytes(q, rep),
                                   c->slot_bytes(q, rep) + c->input_size);
    add_input_by_frame(c, q, rep_bytes.data(), expected, rc);
    if (*rc != kSyncOk) return kNullFrame;
    expected += 1;
  }
  return input_frame;
}

i64 queue_add_input(SyncCore* c, Queue& q, i64 frame, const uint8_t* bytes,
                    int* rc) {
  if (q.last_added != kNullFrame &&
      frame + q.frame_delay != q.last_added + 1) {
    return kNullFrame;  // non-sequential: dropped, as in Python
  }
  i64 new_frame = advance_queue_head(c, q, bytes, frame, rc);
  if (*rc != kSyncOk) return kNullFrame;
  if (new_frame != kNullFrame) {
    add_input_by_frame(c, q, bytes, new_frame, rc);
    if (*rc != kSyncOk) return kNullFrame;
  }
  return new_frame;
}

// input_queue.py input(): confirmed value or repeat-last prediction
int queue_input(SyncCore* c, Queue& q, i64 requested, uint8_t* out,
                int* status) {
  if (q.first_incorrect != kNullFrame) return kSyncErrPredictionPending;
  q.last_requested = requested;
  if (requested < q.frames[q.tail]) return kSyncErrBeforeTail;

  if (q.pred_frame < 0) {
    i64 offset = requested - q.frames[q.tail];
    if (offset < q.length) {
      int pos = static_cast<int>((offset + q.tail) % kQueueLen);
      if (q.frames[pos] != requested) return kSyncErrBadArgs;
      std::memcpy(out, c->slot_bytes(q, pos), c->input_size);
      *status = kStatusConfirmed;
      return kSyncOk;
    }
    // enter prediction mode: repeat the most recently added input
    if (requested != 0 && q.last_added != kNullFrame) {
      int prev_pos = (q.head - 1 + kQueueLen) % kQueueLen;
      std::memcpy(q.pred_input.data(), c->slot_bytes(q, prev_pos),
                  c->input_size);
      q.pred_frame = q.frames[prev_pos] + 1;
    } else {
      std::memset(q.pred_input.data(), 0, c->input_size);
      q.pred_frame = q.pred_frame + 1;  // base_frame = pred_frame (NULL) + 1
    }
  }
  if (q.pred_frame == kNullFrame) return kSyncErrBadArgs;
  std::memcpy(out, q.pred_input.data(), c->input_size);
  *status = kStatusPredicted;
  return kSyncOk;
}

void queue_discard_confirmed(Queue& q, i64 frame) {
  if (q.last_requested != kNullFrame && q.last_requested < frame) {
    frame = q.last_requested;
  }
  if (frame >= q.last_added) {
    q.tail = q.head;
    q.length = 1;
  } else if (frame <= q.frames[q.tail]) {
    // nothing to delete
  } else {
    i64 offset = frame - q.frames[q.tail];
    q.tail = static_cast<int>((q.tail + offset) % kQueueLen);
    q.length -= static_cast<int>(offset);
  }
}

}  // namespace

// ---- C API ----------------------------------------------------------------

extern "C" {

void* ggrs_sync_new(int players, int input_size) {
  if (players < 1 || players > 64 || input_size < 1 || input_size > 4096) {
    return nullptr;
  }
  SyncCore* c = new (std::nothrow) SyncCore();
  if (!c) return nullptr;
  c->players = players;
  c->input_size = input_size;
  c->queues.resize(players);
  for (Queue& q : c->queues) {
    q.frames.assign(kQueueLen, kNullFrame);
    q.arena.assign(static_cast<size_t>(kQueueLen) * input_size, 0);
    q.pred_input.assign(input_size, 0);
  }
  return c;
}

void ggrs_sync_free(void* h) { delete static_cast<SyncCore*>(h); }

void ggrs_sync_set_frame_delay(void* h, int player, int delay) {
  SyncCore* c = static_cast<SyncCore*>(h);
  if (player < 0 || player >= c->players) return;
  c->queues[player].frame_delay = delay;
}

void ggrs_sync_reset_prediction(void* h) {
  SyncCore* c = static_cast<SyncCore*>(h);
  for (Queue& q : c->queues) {
    q.pred_frame = kNullFrame;
    q.first_incorrect = kNullFrame;
    q.last_requested = kNullFrame;
  }
}

// returns the landed frame, kNullFrame when dropped, or a SyncRc error (<-1)
int64_t ggrs_sync_add_input(void* h, int player, int64_t frame,
                            const uint8_t* bytes) {
  SyncCore* c = static_cast<SyncCore*>(h);
  if (player < 0 || player >= c->players) return kSyncErrBadArgs;
  int rc = kSyncOk;
  i64 landed = queue_add_input(c, c->queues[player], frame, bytes, &rc);
  return rc == kSyncOk ? landed : rc;
}

// synchronized inputs for `frame` given per-player connect status.
// disc: players u8; last_frames: players i64; out: players*input_size bytes;
// statuses: players i32 (kStatus*)
int ggrs_sync_synchronized_inputs(void* h, int64_t frame,
                                  const uint8_t* disc,
                                  const int64_t* last_frames, uint8_t* out,
                                  int32_t* statuses) {
  SyncCore* c = static_cast<SyncCore*>(h);
  for (int p = 0; p < c->players; ++p) {
    uint8_t* dst = out + static_cast<size_t>(p) * c->input_size;
    if (disc[p] && last_frames[p] < frame) {
      std::memset(dst, 0, c->input_size);
      statuses[p] = kStatusDisconnected;
    } else {
      int st = 0;
      int rc = queue_input(c, c->queues[p], frame, dst, &st);
      if (rc != kSyncOk) return rc;
      statuses[p] = st;
    }
  }
  return kSyncOk;
}

// confirmed inputs for `frame`; out_frames[p] carries each slot's stored
// frame (kNullFrame for disconnected blanks, matching PlayerInput.blank)
int ggrs_sync_confirmed_inputs(void* h, int64_t frame, const uint8_t* disc,
                               const int64_t* last_frames, uint8_t* out,
                               int64_t* out_frames) {
  SyncCore* c = static_cast<SyncCore*>(h);
  for (int p = 0; p < c->players; ++p) {
    Queue& q = c->queues[p];
    uint8_t* dst = out + static_cast<size_t>(p) * c->input_size;
    if (disc[p] && last_frames[p] < frame) {
      std::memset(dst, 0, c->input_size);
      out_frames[p] = kNullFrame;
      continue;
    }
    // floored mod: C++ % on a negative frame is negative (out-of-bounds UB);
    // Python's positive mod lands on a real slot, which for most negative
    // frames fails the tag check loudly — and for frame -1 legitimately
    // matches a still-blank slot (frames init to kNullFrame), so an early
    // "frame < 0" rejection would NOT be parity
    int offset = static_cast<int>(((frame % kQueueLen) + kQueueLen) % kQueueLen);
    if (q.frames[offset] != frame) return kSyncErrNoConfirmed;
    std::memcpy(dst, c->slot_bytes(q, offset), c->input_size);
    out_frames[p] = frame;
  }
  return kSyncOk;
}

// watermark: `frame` is the POLICY-resolved confirmed frame (Python already
// applied the sparse-saving and current-frame minimums).  Verifies the
// first-incorrect invariant, stores, and discards <= frame-1.
int ggrs_sync_set_last_confirmed(void* h, int64_t frame) {
  SyncCore* c = static_cast<SyncCore*>(h);
  i64 first_incorrect = kNullFrame;
  for (Queue& q : c->queues) {
    if (q.first_incorrect > first_incorrect) {
      first_incorrect = q.first_incorrect;
    }
  }
  if (!(first_incorrect == kNullFrame || first_incorrect >= frame)) {
    return kSyncErrConfirmPastIncorrect;
  }
  c->last_confirmed = frame;
  if (frame > 0) {
    for (Queue& q : c->queues) queue_discard_confirmed(q, frame - 1);
  }
  return kSyncOk;
}

int64_t ggrs_sync_last_confirmed(void* h) {
  return static_cast<SyncCore*>(h)->last_confirmed;
}

// earliest incorrect frame across queues, folded with the caller's seed
// (sync_layer.py check_simulation_consistency)
int64_t ggrs_sync_check_consistency(void* h, int64_t first_incorrect) {
  SyncCore* c = static_cast<SyncCore*>(h);
  for (Queue& q : c->queues) {
    i64 inc = q.first_incorrect;
    if (inc != kNullFrame &&
        (first_incorrect == kNullFrame || inc < first_incorrect)) {
      first_incorrect = inc;
    }
  }
  return first_incorrect;
}

int64_t ggrs_sync_first_incorrect(void* h, int player) {
  SyncCore* c = static_cast<SyncCore*>(h);
  if (player < 0 || player >= c->players) return kSyncErrBadArgs;
  return c->queues[player].first_incorrect;
}

int64_t ggrs_sync_last_added(void* h, int player) {
  SyncCore* c = static_cast<SyncCore*>(h);
  if (player < 0 || player >= c->players) return kSyncErrBadArgs;
  return c->queues[player].last_added;
}

// the per-player ring capacity (session_bank.cpp's harvest clamps against
// this instead of duplicating the literal)
int ggrs_sync_queue_len(void) { return kQueueLen; }

// oldest frame still held by a player's queue (kNullFrame when empty) —
// the lower bound of what a harvest can recover for fallback eviction
int64_t ggrs_sync_tail_frame(void* h, int player) {
  SyncCore* c = static_cast<SyncCore*>(h);
  if (player < 0 || player >= c->players) return kSyncErrBadArgs;
  Queue& q = c->queues[player];
  return q.length > 0 ? q.frames[q.tail] : kNullFrame;
}

// Seed one player's EMPTY queue with `count` consecutive confirmed inputs
// for frames [start, start+count) — the adoption path of fallback eviction.
// Slots are placed at frame % kQueueLen, preserving the invariant normal
// sequential insertion from frame 0 establishes (confirmed_input addresses
// by frame-mod while queue_input walks from the tail).
int ggrs_sync_seed(void* h, int player, int64_t start, int32_t count,
                   const uint8_t* bytes) {
  SyncCore* c = static_cast<SyncCore*>(h);
  if (player < 0 || player >= c->players || start < 0 || count < 0 ||
      count > kQueueLen) {
    return kSyncErrBadArgs;
  }
  Queue& q = c->queues[player];
  if (q.last_added != kNullFrame || q.length != 0) return kSyncErrBadArgs;
  if (count == 0) return kSyncOk;
  for (int32_t i = 0; i < count; ++i) {
    i64 frame = start + i;
    int slot = static_cast<int>(frame % kQueueLen);
    q.frames[slot] = frame;
    std::memcpy(c->slot_bytes(q, slot), bytes + static_cast<size_t>(i) * c->input_size,
                c->input_size);
  }
  q.tail = static_cast<int>(start % kQueueLen);
  q.head = static_cast<int>((start + count) % kQueueLen);
  q.length = count;
  q.first_frame = false;
  q.last_added = start + count - 1;
  return kSyncOk;
}

// confirmed_input for one player (input_queue.py confirmed_input): exact
// slot match required
int ggrs_sync_confirmed_input(void* h, int player, int64_t frame,
                              uint8_t* out) {
  SyncCore* c = static_cast<SyncCore*>(h);
  if (player < 0 || player >= c->players) return kSyncErrBadArgs;
  Queue& q = c->queues[player];
  // floored mod, same reasoning as ggrs_sync_confirmed_inputs: negative %
  // is out-of-bounds UB in C++, and Python-parity for frame -1 means
  // matching the blank slot, not rejecting all negatives up front
  int offset = static_cast<int>(((frame % kQueueLen) + kQueueLen) % kQueueLen);
  if (q.frames[offset] != frame) return kSyncErrNoConfirmed;
  std::memcpy(out, c->slot_bytes(q, offset), c->input_size);
  return kSyncOk;
}

}  // extern "C"
