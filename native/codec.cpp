// Native input codec: XOR-delta + zero-run RLE, byte-compatible with
// ggrs_tpu/net/compression.py (same scheme as the reference's
// network/compression.rs: delta vs last-acked input, chained input-to-input,
// then run-length encoding; hardened decode that errors — never crashes or
// over-allocates — on malicious bytes).
//
// This is the one host-side component hot enough to warrant hand-written
// C++ (SURVEY §2 native-component note): it runs per-packet on the UDP path
// for every peer.  Exposed through a minimal C ABI consumed via ctypes
// (ggrs_tpu/net/_native.py); no pybind11 dependency.  Shared wire-format
// helpers live in wire_common.h (also used by endpoint.cpp, the fused
// per-endpoint datapath).

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <vector>

#include "wire_common.h"

using namespace ggrs;

extern "C" {

// Upper bound on the encoded size for a given total payload.
size_t ggrs_codec_encode_bound(size_t total_input_bytes, size_t n_inputs) {
  // mode byte + count varint + per-input size varints + rle worst case
  // (every byte literal: ~2 bytes/byte of header amortized, bounded by
  // total + 10 bytes per token) + length prefix
  return 1 + 10 + n_inputs * 10 + total_input_bytes * 2 + 20;
}

// Compress `n_inputs` byte strings (concatenated in `inputs`, lengths in
// `input_lens`) against `reference`.  Returns kOk and writes `*out_len`.
int ggrs_codec_encode(const uint8_t* reference, size_t reference_len,
                      const uint8_t* inputs, const size_t* input_lens,
                      size_t n_inputs, uint8_t* out, size_t out_cap,
                      size_t* out_len) {
  bool same_size = reference_len > 0;
  for (size_t i = 0; i < n_inputs && same_size; ++i) {
    if (input_lens[i] != reference_len) same_size = false;
  }

  std::vector<uint8_t> delta;
  {
    const uint8_t* base = reference;
    size_t base_len = reference_len;
    const uint8_t* p = inputs;
    for (size_t i = 0; i < n_inputs; ++i) {
      xor_chain(base, base_len, p, input_lens[i], &delta);
      base = p;
      base_len = input_lens[i];
      p += input_lens[i];
    }
  }

  Writer rle;
  rle_encode(delta, &rle);

  Writer w;
  if (same_size) {
    w.u8(0);
  } else {
    w.u8(1);
    w.uvarint(n_inputs);
    int64_t base = static_cast<int64_t>(reference_len);
    for (size_t i = 0; i < n_inputs; ++i) {
      w.svarint(static_cast<int64_t>(input_lens[i]) - base);
      base = static_cast<int64_t>(input_lens[i]);
    }
  }
  w.uvarint(rle.buf.size());
  w.raw(rle.buf.data(), rle.buf.size());

  if (w.buf.size() > out_cap) return kErrBufferTooSmall;
  std::memcpy(out, w.buf.data(), w.buf.size());
  *out_len = w.buf.size();
  return kOk;
}

// Decompress `data` against `reference`.  Decoded payload is written to
// `out` (cap `out_cap`); per-input sizes to `out_sizes` (cap `max_inputs`);
// `*out_count` receives the number of inputs.  All hardening mirrors the
// Python decoder: malicious bytes produce an error code, never UB or
// unbounded allocation.
int ggrs_codec_decode(const uint8_t* reference, size_t reference_len,
                      const uint8_t* data, size_t data_len, uint8_t* out,
                      size_t out_cap, size_t* out_sizes, size_t max_inputs,
                      size_t* out_count) {
  Reader r{data, data_len};
  uint8_t has_sizes;
  int rc = r.u8(&has_sizes);
  if (rc != kOk) return rc;

  std::vector<size_t> sizes;
  bool explicit_sizes = false;
  if (has_sizes == 1) {
    explicit_sizes = true;
    uint64_t count;
    rc = r.uvarint(&count);
    if (rc != kOk) return rc;
    if (count > kMaxDecodedBytes) return kErrTooLarge;
    // each size delta costs at least one byte, so never reserve more slots
    // than the packet could possibly back (memory-amplification hardening)
    sizes.reserve(static_cast<size_t>(
        count < r.remaining() ? count : r.remaining()));
    int64_t base = static_cast<int64_t>(reference_len);
    uint64_t total = 0;
    for (uint64_t i = 0; i < count; ++i) {
      int64_t d;
      rc = r.svarint(&d);
      if (rc != kOk) return rc;
      // unsigned add: defined on overflow, and any wrapped value is caught
      // by the negative/too-large checks below (base is always in
      // [0, kMaxDecodedBytes], so valid sizes can never wrap)
      int64_t size = static_cast<int64_t>(
          static_cast<uint64_t>(base) + static_cast<uint64_t>(d));
      if (size < 0 || static_cast<uint64_t>(size) > kMaxDecodedBytes)
        return kErrNegativeSize;
      total += static_cast<uint64_t>(size);
      if (total > kMaxDecodedBytes) return kErrTooLarge;
      sizes.push_back(static_cast<size_t>(size));
      base = size;
    }
  } else if (has_sizes != 0) {
    return kErrBadSizeMode;
  }

  const uint8_t* rle;
  size_t rle_len;
  rc = r.byte_string(&rle, &rle_len);
  if (rc != kOk) return rc;
  if (r.remaining() != 0) return kErrTrailing;

  std::vector<uint8_t> delta;
  rc = rle_decode(rle, rle_len, &delta);
  if (rc != kOk) return rc;

  if (!explicit_sizes) {
    if (reference_len == 0) return kErrEmptyReference;
    if (delta.size() % reference_len != 0) return kErrNotMultiple;
    sizes.assign(delta.size() / reference_len, reference_len);
  }

  uint64_t expect = 0;
  for (size_t s : sizes) expect += s;
  if (expect != delta.size()) return kErrSizeMismatch;
  if (sizes.size() > max_inputs) return kErrTooManyInputs;
  if (delta.size() > out_cap) return kErrBufferTooSmall;

  // undo the XOR chain in place into `out`
  const uint8_t* base = reference;
  size_t base_len = reference_len;
  size_t pos = 0;
  for (size_t i = 0; i < sizes.size(); ++i) {
    size_t size = sizes[i];
    uint8_t* dst = out + pos;
    const uint8_t* chunk = delta.data() + pos;
    size_t overlap = base_len < size ? base_len : size;
    for (size_t k = 0; k < overlap; ++k) dst[k] = base[k] ^ chunk[k];
    if (size > overlap) std::memcpy(dst + overlap, chunk + overlap, size - overlap);
    out_sizes[i] = size;
    base = dst;
    base_len = size;
    pos += size;
  }
  *out_count = sizes.size();
  return kOk;
}

}  // extern "C"

// ===========================================================================
// Message framing fast path (ggrs_tpu/net/messages.py + wire.py)
// ===========================================================================
//
// The per-packet envelope — magic, tag, body fields, varints — is the other
// host-side hot path: every peer parses every datagram through it.  The
// format is wire.py's (little-endian fixed ints + LEB128 uvarints + zigzag
// svarints); these functions are byte-compatible with messages.py's
// encode/decode and are property-tested against them
// (tests/test_native_codec.py).  Values a u64 cannot hold (Python's ints are
// unbounded) return kMsgFallback so the caller can use the Python decoder —
// identical observable behavior, just slower, on absurd-but-legal packets.

namespace {

constexpr int kMsgFallback = -100;
constexpr int kMsgBadBool = -20;
constexpr int kMsgUnknownTag = -21;
constexpr int kMsgTooManyStatuses = -22;
constexpr int kMsgTrailing = -23;

}  // namespace

extern "C" {

// Fixed-size decode target, caller-allocated and reused across packets.
// payload_off/len index into the SOURCE buffer (zero-copy for input bytes).
struct GgrsMsg {
  uint16_t magic;
  uint8_t tag;
  uint8_t disconnect_requested;
  int64_t start_frame;
  int64_t ack_frame;
  int64_t frame;
  int16_t frame_advantage;
  uint64_t ping;
  uint64_t pong;
  uint64_t checksum_lo;
  uint64_t checksum_hi;
  uint64_t random_nonce;
  int32_t n_status;
  uint64_t payload_off;
  uint64_t payload_len;
  uint8_t status_disconnected[kMaxPlayersOnWire];
  int64_t status_last_frame[kMaxPlayersOnWire];
};

int ggrs_msg_decode(const uint8_t* buf, size_t len, GgrsMsg* out) {
  Reader r{buf, len};
  const uint8_t* p;
  int rc = r.take(2, &p);
  if (rc != kOk) return rc;
  out->magic = static_cast<uint16_t>(p[0] | (p[1] << 8));
  rc = r.u8(&out->tag);
  if (rc != kOk) return rc;

  auto read_bool = [&](uint8_t* v) -> int {
    uint8_t b;
    int rc2 = r.u8(&b);
    if (rc2 != kOk) return rc2;
    if (b > 1) return kMsgBadBool;
    *v = b;
    return kOk;
  };

  switch (out->tag) {
    case kTagInput: {
      uint64_t n;
      rc = r.uvarint(&n);
      if (rc != kOk) break;
      if (n > kMaxPlayersOnWire) return kMsgTooManyStatuses;
      out->n_status = static_cast<int32_t>(n);
      for (uint64_t i = 0; i < n; ++i) {
        rc = read_bool(&out->status_disconnected[i]);
        if (rc != kOk) break;
        rc = r.svarint(&out->status_last_frame[i]);
        if (rc != kOk) break;
      }
      if (rc != kOk) break;
      rc = read_bool(&out->disconnect_requested);
      if (rc != kOk) break;
      rc = r.svarint(&out->start_frame);
      if (rc != kOk) break;
      rc = r.svarint(&out->ack_frame);
      if (rc != kOk) break;
      const uint8_t* payload;
      size_t payload_len;
      rc = r.byte_string(&payload, &payload_len);
      if (rc != kOk) break;
      out->payload_off = static_cast<uint64_t>(payload - buf);
      out->payload_len = payload_len;
      break;
    }
    case kTagInputAck:
      rc = r.svarint(&out->ack_frame);
      break;
    case kTagQualityReport: {
      rc = r.take(2, &p);
      if (rc != kOk) break;
      out->frame_advantage =
          static_cast<int16_t>(p[0] | (static_cast<uint16_t>(p[1]) << 8));
      rc = r.take(8, &p);
      if (rc != kOk) break;
      std::memcpy(&out->ping, p, 8);
      break;
    }
    case kTagQualityReply:
      rc = r.take(8, &p);
      if (rc != kOk) break;
      std::memcpy(&out->pong, p, 8);
      break;
    case kTagChecksumReport:
      rc = r.svarint(&out->frame);
      if (rc != kOk) break;
      rc = r.take(16, &p);
      if (rc != kOk) break;
      std::memcpy(&out->checksum_lo, p, 8);
      std::memcpy(&out->checksum_hi, p + 8, 8);
      break;
    case kTagKeepAlive:
      break;
    case kTagSyncRequest:
      rc = r.uvarint(&out->random_nonce);
      break;
    case kTagSyncReply:
      rc = r.uvarint(&out->random_nonce);
      break;
    default:
      return kMsgUnknownTag;
  }
  // a varint whose value needs > 64 bits decodes fine in Python (unbounded
  // ints) — hand those packets back to the Python decoder for bit-identical
  // observable behavior
  if (rc == kErrTooLarge) return kMsgFallback;
  if (rc != kOk) return rc;
  if (r.remaining() != 0) return kMsgTrailing;
  return kOk;
}

int ggrs_msg_encode(const GgrsMsg* m, const uint8_t* payload,
                    size_t payload_len, uint8_t* out, size_t cap,
                    size_t* out_len) {
  Writer w;
  w.buf.reserve(64 + payload_len);
  w.u8(static_cast<uint8_t>(m->magic & 0xFF));
  w.u8(static_cast<uint8_t>(m->magic >> 8));
  w.u8(m->tag);
  switch (m->tag) {
    case kTagInput: {
      if (m->n_status < 0 ||
          static_cast<size_t>(m->n_status) > kMaxPlayersOnWire) {
        return kMsgTooManyStatuses;
      }
      w.uvarint(static_cast<uint64_t>(m->n_status));
      for (int32_t i = 0; i < m->n_status; ++i) {
        w.u8(m->status_disconnected[i] ? 1 : 0);
        w.svarint(m->status_last_frame[i]);
      }
      w.u8(m->disconnect_requested ? 1 : 0);
      w.svarint(m->start_frame);
      w.svarint(m->ack_frame);
      w.uvarint(payload_len);
      w.raw(payload, payload_len);
      break;
    }
    case kTagInputAck:
      w.svarint(m->ack_frame);
      break;
    case kTagQualityReport: {
      uint16_t adv = static_cast<uint16_t>(m->frame_advantage);
      w.u8(static_cast<uint8_t>(adv & 0xFF));
      w.u8(static_cast<uint8_t>(adv >> 8));
      for (int i = 0; i < 8; ++i)
        w.u8(static_cast<uint8_t>(m->ping >> (8 * i)));
      break;
    }
    case kTagQualityReply:
      for (int i = 0; i < 8; ++i)
        w.u8(static_cast<uint8_t>(m->pong >> (8 * i)));
      break;
    case kTagChecksumReport:
      w.svarint(m->frame);
      for (int i = 0; i < 8; ++i)
        w.u8(static_cast<uint8_t>(m->checksum_lo >> (8 * i)));
      for (int i = 0; i < 8; ++i)
        w.u8(static_cast<uint8_t>(m->checksum_hi >> (8 * i)));
      break;
    case kTagKeepAlive:
      break;
    case kTagSyncRequest:
    case kTagSyncReply:
      w.uvarint(m->random_nonce);
      break;
    default:
      return kMsgUnknownTag;
  }
  if (w.buf.size() > cap) return kErrBufferTooSmall;
  std::memcpy(out, w.buf.data(), w.buf.size());
  *out_len = w.buf.size();
  return kOk;
}

}  // extern "C"
