// Fused per-endpoint datapath: the per-tick hot path of
// ggrs_tpu/net/protocol.py in one native call each way.
//
// The Python PeerProtocol keeps the reliability *policy* (timers, events,
// state machine, connect-status merging); this module owns the per-tick
// *mechanism* whose Python object churn dominated the live session tick:
//   - the unacked pending-output window and its last-acked compression base
//     (reference: protocol.rs:421-487),
//   - the received-input ring that provides the delta-decode base
//     (reference: protocol.rs:534-682),
//   - building the complete InputMessage datagram (header + statuses +
//     compressed payload) in one pass, byte-identical to messages.py +
//     compression.py,
//   - decoding an incoming InputMessage payload against the ring base and
//     handing back only the NEW frames.
//
// One endpoint object per PeerProtocol; ggrs_tpu/net/endpoint.py wraps this
// ABI and provides the pure-Python fallback core with identical observable
// behavior (tests/test_native_endpoint.py pins wire-level parity).

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <deque>
#include <vector>

#include "wire_common.h"

using namespace ggrs;

namespace {

constexpr int64_t kNullFrame = -1;

// Frames beyond this are malformed (the wire contract is i64 with headroom
// so start_frame±count arithmetic can never overflow); mirrors
// _FRAME_SANE_MIN/MAX in net/endpoint.py.
constexpr int64_t kFrameSaneMin = -(int64_t{1} << 62);
constexpr int64_t kFrameSaneMax = int64_t{1} << 62;

// endpoint-specific return codes (mirrored in _native.py)
constexpr int kEpDrop = -30;      // packet must be dropped (gap / bad base /
                                  // undecodable payload) — matches the Python
                                  // path's silent-drop semantics, no ack
constexpr int kEpFallback = -31;  // legal but exceeds fast-path resources;
                                  // caller retries via the Python codec
constexpr int kEpBadPendingHead = -32;  // pending[0] != last_acked+1 (caller
                                        // raises: protocol invariant broken)

struct FrameBytes {
  int64_t frame;
  std::vector<uint8_t> payload;
};

struct Endpoint {
  // ---- send side ----
  std::deque<FrameBytes> pending;     // unacked outgoing inputs
  std::vector<uint8_t> last_acked;    // delta base for the next send
  int64_t last_acked_frame = kNullFrame;

  // ---- receive side ----
  // ring of recently received frame payloads: the decode base for packet N+1
  // is the payload of start_frame-1.  Ring replaces the Python dict+GC; the
  // explicit cutoff check below reproduces the dict's GC semantics exactly.
  size_t ring_size = 0;
  std::vector<std::vector<uint8_t>> recv_payloads;
  std::vector<int64_t> recv_frames;   // INT64_MIN = empty slot
  std::vector<uint8_t> recv_null_base;  // base before any input arrived
  int64_t last_recv_frame = kNullFrame;
  int64_t max_prediction = 8;

  // scratch for the last on_input decode, awaiting commit()
  std::vector<uint8_t> decoded;       // concatenated new-frame payloads
  std::vector<size_t> decoded_sizes;
  int64_t decoded_first = kNullFrame;

  std::vector<uint8_t> scratch;       // encode scratch

  // ---- observability accumulators (ggrs_ep_stats) ----
  // monotonic counters the stat harvest reads; the datapath never
  // consults them, so they cannot perturb wire behavior
  uint64_t stat_emits = 0;        // input datagrams built (emit_input)
  uint64_t stat_emit_bytes = 0;   // their total wire bytes
  uint64_t stat_acks = 0;         // acks applied (ggrs_ep_ack calls)
  uint64_t stat_datagrams = 0;    // input payloads offered for decode
  uint64_t stat_frames = 0;       // NEW frames staged by decodes
  uint64_t stat_drops = 0;        // kEpDrop outcomes (gap/base/undecodable)
  uint64_t stat_fallbacks = 0;    // kEpFallback outcomes (resource caps)
};

int64_t ring_slot(const Endpoint& ep, int64_t frame) {
  int64_t m = frame % static_cast<int64_t>(ep.ring_size);
  return m < 0 ? m + static_cast<int64_t>(ep.ring_size) : m;
}

// Base payload for delta-decoding a packet that starts at base_frame+1.
// Mirrors _recv_inputs.get(decode_frame) + the GC cutoff
// (protocol.py _on_input): an entry exists iff it was stored and is not
// older than last_recv - 2*max_prediction.
const std::vector<uint8_t>* lookup_base(const Endpoint& ep, int64_t frame) {
  if (frame == kNullFrame) return &ep.recv_null_base;
  if (ep.last_recv_frame != kNullFrame &&
      frame < ep.last_recv_frame - 2 * ep.max_prediction) {
    return nullptr;  // would have been GC'd by the Python dict
  }
  size_t slot = static_cast<size_t>(ring_slot(ep, frame));
  if (ep.recv_frames[slot] != frame) return nullptr;
  return &ep.recv_payloads[slot];
}

void store_recv(Endpoint* ep, int64_t frame, const uint8_t* payload,
                size_t len) {
  size_t slot = static_cast<size_t>(ring_slot(*ep, frame));
  ep->recv_frames[slot] = frame;
  ep->recv_payloads[slot].assign(payload, payload + len);
  if (frame > ep->last_recv_frame) ep->last_recv_frame = frame;
}

}  // namespace

extern "C" {

void* ggrs_ep_new(const uint8_t* send_base, size_t send_base_len,
                  const uint8_t* recv_base, size_t recv_base_len,
                  int64_t max_prediction) {
  Endpoint* ep = new (std::nothrow) Endpoint();
  if (!ep) return nullptr;
  ep->last_acked.assign(send_base, send_base + send_base_len);
  ep->recv_null_base.assign(recv_base, recv_base + recv_base_len);
  ep->max_prediction = max_prediction;
  // ring must outlive the GC window (2*max_prediction) with slack so a slot
  // is never reused while the Python dict would still hold the old entry
  size_t need = static_cast<size_t>(4 * max_prediction + 16);
  ep->ring_size = 64;
  while (ep->ring_size < need) ep->ring_size <<= 1;
  ep->recv_payloads.resize(ep->ring_size);
  ep->recv_frames.assign(ep->ring_size, INT64_MIN);
  return ep;
}

void ggrs_ep_free(void* ptr) { delete static_cast<Endpoint*>(ptr); }

int64_t ggrs_ep_pending_len(void* ptr) {
  return static_cast<int64_t>(static_cast<Endpoint*>(ptr)->pending.size());
}

int64_t ggrs_ep_last_recv_frame(void* ptr) {
  return static_cast<Endpoint*>(ptr)->last_recv_frame;
}

// Pop everything acked through `ack_frame`, keeping the newest popped
// payload as the delta base (protocol.py _pop_pending_output).
void ggrs_ep_ack(void* ptr, int64_t ack_frame) {
  Endpoint* ep = static_cast<Endpoint*>(ptr);
  ep->stat_acks += 1;
  while (!ep->pending.empty() && ep->pending.front().frame <= ack_frame) {
    ep->last_acked_frame = ep->pending.front().frame;
    ep->last_acked = std::move(ep->pending.front().payload);
    ep->pending.pop_front();
  }
}

// Append this frame's joined per-player payload to the pending window.
// Returns the new pending count (the caller raises the 128-overflow
// disconnect event; the send still happens, as in protocol.py).
int64_t ggrs_ep_push(void* ptr, int64_t frame, const uint8_t* payload,
                     size_t len) {
  Endpoint* ep = static_cast<Endpoint*>(ptr);
  ep->pending.push_back(FrameBytes{frame, {payload, payload + len}});
  return static_cast<int64_t>(ep->pending.size());
}

// Build the complete InputMessage datagram for the current pending window:
// magic + tag + statuses + disconnect_requested + start/ack frames +
// compressed payload.  Byte-identical to InputMessage via messages.py with
// compression.py's codec.  out_len = 0 (rc kOk) when pending is empty (the
// Python path queues nothing).  ack_frame is the endpoint's own
// last_recv_frame, as in protocol.py _send_pending_output.
// status_frames_le: n_status little-endian int64s packed as bytes (the
// Python side builds them with one struct.pack instead of per-element
// ctypes array stores).
int ggrs_ep_emit_input(void* ptr, uint16_t magic,
                       const uint8_t* status_disc,
                       const uint8_t* status_frames_le, int32_t n_status,
                       uint8_t disconnect_requested, uint8_t* out, size_t cap,
                       size_t* out_len) {
  Endpoint* ep = static_cast<Endpoint*>(ptr);
  *out_len = 0;
  if (ep->pending.empty()) return kOk;
  if (n_status < 0 || static_cast<size_t>(n_status) > kMaxPlayersOnWire)
    return kErrTooManyInputs;
  const FrameBytes& first = ep->pending.front();
  if (ep->last_acked_frame != kNullFrame &&
      ep->last_acked_frame + 1 != first.frame) {
    return kEpBadPendingHead;
  }

  // delta+RLE compress the whole pending window against last_acked
  // (compression.py encode): XOR chain, then RLE, then the size-mode header
  std::vector<uint8_t> delta;
  {
    const uint8_t* base = ep->last_acked.data();
    size_t base_len = ep->last_acked.size();
    bool same_size = base_len > 0;
    for (const FrameBytes& fb : ep->pending) {
      if (fb.payload.size() != ep->last_acked.size()) same_size = false;
      xor_chain(base, base_len, fb.payload.data(), fb.payload.size(), &delta);
      base = fb.payload.data();
      base_len = fb.payload.size();
    }
    Writer rle;
    rle_encode(delta, &rle);

    Writer w;
    w.buf.reserve(rle.buf.size() + 64);
    w.u8(static_cast<uint8_t>(magic & 0xFF));
    w.u8(static_cast<uint8_t>(magic >> 8));
    w.u8(kTagInput);
    w.uvarint(static_cast<uint64_t>(n_status));
    for (int32_t i = 0; i < n_status; ++i) {
      w.u8(status_disc[i] ? 1 : 0);
      int64_t f;  // host assumed little-endian (x86-64 / aarch64 hosts)
      std::memcpy(&f, status_frames_le + 8 * i, 8);
      w.svarint(f);
    }
    w.u8(disconnect_requested ? 1 : 0);
    w.svarint(first.frame);                // start_frame
    w.svarint(ep->last_recv_frame);        // ack_frame
    // body payload: the compressed stream with compression.py's envelope
    Writer comp;
    if (same_size) {
      comp.u8(0);
    } else {
      comp.u8(1);
      comp.uvarint(ep->pending.size());
      int64_t base_sz = static_cast<int64_t>(ep->last_acked.size());
      for (const FrameBytes& fb : ep->pending) {
        comp.svarint(static_cast<int64_t>(fb.payload.size()) - base_sz);
        base_sz = static_cast<int64_t>(fb.payload.size());
      }
    }
    comp.uvarint(rle.buf.size());
    comp.raw(rle.buf.data(), rle.buf.size());
    w.uvarint(comp.buf.size());
    w.raw(comp.buf.data(), comp.buf.size());

    if (w.buf.size() > cap) return kErrBufferTooSmall;
    std::memcpy(out, w.buf.data(), w.buf.size());
    *out_len = w.buf.size();
    ep->stat_emits += 1;
    ep->stat_emit_bytes += static_cast<uint64_t>(w.buf.size());
  }
  return kOk;
}

// Decode an incoming InputMessage payload against the ring base.  PEEKS
// ONLY: the new frames are staged in scratch until ggrs_ep_commit() — the
// caller validates the inner per-player framing first so a malformed packet
// is all-or-nothing dropped.  Shared by the two entry points below.
//
// Returns kOk with *out_count new frames (possibly 0: pure-duplicate packet,
// still acked by the caller), kEpDrop when the packet must be silently
// dropped (sequence gap / missing base / undecodable payload), kEpFallback
// when legal-but-huge (caller uses the Python codec via ggrs_ep_fetch_base +
// ggrs_ep_store_one).
static int ep_on_input_inner(Endpoint* ep, int64_t start_frame,
                             const uint8_t* comp, size_t comp_len,
                             uint8_t* out, size_t out_cap, size_t* out_sizes,
                             size_t max_frames, size_t* out_count,
                             int64_t* first_new_frame,
                             int64_t* new_last_recv) {
  *out_count = 0;
  *first_new_frame = kNullFrame;
  *new_last_recv = ep->last_recv_frame;
  ep->decoded.clear();
  ep->decoded_sizes.clear();
  ep->decoded_first = kNullFrame;

  // beyond the i64 wire contract: malformed, drop (also keeps the +1/-1/+i
  // frame arithmetic below clear of signed overflow)
  if (start_frame < kFrameSaneMin || start_frame > kFrameSaneMax) {
    return kEpDrop;
  }
  // unrecoverable gap: impossible from an honest peer, drop
  // (protocol.py _on_input; reference asserts, protocol.rs:588-590)
  if (ep->last_recv_frame != kNullFrame &&
      ep->last_recv_frame + 1 < start_frame) {
    return kEpDrop;
  }
  int64_t base_frame =
      ep->last_recv_frame == kNullFrame ? kNullFrame : start_frame - 1;
  const std::vector<uint8_t>* base = lookup_base(*ep, base_frame);
  if (base == nullptr) return kEpDrop;

  // decompress (compression.py decode semantics, incl. hardening)
  Reader r{comp, comp_len};
  uint8_t has_sizes;
  int rc = r.u8(&has_sizes);
  if (rc != kOk) return kEpDrop;
  std::vector<size_t> sizes;
  bool explicit_sizes = false;
  if (has_sizes == 1) {
    explicit_sizes = true;
    uint64_t count;
    rc = r.uvarint(&count);
    if (rc != kOk) return kEpDrop;
    if (count > kMaxDecodedBytes) return kEpDrop;
    sizes.reserve(static_cast<size_t>(
        count < r.remaining() ? count : r.remaining()));
    int64_t base_sz = static_cast<int64_t>(base->size());
    uint64_t total = 0;
    for (uint64_t i = 0; i < count; ++i) {
      int64_t d;
      rc = r.svarint(&d);
      if (rc != kOk) return kEpDrop;
      int64_t size = static_cast<int64_t>(
          static_cast<uint64_t>(base_sz) + static_cast<uint64_t>(d));
      if (size < 0 || static_cast<uint64_t>(size) > kMaxDecodedBytes)
        return kEpDrop;
      total += static_cast<uint64_t>(size);
      if (total > kMaxDecodedBytes) return kEpDrop;
      sizes.push_back(static_cast<size_t>(size));
      base_sz = size;
    }
  } else if (has_sizes != 0) {
    return kEpDrop;
  }
  const uint8_t* rle;
  size_t rle_len;
  rc = r.byte_string(&rle, &rle_len);
  if (rc != kOk) return kEpDrop;
  if (r.remaining() != 0) return kEpDrop;
  std::vector<uint8_t> delta;
  rc = rle_decode(rle, rle_len, &delta);
  if (rc != kOk) return kEpDrop;
  if (!explicit_sizes) {
    if (base->empty()) return kEpDrop;
    if (delta.size() % base->size() != 0) return kEpDrop;
    sizes.assign(delta.size() / base->size(), base->size());
  }
  uint64_t expect = 0;
  for (size_t s : sizes) expect += s;
  if (expect != delta.size()) return kEpDrop;

  // undo the XOR chain into one contiguous buffer (each frame's payload is
  // the base for the next, exactly as codec.cpp's decode)
  std::vector<uint8_t>& all = ep->scratch;
  all.resize(delta.size());
  {
    const uint8_t* chain_base = base->data();
    size_t chain_base_len = base->size();
    size_t pos = 0;
    for (size_t i = 0; i < sizes.size(); ++i) {
      size_t size = sizes[i];
      uint8_t* dst = all.data() + pos;
      const uint8_t* chunk = delta.data() + pos;
      size_t overlap = chain_base_len < size ? chain_base_len : size;
      for (size_t k = 0; k < overlap; ++k) dst[k] = chain_base[k] ^ chunk[k];
      if (size > overlap)
        std::memcpy(dst + overlap, chunk + overlap, size - overlap);
      chain_base = dst;
      chain_base_len = size;
      pos += size;
    }
  }

  // stage only frames newer than last_recv (duplicates are skipped, as in
  // protocol.py's `frame <= last_recv_frame: continue`)
  {
    size_t pos = 0;
    for (size_t i = 0; i < sizes.size(); ++i) {
      size_t size = sizes[i];
      int64_t frame = start_frame + static_cast<int64_t>(i);
      if (frame > ep->last_recv_frame) {
        if (ep->decoded_sizes.size() >= max_frames ||
            ep->decoded.size() + size > out_cap) {
          ep->decoded.clear();
          ep->decoded_sizes.clear();
          ep->decoded_first = kNullFrame;
          return kEpFallback;
        }
        if (ep->decoded_first == kNullFrame) ep->decoded_first = frame;
        ep->decoded.insert(ep->decoded.end(), all.begin() + pos,
                           all.begin() + pos + size);
        ep->decoded_sizes.push_back(size);
      }
      pos += size;
    }
  }

  std::memcpy(out, ep->decoded.data(), ep->decoded.size());
  for (size_t i = 0; i < ep->decoded_sizes.size(); ++i)
    out_sizes[i] = ep->decoded_sizes[i];
  *out_count = ep->decoded_sizes.size();
  *first_new_frame = ep->decoded_first;
  *new_last_recv = ep->decoded_sizes.empty()
                       ? ep->last_recv_frame
                       : ep->decoded_first +
                             static_cast<int64_t>(ep->decoded_sizes.size()) - 1;
  return kOk;
}

// stats wrapper around the decode: counts outcomes without touching the
// decode's many early-return paths
static int ep_on_input_impl(Endpoint* ep, int64_t start_frame,
                            const uint8_t* comp, size_t comp_len,
                            uint8_t* out, size_t out_cap, size_t* out_sizes,
                            size_t max_frames, size_t* out_count,
                            int64_t* first_new_frame,
                            int64_t* new_last_recv) {
  int rc = ep_on_input_inner(ep, start_frame, comp, comp_len, out, out_cap,
                             out_sizes, max_frames, out_count,
                             first_new_frame, new_last_recv);
  ep->stat_datagrams += 1;
  if (rc == kEpDrop) {
    ep->stat_drops += 1;
  } else if (rc == kEpFallback) {
    ep->stat_fallbacks += 1;
  } else if (rc == kOk) {
    ep->stat_frames += static_cast<uint64_t>(*out_count);
  }
  return rc;
}

int ggrs_ep_on_input(void* ptr, int64_t start_frame, const uint8_t* comp,
                     size_t comp_len, uint8_t* out, size_t out_cap,
                     size_t* out_sizes, size_t max_frames, size_t* out_count,
                     int64_t* first_new_frame, int64_t* new_last_recv) {
  return ep_on_input_impl(static_cast<Endpoint*>(ptr), start_frame, comp,
                          comp_len, out, out_cap, out_sizes, max_frames,
                          out_count, first_new_frame, new_last_recv);
}

// The fused receive: parse a complete InputMessage datagram, apply its ack,
// and stage its new frames — ONE crossing for the per-tick hot packet.
// Header fields come back through the scalar/array outs so the Python side
// can run the connect-status merge and inner-framing validation before
// ggrs_ep_commit().
//
// Returns: kOk (frames staged), kEpDrop (header parsed + ack applied, but
// the payload must be dropped: gap / missing base / undecodable), kEpFallback
// (ack applied; caller retries via the object path), or a message-framing
// error (nothing applied — the caller drops the datagram exactly as the
// socket layer drops undecodable packets).
int ggrs_ep_handle_input_datagram(
    void* ptr, const uint8_t* data, size_t len, uint16_t* magic,
    uint8_t* disconnect_requested, uint8_t* status_disc,
    int64_t* status_frames, int32_t* n_status, int64_t* start_frame,
    uint8_t* out, size_t out_cap, size_t* out_sizes, size_t max_frames,
    size_t* out_count, int64_t* first_new_frame, int64_t* new_last_recv) {
  Endpoint* ep = static_cast<Endpoint*>(ptr);
  Reader r{data, len};
  const uint8_t* p;
  int rc = r.take(2, &p);
  if (rc != kOk) return rc;
  *magic = static_cast<uint16_t>(p[0] | (p[1] << 8));
  uint8_t tag;
  rc = r.u8(&tag);
  if (rc != kOk) return rc;
  if (tag != kTagInput) return kEpFallback;  // caller routes by tag; guard

  uint64_t n;
  rc = r.uvarint(&n);
  if (rc == kOk && n > kMaxPlayersOnWire) return kErrTooManyInputs;
  for (uint64_t i = 0; rc == kOk && i < n; ++i) {
    uint8_t b;
    rc = r.u8(&b);
    if (rc != kOk) break;
    if (b > 1) return kErrBadSizeMode;  // bad bool byte: malformed
    status_disc[i] = b;
    rc = r.svarint(&status_frames[i]);
  }
  if (rc == kOk) {
    uint8_t b = 0;
    rc = r.u8(&b);
    if (rc == kOk) {
      if (b > 1) return kErrBadSizeMode;
      *disconnect_requested = b;
    }
  }
  int64_t ack_frame = 0;
  if (rc == kOk) rc = r.svarint(start_frame);
  if (rc == kOk) rc = r.svarint(&ack_frame);
  const uint8_t* payload = nullptr;
  size_t payload_len = 0;
  if (rc == kOk) rc = r.byte_string(&payload, &payload_len);
  // a varint beyond u64 decodes fine under Python's unbounded ints: hand the
  // datagram to the object path for bit-identical behavior
  if (rc == kErrTooLarge) return kEpFallback;
  if (rc != kOk) return rc;
  if (r.remaining() != 0) return kErrTrailing;
  *n_status = static_cast<int32_t>(n);

  // header fully parsed: apply the ack (protocol.py _on_input order), then
  // decode + stage
  ggrs_ep_ack(ptr, ack_frame);
  return ep_on_input_impl(ep, *start_frame, payload, payload_len, out,
                          out_cap, out_sizes, max_frames, out_count,
                          first_new_frame, new_last_recv);
}

// Commit the frames staged by the last ggrs_ep_on_input: store them in the
// recv ring and advance last_recv_frame.  Call after inner-framing
// validation succeeds; skip to drop the packet with no state change.
void ggrs_ep_commit(void* ptr) {
  Endpoint* ep = static_cast<Endpoint*>(ptr);
  const uint8_t* p = ep->decoded.data();
  for (size_t i = 0; i < ep->decoded_sizes.size(); ++i) {
    store_recv(ep, ep->decoded_first + static_cast<int64_t>(i), p,
               ep->decoded_sizes[i]);
    p += ep->decoded_sizes[i];
  }
  ep->decoded.clear();
  ep->decoded_sizes.clear();
  ep->decoded_first = kNullFrame;
}

// ---- escape hatches for the Python-codec fallback path -------------------

// Fetch the decode base for a packet starting at `start_frame` (the payload
// of start_frame-1, or the null base).  rc kEpDrop when unavailable.
int ggrs_ep_fetch_base(void* ptr, int64_t start_frame, uint8_t* out,
                       size_t cap, size_t* out_len) {
  Endpoint* ep = static_cast<Endpoint*>(ptr);
  int64_t base_frame =
      ep->last_recv_frame == kNullFrame ? kNullFrame : start_frame - 1;
  const std::vector<uint8_t>* base = lookup_base(*ep, base_frame);
  if (base == nullptr) return kEpDrop;
  if (base->size() > cap) return kErrBufferTooSmall;
  std::memcpy(out, base->data(), base->size());
  *out_len = base->size();
  return kOk;
}

// Store one received frame payload directly (Python-codec fallback commit).
void ggrs_ep_store_one(void* ptr, int64_t frame, const uint8_t* payload,
                       size_t len) {
  store_recv(static_cast<Endpoint*>(ptr), frame, payload, len);
}

// ---- eviction / supervision support --------------------------------------
//
// The supervised session bank (session_bank.cpp) evicts a faulted slot to
// the per-session Python path, resuming from the slot's last committed
// state.  The dump APIs let the bank's harvest read an endpoint's resumable
// datapath state; the seed API lets a freshly-built core adopt the send side
// (the receive side seeds through the existing ggrs_ep_store_one).  Framing
// is fixed little-endian: [i64 frame][u32 len][bytes] per entry.

namespace {

void dump_i64(uint8_t* out, size_t* pos, int64_t v) {
  std::memcpy(out + *pos, &v, 8);  // little-endian host (wire_common.h)
  *pos += 8;
}

void dump_u32(uint8_t* out, size_t* pos, uint32_t v) {
  std::memcpy(out + *pos, &v, 4);
  *pos += 4;
}

void dump_u16(uint8_t* out, size_t* pos, uint16_t v) {
  std::memcpy(out + *pos, &v, 2);
  *pos += 2;
}

}  // namespace

// Send-side dump:
//   [i64 last_acked_frame][u32 base_len][base bytes]
//   [u16 n_pending] then per entry [i64 frame][u32 len][bytes]
// Returns kOk, or kErrBufferTooSmall with *out_len = needed size.
int ggrs_ep_dump_send(void* ptr, uint8_t* out, size_t cap, size_t* out_len) {
  Endpoint* ep = static_cast<Endpoint*>(ptr);
  size_t need = 8 + 4 + ep->last_acked.size() + 2;
  for (const FrameBytes& fb : ep->pending) need += 12 + fb.payload.size();
  *out_len = need;
  if (need > cap) return kErrBufferTooSmall;
  size_t pos = 0;
  dump_i64(out, &pos, ep->last_acked_frame);
  dump_u32(out, &pos, static_cast<uint32_t>(ep->last_acked.size()));
  std::memcpy(out + pos, ep->last_acked.data(), ep->last_acked.size());
  pos += ep->last_acked.size();
  dump_u16(out, &pos, static_cast<uint16_t>(ep->pending.size()));
  for (const FrameBytes& fb : ep->pending) {
    dump_i64(out, &pos, fb.frame);
    dump_u32(out, &pos, static_cast<uint32_t>(fb.payload.size()));
    std::memcpy(out + pos, fb.payload.data(), fb.payload.size());
    pos += fb.payload.size();
  }
  return kOk;
}

// Receive-side dump: every ring entry still inside the GC window (these are
// the delta-decode bases a resumed core needs so in-flight packets keep
// decoding): [i64 last_recv_frame][u16 n] then per entry
// [i64 frame][u32 len][bytes].  Entry order is ascending frame.
int ggrs_ep_dump_recv(void* ptr, uint8_t* out, size_t cap, size_t* out_len) {
  Endpoint* ep = static_cast<Endpoint*>(ptr);
  int64_t lo = ep->last_recv_frame == kNullFrame
                   ? 0
                   : ep->last_recv_frame - 2 * ep->max_prediction;
  if (lo < 0) lo = 0;  // frames on the ring are >= 0; NULL is the null base
  size_t need = 8 + 2;
  uint16_t n = 0;
  for (int64_t f = lo; f <= ep->last_recv_frame; ++f) {
    const std::vector<uint8_t>* p = lookup_base(*ep, f);
    if (p != nullptr) {
      need += 12 + p->size();
      ++n;
    }
  }
  *out_len = need;
  if (need > cap) return kErrBufferTooSmall;
  size_t pos = 0;
  dump_i64(out, &pos, ep->last_recv_frame);
  dump_u16(out, &pos, n);
  for (int64_t f = lo; f <= ep->last_recv_frame; ++f) {
    const std::vector<uint8_t>* p = lookup_base(*ep, f);
    if (p == nullptr) continue;
    dump_i64(out, &pos, f);
    dump_u32(out, &pos, static_cast<uint32_t>(p->size()));
    std::memcpy(out + pos, p->data(), p->size());
    pos += p->size();
  }
  return kOk;
}

// Adopt the send-side delta base: the resumed pending window (re-fed via
// ggrs_ep_push) compresses against — and must sequentially follow — the
// exact base the peer last acked.
void ggrs_ep_seed_send(void* ptr, int64_t last_acked_frame,
                       const uint8_t* base, size_t len) {
  Endpoint* ep = static_cast<Endpoint*>(ptr);
  ep->last_acked_frame = last_acked_frame;
  ep->last_acked.assign(base, base + len);
}

// Rewind the send window to an earlier delta base (the fleet failover
// seam): a peer that resumed from its durable journal may genuinely hold
// LESS than it once acked, and its repeated regressive acks ask us to
// rebase.  Drops the whole pending window (the caller re-pushes the
// frames after `frame` from its sent-payload ring) and reseeds the base,
// exactly like seed_send on a fresh endpoint.
void ggrs_ep_rewind_send(void* ptr, int64_t frame, const uint8_t* base,
                         size_t len) {
  Endpoint* ep = static_cast<Endpoint*>(ptr);
  ep->pending.clear();
  ep->last_acked_frame = frame;
  ep->last_acked.assign(base, base + len);
}

// ---- observability (the obs stat harvest) --------------------------------

int64_t ggrs_ep_last_acked_frame(void* ptr) {
  return static_cast<Endpoint*>(ptr)->last_acked_frame;
}

// Read the core's monotonic observability counters in one call.
// out7 layout: emits, emit_bytes, acks, datagrams, new_frames, drops,
// fallbacks (all u64; mirrored in ggrs_tpu/net/_native.py EP_STAT_FIELDS
// and read per endpoint by ggrs_bank_stats).
void ggrs_ep_stats(void* ptr, uint64_t* out7) {
  Endpoint* ep = static_cast<Endpoint*>(ptr);
  out7[0] = ep->stat_emits;
  out7[1] = ep->stat_emit_bytes;
  out7[2] = ep->stat_acks;
  out7[3] = ep->stat_datagrams;
  out7[4] = ep->stat_frames;
  out7[5] = ep->stat_drops;
  out7[6] = ep->stat_fallbacks;
}

}  // extern "C"
